"""Tile-coverage prover: the skip grids held against an independent oracle.

Every speed claim in this repo rests on trace-time tile dropping — the
compact causal grids of ``ops/pallas_flash.py`` (``band_plan``) driven by
the per-hop band hints of ``parallel/ring.py`` — and until now nothing
*proved* that the compact grids visit exactly the tiles the mask
requires.  A skipped live tile is silently wrong attention (the missing
block never enters the online softmax); a visited dead tile is silent
perf loss; an interior-classified tile that is not actually full-band
adds UNMASKED garbage, because interior tiles compile the mask out.

The oracle here is deliberately independent of the kernels' offset
algebra: every check starts from GLOBAL token positions (FlashAttention's
tiling contract, arXiv 2205.14135 — attention is defined on positions,
tiles are an implementation detail).  For each strategy x layout x
masking row the prover enumerates, per ring hop and per rank:

  - which global query positions the device holds (contiguous, striped,
    zig-zag, counter-rotated — the q block travels under TokenRing,
    arXiv 2412.20501) and which global key positions the circulating
    stream delivers;
  - the ground-truth element mask (causal, sliding window, document
    equality) on those positions;

and holds the system under test to it at three levels:

  **soundness** — no live element is lost: a tile absent from the band
  table, a hop skipped by ``_hop_has_work``, or a "full span" hop must
  contain no live / only live elements respectively, and an
  interior-classified tile must be fully live for EVERY rank;
  **tightness** — no dead tile is visited: every WORK entry is live for
  some rank, every EDGE entry is non-full for some rank, and the
  closed-form ``_band_tile_count`` equals the enumerated table length;
  **schedule completeness** — summing each hop's computed elements per
  q-origin reproduces the intended global mask exactly once (nothing
  dropped between hops, nothing double-counted into the softmax).

All pure numpy + trace-time helpers — CPU, no devices, no compiles.
CLI: ``tools/check_contracts.py --coverage``; the per-row tile counts
ride bench phase 0 as ``coverage_fingerprint`` and gate in
``analysis/perfgate.py`` (a mask change that visits dead tiles fails
like a contract violation).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# ---------------------------------------------------------------------------
# Oracle construction (global positions — independent of the band algebra)
# ---------------------------------------------------------------------------


def _positions(layout: str, origin: int, n_local: int, ring: int) -> np.ndarray:
    """Global token positions of ``origin``'s local shard."""
    i = np.arange(n_local)
    if layout == "striped":
        return i * ring + origin
    if layout == "contiguous":
        return origin * n_local + i
    raise ValueError(f"unknown layout {layout!r}")


def _doc_of(doc_starts, total: int) -> np.ndarray:
    """Per-position document id for a declared packing layout."""
    ids = np.zeros(total, np.int64)
    for d, s in enumerate(doc_starts):
        ids[s:] = d
    return ids


def oracle_mask(qpos: np.ndarray, kpos: np.ndarray, window: int | None,
                doc_ids: np.ndarray | None = None) -> np.ndarray:
    """Ground-truth (nq, nk) attend mask from global positions: causal,
    optional exact sliding window, optional document equality."""
    m = kpos[None, :] <= qpos[:, None]
    if window is not None:
        m = m & (kpos[None, :] >= qpos[:, None] - (window - 1))
    if doc_ids is not None:
        m = m & (doc_ids[kpos][None, :] == doc_ids[qpos][:, None])
    return m


def band_mask(nq: int, nk: int, hi, lo) -> np.ndarray:
    """The runtime band predicate the kernels mask edge tiles with:
    attend iff ``lo <= j - i <= hi`` in local indices (``lo=None`` = no
    window; ``hi=None`` = unmasked)."""
    if hi is None:
        return np.ones((nq, nk), bool)
    diff = np.arange(nk)[None, :] - np.arange(nq)[:, None]
    m = diff <= int(hi)
    if lo is not None:
        m = m & (diff >= int(lo))
    return m


# ---------------------------------------------------------------------------
# One hop-instance: what one rank actually computes at one hop
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HopInstance:
    """One rank's compute at one (hop, stream): the runtime decisions the
    compiled program makes, next to the oracle they must realize."""

    rank: int
    q_origin: int
    kv_origin: int
    oracle: np.ndarray  # (nq, nk) bool ground truth for this pairing
    static_live: np.ndarray  # truth from trace-droppable constraints only
    hi: int | None  # runtime band offsets the kernel masks with
    lo: int | None
    has_work: bool  # the traced hop-level skip decision
    full: bool  # trace-time full-span elision (no mask at all)
    seg_mask: np.ndarray | None = None  # runtime doc mask (misaligned docs)
    kpos: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int64))
    # ^ global key columns this instance computes against (striped layouts
    #   deliver non-contiguous columns — the schedule check indexes them)
    rt_mask: np.ndarray | None = None
    # ^ generic runtime edge mask (mask-algebra lowerings that are not a
    #   band): when present it replaces the band/segment construction as
    #   the runtime predicate under test


def _instance_runtime(x: HopInstance, nq: int, nk: int) -> np.ndarray:
    """The runtime edge-tile mask this instance's kernel would apply —
    the band scalars (optionally intersected with the runtime document
    mask), or the generic lowering's predicate."""
    if x.rt_mask is not None:
        return x.rt_mask
    rt = band_mask(nq, nk, x.hi, x.lo)
    if x.seg_mask is not None:
        rt = rt & x.seg_mask
    return rt


def _tile_slices(plan, qi: int, ki: int):
    bq, bk = plan.block_q, plan.block_k
    return slice(qi * bq, (qi + 1) * bq), slice(ki * bk, (ki + 1) * bk)


def _check_table_structure(plan, label: str) -> list[str]:
    """The accumulator-lifecycle contract of the tables: outer-major
    order, FIRST/LAST exactly bracketing every outer row, inner index
    non-decreasing within a row (the carried online softmax / dq / dkv
    state is initialized at FIRST and written at LAST)."""
    from ..ops.pallas_flash import _TF_FIRST, _TF_LAST

    out: list[str] = []
    outer = plan.tile_q if plan.outer_is_q else plan.tile_k
    inner = plan.tile_k if plan.outer_is_q else plan.tile_q
    outer_n = plan.n_q_blocks if plan.outer_is_q else plan.n_k_blocks
    flags = plan.flags
    if len(flags) == 0:
        return [f"{label}: empty tile table [rule: tile-lifecycle]"]
    rows = 0
    for t in range(len(flags)):
        first = bool(flags[t] & _TF_FIRST)
        prev_last = t == 0 or bool(flags[t - 1] & _TF_LAST)
        if first != prev_last:
            out.append(
                f"{label}: table entry {t} breaks the FIRST/LAST bracketing "
                f"(accumulator would {'re-initialize mid-row' if first else 'carry across rows'}) "
                f"[rule: tile-lifecycle]"
            )
            break
        if first:
            rows += 1
        if not first and outer[t] != outer[t - 1]:
            out.append(
                f"{label}: entry {t} switches outer row {outer[t-1]}->"
                f"{outer[t]} without LAST/FIRST — the carried accumulator "
                f"would mix rows [rule: tile-lifecycle]"
            )
            break
        if not first and inner[t] <= inner[t - 1]:
            out.append(
                f"{label}: entry {t} revisits inner index {int(inner[t])} "
                f"after {int(inner[t-1])} in one outer row "
                f"[rule: tile-lifecycle]"
            )
            break
    if not out:
        if not (flags[-1] & _TF_LAST):
            out.append(
                f"{label}: final table entry lacks LAST — the last outer "
                f"row's output block is never written back "
                f"[rule: tile-lifecycle]"
            )
        elif rows != outer_n:
            out.append(
                f"{label}: table covers {rows} outer rows, grid has "
                f"{outer_n} — a missing row's output block is never "
                f"written [rule: tile-lifecycle]"
            )
    return out


def verify_plan(plan, instances: list[HopInstance], label: str) -> list[str]:
    """Hold one hop's band tables to the oracle across every rank that
    shares the compiled program.  Returns one-line violations."""
    from ..ops.pallas_flash import _TF_EDGE, _TF_WORK

    out: list[str] = []
    nq = plan.n_q_blocks * plan.block_q
    nk = plan.n_k_blocks * plan.block_k

    # closed form vs enumeration — the property every launch's SMEM-cap
    # decision rides on
    if plan.tiles != len(plan.tile_q):
        out.append(
            f"{label}: closed-form _band_tile_count says {plan.tiles} "
            f"tiles, enumerated table has {len(plan.tile_q)} "
            f"[rule: tile-count]"
        )
    out.extend(_check_table_structure(plan, label))

    work = {}
    for t in range(len(plan.flags)):
        if plan.flags[t] & _TF_WORK:
            work[(int(plan.tile_q[t]), int(plan.tile_k[t]))] = bool(
                plan.flags[t] & _TF_EDGE
            )

    active = [x for x in instances if x.has_work and not x.full]
    for x in instances:
        if not x.has_work:
            if x.oracle.any():
                qi, ki = np.argwhere(x.oracle)[0] // (plan.block_q,
                                                      plan.block_k)
                out.append(
                    f"{label}: rank {x.rank} hop-level skip drops live "
                    f"tile (q-tile {int(qi)}, k-tile {int(ki)}) "
                    f"[rule: tile-coverage-sound]"
                )
            continue
        if x.full:
            if not x.oracle.all():
                i, j = np.argwhere(~x.oracle)[0]
                out.append(
                    f"{label}: rank {x.rank} declared-full span holds a "
                    f"masked-out element at local ({int(i)}, {int(j)}) — "
                    f"it would enter the softmax unmasked "
                    f"[rule: tile-coverage-sound]"
                )
            continue
        rt = _instance_runtime(x, nq, nk)
        for qi in range(plan.n_q_blocks):
            for ki in range(plan.n_k_blocks):
                qs, ks = _tile_slices(plan, qi, ki)
                o_tile = x.oracle[qs, ks]
                if (qi, ki) not in work:
                    if o_tile.any():
                        out.append(
                            f"{label}: rank {x.rank} live tile (q-tile "
                            f"{qi}, k-tile {ki}) is absent from the band "
                            f"table — its keys never enter the softmax "
                            f"[rule: tile-coverage-sound]"
                        )
                    continue
                edge = work[(qi, ki)]
                if not edge:
                    if not x.static_live[qs, ks].all():
                        out.append(
                            f"{label}: rank {x.rank} interior tile "
                            f"(q-tile {qi}, k-tile {ki}) holds out-of-band "
                            f"elements but compiles the mask out "
                            f"[rule: tile-coverage-sound]"
                        )
                    continue
                computed = rt[qs, ks]
                if not np.array_equal(computed, o_tile):
                    kept_dead = computed & ~o_tile
                    kind = ("keeps a dead element" if kept_dead.any()
                            else "drops a live element")
                    i, j = np.argwhere(computed ^ o_tile)[0]
                    out.append(
                        f"{label}: rank {x.rank} edge tile (q-tile {qi}, "
                        f"k-tile {ki}) runtime mask {kind} at local "
                        f"({int(qi * plan.block_q + i)}, "
                        f"{int(ki * plan.block_k + j)}) "
                        f"[rule: tile-coverage-sound]"
                    )

    # tightness: aggregated across ranks (the table is one compiled
    # program shared by all of them)
    if active:
        for (qi, ki), edge in sorted(work.items()):
            qs, ks = _tile_slices(plan, qi, ki)
            if not any(x.static_live[qs, ks].any() for x in active):
                out.append(
                    f"{label}: dead tile (q-tile {qi}, k-tile {ki}) is "
                    f"visited — in the table but live for no rank "
                    f"[rule: tile-coverage-tight]"
                )
            elif edge and all(
                _instance_runtime(x, nq, nk)[qs, ks].all() for x in active
            ):
                out.append(
                    f"{label}: tile (q-tile {qi}, k-tile {ki}) is "
                    f"edge-classified but full-band for every rank — it "
                    f"pays the mask an interior tile would skip "
                    f"[rule: tile-coverage-tight]"
                )
    return out


# ---------------------------------------------------------------------------
# The strategy x layout x masking matrix
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CoverageCase:
    """One row of the prover matrix (ring=1 is the single-sweep path)."""

    name: str
    ring: int = 1
    n_local: int = 32
    block: int = 8
    layout: str = "contiguous"
    window: int | None = None
    passes: int | None = None
    doc_starts: tuple[int, ...] | None = None
    bidirectional: bool = False
    counter: bool = False


CASES: tuple[CoverageCase, ...] = (
    CoverageCase("single/causal", ring=1, n_local=64, block=8),
    CoverageCase("single/causal/window", ring=1, n_local=64, block=8,
                 window=24),
    CoverageCase("single/docs-aligned", ring=1, n_local=64, block=8,
                 doc_starts=(0, 16, 32)),
    CoverageCase("single/docs-aligned/window", ring=1, n_local=64, block=8,
                 window=16, doc_starts=(0, 32)),
    CoverageCase("single/docs-misaligned", ring=1, n_local=64, block=8,
                 doc_starts=(0, 12, 40)),
    CoverageCase("ring/contiguous", ring=4, n_local=16, block=4),
    CoverageCase("ring/contiguous/window", ring=4, n_local=16, block=4,
                 window=24),
    CoverageCase("ring/limited-passes", ring=4, n_local=16, block=4,
                 window=8, passes=2),
    CoverageCase("ring/striped", ring=4, n_local=16, block=4,
                 layout="striped"),
    CoverageCase("ring/striped/window", ring=4, n_local=16, block=4,
                 layout="striped", window=20),
    CoverageCase("ring/bidirectional", ring=4, n_local=16, block=4,
                 bidirectional=True),
    CoverageCase("ring/bidirectional/striped", ring=4, n_local=16, block=4,
                 layout="striped", bidirectional=True),
    CoverageCase("counter/contiguous", ring=4, n_local=16, block=4,
                 counter=True),
    CoverageCase("counter/striped", ring=4, n_local=16, block=4,
                 layout="striped", counter=True),
    CoverageCase("counter/window", ring=4, n_local=16, block=4, window=24,
                 counter=True),
)


def _int_or_none(x):
    return None if x is None else int(x)


def _case_hop_instances(case: CoverageCase):
    """Yield ``(hop_label, stream, hint, windowed, nk, instances)`` per
    (hop, stream) of a case — the runtime/static values straight from the
    ring layer (the system under test), the oracles from global
    positions (the independent truth)."""
    from ..parallel import ring as ring_mod

    W, n = case.ring, case.n_local
    passes = case.passes or W
    striped = case.layout == "striped"
    streams = ring_mod._streams(case.bidirectional and passes == W, n)
    # doc ids span the GLOBAL position space: ring rows index them with
    # positions up to n*W - 1 (a declared layout is global by definition)
    doc_ids = (_doc_of(case.doc_starts, n * W)
               if case.doc_starts is not None else None)
    for i in range(passes):
        if case.counter:
            stream = (1, 0, n)
            full, hint = ring_mod._counter_static_band(
                i, n, True, striped, case.window, W
            )
            instances = []
            for r in range(W):
                qo, ko = ring_mod._counter_origins(r, i, W)
                hi, lo = ring_mod._hop_offsets(
                    qo, ko, n, True, striped, case.window, W
                )
                instances.append(_make_instance(
                    case, r, int(qo), int(ko), _int_or_none(hi),
                    _int_or_none(lo), full, 0, n, doc_ids,
                ))
            yield f"hop{i}", stream, hint, case.window is not None, n, \
                instances
        else:
            for si, stream in enumerate(streams):
                shift, ofs, nk = stream
                full, hint = ring_mod._static_hop_band(
                    stream, i, n, True, striped, case.window, W
                )
                instances = []
                for r in range(W):
                    ko = (r - shift * i) % W
                    hi, lo = ring_mod._stream_offsets(
                        stream, r, i, n, True, striped, case.window, W
                    )
                    instances.append(_make_instance(
                        case, r, r, int(ko), _int_or_none(hi),
                        _int_or_none(lo), full, ofs, nk, doc_ids,
                    ))
                tag = f"hop{i}" + (f"/stream{si}" if len(streams) > 1 else "")
                yield tag, stream, hint, case.window is not None, nk, \
                    instances


def _make_instance(case, rank, q_origin, kv_origin, hi, lo, full, ofs, nk,
                   doc_ids):
    from ..parallel import ring as ring_mod

    W, n = case.ring, case.n_local
    qpos = _positions(case.layout, q_origin, n, W)
    kpos = _positions(case.layout, kv_origin, n, W)[ofs:ofs + nk]
    truth = oracle_mask(qpos, kpos, case.window, doc_ids)
    aligned = (case.doc_starts is not None
               and all(s % case.block == 0 for s in case.doc_starts))
    static_live = (truth if (case.doc_starts is None or aligned)
                   else oracle_mask(qpos, kpos, case.window, None))
    seg_mask = None
    if case.doc_starts is not None and not aligned:
        seg_mask = doc_ids[kpos][None, :] == doc_ids[qpos][:, None]
    has_work = bool(ring_mod._hop_has_work(hi, lo, n, nk))
    return HopInstance(
        rank=rank, q_origin=q_origin, kv_origin=kv_origin, oracle=truth,
        static_live=static_live, hi=None if full else hi,
        lo=None if full else lo, has_work=has_work, full=full,
        seg_mask=seg_mask, kpos=kpos,
    )


@dataclass
class CoverageReport:
    """One matrix row's verdict plus the tile accounting the fingerprint
    and the perf gate pin."""

    name: str
    violations: list[str] = field(default_factory=list)
    hops: int = 0
    tiles: int = 0  # q-major (fwd/dq) table entries summed over hops
    work: int = 0
    edge: int = 0
    tiles_kmajor: int = 0  # dk/dv-pass tables (same hints, k-major)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return {
            "name": self.name, "ok": self.ok, "hops": self.hops,
            "tiles": self.tiles, "work": self.work, "edge": self.edge,
            "tiles_kmajor": self.tiles_kmajor,
            "violations": self.violations,
        }


def prove_case(case: CoverageCase) -> CoverageReport:
    """Run the full proof for one matrix row: per-hop table checks on the
    q-major AND k-major (backward dk/dv) grids, plus the cross-hop
    schedule-completeness check."""
    from ..ops.pallas_flash import band_plan

    report = CoverageReport(name=case.name)
    W, n = case.ring, case.n_local
    if case.doc_starts is not None and W > 1:
        # doc_starts is the SINGLE-SWEEP declaration (the kernels accept
        # it on local spans only; rings carry documents as segment_ids),
        # so a ring x docs row has no realizable system under test —
        # reject it loudly rather than prove an inconsistent layout
        raise ValueError(
            f"{case.name}: doc_starts rows are single-device (ring=1); "
            f"ring document layouts are segment_ids territory"
        )
    # schedule completeness: per q-origin, count how often each (q, k)
    # global element is computed across the whole hop schedule
    counts = {o: np.zeros((n, n * W), np.int64) for o in range(W)}
    visited = {o: np.zeros(n * W, bool) for o in range(W)}
    doc_ids_g = None
    if case.doc_starts is not None:
        doc_ids_g = _doc_of(case.doc_starts, n * W)  # ring=1 for doc rows

    for tag, stream, hint, windowed, nk, instances in \
            _case_hop_instances(case):
        report.hops += 1
        label = f"{case.name}/{tag}"
        full_hop = instances and instances[0].full
        if full_hop:
            # a trace-time full span runs the plain rectangular grid with
            # NO mask and no tables; the only things to prove are that
            # every computing rank's span is fully live and every
            # skipped rank's span is fully dead
            for x in instances:
                if x.has_work and not x.oracle.all():
                    i, j = np.argwhere(~x.oracle)[0]
                    report.violations.append(
                        f"{label}: rank {x.rank} declared-full span holds "
                        f"a masked-out element at local ({int(i)}, "
                        f"{int(j)}) — it would enter the softmax unmasked "
                        f"[rule: tile-coverage-sound]"
                    )
                elif not x.has_work and x.oracle.any():
                    report.violations.append(
                        f"{label}: rank {x.rank} hop-level skip drops "
                        f"live work [rule: tile-coverage-sound]"
                    )
        elif hint is None:
            report.violations.append(
                f"{label}: causal hop produced no static band hint "
                f"[rule: tile-coverage-sound]"
            )
            continue
        else:
            plan = band_plan((n, nk), (case.block, case.block), hint,
                             windowed=windowed, doc_starts=case.doc_starts)
            report.tiles += len(plan.tile_q)
            report.work += plan.work_tiles
            report.edge += plan.edge_tiles
            report.violations.extend(verify_plan(plan, instances, label))
            # the backward dk/dv pass builds k-major tables from the same
            # hint — same oracle, transposed accumulator lifecycle
            plan_k = band_plan((n, nk), (case.block, case.block), hint,
                               windowed=windowed,
                               doc_starts=case.doc_starts,
                               outer_is_q=False)
            report.tiles_kmajor += len(plan_k.tile_q)
            report.violations.extend(
                verify_plan(plan_k, instances, label + "/dkv")
            )
        for x in instances:
            if x.has_work:
                visited[x.q_origin][x.kpos] = True
                counts[x.q_origin][:, x.kpos] += (
                    1 if x.full else x.oracle
                )

    # cross-hop: every intended element exactly once, nothing twice
    for o in range(W):
        qpos = _positions(case.layout, o, n, W)
        intended = oracle_mask(qpos, np.arange(n * W), case.window,
                               doc_ids_g)
        intended = intended & visited[o][None, :]
        if not np.array_equal(counts[o], intended.astype(np.int64)):
            diff = counts[o] - intended.astype(np.int64)
            i, j = np.argwhere(diff)[0]
            kind = ("dropped from" if diff[i, j] < 0
                    else "double-counted into")
            report.violations.append(
                f"{case.name}: schedule {kind} the softmax: q-origin {o} "
                f"element (local q {int(i)}, global k {int(j)}) computed "
                f"{int(counts[o][i, j])}x, intended "
                f"{int(intended[i, j])}x [rule: tile-coverage-sound]"
            )
    return report


# ---------------------------------------------------------------------------
# Zig-zag: the rectangular-grid row (traced offsets, no tables)
# ---------------------------------------------------------------------------


def prove_zigzag(ring: int = 4, chunk: int = 8, block: int = 8,
                 ) -> CoverageReport:
    """The zig-zag path uses traced per-chunk offsets on the RECTANGULAR
    grid (no band tables), so the system under test here is the runtime
    tile predicate set — ``_tile_has_work`` / ``_tile_is_edge`` /
    the band mask — against the same global-position oracle."""
    from ..ops import pallas_flash as pf

    report = CoverageReport(name="zigzag/causal")
    n_global = 2 * ring * chunk
    bq = min(block, chunk)
    bk = block
    while n_global % bk:
        bk //= 2
    for r in range(ring):
        for which, start in ((0, r * chunk), (1, (2 * ring - 1 - r) * chunk)):
            report.hops += 1
            qpos = start + np.arange(chunk)
            kpos = np.arange(n_global)
            truth = oracle_mask(qpos, kpos, None)
            offs = np.asarray([start, 0], np.int64)
            label = f"zigzag/rank{r}/chunk{which}"
            for qi in range(chunk // bq):
                for ki in range(n_global // bk):
                    row0, col0 = qi * bq, ki * bk
                    o_tile = truth[row0:row0 + bq, col0:col0 + bk]
                    has_work = bool(pf._tile_has_work(
                        offs, row0, col0, bq, bk, True, False
                    ))
                    edge = bool(pf._tile_is_edge(
                        offs, row0, col0, bq, bk, True, False
                    ))
                    report.tiles += 1
                    if not has_work:
                        if o_tile.any():
                            report.violations.append(
                                f"{label}: live tile (q-tile {qi}, k-tile "
                                f"{ki}) fails the runtime skip predicate "
                                f"[rule: tile-coverage-sound]"
                            )
                        continue
                    report.work += 1
                    if not edge:
                        if not o_tile.all():
                            report.violations.append(
                                f"{label}: interior-classified tile "
                                f"(q-tile {qi}, k-tile {ki}) holds dead "
                                f"elements but skips the mask "
                                f"[rule: tile-coverage-sound]"
                            )
                        continue
                    report.edge += 1
                    # the kernel's iota mask: cols + col0 <= rows + row0 + hi
                    diff = (np.arange(bk)[None, :] + col0) - (
                        np.arange(bq)[:, None] + row0
                    )
                    rt = diff <= start
                    if not np.array_equal(rt, o_tile):
                        report.violations.append(
                            f"{label}: edge tile (q-tile {qi}, k-tile {ki}) "
                            f"runtime band disagrees with the oracle "
                            f"[rule: tile-coverage-sound]"
                        )
                    if o_tile.all():
                        report.violations.append(
                            f"{label}: tile (q-tile {qi}, k-tile {ki}) "
                            f"edge-classified but fully live "
                            f"[rule: tile-coverage-tight]"
                        )
    return report


# ---------------------------------------------------------------------------
# Mask-algebra rows: arbitrary oracles through the certifying compiler
# ---------------------------------------------------------------------------
#
# PR 11 generalizes the fixed matrix above: ``ring_attention_tpu/masks.py``
# lowers arbitrary mask compositions to the same compact tile tables and
# per-hop work/skip schedules, and :func:`prove_mask_lowering` holds every
# emitted grid to the mask's own global-position oracle.  Band-shaped
# masks lower through the SHIPPING seams (band_plan + the ring hop-band
# helpers), so those rows re-certify the real kernels' grids through the
# mask API; generic masks (prefix-LM, dilated, per-head, Or/Not
# compositions) certify the algebra's tile classifier — the extension
# seam future kernels will launch from.


@dataclass(frozen=True)
class MaskCoverageCase:
    """One mask-algebra matrix row: a textual mask expression (through
    the registry parser, so the row also exercises the mini-language)
    over one execution geometry."""

    name: str
    expr: str
    strategy: str = "single"
    layout: str = "contiguous"
    ring: int = 1
    n_local: int = 64
    block: int = 8
    passes: int | None = None


MASK_CASES: tuple[MaskCoverageCase, ...] = (
    MaskCoverageCase("mask/single/full", "full"),
    MaskCoverageCase("mask/single/causal", "causal"),
    MaskCoverageCase("mask/single/causal-window", "causal&window:24"),
    MaskCoverageCase("mask/single/window-2sided", "window:16"),
    MaskCoverageCase("mask/single/prefixlm", "prefix:24"),
    MaskCoverageCase("mask/single/prefix-window", "prefix:16&window:24"),
    MaskCoverageCase("mask/single/dilated", "causal&dilated:4"),
    MaskCoverageCase("mask/single/docs-causal", "causal&docs:0,16,40"),
    MaskCoverageCase("mask/single/docs-misaligned", "causal&docs:0,12,40"),
    MaskCoverageCase("mask/single/prefix-or-docs", "prefix:16|docs:0,32"),
    MaskCoverageCase("mask/single/far-past", "causal&~window:8"),
    MaskCoverageCase("mask/single/perhead",
                     "perhead(causal;causal&window:16)"),
    MaskCoverageCase("mask/ring/causal", "causal", strategy="ring",
                     ring=4, n_local=16, block=4),
    MaskCoverageCase("mask/ring/causal-window", "causal&window:24",
                     strategy="ring", ring=4, n_local=16, block=4),
    MaskCoverageCase("mask/ring/striped-window", "causal&window:20",
                     strategy="ring", layout="striped", ring=4,
                     n_local=16, block=4),
    MaskCoverageCase("mask/ring/limited-passes", "causal&window:8",
                     strategy="ring", ring=4, n_local=16, block=4,
                     passes=2),
    MaskCoverageCase("mask/ring/prefixlm", "prefix:24", strategy="ring",
                     ring=4, n_local=16, block=4),
    MaskCoverageCase("mask/ring/dilated", "causal&dilated:2",
                     strategy="ring", ring=4, n_local=16, block=4),
    MaskCoverageCase("mask/counter/causal", "causal", strategy="counter",
                     ring=4, n_local=16, block=4),
    MaskCoverageCase("mask/counter/window", "causal&window:24",
                     strategy="counter", ring=4, n_local=16, block=4),
    MaskCoverageCase("mask/counter/prefixlm", "prefix:24",
                     strategy="counter", ring=4, n_local=16, block=4),
)


def _expected_pairings(spec, i: int) -> list[tuple[int, int, int]]:
    """``(rank, q_origin, kv_origin)`` rows of hop ``i`` — recomputed
    HERE from the schedule definitions (single sweep; ring: hop ``i``
    delivers origin ``rank - i``; counter-rotation: the Q stream has
    moved ``ceil(i/2)`` times and KV ``floor(i/2)``, pairing invariant
    ``q_origin - kv_origin ≡ i``), independently of the lowering's own
    origin bookkeeping, which is cross-checked against this."""
    if spec.strategy == "single":
        return [(0, 0, 0)]
    W = spec.ring
    if spec.strategy == "counter":
        return [
            (r, (r + (i + 1) // 2) % W, (r - i // 2) % W) for r in range(W)
        ]
    return [(r, r, (r - i) % W) for r in range(W)]


def prove_mask_lowering(mask, spec, lowering=None) -> CoverageReport:
    """Hold one mask lowering (``masks.lower(mask, spec)``) to the
    mask's own oracle: per-hop table soundness/tightness on the q-major
    AND k-major grids, hop-pairing agreement with the independently
    recomputed schedule, and cross-hop exactly-once completeness.

    ``lowering`` overrides the freshly-built one (the negative-toy seam:
    a doctored lowering must fail with a one-line diagnostic naming the
    mask, hop, and tile)."""
    from .. import masks as masks_mod

    mask = masks_mod.static_mask(mask)  # runtime Segments mask in-kernel
    m = mask.head_mask(spec.head) if mask.per_head else mask
    if lowering is None:
        lowering = masks_mod.lower(mask, spec)
    W, n = spec.ring, spec.n_local
    report = CoverageReport(name=f"{m.key}/{spec.strategy}")
    counts = {o: np.zeros((n, n * W), np.int64) for o in range(W)}
    visited = {o: np.zeros(n * W, bool) for o in range(W)}

    for hop in lowering.hops:
        report.hops += 1
        label = f"{m.key}/{spec.strategy}:{spec.layout}/hop{hop.hop}"
        expected = _expected_pairings(spec, hop.hop)
        if len(hop.ranks) != len(expected):
            report.violations.append(
                f"{label}: lowering schedules {len(hop.ranks)} ranks, "
                f"the {spec.strategy} hop has {len(expected)} "
                f"[rule: tile-coverage-sound]"
            )
            continue
        instances = []
        for rp, (r, qo, ko) in zip(hop.ranks, expected):
            if (rp.rank, rp.q_origin, rp.kv_origin) != (r, qo, ko):
                report.violations.append(
                    f"{label}: rank {r} pairing disagrees — lowering says "
                    f"q-origin {rp.q_origin} x kv-origin {rp.kv_origin}, "
                    f"the schedule pairs {qo} x {ko} "
                    f"[rule: tile-coverage-sound]"
                )
            qpos = _positions(spec.layout, qo, n, W)
            kpos = _positions(spec.layout, ko, n, W)
            truth = m.oracle(qpos, kpos)
            instances.append(HopInstance(
                rank=r, q_origin=qo, kv_origin=ko, oracle=truth,
                static_live=truth, hi=rp.hi, lo=rp.lo,
                has_work=rp.has_work, full=hop.full, kpos=kpos,
                rt_mask=rp.rt_mask,
            ))
        if hop.full:
            for x in instances:
                if x.has_work and not x.oracle.all():
                    i, j = np.argwhere(~x.oracle)[0]
                    report.violations.append(
                        f"{label}: rank {x.rank} declared-full span holds "
                        f"a masked-out element at local ({int(i)}, "
                        f"{int(j)}) — it would enter the softmax unmasked "
                        f"[rule: tile-coverage-sound]"
                    )
                elif not x.has_work and x.oracle.any():
                    report.violations.append(
                        f"{label}: rank {x.rank} hop-level skip drops "
                        f"live work [rule: tile-coverage-sound]"
                    )
        elif hop.plan is None or hop.plan_kmajor is None:
            report.violations.append(
                f"{label}: non-full hop lowered without tile tables "
                f"[rule: tile-coverage-sound]"
            )
            continue
        else:
            report.tiles += len(hop.plan.tile_q)
            report.work += hop.plan.work_tiles
            report.edge += hop.plan.edge_tiles
            report.violations.extend(verify_plan(hop.plan, instances,
                                                 label))
            report.tiles_kmajor += len(hop.plan_kmajor.tile_q)
            report.violations.extend(
                verify_plan(hop.plan_kmajor, instances, label + "/dkv")
            )
        for x in instances:
            if x.has_work:
                visited[x.q_origin][x.kpos] = True
                counts[x.q_origin][:, x.kpos] += (
                    1 if x.full else x.oracle
                )

    for o in range(W):
        qpos = _positions(spec.layout, o, n, W)
        intended = m.oracle(qpos, np.arange(n * W))
        intended = intended & visited[o][None, :]
        if not np.array_equal(counts[o], intended.astype(np.int64)):
            diff = counts[o] - intended.astype(np.int64)
            i, j = np.argwhere(diff)[0]
            kind = ("dropped from" if diff[i, j] < 0
                    else "double-counted into")
            report.violations.append(
                f"{m.key}/{spec.strategy}: schedule {kind} the softmax: "
                f"q-origin {o} element (local q {int(i)}, global k "
                f"{int(j)}) computed {int(counts[o][i, j])}x, intended "
                f"{int(intended[i, j])}x [rule: tile-coverage-sound]"
            )
    return report


def prove_mask_case(case: MaskCoverageCase) -> CoverageReport:
    """One mask-algebra matrix row: parse the expression, lower it onto
    the case's geometry, and prove every head variant's grids."""
    from ..masks import GridSpec, parse_mask

    mask = parse_mask(case.expr)
    heads = mask.head_period
    report = CoverageReport(name=case.name)
    for h in range(heads):
        spec = GridSpec(
            strategy=case.strategy, layout=case.layout, ring=case.ring,
            n_local=case.n_local, block_q=case.block,
            block_k=case.block, passes=case.passes, head=h,
        )
        part = prove_mask_lowering(mask, spec)
        report.violations.extend(part.violations)
        report.hops += part.hops
        report.tiles += part.tiles
        report.work += part.work
        report.edge += part.edge
        report.tiles_kmajor += part.tiles_kmajor
    return report


# ---------------------------------------------------------------------------
# Fused ring: the single-launch grid (ops/pallas_ring.py) held to the
# same global-position oracle
# ---------------------------------------------------------------------------

FUSED_CASES: tuple[CoverageCase, ...] = (
    CoverageCase("fused/contiguous", ring=4, n_local=16, block=4),
    CoverageCase("fused/contiguous/window", ring=4, n_local=16, block=4,
                 window=24),
    CoverageCase("fused/limited-passes", ring=4, n_local=16, block=4,
                 window=8, passes=2),
    CoverageCase("fused/striped", ring=4, n_local=16, block=4,
                 layout="striped"),
    CoverageCase("fused/striped/window", ring=4, n_local=16, block=4,
                 layout="striped", window=20),
)


def prove_fused_case(case: CoverageCase) -> CoverageReport:
    """Prove one fused-ring row: the prefetched hop tables
    (``parallel/ring.py::_fused_tables``) and the kernel's per-tile live
    predicate (``ops/pallas_ring.py::_fused_local_kernel``) against the
    global-position oracle.

    The fused kernel has no interior/edge split — every live tile applies
    the band mask in-kernel — so the obligations are: (a) the per-hop
    band exactly realizes the oracle for that (rank, origin) pairing
    (``work=0`` hops must be all-dead); (b) a tile the live predicate
    skips holds no live element and a tile it visits holds at least one;
    (c) summing live elements across the in-launch hop schedule
    reproduces the intended global mask exactly once per element."""
    from ..parallel import ring as ring_mod

    report = CoverageReport(name=case.name)
    W, n, blk = case.ring, case.n_local, case.block
    passes = case.passes or W
    striped = case.layout == "striped"
    for rank in range(W):
        origins, his, los, works = (
            np.asarray(t) for t in ring_mod._fused_tables(
                rank, passes, n, True, striped, case.window, W
            )
        )
        qpos = _positions(case.layout, rank, n, W)
        counts = np.zeros((n, n * W), np.int64)
        visited = np.zeros(n * W, bool)
        for hop in range(passes):
            report.hops += 1
            label = f"{case.name}/rank{rank}/hop{hop}"
            origin, work = int(origins[hop]), bool(works[hop])
            hi, lo = int(his[hop]), int(los[hop])
            if origin != (rank - hop) % W:
                report.violations.append(
                    f"{label}: table origin {origin}, the in-launch KV "
                    f"stream delivers {(rank - hop) % W} at this hop "
                    f"[rule: tile-coverage-sound]"
                )
                continue
            kpos = _positions(case.layout, origin, n, W)
            truth = oracle_mask(qpos, kpos, case.window)
            # (a) the runtime band IS the oracle for this pairing —
            # the sentinel offsets (hi=n, lo=-n) are vacuous over the
            # in-block diff range, so band_mask takes them unconditionally
            rt = (band_mask(n, n, hi, lo) if work
                  else np.zeros((n, n), bool))
            if not np.array_equal(rt, truth):
                i, j = np.argwhere(rt ^ truth)[0]
                kind = ("drops live" if truth[i, j] else "admits dead")
                report.violations.append(
                    f"{label}: band (hi={hi}, lo={lo}, work={int(work)}) "
                    f"{kind} element at local ({int(i)}, {int(j)}) "
                    f"[rule: tile-coverage-sound]"
                )
                continue
            # (b) the kernel's per-tile live predicate, verbatim
            for qi in range(n // blk):
                for kb in range(n // blk):
                    row0, col0 = qi * blk, kb * blk
                    live = (work
                            and col0 <= row0 + blk - 1 + hi
                            and col0 + blk - 1 >= row0 + lo)
                    report.tiles += 1
                    t = truth[row0:row0 + blk, col0:col0 + blk]
                    if live:
                        report.work += 1
                        if not t.any():
                            report.violations.append(
                                f"{label}: live predicate visits dead "
                                f"tile (q {qi}, k {kb}) "
                                f"[rule: tile-coverage-tight]"
                            )
                    elif t.any():
                        report.violations.append(
                            f"{label}: live predicate skips tile "
                            f"(q {qi}, k {kb}) holding live work "
                            f"[rule: tile-coverage-sound]"
                        )
            if work:
                visited[kpos] = True
                counts[:, kpos] += truth
        # (c) exactly-once across the in-launch schedule
        intended = oracle_mask(qpos, np.arange(n * W), case.window)
        intended = intended & visited[None, :]
        if not np.array_equal(counts, intended.astype(np.int64)):
            diff = counts - intended.astype(np.int64)
            i, j = np.argwhere(diff)[0]
            kind = ("dropped from" if diff[i, j] < 0
                    else "double-counted into")
            report.violations.append(
                f"{case.name}: fused schedule {kind} the softmax: rank "
                f"{rank} element (local q {int(i)}, global k {int(j)}) "
                f"computed {int(counts[i, j])}x, intended "
                f"{int(intended[i, j])}x [rule: tile-coverage-sound]"
            )
    return report


def prove_fused() -> list[CoverageReport]:
    """All fused-ring rows (the coverage half of the fused acceptance)."""
    return [prove_fused_case(case) for case in FUSED_CASES]


# ---------------------------------------------------------------------------
# Suite + fingerprint
# ---------------------------------------------------------------------------


def run_coverage_suite() -> list[CoverageReport]:
    """Every matrix row — the fixed strategy x layout x masking rows,
    the zig-zag rectangular-grid row, the mask-algebra rows, and the
    fused-ring single-launch grid.  All-ok == every grid the compiler
    emits is proven sound and tight."""
    reports = [prove_case(case) for case in CASES]
    reports.append(prove_zigzag())
    reports.extend(prove_mask_case(case) for case in MASK_CASES)
    reports.extend(prove_fused())
    return reports


def coverage_fingerprint() -> dict:
    """Exact per-row tile accounting for bench phase 0 and the perf
    gate: a future mask/hint change that grows (dead tiles visited) or
    shrinks (live tiles at risk) any row's table fails the gate next to
    the PR-5 collective fingerprint."""
    fp: dict = {}
    ok = True
    for report in run_coverage_suite():
        fp[report.name] = {
            "tiles": report.tiles,
            "work": report.work,
            "edge": report.edge,
            "tiles_kmajor": report.tiles_kmajor,
        }
        ok = ok and report.ok
    fp["coverage_ok"] = ok
    return fp
