"""Repo-native AST lint: the rules this codebase keeps re-learning by hand.

Every rule here encodes a failure mode that actually bit (or nearly bit) a
past PR, with the shim/convention that prevents it:

  RA001  ``jax.experimental.shard_map`` / ``jax.shard_map`` used directly —
         must go through ``utils/compat.shard_map``.  The jax-0.4.x
         container has neither ``check_vma`` nor a replication checker that
         understands ``checkpoint_name`` residuals; a direct call crashes
         the whole suite there (see ``utils/compat.py``).
  RA002  ``jax.jit`` used directly in library code — must go through
         ``utils/compat.jit``, which degrades ``donate_argnums`` gracefully
         on jax builds that reject it (and keeps the door open for
         package-wide jit policy).
  RA003  ``pl.pallas_call`` without ``name=`` — unnamed kernels show up in
         XProf as ``custom-call`` soup; every launch must carry its stable
         trace name (docs/observability.md).
  RA004  collective (``ppermute`` / ``all_to_all`` / ``all_gather`` /
         ``psum`` / ``pmax`` / ``pmin`` / ``psum_scatter``) issued outside a
         ``jax.named_scope`` block — unattributable communication time in
         traces.
  RA005  host-side entropy (``time.time`` / ``random.*`` / ``np.random.*``)
         in traced-code subpackages (``ops/``, ``parallel/``, ``models/``) —
         a host clock or RNG read inside a traced function is baked in at
         trace time and silently constant across steps (``jax.random`` is
         fine: it is traced).
  RA006  ``print`` in library code — library output goes through
         ``warnings`` / telemetry, never stdout.
  RA007  public attention entry point (module-level ``def f(q, k, v, ...)``)
         that never calls ``utils/validate.check_attention_args`` — layout
         bugs then surface as einsum errors deep in the kernels instead of
         a one-line ValueError at the API boundary.
  RA008  ``Telemetry.observe`` in library code outside a ``with
         ...collecting()`` block, or with a metric name lacking a unit
         suffix (``_bytes``/``_sec``/``_count``/``_frac``).  ``observe``
         only lands when a collector is active AT THE SAME TRACE LEVEL —
         a library-level call outside any ``collecting()`` silently drops
         every scalar it claims to record; and an unsuffixed name
         ("kv_hop") reads as whatever unit the dashboard author guesses.
  RA009  host ``np.`` / ``numpy.`` calls in traced-code subpackages.  A
         numpy function applied to a traced value either raises a
         TracerArrayConversionError deep in the call or silently
         constant-folds at trace time (the jaxpr then carries a baked-in
         literal — visible to ``analysis/dataflow.py``'s walker as a
         constant where an operation should be); a numpy call on
         genuinely static trace-time data (device topology, tile tables)
         is legitimate and carries a reasoned allow.  ``np.random.*``
         stays RA005's.
  RA010  Pallas grid tables or hop skip-predicates constructed outside
         the ``band_plan()`` / mask-algebra seam.  Calling the private
         table/offset/skip constructors (``_band_tables`` /
         ``_band_tile_count`` / ``_hop_offsets`` / ``_stream_offsets`` /
         ``_static_hop_band`` / ``_counter_static_band`` /
         ``_hop_has_work`` / ``_tile_has_work`` / ``_tile_is_edge``)
         from outside their home modules (``ops/pallas_flash.py``,
         ``parallel/ring.py``), ``masks.py`` (the algebra's lowering),
         or ``analysis/`` (the certifier) builds a skip grid the
         coverage prover never sees — the exact bypass that would dodge
         certification.  New grids go through ``band_plan()`` or the
         mask algebra, which certify; anything else carries a reasoned
         allow.
  RA011  signal/process-kill primitives (``signal.signal`` /
         ``signal.setitimer`` / ``os.kill`` / ``os.killpg`` /
         ``os._exit``) outside the elastic runtime (``elastic/``) or
         ``utils/resilience.py``.  Preemption semantics — drain the
         in-flight step, save, dump the incident, THEN exit — live in
         ``elastic.PreemptionGuard``; an ad-hoc ``signal.signal``
         elsewhere silently replaces the guard's handler and a stray
         ``os.kill``/``os._exit`` bypasses the drain entirely (the
         chaos harness's hard-death points are the ONE sanctioned
         user).  Legitimate uses elsewhere (liveness probes) carry a
         reasoned allow.
  RA012  raw int8 quant/dequant arithmetic outside the ``ops/quant.py``
         seam — any arithmetic use of the int8 full-scale constant 127
         (the absmax divide, the round-and-clip scale, the dequant
         multiply) in library code.  Three call sites grew three copies
         of this codec across PRs (decode cache, hop payload, kernel
         compute); PR 13 collapsed them into ``ops/quant.py`` and this
         rule keeps a fourth from forking the convention — a codec with
         a subtly different scale or clip silently breaks payload
         interchangeability and the precision auditor's dequant model.
         Quantize through the seam; a genuinely unrelated 127 carries a
         reasoned allow.

  RA013  remote-DMA / semaphore primitives (``make_async_remote_copy`` /
         ``make_async_copy`` / ``semaphore_signal`` / ``semaphore_wait`` /
         ``get_barrier_semaphore`` / ``SemaphoreType``) outside the fused
         ring kernel module (``ops/pallas_ring.py``).  The fused ring's
         correctness rests on ONE signal/wait protocol — the send-grant
         barrier and per-slot DMA semaphores that
         ``analysis/contracts.py::check_fused_ring_contract`` pins by
         exact count from the lowered module.  A second module issuing
         raw semaphore ops can deadlock the ring (an unmatched signal
         leaves a neighbor waiting forever) and silently invalidates the
         counted contract; new in-kernel communication goes through the
         fused module's seam, anything else carries a reasoned allow.

  RA014  raw host clocks (``time.time`` / ``time.monotonic`` /
         ``time.perf_counter`` / ...) in the observability-instrumented
         subpackages (``elastic/``, ``utils/``) outside the timestamp
         seam (``utils/tracing.py``).  Every emitted timestamp must
         route through the seam's ``monotonic_wall()``/``monotonic()``/
         ``perf_counter()`` helpers so the cluster-timeline merger can
         correct clocks consistently; a module stamping rows with its
         own ``time.*`` call produces offsets the merger never sees.
         Deadline arithmetic and filesystem-mtime comparisons carry a
         reasoned allow.

  RA015  remote-DMA / semaphore primitive call site inside the fused
         kernel module that no declared ``PROTOCOL`` row covers.  RA013
         fences the primitives to ``ops/pallas_ring.py``; RA015 tightens
         that file fence to a verified-seam fence: every
         ``make_async_*copy`` / ``semaphore_*`` / ``get_barrier_semaphore``
         call must live inside a function named by a ``PROTOCOL`` row's
         ``fn`` field, because ``analysis/schedverify.py`` model-checks
         exactly the declared rows (races, deadlock, semaphore drain) and
         cross-checks them site-by-site against the traced kernel.  A
         primitive issued from an undeclared function is protocol the
         model never saw — the exact blind spot PR 18's review bugs hid
         in.  Declare the row (and re-run the verifier) or carry a
         reasoned allow.  The table must stay a literal assignment
         (``PROTOCOL = (...)``): if it cannot be parsed from the AST,
         every site is flagged.

Silencing: append ``# ra: allow(RA00X reason...)`` to the flagged line
(for RA007, the ``def`` line).  The reason is mandatory — a bare allow is
itself a violation.  See docs/static_analysis.md.

Stdlib-only on purpose: on a box where jax itself cannot import, run this
module as a plain script (``python ring_attention_tpu/analysis/lint.py``)
— the ``-m`` form imports the package ``__init__`` chain, which needs jax.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

# Subpackages whose modules are traced code end-to-end (RA005 scope).
TRACED_SUBPACKAGES = ("ops", "parallel", "models")

# The shim module: the one place allowed to touch the raw APIs.
SHIM_MODULE = "utils/compat.py"

COLLECTIVE_CALLS = {
    "ppermute",
    "all_to_all",
    "all_gather",
    "all_gather_invariant",
    "psum",
    "pmax",
    "pmin",
    "psum_scatter",
    "pshuffle",
}

HOST_TIME_ATTRS = {"time", "time_ns", "perf_counter", "monotonic", "process_time"}

# RA010: the private grid-table / hop-skip constructors, and the modules
# that ARE the seam (their homes, the mask algebra's lowering, and the
# analysis passes that certify them).
GRID_SEAM_CALLS = {
    "_band_tables",
    "_band_tile_count",
    "_hop_offsets",
    "_stream_offsets",
    "_static_hop_band",
    "_counter_static_band",
    "_hop_has_work",
    "_tile_has_work",
    "_tile_is_edge",
}
GRID_SEAM_MODULES = (
    "ops/pallas_flash.py",
    "parallel/ring.py",
    "ring_attention_tpu/masks.py",
    "analysis/",
)

# RA008: metric-name unit suffixes (docs/observability.md glossary)
METRIC_UNIT_SUFFIXES = ("_bytes", "_sec", "_count", "_frac")

# RA011: signal-handling / process-kill primitives, and the modules that
# own preemption semantics (the elastic runtime + the resilience layer).
SIGNAL_CALLS = {
    "signal.signal",
    "signal.setitimer",
    "os.kill",
    "os.killpg",
    "os._exit",
}
SIGNAL_MODULES = (
    "ring_attention_tpu/elastic/",
    "utils/resilience.py",
)

# RA013: the remote-DMA / semaphore primitive surface, and the one module
# (the fused ring kernel) allowed to issue it — its signal/wait protocol
# is pinned by exact count in analysis/contracts.py.
REMOTE_DMA_CALLS = {
    "make_async_remote_copy",
    "make_async_copy",
    "semaphore_signal",
    "semaphore_wait",
    "get_barrier_semaphore",
}
FUSED_KERNEL_MODULE = "ops/pallas_ring.py"

# RA015: the declared-protocol seam inside the fused module — the literal
# table whose rows name (via their "fn" field) the only functions allowed
# to issue REMOTE_DMA_CALLS; analysis/schedverify.py model-checks exactly
# those rows.
PROTOCOL_TABLE_NAME = "PROTOCOL"


def _protocol_fns(tree: ast.Module) -> frozenset[str]:
    """Function names declared by the module's literal ``PROTOCOL`` table
    (empty when the assignment is missing or not a pure literal — which
    flags every primitive site, keeping the table honest)."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == PROTOCOL_TABLE_NAME
                   for t in node.targets):
            continue
        try:
            rows = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            return frozenset()
        return frozenset(
            row["fn"] for row in rows
            if isinstance(row, dict) and isinstance(row.get("fn"), str)
        )
    return frozenset()

# RA012: the one module allowed to spell the int8 full-scale constant in
# arithmetic (every quant/dequant codec lives there).
QUANT_SEAM_MODULE = "ops/quant.py"
INT8_FULL_SCALE = 127  # ra: allow(RA012 the rule's own definition of the constant)

# RA014: subpackages whose host-side timestamps must route through the
# tracing seam (the merger's clock-offset correction needs ONE source of
# wall/monotonic pairs), and the seam module itself.
TIMESTAMP_SCOPES = (
    "ring_attention_tpu/elastic/",
    "ring_attention_tpu/utils/",
)
TIMESTAMP_SEAM_MODULE = "utils/tracing.py"

_ALLOW_RE = re.compile(r"#\s*ra:\s*allow\(\s*(RA\d{3})\b([^)]*)\)")


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:  # the one-line diagnostic format
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute chain (``jax.experimental.shard_map``)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _allowed(source_lines: list[str], lineno: int, rule: str) -> tuple[bool, bool]:
    """(allowed, bare) — whether the line carries an ``# ra: allow`` pragma
    for ``rule``, and whether the pragma is missing its reason."""
    if 1 <= lineno <= len(source_lines):
        m = _ALLOW_RE.search(source_lines[lineno - 1])
        if m and m.group(1) == rule:
            return True, not m.group(2).strip()
    return False, False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.lines = source.splitlines()
        self.violations: list[Violation] = []
        self.scope_depth = 0  # nesting inside `with jax.named_scope(...)`
        self.collecting_depth = 0  # nesting inside `with ....collecting()`
        self.is_shim = rel.replace("\\", "/").endswith(SHIM_MODULE)
        self.in_grid_seam = any(
            m in rel.replace("\\", "/") for m in GRID_SEAM_MODULES
        )
        self.in_signal_scope = any(
            m in rel.replace("\\", "/") for m in SIGNAL_MODULES
        )
        self.in_quant_seam = rel.replace("\\", "/").endswith(QUANT_SEAM_MODULE)
        self.in_fused_seam = rel.replace("\\", "/").endswith(
            FUSED_KERNEL_MODULE
        )
        self.fn_stack: list[str] = []  # enclosing FunctionDef names (RA015)
        self.protocol_fns: frozenset[str] = frozenset()
        self.traced_pkg = any(
            rel.replace("\\", "/").startswith(f"ring_attention_tpu/{p}/")
            or f"/{p}/" in rel.replace("\\", "/")
            for p in TRACED_SUBPACKAGES
        )
        self.in_time_scope = any(
            m in rel.replace("\\", "/") for m in TIMESTAMP_SCOPES
        ) and not rel.replace("\\", "/").endswith(TIMESTAMP_SEAM_MODULE)

    def flag(self, node: ast.AST, rule: str, message: str) -> None:
        lineno = getattr(node, "lineno", 1)
        allowed, bare = _allowed(self.lines, lineno, rule)
        if allowed and not bare:
            return
        if allowed and bare:
            message = f"bare '# ra: allow({rule})' — a reason is mandatory"
        self.violations.append(Violation(self.rel, lineno, rule, message))

    # -- RA001 / RA002: shim bypass -----------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.startswith("jax.experimental.shard_map") and not self.is_shim:
                self.flag(node, "RA001",
                          "import of jax.experimental.shard_map bypasses "
                          "utils/compat.shard_map (breaks on jax 0.4.x)")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if not self.is_shim:
            if mod.startswith("jax.experimental.shard_map") or (
                mod == "jax.experimental"
                and any(a.name == "shard_map" for a in node.names)
            ):
                self.flag(node, "RA001",
                          "import of jax.experimental.shard_map bypasses "
                          "utils/compat.shard_map (breaks on jax 0.4.x)")
            if mod == "jax" and any(a.name == "jit" for a in node.names):
                self.flag(node, "RA002",
                          "'from jax import jit' bypasses utils/compat.jit")
            if mod == "jax" and any(a.name == "shard_map" for a in node.names):
                self.flag(node, "RA001",
                          "'from jax import shard_map' bypasses "
                          "utils/compat.shard_map")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if not self.is_shim:
            chain = _attr_chain(node)
            if chain in ("jax.shard_map", "jax.experimental.shard_map",
                         "jax.experimental.shard_map.shard_map"):
                self.flag(node, "RA001",
                          f"{chain} bypasses utils/compat.shard_map "
                          "(breaks on jax 0.4.x)")
                return  # don't re-flag the chain's own sub-attributes
            if chain == "jax.jit":
                self.flag(node, "RA002",
                          "jax.jit bypasses utils/compat.jit "
                          "(donation degradation, package jit policy)")
        if (not self.in_fused_seam
                and "SemaphoreType" in _attr_chain(node).split(".")):
            self.flag(node, "RA013",
                      "SemaphoreType outside ops/pallas_ring.py — semaphore "
                      "scratch allocation belongs to the fused ring's "
                      "counted signal/wait protocol (contracts.py pins it)")
            return  # don't re-flag the chain's own sub-attributes
        self.generic_visit(node)

    # -- RA003..RA007: calls ------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else ""
        )

        if name == "pallas_call":
            if not any(kw.arg == "name" for kw in node.keywords):
                self.flag(node, "RA003",
                          "pl.pallas_call without name= — kernel is "
                          "unattributable in XProf traces")

        if name in GRID_SEAM_CALLS and not self.in_grid_seam:
            self.flag(node, "RA010",
                      f"grid/skip constructor {name}() outside the "
                      "band_plan()/mask-algebra seam — this skip grid "
                      "dodges the coverage certifier; lower through "
                      "band_plan() or ring_attention_tpu.masks")

        if isinstance(func, ast.Attribute) and not self.in_signal_scope:
            sig_chain = _attr_chain(func)
            if sig_chain in SIGNAL_CALLS:
                self.flag(node, "RA011",
                          f"{sig_chain}() outside elastic//resilience.py — "
                          "preemption semantics (drain, save, incident "
                          "dump) live in elastic.PreemptionGuard/chaos; "
                          "an ad-hoc handler or kill bypasses the drain")

        if name in REMOTE_DMA_CALLS and not self.in_fused_seam:
            self.flag(node, "RA013",
                      f"remote-DMA/semaphore primitive {name}() outside "
                      "ops/pallas_ring.py — the fused ring owns the one "
                      "counted signal/wait protocol (contracts.py pins "
                      "it); a stray semaphore op can deadlock the ring")
        elif (name in REMOTE_DMA_CALLS and self.in_fused_seam
                and not any(f in self.protocol_fns for f in self.fn_stack)):
            self.flag(node, "RA015",
                      f"remote-DMA/semaphore primitive {name}() outside a "
                      "declared PROTOCOL row — schedverify model-checks "
                      "only the rows' fn seams (races/deadlock/drain); "
                      "declare the row and re-run the verifier, or allow "
                      "with a reason")

        if name in COLLECTIVE_CALLS and self.scope_depth == 0:
            self.flag(node, "RA004",
                      f"collective lax.{name} outside jax.named_scope — "
                      "communication time unattributable in traces")

        if self.traced_pkg:
            chain = _attr_chain(func) if isinstance(func, ast.Attribute) else ""
            if chain.startswith(("time.",)) and name in HOST_TIME_ATTRS:
                self.flag(node, "RA005",
                          f"host clock {chain}() in traced code — constant "
                          "after trace; pass times in as arguments")
            elif chain.startswith(("random.", "np.random.", "numpy.random.")):
                self.flag(node, "RA005",
                          f"host RNG {chain}() in traced code — constant "
                          "after trace; use jax.random with an explicit key")
            elif chain.startswith(("np.", "numpy.")):
                self.flag(node, "RA009",
                          f"host numpy {chain}() in traced code — on a "
                          "traced value this raises or silently constant-"
                          "folds at trace time; use jnp, or allow with a "
                          "reason for provably static trace-time data")

        if self.in_time_scope and isinstance(func, ast.Attribute):
            chain = _attr_chain(func)
            if chain.startswith("time.") and name in HOST_TIME_ATTRS:
                self.flag(node, "RA014",
                          f"raw host clock {chain}() outside the "
                          "utils/tracing.py timestamp seam — emitted "
                          "timestamps must come from the seam's helpers "
                          "(monotonic_wall/monotonic/perf_counter) so "
                          "the cluster-timeline merger's clock-offset "
                          "correction covers them; deadline arithmetic "
                          "or mtime comparisons carry a reasoned allow")

        if (name == "print" and isinstance(func, ast.Name)
                and not self.rel.endswith("__main__.py")):  # __main__ IS a CLI
            self.flag(node, "RA006",
                      "print() in library code — use warnings or telemetry")

        if (name == "observe" and isinstance(func, ast.Attribute)
                and not self.rel.replace("\\", "/").endswith(
                    "utils/telemetry.py")):  # the registry's own module
            if self.collecting_depth == 0:
                self.flag(node, "RA008",
                          "Telemetry.observe outside a collecting() block — "
                          "observations only land when a collector is "
                          "active at the same trace level; this scalar "
                          "silently drops")
            metric = node.args[0] if node.args else None
            if (isinstance(metric, ast.Constant)
                    and isinstance(metric.value, str)
                    and not metric.value.endswith(METRIC_UNIT_SUFFIXES)):
                self.flag(node, "RA008",
                          f"metric name {metric.value!r} lacks a unit "
                          f"suffix ({'/'.join(METRIC_UNIT_SUFFIXES)}) — "
                          "an unitless series reads as whatever the "
                          "dashboard author guesses")

        self.generic_visit(node)

    # -- RA012: int8 quant arithmetic outside the seam ------------------
    def visit_Constant(self, node: ast.Constant) -> None:
        if (not self.in_quant_seam
                and isinstance(node.value, (int, float))
                and not isinstance(node.value, bool)
                and abs(node.value) == INT8_FULL_SCALE):
            self.flag(node, "RA012",
                      "int8 full-scale constant 127 outside ops/quant.py — "
                      "raw quant/dequant arithmetic forks the codec seam; "
                      "quantize through ops.quant (or allow with a reason "
                      "if this 127 is unrelated to quantization)")
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        def _ctx_is(call_name: str) -> bool:
            return any(
                isinstance(item.context_expr, ast.Call)
                and (
                    (isinstance(item.context_expr.func, ast.Attribute)
                     and item.context_expr.func.attr == call_name)
                    or (isinstance(item.context_expr.func, ast.Name)
                        and item.context_expr.func.id == call_name)
                )
                for item in node.items
            )

        named = _ctx_is("named_scope")
        collecting = _ctx_is("collecting")
        if named:
            self.scope_depth += 1
        if collecting:
            self.collecting_depth += 1
        self.generic_visit(node)
        if named:
            self.scope_depth -= 1
        if collecting:
            self.collecting_depth -= 1

    # -- RA007: entry points must validate ----------------------------
    def _check_entry_point(self, node: ast.FunctionDef) -> None:
        if node.name.startswith("_"):
            return
        first3 = [a.arg for a in node.args.args[:3]]
        if first3 != ["q", "k", "v"]:
            return
        validates = any(
            isinstance(n, ast.Call)
            and (
                (isinstance(n.func, ast.Name)
                 and n.func.id == "check_attention_args")
                or (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "check_attention_args")
            )
            for n in ast.walk(node)
        )
        if not validates:
            self.flag(node, "RA007",
                      f"public entry point {node.name}(q, k, v, ...) never "
                      "calls utils/validate.check_attention_args — layout "
                      "bugs will surface deep in the kernels instead")

    def visit_Module(self, node: ast.Module) -> None:
        if self.in_fused_seam:
            self.protocol_fns = _protocol_fns(node)
        for child in node.body:
            if isinstance(child, ast.FunctionDef):
                self._check_entry_point(child)
        self.generic_visit(node)

    # -- RA015: enclosing-function tracking ----------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def lint_source(source: str, rel: str, path: str = "") -> list[Violation]:
    """Lint one module's source text; returns violations (possibly empty)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:  # a file that cannot parse is its own finding
        return [Violation(rel, e.lineno or 1, "RA000", f"syntax error: {e.msg}")]
    linter = _Linter(path or rel, rel, source)
    linter.visit(tree)
    return sorted(linter.violations, key=lambda v: (v.path, v.line, v.rule))


def lint_file(path: str | Path, root: str | Path | None = None) -> list[Violation]:
    path = Path(path)
    rel = str(path.relative_to(root)) if root else str(path)
    return lint_source(path.read_text(), rel, str(path))


def lint_package(root: str | Path | None = None) -> list[Violation]:
    """Lint every module under ``ring_attention_tpu/`` (the library scope:
    tools/, examples/, bench.py and tests/ are host-side and exempt)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    pkg = root / "ring_attention_tpu"
    out: list[Violation] = []
    for path in sorted(pkg.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        out.extend(lint_file(path, root))
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="ring-attention-tpu repo-native lint (rules RA001-RA015)"
    )
    parser.add_argument("paths", nargs="*",
                        help="files to lint (default: the whole package)")
    args = parser.parse_args(argv)
    if args.paths:
        violations = []
        for p in args.paths:
            violations.extend(lint_file(p))
    else:
        violations = lint_package()
    for v in violations:
        print(str(v))  # ra: allow(RA006 the lint CLI's own report output)
    if violations:
        print(f"{len(violations)} violation(s)")  # ra: allow(RA006 CLI output)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
