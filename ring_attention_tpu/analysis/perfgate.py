"""Benchmark-history regression gate: the perf trajectory as a CHECK.

ROADMAP item 5's measurement half: the repo accumulates perf evidence in
three places — ``BENCH_r*.json`` round files, ``docs/hwlogs/
results.jsonl`` hardware rows, and the committed ``docs/
perf_baseline.json`` CPU-signal baseline — and until now nothing read
them back.  This module ingests all three into one schema'd history and
gates on it, in the IO-accounting spirit of FlashAttention (arXiv
2205.14135): measure the hardware-facing quantities (collective counts,
bytes per hop, compiled peak scratch, tokens/sec) and fail loudly when
one regresses, instead of trusting the narrative.

Wedge-honest policy: the TPU probe has been wedged in 4 of 5 bench
rounds (docs/hardware_log.md), so the gate's PRIMARY signals are the
CPU-computable ones that land even on wedged rounds — the
``collective_fingerprint`` (compiled HLO collective counts per
strategy), the analytic hop/byte accounting, ``compiled_cost`` FLOPs /
bytes, ``compiled_memory`` peak temp bytes, and the retrace-sentinel
compile count.  Hardware tokens/sec is checked only between rounds where
the probe actually ran; a round with no measurement is RECORDED as a
note (and wedge frequency is itself a tracked series via the
``probe_failure`` rows bench.py appends) — never silently passed, never
a false failure.

Like ``utils/telemetry.py``, this module is stdlib-only at module level:
``bench.py``'s parent process loads it by file path for
:data:`GATE_SCHEMA_VERSION` before the subprocess-isolated device probe;
everything jax-flavored imports inside functions.  CLI:
``tools/perf_gate.py``; gate semantics: docs/observability.md
§Observatory.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any

# Version stamped on every gate artifact (bench phase payloads, the
# committed baseline, gate reports).  Bump when a field is renamed or its
# meaning changes; adding fields needs no bump.
GATE_SCHEMA_VERSION = 1

# Relative tolerance per compiled-signal family (exact-count families —
# fingerprints, hop/byte accounting, compile count — tolerate nothing).
DEFAULT_TOLERANCES = {
    "temp_bytes": 0.25,      # scheduler jitter in scratch accounting
    "output_bytes": 0.25,
    "xla_flops": 0.10,       # counted FLOPs barely move for one program
    "bytes_accessed": 0.35,  # fusion decisions move this the most
    "hardware": 0.15,        # round-over-round tokens/sec / TFLOPs
}

# Hardware series pulled from each bench round's payload:
# name -> (payload key, direction) where direction +1 means higher is
# better (throughput) and -1 means lower is better (latency).
HARDWARE_SERIES = {
    "fwd_tflops": ("value", +1),
    "fwdbwd_tflops": ("fwdbwd_tflops", +1),
    "tokens_per_sec": ("tokens_per_sec", +1),
    "train1m_tokens_per_sec": ("train1m_tokens_per_sec", +1),
    "hybrid262k_tflops": ("hybrid262k", +1),
    "counter262k_tflops": ("counter262k", +1),
    "fwd262k_q8_tflops": ("fwd262k_q8", +1),
    "fused262k_tflops": ("fused262k", +1),
    "packed262k_tokens_per_sec": ("packed262k", +1),
    "decode_ms_per_token": ("decode_ms_per_token", -1),
    # per-call decode latency distribution (bench phase 6's eager loop
    # through utils/tracing.LatencyHistogram): the tail regresses before
    # the chained mean does — a slow outlier every 20 tokens moves p95
    # 1:1 but the amortized ms/token by only 5%
    "decode_ms_p50": ("decode_ms_p50", -1),
    "decode_ms_p95": ("decode_ms_p95", -1),
}

# The analytic comms reference table: fixed north-star-shaped configs
# whose ``ring_comms_accounting`` outputs are pure arithmetic (no jax, no
# device) — pinned against the baseline so a formula regression (a hop
# miscounted, a payload byte-size change nobody meant) fails the gate
# with the same one-line diagnostic as a real comms regression.
COMMS_REFERENCE: dict[str, dict[str, Any]] = {
    "ring8_262k": dict(
        ring_size=8, seq_len=262144, kv_heads=8, dim_head=64,
        dtype_bytes=2,
    ),
    "hybrid2x4_262k": dict(
        ring_size=4, ulysses_size=2, seq_len=262144, kv_heads=8, heads=8,
        dim_head=64, dtype_bytes=2,
    ),
    "counter8_262k": dict(
        ring_size=8, seq_len=262144, kv_heads=8, dim_head=64,
        dtype_bytes=2, counter_rotate=True,
    ),
    "counter8_262k_int8": dict(
        ring_size=8, seq_len=262144, kv_heads=8, dim_head=64,
        dtype_bytes=2, counter_rotate=True, hop_compression="int8",
    ),
    # PR 13: the int8 COMPUTE path at the north-star shape — identical
    # wire accounting to counter8_262k_int8 (the quantized matmuls change
    # the kernel FEED, never the collectives) plus the operand-bytes /
    # f32-accumulator-bytes keys the q8 bench phase reports
    "ring8_262k_q8": dict(
        ring_size=8, seq_len=262144, kv_heads=8, dim_head=64,
        dtype_bytes=2, counter_rotate=True, hop_compression="int8",
        compute_dtype="int8",
    ),
    # PR 18: the fused single-launch ring at the north-star shape — the
    # analytic hop/byte accounting matches ring8_262k exactly (the data
    # that must move is impl-independent); what the row pins is the
    # launch model: kernel_launches=1 and fwd_collectives=0 (hops are
    # in-kernel remote DMAs, not ppermutes)
    "fused8_262k": dict(
        ring_size=8, seq_len=262144, kv_heads=8, dim_head=64,
        dtype_bytes=2, impl="fused",
    ),
}

# ring_comms_accounting keys kept per reference config (all exact ints).
COMMS_KEYS = (
    "ring_hops", "pure_ring_hops", "hop_bytes", "q_pack_bytes",
    "fwd_collectives", "bwd_collectives", "ring_bytes_per_step",
    "ring_bytes_per_step_bwd", "a2a_bytes_per_step",
    # PR 13: the matmul feed (operand width tracks compute_dtype) and the
    # f32 (acc, m, l) state (invariant under every compute_dtype — the
    # precision auditor's contract as a pinned number)
    "matmul_operand_bytes", "accumulator_bytes",
    # PR 18: the launch model — passes launches for the scan path, 1 for
    # the fused ring (the launch-free-hops claim as a pinned int)
    "kernel_launches",
)


@dataclass(frozen=True)
class GateFinding:
    """One regressed series: the gate's one-line diagnostic unit."""

    series: str
    baseline: Any
    current: Any
    message: str

    def __str__(self) -> str:
        return f"perf-gate: {self.series}: {self.message}"


@dataclass
class GateReport:
    """Findings (regressions — any means the gate fails), notes (the
    wedge-honest record: what could not be compared and why), and the
    list of series actually checked (an empty ``checked`` with a green
    verdict would be vacuous — callers can assert coverage)."""

    findings: list[GateFinding] = field(default_factory=list)
    notes: list[str] = field(default_factory=list)
    checked: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict[str, Any]:
        return {
            "gate_schema": GATE_SCHEMA_VERSION,
            "ok": self.ok,
            "checked": list(self.checked),
            "findings": [
                {
                    "series": f.series,
                    "baseline": f.baseline,
                    "current": f.current,
                    "message": f.message,
                }
                for f in self.findings
            ],
            "notes": list(self.notes),
        }


@dataclass
class BenchRound:
    """One ``BENCH_rNN.json`` round, normalized."""

    number: int
    path: str
    payload: dict[str, Any]

    @property
    def probe_ok(self) -> bool:
        """Did a hardware measurement actually run this round?  A wedged
        probe leaves ``error`` + ``value == 0`` — wedge-honesty means
        such a round contributes NO hardware points (its standing
        ``last_measured`` echo is an echo, not a measurement)."""
        return "error" not in self.payload and bool(self.payload.get("value"))

    @property
    def fingerprint(self) -> dict[str, Any] | None:
        fp = self.payload.get("collective_fingerprint")
        if isinstance(fp, dict) and "error" not in fp:
            # bench stamps its schema version on every phase payload;
            # that's provenance, not a collective count — a version bump
            # between rounds must not read as fingerprint drift
            return {k: v for k, v in fp.items() if k != "gate_schema"}
        return None

    @property
    def coverage(self) -> dict[str, Any] | None:
        """The tile-coverage fingerprint (bench phase 0b): per-row
        compact-grid tile counts from ``analysis/coverage.py``."""
        fp = self.payload.get("coverage_fingerprint")
        if isinstance(fp, dict) and "error" not in fp:
            return {k: v for k, v in fp.items() if k != "gate_schema"}
        return None

    @property
    def protocol(self) -> dict[str, Any] | None:
        """The fused-ring DMA-protocol fingerprint (bench phase 0f):
        schedverify's derived primitive counts, PROTOCOL row count,
        per-ring model event counts, and total violations
        (``analysis/schedverify.py::protocol_fingerprint``)."""
        fp = self.payload.get("protocol_fingerprint")
        if isinstance(fp, dict) and "error" not in fp:
            return {k: v for k, v in fp.items() if k != "gate_schema"}
        return None

    @property
    def multihost(self) -> dict[str, Any] | None:
        """The multihost-dryrun DCN fingerprint (bench phase 0e):
        per-row forward collective counts over the hierarchical
        ``(dcn_data, ...)`` mesh + the dcn-isolation verdict
        (``analysis/contracts.py::dcn_collective_fingerprint``)."""
        fp = self.payload.get("multihost_dryrun")
        if isinstance(fp, dict) and "error" not in fp:
            return {k: v for k, v in fp.items() if k != "gate_schema"}
        return None


@dataclass
class History:
    """The ingested perf history: bench rounds (oldest first), the
    standing hardware-log rows, and the wedge series."""

    rounds: list[BenchRound] = field(default_factory=list)
    hwlog: dict[str, dict[str, Any]] = field(default_factory=dict)
    probe_failures: list[dict[str, Any]] = field(default_factory=list)

    @property
    def wedged_rounds(self) -> list[BenchRound]:
        return [r for r in self.rounds if not r.probe_ok]


def _parse_round_payload(rec: Any) -> dict[str, Any] | None:
    """The bench JSON out of one round file: the driver wraps it as
    ``{"parsed": {...}}`` (preferred) with the raw line under ``tail``;
    a bare payload dict (a hand-rolled round) passes through."""
    if not isinstance(rec, dict):
        return None
    if isinstance(rec.get("parsed"), dict):
        return rec["parsed"]
    tail = rec.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                return obj
    if "metric" in rec or "value" in rec:
        return rec
    return None


def load_history(root: str | None = None) -> History:
    """Ingest ``BENCH_r*.json`` + ``docs/hwlogs/results.jsonl`` under
    ``root`` (default: the repo this file lives in).

    Malformed files are skipped (a corrupt archive row must not brick the
    gate); ``probe_failure`` rows join as their own series so wedge
    frequency is trackable (``grep`` was the previous interface).
    """
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    hist = History()
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        payload = _parse_round_payload(rec)
        if payload is not None:
            hist.rounds.append(BenchRound(int(m.group(1)), path, payload))
    hist.rounds.sort(key=lambda r: r.number)
    log_path = os.path.join(root, "docs", "hwlogs", "results.jsonl")
    try:
        with open(log_path) as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for line in lines:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        step = rec.get("step")
        if step == "probe_failure":
            hist.probe_failures.append(rec)
        elif step and isinstance(rec.get("result"), dict):
            hist.hwlog[step] = rec  # newest row per step wins
    return hist


# ----------------------------------------------------------------------
# Current-build CPU signals
# ----------------------------------------------------------------------


def comms_reference_signals() -> dict[str, dict[str, int]]:
    """The analytic hop/byte table at the pinned reference configs —
    pure arithmetic (fixed v5e rate constants), runnable with no jax
    and no devices."""
    from ring_attention_tpu.utils.telemetry import ring_comms_accounting

    out: dict[str, dict[str, int]] = {}
    for name, cfg in COMMS_REFERENCE.items():
        acct = ring_comms_accounting(
            peak_tflops=197.0, ici_gbps=186.0, **cfg
        )
        out[name] = {k: int(acct[k]) for k in COMMS_KEYS}
    return out


def latency_reference_signals() -> dict[str, Any]:
    """The latency-histogram codec's fixed point — pure arithmetic, no
    jax, no devices, no clock.

    A deterministic LCG sample pushed through
    ``utils/tracing.LatencyHistogram`` pins the bucket geometry (count,
    scale tag, edge checksum) and the integer percentile read-off as
    EXACT values.  Any change to the bucket edges or the rank rule
    silently re-scales every decode-latency number the hardware gate
    compares across rounds — this family makes that a one-line gate
    failure instead, and the baseline must be consciously re-recorded
    together with the hardware history it invalidates."""
    from ring_attention_tpu.utils.tracing import (
        BUCKET_BOUNDS_NS,
        HIST_BUCKETS,
        HIST_SCALE,
        LatencyHistogram,
    )

    hist = LatencyHistogram()
    x = 1
    for _ in range(1000):
        x = (x * 48271) % 2147483647  # minstd LCG: portable, seedless
        hist.record_ns(1_000 + x % 50_000_000)  # 1 us .. 50 ms spread
    return {
        "hist_scale": HIST_SCALE,
        "hist_buckets": int(HIST_BUCKETS),
        "edge_checksum": int(sum(BUCKET_BOUNDS_NS)),
        "n": int(hist.n),
        "p50_ns": int(hist.percentile_ns(50)),
        "p95_ns": int(hist.percentile_ns(95)),
        "p99_ns": int(hist.percentile_ns(99)),
    }


def compiled_reference_signals() -> dict[str, Any]:
    """Compiler-facing signals of the reference train step: counted
    FLOPs/bytes (``compiled_cost``), peak scratch (``compiled_memory``),
    and the retrace-sentinel compile count of a 2-step drive.

    The reference step is the telemetry suite's instrumented
    RingTransformer at a tiny shape — already compiled by tier-1, so the
    persistent compile cache makes this cheap on a test box.  These
    signals are compiler-version-scoped: the gate compares them only when
    the baseline was recorded under the same jax version.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ring_attention_tpu import RingTransformer, create_mesh
    from ring_attention_tpu.utils import (
        compat,
        init_train_metrics,
        make_train_step,
    )
    from ring_attention_tpu.utils.telemetry import (
        compiled_cost,
        compiled_memory,
    )
    from . import recompile

    mesh = create_mesh(ring_size=min(4, len(jax.devices())))
    model = RingTransformer(
        num_tokens=64, dim=32, depth=1, heads=4, dim_head=8, causal=True,
        striped=True, bucket_size=8, mesh=mesh, use_ring=True,
    )
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 64)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), toks, return_loss=True)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = compat.jit(make_train_step(
        lambda p, t: model.apply(p, t, return_loss=True), opt,
        collect_metrics=True, skip_nonfinite=True, clip_grad_norm=1.0,
    ))
    metrics = init_train_metrics()
    with recompile.CompileCounter() as counter:
        out = step(params, opt_state, metrics, toks)
        jax.block_until_ready(step(*out[:3], toks))
    exe = step.lower(params, opt_state, metrics, toks).compile()
    signals: dict[str, Any] = {"compile_count": counter.total}
    signals.update(compiled_cost(exe))
    mem = compiled_memory(exe)
    for key in ("temp_bytes", "output_bytes"):
        if key in mem:
            signals[key] = mem[key]
    return signals


def collect_current(
    *,
    strategies: tuple[str, ...] | None = (
        "ring", "ulysses", "hybrid", "counter", "ring_compressed",
        "counter_q8", "blockwise_ffn",
    ),
    compiled: bool = True,
    coverage: bool = True,
    multihost: bool = True,
    protocol: bool = True,
) -> dict[str, Any]:
    """The current build's CPU gate signals.

    ``strategies=None`` skips the (compile-paying) fingerprint;
    ``compiled=False`` skips the reference-step compile — the arithmetic
    comms table and the (numpy-only) tile-coverage fingerprint always
    land.  ``multihost=False`` skips the DCN dryrun fingerprint (it
    needs >= 4 devices); ``protocol=False`` skips the fused-ring
    DMA-protocol fingerprint (its extraction cross-check traces on the
    8-device ring).  Each skipped family is simply absent, and
    :func:`check` notes absent families instead of passing them
    silently.
    """
    import jax

    signals: dict[str, Any] = {
        "gate_schema": GATE_SCHEMA_VERSION,
        "jax": jax.__version__,
        "comms": comms_reference_signals(),
        "latency": latency_reference_signals(),
    }
    if coverage:
        from .coverage import coverage_fingerprint

        signals["coverage"] = coverage_fingerprint()
    if strategies:
        from .contracts import collective_fingerprint

        signals["fingerprint"] = collective_fingerprint(tuple(strategies))
    if multihost and len(jax.devices()) >= 4:
        from .contracts import dcn_collective_fingerprint

        signals["multihost"] = dcn_collective_fingerprint()
    if protocol and len(jax.devices()) >= 8:
        from .schedverify import protocol_fingerprint

        signals["protocol"] = protocol_fingerprint()
    if compiled:
        signals["compiled"] = compiled_reference_signals()
    return signals


# ----------------------------------------------------------------------
# The gate
# ----------------------------------------------------------------------


def _flat(tree: Any, prefix: str) -> dict[str, Any]:
    out: dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flat(v, f"{prefix}.{k}"))
    else:
        out[prefix] = tree
    return out


def check_baseline(
    current: dict[str, Any],
    baseline: dict[str, Any],
    *,
    tolerances: dict[str, float] | None = None,
) -> GateReport:
    """Current CPU signals vs the committed baseline.

    Exact families (fingerprint counts, comms reference ints, compile
    count) tolerate nothing — a dropped hop and a grown hop both mean
    the program changed and the baseline must be consciously re-recorded
    (``tools/perf_gate.py --update-baseline``).  Compiled cost/memory
    compare within per-series tolerance, and only under the same jax
    version as the baseline (noted and skipped otherwise — a compiler
    upgrade is not a regression).
    """
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    report = GateReport()
    base_signals = baseline.get("signals", baseline)

    # exact families -----------------------------------------------------
    for family in ("fingerprint", "comms", "coverage", "multihost",
                   "protocol", "latency"):
        base = base_signals.get(family)
        cur = current.get(family)
        if base is None:
            report.notes.append(f"{family}: not in baseline — recorded "
                                f"fresh on the next --update-baseline")
            continue
        if cur is None:
            report.notes.append(f"{family}: not collected this run "
                                f"(skipped family) — not compared")
            continue
        flat_base = _flat(base, family)
        flat_cur = _flat(cur, family)
        for series, want in sorted(flat_base.items()):
            if series not in flat_cur:
                # only a finding when the strategy/config was collected
                # at all — a subset run must not fail on what it skipped
                head = series.split(".")[1] if "." in series else series
                if any(k.startswith(f"{family}.{head}.")
                       or k == f"{family}.{head}" for k in flat_cur):
                    report.findings.append(GateFinding(
                        series, want, None,
                        f"series vanished from the current build "
                        f"(baseline {want})",
                    ))
                else:
                    report.notes.append(
                        f"{series}: not collected this run — not compared"
                    )
                continue
            report.checked.append(series)
            got = flat_cur[series]
            if got != want:
                report.findings.append(GateFinding(
                    series, want, got,
                    f"exact-count regression: baseline {want} -> "
                    f"current {got}",
                ))
        for series in sorted(set(flat_cur) - set(flat_base)):
            report.notes.append(
                f"{series}: new series (no baseline) — recorded on the "
                f"next --update-baseline"
            )

    # compiled family (tolerance + jax-version scoped) -------------------
    base_c = base_signals.get("compiled")
    cur_c = current.get("compiled")
    if base_c is None or cur_c is None:
        which = "baseline" if base_c is None else "current run"
        report.notes.append(f"compiled: absent from {which} — not compared")
        return report
    base_jax = baseline.get("jax", base_signals.get("jax"))
    if base_jax and base_jax != current.get("jax"):
        report.notes.append(
            f"compiled: baseline recorded under jax {base_jax}, running "
            f"{current.get('jax')} — compiler-scoped signals not compared"
        )
        return report
    for key, want in sorted(base_c.items()):
        got = cur_c.get(key)
        series = f"compiled.{key}"
        if got is None:
            report.notes.append(f"{series}: backend reports no value — "
                                f"not compared")
            continue
        report.checked.append(series)
        if key == "compile_count":
            if got > want:
                report.findings.append(GateFinding(
                    series, want, got,
                    f"retrace regression: {want} compile(s) -> {got} for "
                    f"the same 2-step drive",
                ))
            continue
        limit = tol.get(key, tol["temp_bytes"])
        if want and (got - want) / want > limit:
            report.findings.append(GateFinding(
                series, want, got,
                f"regression: baseline {want:,} -> current {got:,} "
                f"(+{(got - want) / want:.1%} > {limit:.0%} tolerance)",
            ))
        elif want and (want - got) / want > limit:
            report.notes.append(
                f"{series}: improved {want:,} -> {got:,} — re-record the "
                f"baseline to lock the win in"
            )
    return report


def check_history(
    history: History,
    *,
    tolerances: dict[str, float] | None = None,
) -> GateReport:
    """Round-over-round checks on the ingested bench history.

    Hardware series compare only between rounds where the probe ran
    (direction-aware: throughput down or latency up beyond tolerance is
    the finding).  Fingerprints compare exactly between consecutive
    rounds that carry one.  Wedged rounds and the ``probe_failure`` rows
    land as notes — the wedge-honest record.
    """
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    report = GateReport()
    ok_rounds = [r for r in history.rounds if r.probe_ok]
    for r in history.wedged_rounds:
        err = str(r.payload.get("error", "no measurement"))[:100]
        report.notes.append(
            f"round {r.number} ({os.path.basename(r.path)}): no hardware "
            f"measurement — {err}"
        )
    if history.rounds:
        report.notes.append(
            f"wedge record: {len(history.wedged_rounds)} of "
            f"{len(history.rounds)} rounds had no hardware measurement; "
            f"{len(history.probe_failures)} probe_failure row(s) in "
            f"docs/hwlogs/results.jsonl"
        )
    if history.hwlog:
        # the standing on-silicon numbers ride the report so a wedged
        # stretch still shows WHAT the last measured truth was (and when)
        standing = ", ".join(
            f"{step} {rec['result'].get('value')}"
            f"{' (' + rec['date'] + ')' if rec.get('date') else ''}"
            for step, rec in sorted(history.hwlog.items())
            if isinstance(rec.get("result"), dict)
            and "value" in rec["result"]
        )
        if standing:
            report.notes.append(f"standing hardware measurements: {standing}")
    # hardware series over ok rounds -------------------------------------
    if len(ok_rounds) < 2:
        if history.rounds:
            report.notes.append(
                "hardware: fewer than 2 measured rounds — CPU signals "
                "are the gate (wedge-honest: nothing passed silently)"
            )
    else:
        prev, last = ok_rounds[-2], ok_rounds[-1]
        limit = tol["hardware"]
        for name, (key, direction) in sorted(HARDWARE_SERIES.items()):
            a, b = prev.payload.get(key), last.payload.get(key)
            if not isinstance(a, (int, float)) or not isinstance(
                b, (int, float)
            ) or not a:
                continue
            series = f"hardware.{name}"
            report.checked.append(series)
            drop = (a - b) / a * direction
            if drop > limit:
                report.findings.append(GateFinding(
                    series, a, b,
                    f"regression r{prev.number} -> r{last.number}: "
                    f"{a:,} -> {b:,} ({'-' if direction > 0 else '+'}"
                    f"{abs(drop):.1%} > {limit:.0%} tolerance)",
                ))
    # fingerprint drift between consecutive carrying rounds ---------------
    for family, getter in (("fingerprint", lambda r: r.fingerprint),
                           ("coverage", lambda r: r.coverage),
                           ("multihost", lambda r: r.multihost),
                           ("protocol", lambda r: r.protocol)):
        fps = [(r.number, getter(r)) for r in history.rounds
               if getter(r) is not None]
        for (n0, fp0), (n1, fp1) in zip(fps, fps[1:]):
            flat0 = _flat(fp0, family)
            flat1 = _flat(fp1, family)
            for series in sorted(set(flat0) & set(flat1)):
                report.checked.append(f"{series}[r{n0}->r{n1}]")
                if flat0[series] != flat1[series]:
                    report.findings.append(GateFinding(
                        series, flat0[series], flat1[series],
                        f"drift r{n0} -> r{n1}: {flat0[series]} -> "
                        f"{flat1[series]}",
                    ))
    return report


def _downgrade_acknowledged_drift(
    report: GateReport, baseline_report: GateReport
) -> None:
    """History fingerprint drift needs the same conscious-override escape
    as the baseline family: an INTENTIONAL collective change lands with
    ``--update-baseline``, after which the current build MATCHES the new
    baseline — but the archived round files still disagree with each
    other forever.  When the same series passed the current-vs-baseline
    check, the historical drift is demoted to a note (it already served
    its purpose: the change is acknowledged).  Without a baseline
    verdict for the series (history-only runs), drift stays a finding.
    """
    acknowledged = {
        s for s in baseline_report.checked
        if s.startswith(("fingerprint.", "coverage.", "multihost.",
                         "protocol."))
        and not any(f.series == s for f in baseline_report.findings)
    }
    kept: list[GateFinding] = []
    for f in report.findings:
        if f.series in acknowledged and "drift" in f.message:
            report.notes.append(
                f"{f.series}: historical {f.message} — acknowledged "
                f"(current build matches docs/perf_baseline.json)"
            )
        else:
            kept.append(f)
    report.findings[:] = kept


def run_gate(
    current: dict[str, Any] | None = None,
    *,
    root: str | None = None,
    baseline_path: str | None = None,
    tolerances: dict[str, float] | None = None,
) -> GateReport:
    """The whole gate: history checks + baseline checks, merged.

    ``current=None`` runs history-only (plus a note that no live signals
    were collected).  A missing baseline file is a note, not a failure —
    but the tier-1 test pins that the committed baseline exists and
    passes, so "delete the baseline" cannot green a regression.
    """
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    if baseline_path is None:
        baseline_path = os.path.join(root, "docs", "perf_baseline.json")
    history = load_history(root)
    report = check_history(history, tolerances=tolerances)
    if current is None:
        report.notes.append("no live signals collected (history-only run)")
        return report
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except OSError:
        report.notes.append(
            f"no baseline at {baseline_path} — run tools/perf_gate.py "
            f"--update-baseline to record one"
        )
        return report
    except ValueError as e:
        report.findings.append(GateFinding(
            "baseline", baseline_path, None,
            f"unreadable baseline JSON: {e}",
        ))
        return report
    b_report = check_baseline(current, baseline, tolerances=tolerances)
    _downgrade_acknowledged_drift(report, b_report)
    report.findings.extend(b_report.findings)
    report.notes.extend(b_report.notes)
    report.checked.extend(b_report.checked)
    return report


def write_baseline(
    current: dict[str, Any], path: str, *, note: str = ""
) -> dict[str, Any]:
    """Record ``current`` as the committed baseline (atomic write)."""
    import time as _time

    payload = {
        "gate_schema": GATE_SCHEMA_VERSION,
        "recorded": _time.strftime("%Y-%m-%d"),
        "jax": current.get("jax"),
        **({"note": note} if note else {}),
        "signals": {
            k: v for k, v in current.items()
            if k not in ("gate_schema", "jax")
        },
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return payload
