"""Retrace sentinel + accumulator-dtype audit.

A steady-state train/decode loop must compile each entry point exactly
once; a retrace-per-step (a Python scalar changing dtype, a fresh closure
per step, an unhashable static arg) turns a multi-hour run into a
compile benchmark and is invisible in small tests — each step still
*works*.  The sentinel here counts real XLA compilations two ways:

  - :func:`assert_compiles_once` — drives a jitted callable through a
    multi-step loop with fresh same-shaped inputs and asserts its compile
    cache holds exactly one entry afterwards;
  - :class:`CompileCounter` — a context manager counting backend
    compilations process-wide (via jax's compilation logging), for loops
    that call through several entry points at once.

The dtype audit (:func:`audit_accumulator_dtypes`) pins the numerics
contract the flash kernels are built on: the online-softmax running state
``(acc, m, l)`` accumulates in float32 regardless of the input dtype —
bf16 inputs with bf16 accumulation drift visibly over 262k-token sweeps.
Both the XLA carry and the Pallas partials are checked via ``eval_shape``
(abstract: no kernel runs, works on any backend).
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field

# Loggers that announce an actual backend compilation (cache miss) in the
# jax versions this repo supports; record format pinned by _COMPILE_RE.
_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",
    "jax._src.dispatch",
)
_COMPILE_RE = re.compile(r"^Compiling ([^\s]+) with global shapes")


class RetraceError(AssertionError):
    """An entry point compiled more than once across a steady-state loop."""


@dataclass
class CompileCounter:
    """Counts XLA compilations (trace-cache misses) under the context.

    >>> with CompileCounter() as counter:
    ...     for step in range(3):
    ...         train_step(params, batch)
    >>> counter.total  # 1 for a healthy loop
    """

    names: list[str] = field(default_factory=list)

    @property
    def total(self) -> int:
        return len(self.names)

    def __enter__(self) -> "CompileCounter":
        self._handlers = []
        for name in _COMPILE_LOGGERS:
            logger = logging.getLogger(name)
            handler = logging.Handler(level=logging.DEBUG)
            handler.emit = self._emit  # type: ignore[method-assign]
            self._old_levels = getattr(self, "_old_levels", {})
            self._old_levels[name] = logger.level
            if logger.level > logging.DEBUG or logger.level == logging.NOTSET:
                logger.setLevel(logging.DEBUG)
            logger.addHandler(handler)
            self._handlers.append((logger, handler))
        return self

    def _emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.match(record.getMessage())
        if m:
            self.names.append(m.group(1))

    def __exit__(self, *exc) -> None:
        for logger, handler in self._handlers:
            logger.removeHandler(handler)
            logger.setLevel(self._old_levels[logger.name])


def _cache_size(jitted) -> int | None:
    """Trace-cache entry count of a ``jax.jit``-wrapped callable (None when
    the running jax build does not expose it — callers then fall back to
    the logging counter)."""
    fn = getattr(jitted, "_cache_size", None)
    return fn() if callable(fn) else None


def assert_compiles_once(jitted, make_args, steps: int = 3,
                         label: str | None = None):
    """Drive ``jitted`` through ``steps`` calls with fresh same-shaped args
    and fail unless it compiled exactly once.

    ``make_args(step) -> tuple`` builds each step's arguments — fresh
    arrays each call, the way a real data loader feeds a train loop (a
    sentinel fed the identical array object would miss dtype/weak-type
    churn).  Raises :class:`RetraceError` with a one-line diagnostic
    naming the entry point; returns the loop's compile count on success
    (1 for a cold callable, 0 when this shape was already warmed before
    the loop — both are healthy steady states; pre-existing cache entries
    for *other* shapes are not charged to this loop).
    """
    label = label or getattr(jitted, "__name__", str(jitted))
    # build every step's args up front: array construction can itself
    # trigger tiny compiles that would pollute the fallback counter
    all_args = [make_args(step) for step in range(steps)]
    cache_before = _cache_size(jitted)
    with CompileCounter() as counter:
        for args in all_args:
            jitted(*args)
    cache_after = _cache_size(jitted)
    if cache_before is not None and cache_after is not None:
        compiles = cache_after - cache_before
    else:
        compiles = counter.total
    if compiles > 1:
        raise RetraceError(
            f"{label}: {compiles} compilations across {steps} same-shape "
            f"steps (expected at most 1) — a static arg, weak-typed "
            f"scalar, or fresh closure is forcing a retrace per step "
            f"[rule: compile-once]"
        )
    return compiles


def audit_accumulator_dtypes() -> list[str]:
    """Verify the flash kernels accumulate in float32 for sub-f32 inputs.

    Returns a list of one-line violations (empty = clean): checks the XLA
    path's online-softmax carry (``ops/flash.init_carry``) and the Pallas
    partials' ``(acc, m, l)`` output dtypes, both under bf16 inputs, via
    ``eval_shape`` — abstract evaluation only, no kernel runs.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import flash, pallas_flash

    violations: list[str] = []
    b, h, n, d = 1, 2, 32, 8
    q = jax.ShapeDtypeStruct((b, h, n, d), jnp.bfloat16)

    carry = jax.eval_shape(
        lambda q: flash.init_carry(b, h, 1, n, d, like=q), q
    )
    for name, leaf in zip(("acc", "m", "l"), jax.tree_util.tree_leaves(carry)):
        if leaf.dtype != jnp.float32:
            violations.append(
                f"ops/flash.init_carry: {name} accumulates in {leaf.dtype}, "
                f"contract says float32 [rule: f32-accumulator]"
            )

    parts = jax.eval_shape(
        lambda q, k, v: pallas_flash.pallas_flash_partials(
            q, k, v, scale=1.0, block_q=16, block_k=16, interpret=True,
        ),
        q, q, q,
    )
    for name, leaf in zip(("acc", "m", "l"), jax.tree_util.tree_leaves(parts)):
        if leaf.dtype != jnp.float32:
            violations.append(
                f"ops/pallas_flash.pallas_flash_partials: {name} is "
                f"{leaf.dtype}, contract says float32 [rule: f32-accumulator]"
            )
    return violations
