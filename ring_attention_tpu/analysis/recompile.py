"""Retrace sentinel + accumulator-dtype / memory-placement audits.

A steady-state train/decode loop must compile each entry point exactly
once; a retrace-per-step (a Python scalar changing dtype, a fresh closure
per step, an unhashable static arg) turns a multi-hour run into a
compile benchmark and is invisible in small tests — each step still
*works*.  The sentinel here counts real XLA compilations two ways:

  - :func:`assert_compiles_once` — drives a jitted callable through a
    multi-step loop with fresh same-shaped inputs and asserts its compile
    cache holds exactly one entry afterwards;
  - :class:`CompileCounter` — a context manager counting backend
    compilations process-wide (via jax's compilation logging), for loops
    that call through several entry points at once.

The dtype audit (:func:`audit_accumulator_dtypes`) pins the numerics
contract the flash kernels are built on: the online-softmax running state
``(acc, m, l)`` accumulates in float32 regardless of the input dtype —
bf16 inputs with bf16 accumulation drift visibly over 262k-token sweeps.
Both the XLA carry and the Pallas partials are checked via ``eval_shape``
(abstract: no kernel runs, works on any backend).

The memory audits close the loop on the million-token knobs
(docs/memory.md), because every one of them fails *silently*: a remat
policy that quietly saves the ``mult*dim`` FFN intermediate still
computes the right numbers, a donated buffer that double-allocates still
trains, and an "offloaded" optimizer state that lands back in HBM still
converges — each just OOMs at the context length the knob was supposed
to unlock.

  - :func:`audit_remat_residuals` — walks the differentiated
    ``remat2`` blocks of a grad jaxpr and flags any saved residual whose
    shape the policy promised to recompute;
  - :func:`audit_donation` — donated inputs must actually alias outputs
    in the compiled executable (``input_output_alias``), not silently
    double-allocate;
  - :func:`audit_host_offload` — outputs declared host-resident must
    report the host memory kind in the compiled output shardings
    (vacuous on backends without a host space, where offload is a
    documented no-op — ``utils/compat.host_memory_kind``).

All run on CPU; ``tools/check_contracts.py --memory`` is the CLI.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field

# Loggers that announce an actual backend compilation (cache miss) in the
# jax versions this repo supports; record format pinned by _COMPILE_RE.
_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",
    "jax._src.dispatch",
)
_COMPILE_RE = re.compile(r"^Compiling ([^\s]+) with global shapes")


class RetraceError(AssertionError):
    """An entry point compiled more than once across a steady-state loop."""


@dataclass
class CompileCounter:
    """Counts XLA compilations (trace-cache misses) under the context.

    >>> with CompileCounter() as counter:
    ...     for step in range(3):
    ...         train_step(params, batch)
    >>> counter.total  # 1 for a healthy loop
    """

    names: list[str] = field(default_factory=list)

    @property
    def total(self) -> int:
        return len(self.names)

    def __enter__(self) -> "CompileCounter":
        self._handlers = []
        for name in _COMPILE_LOGGERS:
            logger = logging.getLogger(name)
            handler = logging.Handler(level=logging.DEBUG)
            handler.emit = self._emit  # type: ignore[method-assign]
            self._old_levels = getattr(self, "_old_levels", {})
            self._old_levels[name] = logger.level
            if logger.level > logging.DEBUG or logger.level == logging.NOTSET:
                logger.setLevel(logging.DEBUG)
            logger.addHandler(handler)
            self._handlers.append((logger, handler))
        return self

    def _emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.match(record.getMessage())
        if m:
            self.names.append(m.group(1))

    def __exit__(self, *exc) -> None:
        for logger, handler in self._handlers:
            logger.removeHandler(handler)
            logger.setLevel(self._old_levels[logger.name])


def _cache_size(jitted) -> int | None:
    """Trace-cache entry count of a ``jax.jit``-wrapped callable (None when
    the running jax build does not expose it — callers then fall back to
    the logging counter)."""
    fn = getattr(jitted, "_cache_size", None)
    return fn() if callable(fn) else None


def assert_compiles_once(jitted, make_args, steps: int = 3,
                         label: str | None = None):
    """Drive ``jitted`` through ``steps`` calls with fresh same-shaped args
    and fail unless it compiled exactly once.

    ``make_args(step) -> tuple`` builds each step's arguments — fresh
    arrays each call, the way a real data loader feeds a train loop (a
    sentinel fed the identical array object would miss dtype/weak-type
    churn).  Raises :class:`RetraceError` with a one-line diagnostic
    naming the entry point; returns the loop's compile count on success
    (1 for a cold callable, 0 when this shape was already warmed before
    the loop — both are healthy steady states; pre-existing cache entries
    for *other* shapes are not charged to this loop).
    """
    label = label or getattr(jitted, "__name__", str(jitted))
    # build every step's args up front: array construction can itself
    # trigger tiny compiles that would pollute the fallback counter
    all_args = [make_args(step) for step in range(steps)]
    cache_before = _cache_size(jitted)
    with CompileCounter() as counter:
        for args in all_args:
            jitted(*args)
    cache_after = _cache_size(jitted)
    if cache_before is not None and cache_after is not None:
        compiles = cache_after - cache_before
    else:
        compiles = counter.total
    if compiles > 1:
        raise RetraceError(
            f"{label}: {compiles} compilations across {steps} same-shape "
            f"steps (expected at most 1) — a static arg, weak-typed "
            f"scalar, or fresh closure is forcing a retrace per step "
            f"[rule: compile-once]"
        )
    return compiles


_REMAT_PRIMS = ("remat2", "checkpoint")


def audit_remat_residuals(fn, *args, forbidden, label: str | None = None
                          ) -> list[str]:
    """Flag saved remat residuals the policy claims are recomputed.

    Traces ``jax.grad`` of the scalar-valued ``fn(*args)`` (grad wrt
    argument 0) and walks every *differentiated* ``remat2`` block in the
    jaxpr — the backward half of a checkpointed region, whose operands
    are exactly the residuals the forward saved for it.  Any operand
    whose shape appears in ``forbidden`` (a collection of shape tuples)
    is a policy leak: e.g. a ``(b, n, mult*dim)`` FFN intermediate
    surviving under ``nothing_saveable`` means the config's memory claim
    is fiction even though every value it computes is correct.  Returns
    one-line violations (empty = the forbidden shapes are all recomputed,
    never saved).  Runs at trace level — no compile, any backend.
    """
    import jax

    label = label or getattr(fn, "__name__", str(fn))
    forbidden = {tuple(s) for s in forbidden}
    jaxpr = jax.make_jaxpr(jax.grad(fn))(*args)
    violations: list[str] = []

    def walk(jx) -> None:
        for eqn in jx.eqns:
            if (eqn.primitive.name in _REMAT_PRIMS
                    and eqn.params.get("differentiated")):
                for var in eqn.invars:
                    shape = tuple(getattr(var.aval, "shape", ()))
                    if shape in forbidden:
                        violations.append(
                            f"{label}: rematted backward holds a saved "
                            f"residual of shape {shape} — the remat policy "
                            f"keeps an activation this configuration "
                            f"claims is recomputed [rule: remat-residual]"
                        )
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub)

    walk(jaxpr.jaxpr)
    # several residuals of one shape are one policy bug: one line each
    return list(dict.fromkeys(violations))


def _sub_jaxprs(value):
    import jax

    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            stack.extend(v)


def audit_donation(jitted, *args, donate_argnums=(0, 1),
                   label: str | None = None) -> list[str]:
    """Donated inputs must actually alias outputs in the compiled program.

    Donation is a *hint*: XLA silently ignores it when shapes/dtypes or a
    backend quirk block the alias, and the program then double-allocates
    exactly the buffers (params + Adam moments) the donation existed to
    fold.  Compiles ``jitted`` (already wrapped with ``donate_argnums`` —
    e.g. ``make_train_step(jit_donate=True)``) and counts the
    ``input_output_alias`` entries in the executable's HLO header against
    the number of donated argument leaves.  (The header survives
    persistent-compile-cache hits; ``memory_analysis().alias_size_in_
    bytes`` reports 0 on a deserialized executable and would
    false-alarm.)  Returns one-line violations; a program exposing no HLO
    text reports itself rather than silently passing.
    """
    import jax

    label = label or getattr(jitted, "__name__", str(jitted))
    compiled = jitted.lower(*args).compile()
    try:
        txt = compiled.as_text()
    except Exception:  # noqa: BLE001 — absence must be reported, not raised
        txt = None
    if not txt:
        return [
            f"{label}: compiled executable exposes no HLO text — donation "
            f"cannot be verified on this build [rule: donation-alias]"
        ]
    # one "(param, {index}, may|must-alias)" clause per aliased buffer,
    # all in the module header (nested braces defeat a bracket regex)
    entries = len(re.findall(r"\(\d+, \{[^}]*\}, (?:may|must)-alias\)", txt))
    donated = sum(
        len(jax.tree.leaves(args[i])) for i in donate_argnums
    )
    if entries < donated:
        return [
            f"{label}: {entries} input/output aliases for {donated} "
            f"donated argument leaves — donated buffers are "
            f"double-allocating instead of updating in place "
            f"[rule: donation-alias]"
        ]
    return []


def audit_host_offload(jitted, *args, out_index: int = 1,
                       label: str | None = None) -> list[str]:
    """Outputs declared host-resident must compile to host-space buffers.

    Checks output ``out_index`` of the compiled ``jitted(*args)`` (the
    optimizer state, in ``make_train_step``'s layout): every array leaf's
    output sharding must carry the backend's host memory kind — an
    offloaded buffer whose output sharding says device memory has silently
    aliased back into HBM, which is exactly the failure mode that turns
    "offload" into a no-op that still OOMs.  On backends with no host
    memory space (jax 0.4.x CPU) offload is a documented identity and the
    audit passes vacuously — gate on
    ``utils.compat.host_memory_kind()`` for a hard guarantee.
    """
    import jax

    from ..utils import compat

    label = label or getattr(jitted, "__name__", str(jitted))
    kind = compat.host_memory_kind()
    if kind is None:
        return []  # no host space: offload degrades to the identity
    compiled = jitted.lower(*args).compile()
    try:
        shardings = compiled.output_shardings
    except Exception:  # noqa: BLE001 — absence must be reported, not raised
        return [
            f"{label}: compiled executable exposes no output shardings — "
            f"host placement cannot be verified [rule: host-offload]"
        ]
    out = shardings[out_index]
    violations = []
    for leaf in jax.tree.leaves(out):
        got = getattr(leaf, "memory_kind", None)
        if got != kind:
            violations.append(
                f"{label}: output {out_index} leaf landed in "
                f"{got or 'device'} memory, expected {kind} — the "
                f"offloaded state aliased back into HBM "
                f"[rule: host-offload]"
            )
    return violations


def run_memory_suite() -> list[tuple[str, list[str]]]:
    """The memory-axis audit suite behind ``check_contracts.py --memory``.

    Returns ``(check name, violations)`` pairs — all empty lists = the
    memory contracts hold.  Covers: the f32 accumulator audit, the
    remat-residual audit on the chunked-FFN path, a negative toy proving
    the residual audit actually catches a saved ``mult*dim`` activation
    (a checker that cannot fail its toy is a no-op wearing a green
    checkmark), the donation audit on the composed chunked train step,
    the host-offload placement audit, and the compiled peak-temp-bytes
    relation (chunked FFN strictly below dense at equal shape).  Small
    shapes; CPU-runnable end to end.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ..models import FeedForward, RingTransformer
    from ..utils import compat, make_train_step
    from ..utils.telemetry import compiled_memory

    checks: list[tuple[str, list[str]]] = []
    b, n, d, mult = 1, 128, 32, 4
    forbidden = [(b, n, mult * d)]

    checks.append(("accumulator-dtypes", audit_accumulator_dtypes()))

    ff = FeedForward(d, mult, chunk_size=32)
    x = jnp.ones((b, n, d))
    ff_params = ff.init(jax.random.PRNGKey(0), x)
    checks.append((
        "remat-residuals: blockwise ffn",
        audit_remat_residuals(
            lambda p: ff.apply(p, x).astype(jnp.float32).sum(), ff_params,
            forbidden=forbidden, label="blockwise_ffn",
        ),
    ))

    # negative toy: a remat that SAVES the mult*dim activation while the
    # config claims nothing_saveable — the audit must flag it, one line
    w1, w2 = jnp.ones((d, mult * d)), jnp.ones((mult * d, d))
    bad = jax.checkpoint(
        lambda x: ((jax.nn.gelu(x @ w1)) @ w2).sum(),
        policy=jax.checkpoint_policies.everything_saveable,
    )
    caught = audit_remat_residuals(
        bad, x, forbidden=forbidden, label="negative-toy",
    )
    checks.append((
        "remat-residuals: negative toy caught",
        [] if caught else [
            "negative toy: a saved (b, n, mult*dim) activation went "
            "unflagged — the residual audit is not live "
            "[rule: remat-residual]"
        ],
    ))

    # the composed step every knob feeds: chunked FFN + chunked CE +
    # nothing_saveable remat, donated and (where supported) offloaded
    model = RingTransformer(
        num_tokens=64, dim=d, depth=1, heads=2, dim_head=16, bucket_size=32,
        causal=True, use_ring=False, remat=True,
        remat_policy="nothing_saveable", ff_chunk_size=32,
        loss_chunk_size=32,
    )
    tokens = jnp.zeros((1, n + 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, t):
        return model.apply(p, t, return_loss=True)

    step = make_train_step(loss_fn, opt, jit_donate=True)
    checks.append((
        "donation-alias: chunked train step",
        audit_donation(step, params, opt_state, tokens, label="train_step"),
    ))

    off_step = make_train_step(
        loss_fn, opt, jit_donate=True, offload_opt_state=True,
    )
    kind = compat.host_memory_kind()
    name = (
        "host-offload placement"
        if kind else
        "host-offload placement (no host space: no-op fallback verified)"
    )
    checks.append((
        name,
        audit_host_offload(
            off_step, params, opt_state, tokens, label="offload_step",
        ),
    ))

    # the headline relation, from the compiler's own accounting: the
    # chunked step's scratch high-water mark strictly below the dense
    # step's at equal shape
    dense_model = RingTransformer(
        num_tokens=64, dim=d, depth=1, heads=2, dim_head=16, bucket_size=32,
        causal=True, use_ring=False, remat=True,
        remat_policy="nothing_saveable",
    )

    def temp_bytes(m):
        fn = compat.jit(jax.value_and_grad(
            lambda p: m.apply(p, tokens, return_loss=True)
        ))
        return compiled_memory(fn.lower(params).compile()).get("temp_bytes")

    t_chunk, t_dense = temp_bytes(model), temp_bytes(dense_model)
    if t_chunk is None or t_dense is None:
        mem_violations = [
            "backend exposes no memory analysis — peak temp bytes "
            "unverifiable on this build [rule: chunked-peak]"
        ]
    elif t_chunk >= t_dense:
        mem_violations = [
            f"chunked-FFN step temp bytes {t_chunk} NOT below the dense "
            f"step's {t_dense} at equal shape [rule: chunked-peak]"
        ]
    else:
        mem_violations = []
    checks.append((
        f"chunked peak temp bytes < dense ({t_chunk} < {t_dense})",
        mem_violations,
    ))
    return checks


def audit_accumulator_dtypes() -> list[str]:
    """Verify the flash kernels accumulate in float32 for sub-f32 inputs.

    Returns a list of one-line violations (empty = clean): checks the XLA
    path's online-softmax carry (``ops/flash.init_carry``) and the Pallas
    partials' ``(acc, m, l)`` output dtypes, both under bf16 inputs, via
    ``eval_shape`` — abstract evaluation only, no kernel runs.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import flash, pallas_flash

    violations: list[str] = []
    b, h, n, d = 1, 2, 32, 8
    q = jax.ShapeDtypeStruct((b, h, n, d), jnp.bfloat16)

    carry = jax.eval_shape(
        lambda q: flash.init_carry(b, h, 1, n, d, like=q), q
    )
    for name, leaf in zip(("acc", "m", "l"), jax.tree_util.tree_leaves(carry)):
        if leaf.dtype != jnp.float32:
            violations.append(
                f"ops/flash.init_carry: {name} accumulates in {leaf.dtype}, "
                f"contract says float32 [rule: f32-accumulator]"
            )

    parts = jax.eval_shape(
        lambda q, k, v: pallas_flash.pallas_flash_partials(
            q, k, v, scale=1.0, block_q=16, block_k=16, interpret=True,
        ),
        q, q, q,
    )
    for name, leaf in zip(("acc", "m", "l"), jax.tree_util.tree_leaves(parts)):
        if leaf.dtype != jnp.float32:
            violations.append(
                f"ops/pallas_flash.pallas_flash_partials: {name} is "
                f"{leaf.dtype}, contract says float32 [rule: f32-accumulator]"
            )
    return violations
