"""Static-analysis subsystem: machine-checked contracts for the package.

Three passes, all CPU-runnable in tier-1 (see docs/static_analysis.md):

  - :mod:`~ring_attention_tpu.analysis.contracts` — declarative
    collective/HLO contracts per sequence-parallel strategy, verified
    against optimized HLO and jaxpr structure;
  - :mod:`~ring_attention_tpu.analysis.lint` — repo-native AST lint
    (compat-shim bypasses, unnamed kernels, unscoped collectives, host
    entropy in traced code, unvalidated entry points);
  - :mod:`~ring_attention_tpu.analysis.recompile` — retrace sentinel
    (each entry point compiles exactly once per shape) and the f32
    accumulator-dtype audit;
  - :mod:`~ring_attention_tpu.analysis.perfgate` — the perf-observatory
    regression gate: BENCH_r*.json / hwlog history ingest + CPU-signal
    checks against ``docs/perf_baseline.json`` (wedge-honest: rounds
    whose TPU probe never ran are recorded, never silently passed).

CLI: ``tools/check_contracts.py`` (full contract suite),
``tools/perf_gate.py`` (the regression gate), and
``python -m ring_attention_tpu.analysis`` (lint + dtype audit +
compile-free gate self-run).
On a host without jax, run the lint as a plain script —
``python ring_attention_tpu/analysis/lint.py`` — which skips this
package ``__init__`` chain entirely.
"""

from .lint import Violation, lint_file, lint_package, lint_source
from .perfgate import (
    GATE_SCHEMA_VERSION,
    GateFinding,
    GateReport,
    History,
    collect_current,
    load_history,
    run_gate,
    write_baseline,
)
from .recompile import (
    CompileCounter,
    RetraceError,
    assert_compiles_once,
    audit_accumulator_dtypes,
    audit_donation,
    audit_host_offload,
    audit_remat_residuals,
)

__all__ = [
    "CompileCounter",
    "GATE_SCHEMA_VERSION",
    "GateFinding",
    "GateReport",
    "History",
    "RetraceError",
    "Violation",
    "collect_current",
    "load_history",
    "run_gate",
    "write_baseline",
    "assert_compiles_once",
    "audit_accumulator_dtypes",
    "audit_donation",
    "audit_host_offload",
    "audit_remat_residuals",
    "lint_file",
    "lint_package",
    "lint_source",
    # contracts is imported lazily (it pulls in jax + the parallel stack):
    "contracts",
]


def __getattr__(name: str):
    if name == "contracts":
        import importlib

        return importlib.import_module(".contracts", __name__)
    raise AttributeError(name)
