"""Static-analysis subsystem: machine-checked contracts for the package.

The passes, all CPU-runnable in tier-1 (see docs/static_analysis.md):

  - :mod:`~ring_attention_tpu.analysis.contracts` — declarative
    collective/HLO contracts per sequence-parallel strategy, verified
    against optimized HLO and jaxpr structure;
  - :mod:`~ring_attention_tpu.analysis.lint` — repo-native AST lint
    (compat-shim bypasses, unnamed kernels, unscoped collectives, host
    entropy/numpy in traced code, unvalidated entry points);
  - :mod:`~ring_attention_tpu.analysis.recompile` — retrace sentinel
    (each entry point compiles exactly once per shape) and the f32
    accumulator-dtype spot audit;
  - :mod:`~ring_attention_tpu.analysis.dataflow` — jaxpr abstract
    interpretation: the precision-flow auditor (bf16/int8 taint to every
    reduction/accumulator, generalizing the spot audit) and the SPMD
    divergence checker (branch-invariant collective sequences);
  - :mod:`~ring_attention_tpu.analysis.coverage` — the tile-coverage
    prover: the compact skip grids held to a global-position oracle for
    soundness (no live tile skipped), tightness (no dead tile visited),
    and schedule completeness, per strategy x layout x masking row;
  - :mod:`~ring_attention_tpu.analysis.perfgate` — the perf-observatory
    regression gate: BENCH_r*.json / hwlog history ingest + CPU-signal
    checks against ``docs/perf_baseline.json`` (wedge-honest: rounds
    whose TPU probe never ran are recorded, never silently passed);
  - :mod:`~ring_attention_tpu.analysis.schedverify` — the DMA/semaphore
    protocol verifier for the fused-ring kernel: jaxpr extraction of
    every DMA/semaphore site cross-checked against the declared
    ``PROTOCOL`` table, then a symbolic N-device model check (rings
    2..8) for matched waits, overwrite-before-read races
    (happens-before from semaphore edges), semaphore drain, and
    deadlock freedom under arbitrary compute skew.

CLI: ``tools/check_contracts.py`` (contract suite; ``--coverage`` /
``--dataflow`` for the prover and jaxpr audits), ``tools/perf_gate.py``
(the regression gate), and ``python -m ring_attention_tpu.analysis``
(lint + dtype audit + precision flow + divergence + coverage +
compile-free gate self-run).
On a host without jax, run the lint as a plain script —
``python ring_attention_tpu/analysis/lint.py`` — which skips this
package ``__init__`` chain entirely.
"""

from .dataflow import (
    JaxprWalker,
    PrecisionFlow,
    audit_precision_flow,
    check_spmd_divergence,
    collective_signature,
    run_divergence_suite,
    run_precision_suite,
)
from .lint import Violation, lint_file, lint_package, lint_source
from .perfgate import (
    GATE_SCHEMA_VERSION,
    GateFinding,
    GateReport,
    History,
    collect_current,
    load_history,
    run_gate,
    write_baseline,
)
from .recompile import (
    CompileCounter,
    RetraceError,
    assert_compiles_once,
    audit_accumulator_dtypes,
    audit_donation,
    audit_host_offload,
    audit_remat_residuals,
)

__all__ = [
    "CompileCounter",
    "GATE_SCHEMA_VERSION",
    "GateFinding",
    "GateReport",
    "History",
    "JaxprWalker",
    "PrecisionFlow",
    "RetraceError",
    "Violation",
    "audit_precision_flow",
    "check_spmd_divergence",
    "collect_current",
    "collective_signature",
    "load_history",
    "run_divergence_suite",
    "run_gate",
    "run_precision_suite",
    "write_baseline",
    "assert_compiles_once",
    "audit_accumulator_dtypes",
    "audit_donation",
    "audit_host_offload",
    "audit_remat_residuals",
    "lint_file",
    "lint_package",
    "lint_source",
    # imported lazily (contracts pulls in jax + the parallel stack;
    # coverage pulls the kernel module for band_plan; schedverify pulls
    # the kernel module for its PROTOCOL table):
    "contracts",
    "coverage",
    "schedverify",
]


def __getattr__(name: str):
    if name in ("contracts", "coverage", "schedverify"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
