"""DMA/semaphore protocol verifier for the fused-ring kernel.

PR 18's ``fused_ring_remote`` is the only code in the package with real
device-to-device concurrency, and its review found two genuine races
that no shipped analyzer could have caught: a grant-less push variant
whose incoming DMA overwrote KV mid-read under causal compute skew, and
ring-rank LOGICAL DMA device ids that address the wrong replica group on
multi-axis meshes.  ``contracts.py`` counts the kernel's DMA/semaphore
primitives but proves nothing about their *ordering* — this module adds
the ordering proof, in three layers:

  1. **Extraction** (:func:`extract_fused_schedule`) — a
     :class:`dataflow.JaxprWalker` subclass threads kernel-invar
     IDENTITY (not dtype) through the pallas kernel's cond branches and
     while carries, so every ``dma_start`` / ``dma_wait`` /
     ``semaphore_signal`` / ``semaphore_wait`` equation in the traced
     kernel resolves to named buffers and semaphores, a remote/local
     classification (the param tree's trailing device-id leaves), and
     its ``DeviceIdType`` (anything but MESH on the remote ops is a
     finding — the logical-id review bug, caught at the jaxpr).
  2. **The declared protocol** (``ops/pallas_ring.py::PROTOCOL``) — a
     literal table of copy/handshake rows (slots, semaphores, guards,
     the receiver->sender grant) that the extracted equations are
     cross-checked against site-by-site (:func:`crosscheck_protocol`).
     The fused contract's primitive counts are DERIVED from the table
     (:func:`derived_fused_counts`), so the pins can never drift from
     the verified model; lint RA015 fences the call sites to the rows.
  3. **Model check** (:func:`verify_protocol`) — the table is expanded
     into the composed N-device event schedule for ring sizes 2..8 (and
     a 2-group mesh, proving MESH addressing stays inside the replica
     group) and checked symbolically: every ``dma_start`` has a
     matching wait on both ends; no kvbuf slot is written while a
     concurrent reader holds it (the race detector — a guaranteed
     happens-before graph built to a fixpoint from semaphore signal->
     wait edges, sound under ARBITRARY per-device compute skew); all
     semaphores drain to zero at schedule end; and the schedule cannot
     deadlock (greedy maximal simulation — semaphore-only programs are
     confluent: signals only produce and waits only consume, so if the
     eager schedule completes, every fair schedule completes).

Violations are one-line diagnostics naming hop/slot/semaphore, the house
style of ``coverage.py``/``contracts.py``.  The grant-less and
logical-id review bugs are kept alive as protocol variants
(:func:`grantless_protocol`, :func:`logical_id_protocol`) that the
negative regression tests feed back through the verifier.

The happens-before construction: a signal->wait edge is added only when
the wait CANNOT complete in any execution without that signal — for a
wait needing cumulative count C on a semaphore instance, a signal is
necessary iff the other signals that could still land before the wait
(those not already ordered after it) sum below C.  Adding an edge
shrinks "could still land" for other waits, so the rule iterates to a
fixpoint; the result under-approximates real ordering (sound: a race it
cannot exclude is reported).  Local tile-scoped pairs (``load_sem``,
``kv_sems`` — start and wait in the same tile) are proven by the
extraction cross-check and modeled as atomic; the circulated
``send/recv/grant/barrier`` semaphores carry the cross-device protocol
and are modeled exactly.

Like ``dataflow.py``: stdlib-only at module level; jax (and the kernel
module) import inside functions.  Extraction runs at trace level on any
backend — CPU with 8 virtual devices is the test tier.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from dataclasses import dataclass, field

from .dataflow import EqnSite, JaxprWalker

# The primitive surface the verifier accounts for (the fused contract's
# FUSED_RING_PRIMS minus ppermute, whose pin is "zero, anywhere").
SCHED_PRIMS = (
    "dma_start", "dma_wait", "semaphore_signal", "semaphore_wait",
    "get_barrier_semaphore",
)

# Ring sizes the model check proves (the ISSUE's 2..8), and the second
# mesh axis size used to prove MESH addressing resolves inside the
# sender's replica group.
VERIFY_RINGS = (2, 3, 4, 5, 6, 7, 8)
MESH_GROUPS = 2


def _protocol():
    from ..ops.pallas_ring import PROTOCOL

    return PROTOCOL


# ---------------------------------------------------------------------------
# Layer 1: jaxpr extraction
# ---------------------------------------------------------------------------

# Kernel invar order is fixed by fused_ring_remote's grid spec: 4 scalar-
# prefetch tables, the operands, 6 outputs (out/lse + the HBM working
# buffers), 9 scratch refs.  The quantized feed inserts the q-scale
# operand; payload parts take the k/v source roles.
_REF_NAMES_PLAIN = (
    "his", "los", "works", "nbrs", "q", "k_src", "v_src",
    "out", "lse", "kvbuf", "accb", "mb", "lb",
    "kvv", "acc", "m", "l",
    "load_sem", "kv_sems", "send_sem", "recv_sem", "grant_sem",
)
_REF_NAMES_Q8 = (
    "his", "los", "works", "nbrs", "q", "qs", "k_src", "v_src",
    "out", "lse", "kvbuf", "accb", "mb", "lb",
    "kvv", "acc", "m", "l",
    "load_sem", "kv_sems", "send_sem", "recv_sem", "grant_sem",
)


@dataclass(frozen=True)
class ExtractedOp:
    """One DMA/semaphore equation from the traced kernel, resolved."""

    kind: str               # primitive name
    path: str               # EqnSite path string inside the kernel
    bufs: tuple             # non-semaphore ref names, invar order
    sems: tuple             # semaphore ref names, invar order
    remote: bool            # carries device-id leaves in its param tree
    device_id_type: str     # "mesh" / "logical" / "" (local)
    lits: tuple             # integer literals in the invars (slot indices)

    def __str__(self) -> str:
        where = "remote" if self.remote else "local"
        return (f"{self.kind}[{where}] at {self.path} "
                f"bufs={list(self.bufs)} sems={list(self.sems)}")


class _ScheduleExtractor(JaxprWalker):
    """Threads kernel-invar identity (position tokens) through the
    kernel body so each DMA/semaphore equation's refs resolve to names.
    Refs pass through cond-branch seeding and while carries positionally
    (the base walker's descent), so their lattice values stay singleton
    tokens; the barrier semaphore is the one ref born inside the kernel
    and gets its own token at the ``get_barrier_semaphore`` site."""

    def __init__(self, names: tuple):
        super().__init__()
        self.names = names
        self.ops: dict = {}  # (path, idx) -> ExtractedOp

    def pallas_kernel_env(self, body, eqn) -> dict:
        env = {}
        for i, v in enumerate(body.invars):
            env[v] = frozenset({i})
        for v in body.constvars:
            env[v] = frozenset()
        return env

    def transfer(self, eqn, in_vals, site):
        if eqn.primitive.name == "get_barrier_semaphore":
            return [frozenset({"barrier"}) for _ in eqn.outvars]
        return super().transfer(eqn, in_vals, site)

    def _name(self, val) -> str:
        if isinstance(val, frozenset) and len(val) == 1:
            tok = next(iter(val))
            if tok == "barrier":
                return "barrier"
            if isinstance(tok, int) and tok < len(self.names):
                return self.names[tok]
        return "?"

    def visit(self, eqn, in_vals, out_vals, site: EqnSite) -> None:
        if eqn.primitive.name not in SCHED_PRIMS:
            return
        if not any(p.startswith("pallas_call#") for p in site.path):
            return
        key = (site.path, site.index)
        if key in self.ops:
            return  # fixpoint sweeps revisit loop bodies
        import jax

        bufs, sems, lits = [], [], []
        for atom, val in zip(eqn.invars, in_vals):
            aval_s = str(getattr(atom, "aval", ""))
            if "MemRef" in aval_s:
                (sems if "sem" in aval_s else bufs).append(self._name(val))
            elif isinstance(atom, jax.core.Literal):
                try:
                    lits.append(int(atom.val))
                except (TypeError, ValueError):
                    pass
        tree = eqn.params.get("tree", eqn.params.get("args_tree", ""))
        remote = "(*, *)" in str(tree)
        dit = str(eqn.params.get("device_id_type", "")).lower()
        dit = ("mesh" if "mesh" in dit
               else "logical" if "logical" in dit else "")
        self.ops[key] = ExtractedOp(
            kind=eqn.primitive.name, path=str(site),
            bufs=tuple(bufs), sems=tuple(sems), remote=remote,
            device_id_type=dit, lits=tuple(lits),
        )


def extract_fused_schedule(*, quantized: bool = False) -> list[ExtractedOp]:
    """Trace ``fused_ring_remote`` under ``shard_map`` on the full-device
    CPU ring (the contract trace) and resolve every in-kernel
    DMA/semaphore equation to named buffers and semaphores.  Needs the
    simulated multi-device backend
    (``--xla_force_host_platform_device_count``); make_jaxpr only."""
    from . import contracts

    jaxpr, _ = contracts.trace_fused_ring(quantized=quantized)
    ex = _ScheduleExtractor(_REF_NAMES_Q8 if quantized else _REF_NAMES_PLAIN)
    ex.run(jaxpr)
    return [ex.ops[k] for k in sorted(ex.ops)]


# ---------------------------------------------------------------------------
# Layer 2: cross-check against the declared PROTOCOL
# ---------------------------------------------------------------------------


def _row_signatures(row) -> list[tuple]:
    """The (kind, bufs, sems, remote) equation signatures a row accounts
    for — what the extractor's resolved ops are matched against."""
    op = row["op"]
    if op == "copy":
        sig = ((row["src"], row["dst"]), (row["sem"],), False)
        return [("dma_start",) + sig, ("dma_wait",) + sig]
    if op == "remote_copy":
        bufs = (row["src"], row["dst"])
        return [  # the lowered equation does not pin sem operand order
            ("dma_start", bufs, (row["send_sem"], row["recv_sem"]), True),
            ("dma_start", bufs, (row["recv_sem"], row["send_sem"]), True),
        ]
    if op == "remote_drain":
        bufs = ("kvbuf", "kvbuf")
        return [  # each drained descriptor waits send AND recv; the
                  # waited semaphore leads the equation's sem operands
            ("dma_wait", bufs, (row["send_sem"], row["recv_sem"]), True),
            ("dma_wait", bufs, (row["recv_sem"], row["send_sem"]), True),
        ]
    if op == "barrier":
        return [
            ("get_barrier_semaphore", (), (), False),
            ("semaphore_signal", (), (row["sem"],), True),
            ("semaphore_wait", (), (row["sem"],), False),
        ]
    if op == "sem_signal":
        return [("semaphore_signal", (), (row["sem"],), True)]
    if op == "sem_wait":
        return [("semaphore_wait", (), (row["sem"],), False)]
    raise ValueError(f"unknown protocol op {op!r}")


def crosscheck_protocol(ops: list, protocol=None,
                        label: str = "fused_ring") -> list[str]:
    """Hold the extracted equations to the declared table: every op must
    match a row's signature, every row's per-kind site count must match
    what the trace contains, and every remote op must address by MESH
    coordinates.  One-line violations, empty = the trace IS the table."""
    protocol = _protocol() if protocol is None else protocol
    sig2row = {}
    for row in protocol:
        for sig in _row_signatures(row):
            sig2row[sig] = row["row"]
    observed: Counter = Counter()
    out: list[str] = []
    for op in ops:
        if op.remote and op.device_id_type != "mesh":
            out.append(
                f"{label}: {op.kind} at {op.path} uses "
                f"DeviceIdType.{op.device_id_type.upper() or '?'} — remote "
                f"DMA/semaphore ops must address by per-axis MESH "
                f"coordinates (a ring-rank LOGICAL id targets the wrong "
                f"replica group on multi-axis meshes) [rule: dma-device-id]"
            )
        row = sig2row.get((op.kind, op.bufs, op.sems, op.remote))
        if row is None:
            out.append(
                f"{label}: {op} matches no PROTOCOL row — undeclared "
                f"DMA/semaphore site [rule: protocol-coverage]"
            )
            continue
        observed[(row, op.kind)] += 1
    for row in protocol:
        for kind, want in row["sites"].items():
            got = observed.pop((row["row"], kind), 0)
            if got != want:
                out.append(
                    f"{label}: protocol row {row['row']}: {kind} x{got} in "
                    f"the traced kernel, table declares {want} "
                    f"[rule: protocol-sites]"
                )
    for (row, kind), got in sorted(observed.items()):
        out.append(
            f"{label}: protocol row {row}: {kind} x{got} traced beyond the "
            f"declared sites [rule: protocol-sites]"
        )
    return out


def derived_fused_counts(protocol=None) -> dict[str, int]:
    """The fused contract's expected primitive counts, derived from the
    PROTOCOL table's ``sites`` fields (plus the zero-ppermute pin) — the
    hand-pinned numbers this replaces can no longer drift from the
    verified schedule."""
    protocol = _protocol() if protocol is None else protocol
    counts = {k: 0 for k in SCHED_PRIMS}
    for row in protocol:
        for kind, n in row["sites"].items():
            counts[kind] += n
    counts["ppermute"] = 0
    return counts


# ---------------------------------------------------------------------------
# Layer 3: the N-device model check
# ---------------------------------------------------------------------------


@dataclass
class _Ev:
    """One schedule event.  ``at`` is the device whose semaphore
    instance / buffer the event touches (== ``dev`` except for remote
    signals and DMA landings); ``frm`` names a write's sender."""

    i: int
    dev: tuple
    hop: int
    kind: str           # "sig" | "wait" | "start" | "write" | "read" | "local"
    sem: str = ""
    at: tuple = ()
    inc: int = 0
    need: int = 0
    slot: int = -1
    row: str = ""
    frm: tuple = ()


@dataclass
class _Schedule:
    ring: int
    groups: int
    hops: int
    evs: list = field(default_factory=list)
    po: dict = field(default_factory=dict)      # dev -> [event ids]
    edges: list = field(default_factory=list)   # async HB edges (a, b)
    spawn: dict = field(default_factory=dict)   # start id -> [async ids]
    reads: list = field(default_factory=list)   # (dev, slot, begin, end, hop, row)
    static: list = field(default_factory=list)  # expansion-time violations

    @property
    def tag(self) -> str:
        return (f"ring={self.ring}" if self.groups == 1
                else f"ring={self.ring}x{self.groups}")

    def dev_str(self, dev: tuple) -> str:
        return str(dev[1]) if self.groups == 1 else f"{dev[0]}.{dev[1]}"


def _guard(expr: str, hop: int, hops: int) -> bool:
    return bool(eval(expr, {"__builtins__": {}}, {"hop": hop, "hops": hops}))


def _slot(expr: str, hop: int, hops: int) -> int:
    return int(eval(expr, {"__builtins__": {}}, {"hop": hop, "hops": hops}))


def _expand(protocol, ring: int, groups: int = 1) -> _Schedule:
    """Per-device, per-hop event lists from the protocol table, in table
    (== kernel program) order; remote copies spawn their async
    completions (send signal, landing write, recv signal) off program
    order.  Logical-id rows resolve their target the way the bug did —
    the ring-rank index linearized over the WHOLE mesh — and flag the
    replica-group escape statically."""
    hops = ring
    sched = _Schedule(ring=ring, groups=groups, hops=hops)
    flagged = set()

    def add(dev, hop, kind, **kw):
        ev = _Ev(len(sched.evs), dev, hop, kind, **kw)
        sched.evs.append(ev)
        return ev

    def target(dev, to, row, hop):
        g, r = dev
        delta = -1 if to == "left" else 1
        mesh_t = (g, (r + delta) % ring)
        if row.get("addressing", "mesh") != "logical":
            return mesh_t
        flat = (r + delta) % ring  # ring-rank id over the FULL mesh
        logical_t = (flat // ring, flat % ring)
        if logical_t != mesh_t and (row["row"], dev) not in flagged:
            flagged.add((row["row"], dev))
            sched.static.append(
                f"{sched.tag}: hop {hop} {row['row']}: push from device "
                f"{sched.dev_str(dev)} addresses logical ring-rank id "
                f"{flat} = device {sched.dev_str(logical_t)} — outside its "
                f"replica group (per-axis MESH coordinates required) "
                f"[rule: dma-device-id]"
            )
        return logical_t

    for g in range(groups):
        for r in range(ring):
            dev = (g, r)
            order = sched.po.setdefault(dev, [])
            for hop in range(hops):
                for row in protocol:
                    if not _guard(row["guard"], hop, hops):
                        continue
                    op, rid = row["op"], row["row"]
                    if op == "copy":
                        if row.get("src") == "kvbuf" and row["src_slot"]:
                            ev = add(dev, hop, "read", at=dev, row=rid,
                                     slot=_slot(row["src_slot"], hop, hops))
                            order.append(ev.i)
                            sched.reads.append(
                                (dev, ev.slot, ev.i, ev.i, hop, rid))
                        elif row.get("dst") == "kvbuf" and row["dst_slot"]:
                            ev = add(dev, hop, "write", at=dev, frm=dev,
                                     row=rid,
                                     slot=_slot(row["dst_slot"], hop, hops))
                            order.append(ev.i)
                        else:
                            order.append(add(dev, hop, "local", row=rid).i)
                    elif op == "remote_copy":
                        tgt = target(dev, row["to"], row, hop)
                        s = add(dev, hop, "start", row=rid)
                        order.append(s.i)
                        snd = add(dev, hop, "sig", sem=row["send_sem"],
                                  at=dev, inc=1, row=rid)
                        wrt = add(dev, hop, "write", at=tgt, frm=dev,
                                  row=rid,
                                  slot=_slot(row["dst_slot"], hop, hops))
                        rcv = add(dev, hop, "sig", sem=row["recv_sem"],
                                  at=tgt, inc=1, row=rid)
                        sched.spawn[s.i] = [snd.i, wrt.i, rcv.i]
                        sched.edges += [(s.i, snd.i), (s.i, wrt.i),
                                        (wrt.i, rcv.i)]
                        # the outbound copy READS the source slot until
                        # the send semaphore fires
                        sched.reads.append(
                            (dev, _slot(row["src_slot"], hop, hops),
                             s.i, snd.i, hop, rid))
                    elif op == "remote_drain":
                        for sem in (row["send_sem"], row["recv_sem"]):
                            ev = add(dev, hop, "wait", sem=sem, at=dev,
                                     need=1, row=rid)
                            order.append(ev.i)
                    elif op == "barrier":
                        for to in row["signal_to"]:
                            tgt = target(dev, to, row, hop)
                            order.append(add(dev, hop, "sig", sem=row["sem"],
                                             at=tgt, inc=row["inc"],
                                             row=rid).i)
                        order.append(add(dev, hop, "wait", sem=row["sem"],
                                         at=dev, need=row["value"],
                                         row=rid).i)
                    elif op == "sem_signal":
                        tgt = target(dev, row["to"], row, hop)
                        order.append(add(dev, hop, "sig", sem=row["sem"],
                                         at=tgt, inc=row["inc"], row=rid).i)
                    elif op == "sem_wait":
                        order.append(add(dev, hop, "wait", sem=row["sem"],
                                         at=dev, need=row["value"],
                                         row=rid).i)
    return sched


def _check_matched(sched: _Schedule) -> list[str]:
    """Every dma_start has a matching wait on both ends: per semaphore
    instance, total signaled == total waited (send side on the sender,
    recv side on the landing device, grant/barrier handshakes even)."""
    inc: Counter = Counter()
    need: Counter = Counter()
    for e in sched.evs:
        if e.kind == "sig":
            inc[(e.at, e.sem)] += e.inc
        elif e.kind == "wait":
            need[(e.at, e.sem)] += e.need
    out = []
    for dev, sem in sorted(set(inc) | set(need)):
        a, b = inc[(dev, sem)], need[(dev, sem)]
        if a != b:
            out.append(
                f"{sched.tag}: {sem} on device {sched.dev_str(dev)}: {a} "
                f"signal(s) against {b} wait(s) — every dma_start/signal "
                f"needs a matching wait on both ends "
                f"[rule: dma-matched-wait]"
            )
    return out


def _simulate(sched: _Schedule) -> list[str]:
    """Greedy maximal execution: deadlock freedom (if the eager schedule
    completes, every fair schedule does — signals only produce, waits
    only consume, no shared-token conflicts) plus end-state semaphore
    drain."""
    sem: Counter = Counter()
    ptr = {d: 0 for d in sched.po}

    def fire(ev):
        if ev.kind == "sig":
            sem[(ev.at, ev.sem)] += ev.inc
        for a in sched.spawn.get(ev.i, ()):
            fire(sched.evs[a])  # eager async completion

    progress = True
    while progress:
        progress = False
        for dev, order in sched.po.items():
            while ptr[dev] < len(order):
                ev = sched.evs[order[ptr[dev]]]
                if ev.kind == "wait":
                    if sem[(ev.at, ev.sem)] < ev.need:
                        break
                    sem[(ev.at, ev.sem)] -= ev.need
                fire(ev)
                ptr[dev] += 1
                progress = True
    out = []
    stuck = {d: o[ptr[d]] for d, o in sched.po.items() if ptr[d] < len(o)}
    for dev in sorted(stuck):
        ev = sched.evs[stuck[dev]]
        out.append(
            f"{sched.tag}: deadlock — device {sched.dev_str(dev)} stuck at "
            f"hop {ev.hop} {ev.row} waiting {ev.sem} (have "
            f"{sem[(ev.at, ev.sem)]}, need {ev.need}) [rule: ring-deadlock]"
        )
    if not stuck:
        for (dev, s), c in sorted(sem.items()):
            if c:
                out.append(
                    f"{sched.tag}: semaphore {s} on device "
                    f"{sched.dev_str(dev)} drains to {c}, not 0 — "
                    f"unconsumed signal at schedule end "
                    f"[rule: semaphore-drain]"
                )
    return out


def _closure(n: int, succ: list) -> list | None:
    """Transitive-closure bitmasks over a DAG (None on a cycle)."""
    indeg = [0] * n
    for v in range(n):
        for u in succ[v]:
            indeg[u] += 1
    order, head = [v for v in range(n) if not indeg[v]], 0
    while head < len(order):
        v = order[head]
        head += 1
        for u in succ[v]:
            indeg[u] -= 1
            if not indeg[u]:
                order.append(u)
    if len(order) < n:
        return None
    reach = [0] * n
    for v in reversed(order):
        m = 1 << v
        for u in succ[v]:
            m |= reach[u]
        reach[v] = m
    return reach


def _happens_before(sched: _Schedule):
    """The guaranteed happens-before relation: program order + DMA
    spawn/landing edges, plus signal->wait edges iterated to a fixpoint
    (an edge exists iff the wait cannot complete in any execution
    without that signal).  Returns (reach bitmasks, cycle flag)."""
    n = len(sched.evs)
    succ = [[] for _ in range(n)]
    for order in sched.po.values():
        for a, b in zip(order, order[1:]):
            succ[a].append(b)
    for a, b in sched.edges:
        succ[a].append(b)

    waits_by = defaultdict(list)
    sigs_by = defaultdict(list)
    for order in sched.po.values():
        for i in order:
            ev = sched.evs[i]
            if ev.kind == "wait":
                waits_by[(ev.at, ev.sem)].append(ev)
    for ev in sched.evs:
        if ev.kind == "sig":
            sigs_by[(ev.at, ev.sem)].append(ev)

    have = set()
    while True:
        reach = _closure(n, succ)
        if reach is None:
            return None, True
        added = False
        for key, waits in waits_by.items():
            sigs = sigs_by.get(key, ())
            cum = 0
            for w in waits:
                cum += w.need
                for s in sigs:
                    if (s.i, w.i) in have:
                        continue
                    avail = sum(
                        s2.inc for s2 in sigs
                        if s2.i != s.i and not (reach[w.i] >> s2.i) & 1
                    )
                    if avail < cum:
                        succ[s.i].append(w.i)
                        have.add((s.i, w.i))
                        added = True
        if not added:
            return reach, False


def _check_races(sched: _Schedule) -> list[str]:
    """No kvbuf slot is written while a concurrent reader holds it: for
    every (write, read-interval) and (write, write) pair on the same
    device and slot, the guaranteed happens-before graph must order one
    side fully before the other."""
    reach, cyclic = _happens_before(sched)
    if cyclic:
        return [f"{sched.tag}: happens-before graph is cyclic — the "
                f"wait-for relation cannot be acyclic [rule: ring-deadlock]"]
    out = []
    writes = [e for e in sched.evs if e.kind == "write"]
    before = lambda a, b: bool((reach[a] >> b) & 1)
    for w in writes:
        for dev, slot, begin, end, hop, rid in sched.reads:
            if dev != w.at or slot != w.slot or w.i in (begin, end):
                continue
            if not (before(w.i, begin) or before(end, w.i)):
                out.append(
                    f"{sched.tag}: kvbuf slot {slot} on device "
                    f"{sched.dev_str(dev)} written at hop {w.hop} (push "
                    f"from device {sched.dev_str(w.frm)}) while the "
                    f"hop-{hop} {rid} read holds it — no happens-before "
                    f"edge orders them [rule: slot-overwrite-race]"
                )
        for w2 in writes:
            if (w2.i <= w.i or w2.at != w.at or w2.slot != w.slot):
                continue
            if not (before(w.i, w2.i) or before(w2.i, w.i)):
                out.append(
                    f"{sched.tag}: kvbuf slot {w.slot} on device "
                    f"{sched.dev_str(w.at)} written concurrently at hops "
                    f"{w.hop} and {w2.hop} (from devices "
                    f"{sched.dev_str(w.frm)}, {sched.dev_str(w2.frm)}) "
                    f"[rule: slot-overwrite-race]"
                )
    return out


def verify_ring(protocol=None, *, ring: int, groups: int = 1) -> list[str]:
    """Model-check one composed schedule; one-line violations."""
    protocol = _protocol() if protocol is None else protocol
    sched = _expand(protocol, ring, groups)
    out = list(sched.static)
    out += _check_matched(sched)
    out += _simulate(sched)
    out += _check_races(sched)
    return list(dict.fromkeys(out))


def verify_protocol(protocol=None, *, rings=VERIFY_RINGS,
                    mesh_groups: int = MESH_GROUPS) -> list[str]:
    """The full proof: every ring size on the bare ring AND on a
    ``mesh_groups``-wide multi-axis mesh (replica-group isolation of the
    MESH addressing).  Empty = grant balance, no overwrite-before-read,
    semaphore drain, and deadlock freedom all hold."""
    protocol = _protocol() if protocol is None else protocol
    out: list[str] = []
    for ring in rings:
        out += verify_ring(protocol, ring=ring, groups=1)
        out += verify_ring(protocol, ring=ring, groups=mesh_groups)
    return list(dict.fromkeys(out))


# ---------------------------------------------------------------------------
# The PR-18 review bugs, kept alive as protocol variants
# ---------------------------------------------------------------------------


def grantless_protocol():
    """Review bug #1: the push WITHOUT the receiver->sender grant.  A
    one-hop compute skew (guaranteed under causal works schedules) lets
    hop i+1's incoming DMA overwrite the slot hop i is still reading —
    the verifier reports the overwrite race at every ring size >= 3."""
    return tuple(r for r in _protocol()
                 if r["row"] not in ("push-grant", "grant"))


def logical_id_protocol():
    """Review bug #2: the push addressed by ring-rank LOGICAL device id.
    Correct on a bare ring (group 0 IS the mesh), wrong the moment the
    mesh grows a second axis: every replica outside group 0 pushes its
    KV into group 0's buffers — the verifier reports the replica-group
    escape, the orphaned recv waits, and the resulting deadlock."""
    return tuple(
        {**r, "addressing": "logical"} if r["row"] == "push-kv" else r
        for r in _protocol()
    )


# ---------------------------------------------------------------------------
# Suite / fingerprint entry points
# ---------------------------------------------------------------------------


def run_schedverify_suite(*, feeds=(False, True)) -> list[tuple[str, list]]:
    """The full verifier, house-suite shaped (``(name, violations)``
    rows): the N-device model check over rings 2..8 (bare + 2-group
    mesh), then the jaxpr extraction cross-check for the plain and q8
    feeds.  Extraction needs the simulated multi-device backend; the
    model check is pure python."""
    checks: list[tuple[str, list]] = [(
        f"schedverify: protocol model (rings "
        f"{VERIFY_RINGS[0]}-{VERIFY_RINGS[-1]}, mesh x{MESH_GROUPS})",
        verify_protocol(),
    )]
    for quantized in feeds:
        feed = "q8" if quantized else "plain"
        label = f"fused_ring_{feed}" if quantized else "fused_ring"
        ops = extract_fused_schedule(quantized=quantized)
        checks.append((
            f"schedverify: jaxpr extraction ({feed}, {len(ops)} ops)",
            crosscheck_protocol(ops, label=label),
        ))
    return checks


def protocol_fingerprint() -> dict:
    """The exact-gated perfgate family: derived primitive counts, table
    size, per-ring model event counts, total violations (0 on a healthy
    tree), and per-feed extracted-op counts.  Deterministic — any edit
    to the kernel's hop schedule or the PROTOCOL table moves it."""
    protocol = _protocol()
    fp: dict = {
        "counts": derived_fused_counts(protocol),
        "rows": len(protocol),
        "rings": {},
        "violations": 0,
    }
    for ring in VERIFY_RINGS:
        sched = _expand(protocol, ring, 1)
        fp["rings"][f"ring{ring}"] = len(sched.evs)
    for name, violations in run_schedverify_suite():
        fp["violations"] += len(violations)
    for quantized in (False, True):
        ops = extract_fused_schedule(quantized=quantized)
        fp["q8_ops" if quantized else "plain_ops"] = len(ops)
    return fp
