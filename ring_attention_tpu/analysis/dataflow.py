"""Jaxpr abstract interpretation: the dataflow layer under the audit passes.

PR 5's audits are spot checks — ``audit_accumulator_dtypes`` eval_shapes
two entry points and trusts that the rest of the package holds the same
discipline, and the contracts checker's no-collective-under-cond rule is a
*syntactic* ban rather than the actual SPMD requirement (branches may
collectively communicate, as long as every rank communicates the SAME
way).  This module adds the missing machinery: a small worklist walker
over closed jaxprs (:class:`JaxprWalker`) that descends into
scan/while/cond/pjit/custom-vjp/remat/shard_map/pallas sub-jaxprs, carries
a per-variable lattice value to a fixpoint through loop carries, and keeps
an equation provenance path so every finding is a one-line diagnostic
naming where in the program the violation sits.

Two passes ride on it:

  - :func:`audit_precision_flow` — the precision-flow auditor
    generalizing ``audit_accumulator_dtypes``: quantized-int8 content is
    tracked as taint through the whole program while every
    reduction/dot/carry site is checked against its storage dtype, and a
    violation is raised when (a) a float reduction / exponential / dot
    accumulation executes at sub-f32 storage (the softmax
    ``(acc, m, l)``/lse/delta contract), (b) a scan/while carry
    accumulates arithmetic results at sub-f32 storage, or (c) a
    quantized int8 payload reaches accumulation without its
    dequantization scale multiply (the int8 hop-compression contract,
    TokenRing lineage, arXiv 2412.20501).
  - :func:`check_spmd_divergence` — the SPMD divergence checker: for
    every ``lax.cond`` the *collective sequence* (kind, axes, operand
    shape/dtype, in order, scan-aware) must be identical across all
    branches, and no ``lax.while_loop`` body may communicate at all
    (its trip count is rank-local, so no sequence can be proven) — the
    proof-level upgrade of the lint/contract heuristics: no rank can
    deadlock waiting for a collective another rank never issues.

Like ``recompile.py``, this module is stdlib-only at module level; jax
imports live inside functions.  Everything runs at trace level (``jax.
make_jaxpr``) — no compile, no devices, any backend.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

# ---------------------------------------------------------------------------
# The walker
# ---------------------------------------------------------------------------

# Primitive param keys that hold sub-jaxprs, by descent style.  Anything
# not listed falls back to the conservative generic descent (operands
# joined into every sub-jaxpr input).
_SCAN, _WHILE, _COND, _CALL, _PALLAS = "scan", "while", "cond", "call", "pallas"

_DESCENT_STYLE = {
    "scan": _SCAN,
    "while": _WHILE,
    "cond": _COND,
    "pjit": _CALL,
    "closed_call": _CALL,
    "core_call": _CALL,
    "remat2": _CALL,
    "checkpoint": _CALL,
    "custom_jvp_call": _CALL,
    "custom_vjp_call": _CALL,
    "custom_jvp_call_jaxpr": _CALL,
    "custom_vjp_call_jaxpr": _CALL,
    "shard_map": _CALL,
    "pallas_call": _PALLAS,
}

# Max fixpoint sweeps through a loop body.  The lattices used here are
# tiny finite joins (taint tag sets), so 2-3 sweeps converge; the cap is
# a backstop against a non-monotone custom transfer, never a correctness
# input (the walker joins, so an early stop under-reports rather than
# crashes).
_MAX_FIXPOINT_SWEEPS = 8


@dataclass(frozen=True)
class EqnSite:
    """Provenance of one equation: the enclosing-primitive path plus the
    equation's own primitive and output signature — enough to name the
    offending operation in one line without a traceback."""

    path: tuple[str, ...]
    prim: str
    index: int
    out_aval: str

    def __str__(self) -> str:
        where = "/".join(self.path) or "top"
        return f"{where}::{self.prim}#{self.index} -> {self.out_aval}"


def _aval_str(var) -> str:
    aval = getattr(var, "aval", None)
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dtype is None:
        return "?"
    return f"{dtype}{list(shape) if shape is not None else ''}"


def _inner_aval(aval):
    """The value aval behind a pallas/state Ref aval (identity otherwise)."""
    return getattr(aval, "inner_aval", aval)


def _sub_closed_jaxprs(value):
    """Yield every (Closed)Jaxpr nested in a params value."""
    import jax

    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            stack.extend(v)


def _as_jaxpr(value):
    import jax

    if isinstance(value, jax.core.ClosedJaxpr):
        return value.jaxpr
    return value


class JaxprWalker:
    """Forward abstract interpretation over a closed jaxpr.

    Subclasses define the lattice: :meth:`init_value` (an input/constant
    variable's starting value, from its aval), :meth:`join`, and
    :meth:`transfer` (equation semantics -> output values).  The base
    class owns the structure: environment threading, descent into
    control-flow sub-jaxprs (scan/while carries run to a join fixpoint;
    cond branches are walked independently and joined), conservative
    descent into unknown sub-jaxpr-carrying primitives, and provenance
    (:class:`EqnSite`) for every visited equation.  :meth:`visit` is the
    hook passes use to emit findings.
    """

    def __init__(self) -> None:
        self.findings: list[str] = []

    # -- lattice interface -------------------------------------------------
    def init_value(self, aval) -> Any:
        return frozenset()

    def join(self, a, b):
        return a | b

    def transfer(self, eqn, in_vals, site: EqnSite) -> list[Any]:
        joined = self.init_value(None)
        for v in in_vals:
            joined = self.join(joined, v)
        return [joined for _ in eqn.outvars]

    def visit(self, eqn, in_vals, out_vals, site: EqnSite) -> None:
        """Pass hook, called once per equation (per fixpoint sweep —
        emit findings idempotently; the base class dedups)."""

    # -- environment helpers ----------------------------------------------
    def _read(self, env, atom):
        import jax

        if isinstance(atom, jax.core.Literal):
            return self.init_value(getattr(atom, "aval", None))
        if atom in env:
            return env[atom]
        return self.init_value(atom.aval)

    def _write(self, env, var, val) -> None:
        env[var] = self.join(env.get(var, self.init_value(var.aval)), val)

    # -- the walk ----------------------------------------------------------
    def run(self, closed_jaxpr, label: str = "") -> list[str]:
        jaxpr = _as_jaxpr(closed_jaxpr)
        env: dict = {}
        for v in list(jaxpr.invars) + list(jaxpr.constvars):
            env[v] = self.init_value(v.aval)
        self._walk(jaxpr, env, path=())
        # findings are emitted per sweep; keep first occurrence order
        self.findings = list(dict.fromkeys(self.findings))
        if label:
            self.findings = [f"{label}: {f}" for f in self.findings]
        return self.findings

    def _seed(self, jaxpr, in_vals) -> dict:
        env: dict = {}
        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = val
        for var in jaxpr.constvars:
            env[var] = self.init_value(var.aval)
        return env

    def _walk(self, jaxpr, env, path) -> list[Any]:
        for idx, eqn in enumerate(jaxpr.eqns):
            in_vals = [self._read(env, a) for a in eqn.invars]
            site = EqnSite(
                path, eqn.primitive.name, idx,
                _aval_str(eqn.outvars[0]) if eqn.outvars else "()",
            )
            style = _DESCENT_STYLE.get(eqn.primitive.name)
            if style == _SCAN:
                out_vals = self._walk_scan(eqn, in_vals, path, idx)
            elif style == _WHILE:
                out_vals = self._walk_while(eqn, in_vals, path, idx)
            elif style == _COND:
                out_vals = self._walk_cond(eqn, in_vals, path, idx)
            elif style == _CALL:
                out_vals = self._walk_call(eqn, in_vals, path, idx)
            elif style == _PALLAS:
                out_vals = self._walk_pallas(eqn, in_vals, path, idx)
            else:
                out_vals = self._walk_generic(eqn, in_vals, path, idx, site)
            self.visit(eqn, in_vals, out_vals, site)
            for var, val in zip(eqn.outvars, out_vals):
                self._write(env, var, val)
            self.post_eqn(env, eqn, in_vals, out_vals)
        return [self._read(env, a) for a in jaxpr.outvars]

    def post_eqn(self, env, eqn, in_vals, out_vals) -> None:
        """Post-write hook (e.g. ref-mutation semantics for pallas/state
        primitives — the environment is mutable here)."""

    def _walk_scan(self, eqn, in_vals, path, idx):
        body = _as_jaxpr(eqn.params["jaxpr"])
        n_consts = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        length = int(eqn.params["length"])
        consts = in_vals[:n_consts]
        carry = list(in_vals[n_consts:n_consts + n_carry])
        xs = in_vals[n_consts + n_carry:]
        sub_path = path + (f"scan[{length}]#{idx}",)
        ys = [self.init_value(v.aval) for v in body.outvars[n_carry:]]
        for _ in range(_MAX_FIXPOINT_SWEEPS):
            env = self._seed(body, consts + carry + xs)
            outs = self._walk(body, env, sub_path)
            new_carry = [self.join(c, o) for c, o in zip(carry, outs[:n_carry])]
            ys = [self.join(y, o) for y, o in zip(ys, outs[n_carry:])]
            if new_carry == carry:
                break
            carry = new_carry
        self._check_loop_carries(eqn, body, carry, n_consts, n_carry,
                                 sub_path, kind="scan")
        return carry + ys

    def _walk_while(self, eqn, in_vals, path, idx):
        cond = _as_jaxpr(eqn.params["cond_jaxpr"])
        body = _as_jaxpr(eqn.params["body_jaxpr"])
        nc = eqn.params["cond_nconsts"]
        nb = eqn.params["body_nconsts"]
        cond_consts = in_vals[:nc]
        body_consts = in_vals[nc:nc + nb]
        carry = list(in_vals[nc + nb:])
        sub_path = path + (f"while#{idx}",)
        for _ in range(_MAX_FIXPOINT_SWEEPS):
            env = self._seed(cond, cond_consts + carry)
            self._walk(cond, env, sub_path + ("cond",))
            env = self._seed(body, body_consts + carry)
            outs = self._walk(body, env, sub_path + ("body",))
            new_carry = [self.join(c, o) for c, o in zip(carry, outs)]
            if new_carry == carry:
                break
            carry = new_carry
        self._check_loop_carries(eqn, body, carry, nb, len(carry),
                                 sub_path, kind="while", body_offset=nb)
        return carry

    def _check_loop_carries(self, eqn, body, carry_vals, n_consts, n_carry,
                            sub_path, kind, body_offset=None):
        """Hook for carry-level checks (the precision pass overrides)."""

    def _walk_cond(self, eqn, in_vals, path, idx):
        ops = in_vals[1:]
        out_vals = None
        for bi, branch in enumerate(eqn.params["branches"]):
            body = _as_jaxpr(branch)
            env = self._seed(body, ops)
            outs = self._walk(body, env, path + (f"cond#{idx}/branch{bi}",))
            if out_vals is None:
                out_vals = list(outs)
            else:
                out_vals = [self.join(a, b) for a, b in zip(out_vals, outs)]
        return out_vals or []

    def _walk_call(self, eqn, in_vals, path, idx):
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in eqn.params:
                body = _as_jaxpr(eqn.params[key])
                break
        else:
            return self._walk_generic(eqn, in_vals, path, idx, None)
        if len(body.invars) != len(in_vals):
            return self._walk_generic(eqn, in_vals, path, idx, None)
        env = self._seed(body, in_vals)
        outs = self._walk(env=env, jaxpr=body,
                          path=path + (f"{eqn.primitive.name}#{idx}",))
        if len(outs) == len(eqn.outvars):
            return outs
        joined = self.init_value(None)
        for o in outs:
            joined = self.join(joined, o)
        return [joined for _ in eqn.outvars]

    def _walk_pallas(self, eqn, in_vals, path, idx):
        """Kernel descent: the grid machinery between the outer operands
        and the kernel's refs is layout-dependent across jax versions, so
        the kernel jaxpr is re-seeded from its OWN ref avals (dtype-exact
        — precisely what a dtype/taint lattice needs) rather than mapped
        positionally; outer outputs re-seed from their avals likewise.
        The seeding itself is a hook (:meth:`pallas_kernel_env`) so a
        pass that needs ref IDENTITY rather than ref dtype — the
        schedule extractor threads each kernel invar's POSITION through
        cond branches and while carries to name the buffer/semaphore
        behind every DMA equation — can override just the environment."""
        body = None
        for key in ("jaxpr", "kernel_jaxpr"):
            if key in eqn.params:
                body = _as_jaxpr(eqn.params[key])
                break
        if body is not None:
            env = self.pallas_kernel_env(body, eqn)
            self._walk(body, env, path + (f"pallas_call#{idx}",))
        return [self.init_value(v.aval) for v in eqn.outvars]

    def pallas_kernel_env(self, body, eqn) -> dict:
        """Initial environment for a pallas kernel body.  Default: every
        ref invar/constvar starts at ``init_value`` of its inner aval."""
        return {
            v: self.init_value(_inner_aval(v.aval))
            for v in list(body.invars) + list(body.constvars)
        }

    def _walk_generic(self, eqn, in_vals, path, idx, site):
        """Default: apply the transfer function; conservatively descend
        into any nested jaxprs with every input joined (sound for a
        union lattice — over-approximates, never drops, taint)."""
        subs = []
        for v in eqn.params.values():
            subs.extend(_sub_closed_jaxprs(v))
        if subs:
            joined = self.init_value(None)
            for val in in_vals:
                joined = self.join(joined, val)
            for sub in subs:
                env = {
                    var: self.join(joined, self.init_value(var.aval))
                    for var in list(sub.invars) + list(sub.constvars)
                }
                self._walk(sub, env, path + (f"{eqn.primitive.name}#{idx}",))
        return self.transfer(
            eqn, in_vals,
            site or EqnSite(path, eqn.primitive.name, idx,
                            _aval_str(eqn.outvars[0]) if eqn.outvars else "()"),
        )


# ---------------------------------------------------------------------------
# Pass 1: precision flow
# ---------------------------------------------------------------------------

# The one flow-sensitive tag: INT8Q marks quantized content that has not
# yet met its dequantization scale.  Sub-f32 precision needs no taint —
# a bf16 INPUT is fine and expected; the violation is a reduction /
# accumulation EXECUTING at sub-f32 storage, which the sinks and carry
# checks read straight off the avals at the site.
INT8Q = "int8-quantized"

# Reductions/exponentials on the softmax-accumulator path: executing one
# at sub-f32 storage is the contract violation audit_accumulator_dtypes
# spot-checked for (acc, m, l) and this pass proves everywhere.
_REDUCTION_SINKS = {
    "reduce_sum", "reduce_max", "reduce_min", "cumsum", "cumlogsumexp",
    "exp", "exp2", "log", "log1p", "logistic",
}

# Arithmetic that constitutes "accumulation" for carry/backtrace checks.
_ARITH_PRIMS = {
    "add", "sub", "mul", "div", "dot_general", "max", "min", "exp", "exp2",
    "log", "integer_pow", "pow", "rsqrt", "sqrt", "tanh", "reduce_sum",
    "reduce_max", "reduce_min", "cumsum",
}

# Structure-only primitives: taint flows through, no arithmetic happened.
_TRANSPARENT_PRIMS = {
    "transpose", "reshape", "broadcast_in_dim", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate", "rev",
    "gather", "scatter", "pad", "select_n", "copy", "stop_gradient",
    "convert_element_type", "ppermute", "all_to_all", "all_gather",
    "all_gather_invariant", "pbroadcast", "pvary", "device_put", "iota",
    "split", "tie_in",
}

# Predicate-producing primitives: a bool output carries no precision.
_PREDICATE_PRIMS = {"eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not",
                    "xor", "is_finite", "reduce_and", "reduce_or"}


def _dtype_of(aval):
    return getattr(aval, "dtype", None)


def _is_float(dtype) -> bool:
    # bf16's numpy dtype kind is 'V' (ml_dtypes extension type), so kind
    # checks lie; issubdtype knows the extension hierarchy
    if dtype is None:
        return False
    import jax.numpy as jnp

    return bool(jnp.issubdtype(dtype, jnp.floating))


def _is_sub_f32(dtype) -> bool:
    return _is_float(dtype) and dtype.itemsize < 4


def _is_int8(dtype) -> bool:
    if dtype is None:
        return False
    import jax.numpy as jnp

    return bool(jnp.issubdtype(dtype, jnp.integer)) and dtype.itemsize == 1


class PrecisionFlow(JaxprWalker):
    """Quantized-content taint (frozensets over {INT8Q}) plus storage-
    dtype checks at every reduction/dot/carry site."""

    def __init__(self, sinks_checked: list[str] | None = None):
        super().__init__()
        self.sinks_checked = sinks_checked if sinks_checked is not None else []

    def init_value(self, aval):
        dtype = _dtype_of(_inner_aval(aval) if aval is not None else None)
        if _is_int8(dtype):
            return frozenset({INT8Q})
        return frozenset()

    def transfer(self, eqn, in_vals, site):
        name = eqn.primitive.name
        union = frozenset().union(*in_vals) if in_vals else frozenset()
        out_dtype = (_dtype_of(getattr(eqn.outvars[0], "aval", None))
                     if eqn.outvars else None)
        if name in _PREDICATE_PRIMS:
            out = frozenset()
        elif name in ("mul", "div") and INT8Q in union and not all(
            INT8Q in v for v in in_vals
        ):
            # the dequantization pattern: quantized values scaled by a
            # non-quantized factor — content is real again
            out = union - {INT8Q}
        elif name == "bitcast_convert_type":
            # the hop-payload codec (quantize_ring_payload): a float
            # scale bitcast into bytes is opaque payload, and bytes
            # bitcast back to float are the scale again — not content
            # that needs a dequant multiply
            out = (union - {INT8Q}) if _is_float(out_dtype) else union
        else:
            out = union
        if _is_int8(out_dtype) and name not in _PREDICATE_PRIMS:
            # int8 storage is quantized content until a scale multiply
            # proves otherwise (pure index/flag math exits through
            # predicates or integer sinks, which the rules ignore)
            out = out | {INT8Q}
        return [out for _ in eqn.outvars]

    def post_eqn(self, env, eqn, in_vals, out_vals):
        # ref mutation: a store joins the stored value's taint into the
        # ref variable so later loads observe it
        if eqn.primitive.name in ("swap", "addupdate") and eqn.invars:
            import jax

            ref = eqn.invars[0]
            stored = (frozenset().union(*in_vals[1:])
                      if in_vals[1:] else frozenset())
            if not isinstance(ref, jax.core.Literal):
                env[ref] = env.get(ref, frozenset()) | stored

    def visit(self, eqn, in_vals, out_vals, site):
        name = eqn.primitive.name
        if name in _REDUCTION_SINKS and eqn.invars:
            op_dtype = _dtype_of(getattr(eqn.invars[0], "aval", None))
            if _is_sub_f32(op_dtype):
                self.findings.append(
                    f"sub-f32 value ({op_dtype}) reaches {name} at {site} — "
                    f"softmax/accumulator reductions must execute in "
                    f"float32 [rule: f32-accumulator-flow]"
                )
            if _is_float(op_dtype) and any(INT8Q in v for v in in_vals):
                # a dropped QK^T dequant reaches the softmax max/exp first
                self.findings.append(
                    f"quantized int8 content reaches {name} without a "
                    f"dequantization scale at {site} [rule: int8-dequant]"
                )
            self.sinks_checked.append(f"{name}@{site}")
        if name == "dot_general":
            out_dtype = _dtype_of(eqn.outvars[0].aval)
            if _is_sub_f32(out_dtype):
                self.findings.append(
                    f"dot_general accumulates at {out_dtype} at {site} — "
                    f"matmul accumulation must target float32 "
                    f"(preferred_element_type) [rule: f32-accumulator-flow]"
                )
            operand_dtypes = [
                _dtype_of(getattr(a, "aval", None)) for a in eqn.invars[:2]
            ]
            if all(_is_int8(t) for t in operand_dtypes):
                # the int8 compute path's own matmul: int8 x int8 into an
                # f32 accumulator is the LEGAL quantized form — its output
                # is still quantized content (the taint propagates through
                # the transfer) until the per-row/per-block scale multiply
                # strips it; accumulating it unscaled is caught below
                pass
            elif any(INT8Q in v for v in in_vals[:2]) and _is_float(out_dtype):
                self.findings.append(
                    f"quantized int8 operand reaches dot_general without a "
                    f"dequantization scale at {site} [rule: int8-dequant]"
                )
            self.sinks_checked.append(f"{name}@{site}")
        elif name in ("add", "sub", "reduce_sum", "cumsum"):
            out_dtype = _dtype_of(eqn.outvars[0].aval) if eqn.outvars else None
            if _is_float(out_dtype) and any(INT8Q in v for v in in_vals):
                self.findings.append(
                    f"quantized int8 content accumulated ({name}) without a "
                    f"dequantization scale at {site} [rule: int8-dequant]"
                )

    # -- carry checks ------------------------------------------------------
    def _check_loop_carries(self, eqn, body, carry_vals, n_consts, n_carry,
                            sub_path, kind, body_offset=None):
        # body.outvars lead with the carries for both scan and while
        for ci in range(n_carry):
            outvar = body.outvars[ci]
            dtype = _dtype_of(getattr(outvar, "aval", None))
            if not _is_sub_f32(dtype):
                continue
            arith = _producing_arithmetic(body, outvar)
            if arith is not None:
                self.findings.append(
                    f"{dtype} loop carry #{ci} of {kind} at "
                    f"{'/'.join(sub_path)} accumulates through "
                    f"{arith.primitive.name} — (acc, m, l)/lse-class "
                    f"carries must be float32 [rule: f32-accumulator-flow]"
                )


def _producing_arithmetic(jaxpr, outvar, _depth: int = 0):
    """Backtrace ``outvar`` through structure-only primitives: the first
    arithmetic equation on the producing chain, or None when the value is
    a pure pass-through of the loop inputs (a rotating payload — a
    ``ppermute`` of the carry — is movement, not accumulation)."""
    import jax

    if _depth > 6:
        return None
    producers = {v: e for e in jaxpr.eqns for v in e.outvars}
    seen = set()
    stack = [outvar]
    while stack:
        v = stack.pop()
        if isinstance(v, jax.core.Literal) or id(v) in seen:
            continue
        seen.add(id(v))
        e = producers.get(v)
        if e is None:
            continue  # reached an invar/constvar: pass-through
        name = e.primitive.name
        if name in _ARITH_PRIMS:
            return e
        if name in _TRANSPARENT_PRIMS:
            stack.extend(a for a in e.invars
                         if not isinstance(a, jax.core.Literal))
            continue
        # control flow: look through the sub-jaxpr outputs feeding v
        subs = []
        for val in e.params.values():
            subs.extend(_sub_closed_jaxprs(val))
        if subs:
            try:
                pos = list(e.outvars).index(v)
            except ValueError:
                pos = None
            for sub in subs:
                if pos is not None and pos < len(sub.outvars):
                    hit = _producing_arithmetic(sub, sub.outvars[pos],
                                                _depth + 1)
                    if hit is not None:
                        return hit
            stack.extend(a for a in e.invars
                         if not isinstance(a, jax.core.Literal))
            continue
        # unknown leaf primitive (erf, sin, a future custom op): treat as
        # arithmetic — a carry produced by computation the walker cannot
        # classify must FLAG, not silently pass (only the listed
        # structure-only primitives are pass-through)
        return e
    return None


def count_int8_quantize_ops(closed_jaxpr, *, skip_pallas: bool = True) -> int:
    """Number of float→int8 quantization casts in a program.

    Counts ``convert_element_type`` equations whose input is float and
    whose output is int8 — the one cast every absmax codec in
    ``ops/quant.py`` ends with (bool/int flag casts don't match).  Kernel
    bodies are skipped by default (``skip_pallas``): the in-kernel ``p``
    quantization is per-tile tile math, not a payload quantization.

    This is the requant pin behind the dequant-free ring composition
    (``docs/precision.md``): a counter-rotated int8 ring with
    ``compute_dtype="int8"`` must quantize each KV payload exactly ONCE
    at ring entry (2 casts — k and v) plus one q cast per hop's launcher;
    a dequant→requant round trip would add two more per hop and fails the
    pinned count (``tests/test_quant.py``).
    """

    def walk(jaxpr) -> int:
        n = 0
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "pallas_call" and skip_pallas:
                continue
            if name == "convert_element_type":
                in_dtype = _dtype_of(getattr(eqn.invars[0], "aval", None))
                out_dtype = _dtype_of(getattr(eqn.outvars[0], "aval", None))
                if _is_float(in_dtype) and _is_int8(out_dtype):
                    n += 1
            for v in eqn.params.values():
                for sub in _sub_closed_jaxprs(v):
                    n += walk(sub)
        return n

    return walk(_as_jaxpr(closed_jaxpr))


def audit_precision_flow(fn: Callable, *args, label: str | None = None,
                         ) -> list[str]:
    """Trace ``fn(*args)`` and run the precision-flow lattice over the
    jaxpr.  Returns one-line violations (empty = every reduction, dot
    accumulation, and loop carry on the traced paths executes at f32, and
    every quantized payload is dequantized before accumulation)."""
    import jax

    label = label or getattr(fn, "__name__", str(fn))
    closed = jax.make_jaxpr(fn)(*args)
    return PrecisionFlow().run(closed, label=label)


def run_precision_suite() -> list[tuple[str, list[str]]]:
    """The package-wide precision audit behind ``check_contracts.py
    --dataflow`` and the ``python -m ring_attention_tpu.analysis``
    self-run: both flash paths (XLA scan and Pallas kernels, forward AND
    backward through their custom_vjps), the int8 hop-compression
    quantize→hop→dequantize→accumulate chain, and the counter-rotation
    backward's packed circulation.  Mesh-free (the ring entry's
    collective structure is the divergence checker's job); tiny bf16
    shapes; make_jaxpr only — no compile, any backend.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import flash, pallas_flash
    from ..parallel.collectives import (
        dequantize_ring_payload,
        quantize_ring_payload,
    )
    from ..parallel.ring import _pack_counter, _unpack_counter

    checks: list[tuple[str, list[str]]] = []
    b, h, hk, n, d = 1, 2, 1, 32, 8
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, h, n, d), jnp.bfloat16)
    kv = jax.random.normal(rng, (b, hk, n, d), jnp.bfloat16)

    def xla_step(q, k, v):
        return jax.grad(
            lambda q, k, v: flash.flash_attention(
                q, k, v, causal=True, bucket_size=8, window=16,
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)

    checks.append((
        "precision: xla flash fwd+bwd",
        audit_precision_flow(xla_step, q, kv, kv, label="flash_attention"),
    ))

    def pallas_step(q, k, v):
        return jax.grad(
            lambda q, k, v: pallas_flash.pallas_flash_attention(
                q, k, v, causal=True, interpret=True,
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)

    checks.append((
        "precision: pallas flash fwd+bwd (kernel jaxprs)",
        audit_precision_flow(pallas_step, q, kv, kv,
                             label="pallas_flash_attention"),
    ))

    def int8_hop(q, k, v):
        # the hop-compression chain: quantize once at ring entry, hop
        # (ppermute elided — movement is taint-neutral), dequantize,
        # accumulate — the int8-dequant rule's real positive path
        handle = quantize_ring_payload(k, v)
        kx, vx = dequantize_ring_payload(handle, q.dtype)
        carry = flash.init_carry(b, hk, h // hk, n, d, like=q)
        carry = flash.attend_blocks(
            q, kx, vx, carry, scale=d ** -0.5, bucket_size=8,
            causal_offset=0,
        )
        out, lse = flash.finalize(carry)
        return out.sum() + lse.sum()

    checks.append((
        "precision: int8 hop quantize->dequant->accumulate",
        audit_precision_flow(int8_hop, q, kv, kv, label="int8_hop"),
    ))

    def counter_pack(q, k, v, do):
        # the counter-rotation backward circulates ONE f32 pack
        # [q|acc|m|l]; prove the pack/unpack round-trip plus the
        # backward accumulation stay f32 under bf16 q/do
        acc = jnp.zeros((b, h, n, d), jnp.float32)
        m = jnp.zeros((b, h, n), jnp.float32)
        l = jnp.ones((b, h, n), jnp.float32)
        pack = _pack_counter(q, acc, m, l)
        qx, acc, m, l = _unpack_counter(pack, d, q.dtype)
        lse = (m + jnp.log(l)).reshape(b, hk, h // hk, n)
        delta = (do.astype(jnp.float32) * acc).sum(-1).reshape(
            b, hk, h // hk, n
        )
        dq, dk, dv = flash.flash_backward_blocks(
            do, qx, k, v, lse, delta, scale=d ** -0.5, bucket_size=8,
            causal_offset=0,
        )
        return dq.sum() + dk.sum() + dv.sum()

    checks.append((
        "precision: counter-rotation bwd pack",
        audit_precision_flow(counter_pack, q, kv, kv, q,
                             label="counter_bwd_pack"),
    ))

    checks.append((
        "precision: pallas decode q8 (quantized cache)",
        audit_precision_flow(
            lambda q, k, v: pallas_flash.pallas_flash_decode_q8(
                q[:, :, :1], pallas_flash.quantize_kv_cache(k, v),
                interpret=True,
            )[0].astype(jnp.float32).sum(),
            q, kv, kv, label="pallas_flash_decode_q8",
        ),
    ))

    # the int8 COMPUTE path (PR 13): quantized QK^T/PV inside the flash
    # kernels — the walker descends into the kernel jaxprs and must see
    # every int8 matmul output meet its scale multiply before any
    # reduction/accumulation, and the f32 (acc, m, l) refs untouched
    def pallas_q8_step(q, k, v):
        return jax.grad(
            lambda q, k, v: pallas_flash.pallas_flash_attention(
                q, k, v, causal=True, interpret=True, compute_dtype="int8",
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)

    checks.append((
        "precision: pallas flash int8 compute fwd + bf16 bwd",
        audit_precision_flow(pallas_q8_step, q, kv, kv,
                             label="pallas_flash_attention[int8]"),
    ))

    from ..ops import quant

    def q8_hop_feed(q, k, v):
        # the dequant-free ring composition: pack once with kernel-ready
        # v scales, feed the int8 kernel DIRECTLY (no dequant→requant),
        # finalize from the f32 partials
        payload = quant.pack_kv(k, v, v_block=8)
        feed = quant.payload_kernel_feed(payload, 8)
        p = pallas_flash.pallas_flash_partials(
            q, None, None, scale=d ** -0.5, causal_offset=0,
            compute_dtype="int8", kv_quantized=feed, block_q=8, block_k=8,
            interpret=True,
        )
        out, lse = pallas_flash.finalize_partials(p)
        return out.sum() + lse.sum()

    checks.append((
        "precision: int8 hop payload -> dequant-free kernel feed",
        audit_precision_flow(q8_hop_feed, q, kv, kv, label="q8_hop_feed"),
    ))
    return checks


# ---------------------------------------------------------------------------
# Pass 2: SPMD divergence
# ---------------------------------------------------------------------------

# Collective primitives whose cross-rank sequence must be convergent.
COLLECTIVE_PRIMS = {
    "ppermute", "pbroadcast", "all_to_all", "all_gather",
    "all_gather_invariant", "psum", "psum_invariant", "pmax", "pmin",
    "reduce_scatter", "psum_scatter", "pshuffle",
}

_COLLECTIVE_PARAM_KEYS = ("axis_name", "axes", "perm", "split_axis",
                          "concat_axis", "axis_index_groups", "tiled")


def _collective_element(eqn) -> tuple:
    aval = getattr(eqn.invars[0], "aval", None) if eqn.invars else None
    params = []
    for key in _COLLECTIVE_PARAM_KEYS:
        if key in eqn.params:
            params.append((key, repr(eqn.params[key])))
    return (
        eqn.primitive.name,
        tuple(params),
        tuple(getattr(aval, "shape", ())),
        str(getattr(aval, "dtype", "?")),
    )


@dataclass
class _DivergenceScan:
    findings: list[str] = field(default_factory=list)


def _signature(jaxpr, state: _DivergenceScan, path: tuple) -> tuple:
    """Ordered collective signature of one jaxpr, recursing into control
    flow.  Emits findings into ``state`` for divergent cond branches and
    communicating while loops as it goes; a cond whose branches agree
    contributes that agreed sequence to the enclosing signature."""
    out: list = []
    for idx, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            out.append(_collective_element(eqn))
        elif name == "scan":
            body_sig = _signature(_as_jaxpr(eqn.params["jaxpr"]), state,
                                  path + (f"scan#{idx}",))
            if body_sig:
                out.append(("scan", int(eqn.params["length"]), body_sig))
        elif name == "while":
            for key, leg in (("cond_jaxpr", "cond"), ("body_jaxpr", "body")):
                leg_sig = _signature(_as_jaxpr(eqn.params[key]), state,
                                     path + (f"while#{idx}/{leg}",))
                if leg_sig:
                    kinds = sorted(_sig_kinds(leg_sig))
                    state.findings.append(
                        f"collective(s) {kinds} inside a lax.while_loop "
                        f"{leg} at {'/'.join(path) or 'top'} — the trip "
                        f"count is rank-local, so the collective sequence "
                        f"cannot be proven convergent "
                        f"[rule: while-collective]"
                    )
        elif name == "cond":
            sigs = [
                _signature(_as_jaxpr(br), state,
                           path + (f"cond#{idx}/branch{bi}",))
                for bi, br in enumerate(eqn.params["branches"])
            ]
            for bi, sig in enumerate(sigs[1:], start=1):
                if sig != sigs[0]:
                    state.findings.append(
                        f"cond#{idx} at {'/'.join(path) or 'top'}: branch 0 "
                        f"issues {_sig_str(sigs[0])} but branch {bi} issues "
                        f"{_sig_str(sig)} — ranks taking different branches "
                        f"deadlock on the first mismatch "
                        f"[rule: branch-collective-divergence]"
                    )
            if sigs and sigs[0]:
                out.extend(sigs[0])
        else:
            for v in eqn.params.values():
                for sub in _sub_closed_jaxprs(v):
                    out.extend(_signature(sub, state,
                                          path + (f"{name}#{idx}",)))
    return tuple(out)


def _sig_kinds(sig: tuple) -> set[str]:
    """Collective primitive names in a signature, looking through the
    structural ``("scan", length, body_sig)`` wrappers so a diagnostic
    names the actual collective, never "scan"."""
    kinds: set[str] = set()
    for e in sig:
        if isinstance(e, tuple) and e and e[0] == "scan" and len(e) == 3 \
                and isinstance(e[2], tuple):
            kinds |= _sig_kinds(e[2])
        elif isinstance(e, tuple) and e and isinstance(e[0], str):
            kinds.add(e[0])
    return kinds


def _sig_str(sig: tuple) -> str:
    if not sig:
        return "[no collectives]"
    return "[" + ", ".join(
        e[0] if isinstance(e, tuple) and isinstance(e[0], str) else str(e)
        for e in sig[:4]
    ) + (", ..." if len(sig) > 4 else "") + "]"


def collective_signature(closed_jaxpr) -> tuple:
    """The program's ordered collective sequence (kind, axes/perm params,
    operand shape, dtype), with scan bodies kept structural
    (``("scan", length, body_sig)``) so equality is well-defined at any
    trip count.  Divergence findings raised during construction are
    discarded here — use :func:`check_spmd_divergence` for the audit."""
    return _signature(_as_jaxpr(closed_jaxpr), _DivergenceScan(), ())


def check_spmd_divergence(closed_jaxpr, label: str = "") -> list[str]:
    """Prove the program's collective schedule is branch-invariant: every
    ``lax.cond``'s branches issue identical collective sequences (kind,
    axes, operand shapes, order — scan-aware) and no ``lax.while_loop``
    communicates.  Returns one-line violations naming the cond/branch."""
    state = _DivergenceScan()
    _signature(_as_jaxpr(closed_jaxpr), state, ())
    findings = list(dict.fromkeys(state.findings))
    if label:
        findings = [f"{label}: {f}" for f in findings]
    return findings


def run_divergence_suite(strategies=None, directions=None,
                         ) -> list[tuple[str, list[str]]]:
    """The SPMD divergence proof over every sequence-parallel strategy:
    trace each contract entry (both impls where they differ) and require
    a branch-invariant collective sequence.  Needs multiple simulated
    devices (``--xla_force_host_platform_device_count``); pure
    ``make_jaxpr`` — no compile."""
    import jax

    from . import contracts

    checks: list[tuple[str, list[str]]] = []
    if strategies is None:
        strategies = list(contracts.CONTRACTS)
    for strategy in strategies:
        contract = contracts.CONTRACTS[strategy]
        mesh = contracts.default_mesh(strategy)
        dirs = directions or contract.get("directions", ("fwd", "fwdbwd"))
        impls = {contract["impl"]}
        if "scan" in contract:
            impls.add("xla")
        for impl in sorted(impls):
            fn, args, _ = contracts.build_entry(strategy, mesh, impl=impl)
            for direction in dirs:
                dfn = contracts._direction_fn(fn, direction)
                label = f"{strategy}/{impl}/{direction}"
                checks.append((
                    f"divergence: {label}",
                    check_spmd_divergence(jax.make_jaxpr(dfn)(*args), label),
                ))
    return checks
