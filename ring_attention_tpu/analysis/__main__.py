"""Self-run: ``python -m ring_attention_tpu.analysis``.

Lints the whole package tree, runs the f32 accumulator-dtype spot audit
(unless ``--no-audit``), the jaxpr dataflow passes (unless
``--no-dataflow``: the precision-flow auditor over both flash paths /
the int8 hop chain / the counter bwd pack, and the SPMD divergence
checker over every strategy when multiple simulated devices are
available), the tile-coverage prover (unless ``--no-coverage``), the
fused-ring DMA/semaphore protocol verifier (unless ``--no-schedverify``:
the rings-2..8 model check always, plus the jaxpr extraction
cross-check when virtual devices are available), the
elastic checkpoint contracts (unless ``--no-elastic``), and
the perf-observatory gate (unless ``--no-gate``): benchmark-history
trend checks plus the arithmetic comms-reference table and the coverage
fingerprint against ``docs/perf_baseline.json``.  The default gate pass
compiles nothing; ``--gate-full`` adds the collective fingerprint and
the reference-step compiled cost/memory signals (what
``tools/perf_gate.py --check`` runs).  Exit status 0 = clean.

The ``-m`` form imports the package ``__init__`` chain (which needs
jax); on a host without jax, run the lint as a plain script instead:
``python ring_attention_tpu/analysis/lint.py``.  The full
collective-contract suite needs virtual devices and lives in
``tools/check_contracts.py`` (which also fronts ``--coverage`` /
``--dataflow`` individually).
"""

from __future__ import annotations

import argparse
import os

from .lint import lint_package
from . import perfgate, recompile


def _request_virtual_devices(n: int = 8) -> None:
    """Ask for simulated host devices BEFORE anything initializes a jax
    backend.  Importing jax does not initialize one, so setting the flag
    at the top of main() is early enough in the normal CLI invocation —
    the precision suite would otherwise initialize a single-device CPU
    backend and starve the divergence suite of its mesh."""
    flag = f"--xla_force_host_platform_device_count={n}"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + f" {flag}"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _have_virtual_devices(n: int = 2) -> bool:
    import jax

    try:
        return len(jax.devices()) >= n
    except Exception:  # noqa: BLE001 — no backend at all: skip, don't crash
        return False


def main(argv: list[str] | None = None) -> int:
    _request_virtual_devices()
    parser = argparse.ArgumentParser(
        prog="python -m ring_attention_tpu.analysis",
        description="lint the package tree + audit kernel accumulator "
                    "dtypes + precision-flow/divergence dataflow passes + "
                    "tile-coverage prover + perf-observatory gate",
    )
    parser.add_argument("--no-audit", action="store_true",
                        help="skip the (jax-importing) f32 accumulator audit")
    parser.add_argument("--no-dataflow", action="store_true",
                        help="skip the jaxpr precision-flow and SPMD "
                             "divergence passes")
    parser.add_argument("--no-coverage", action="store_true",
                        help="skip the tile-coverage prover")
    parser.add_argument("--no-schedverify", action="store_true",
                        help="skip the fused-ring DMA/semaphore protocol "
                             "verifier (model check + jaxpr extraction)")
    parser.add_argument("--no-elastic", action="store_true",
                        help="skip the elastic checkpoint contracts "
                             "(manifest round-trip, resharded==direct "
                             "load, corrupt-shard fallback, debris sweep)")
    parser.add_argument("--no-gate", action="store_true",
                        help="skip the perf gate (history + comms baseline)")
    parser.add_argument("--gate-full", action="store_true",
                        help="gate on the full CPU signal set (fingerprint "
                             "+ reference-step compile) — pays compiles; "
                             "the default gates only the compile-free "
                             "signals")
    args = parser.parse_args(argv)

    notes: list[str] = []
    failures = [str(v) for v in lint_package()]
    if not args.no_audit:
        failures.extend(recompile.audit_accumulator_dtypes())
    if not args.no_dataflow:
        from . import dataflow

        for name, violations in dataflow.run_precision_suite():
            failures.extend(f"{name}: {v}" if name not in v else v
                            for v in violations)
        if _have_virtual_devices():
            for name, violations in dataflow.run_divergence_suite():
                failures.extend(violations)
        else:
            notes.append(
                "divergence checker skipped: backend already initialized "
                "with < 2 devices (tools/check_contracts.py --dataflow "
                "runs it with virtual devices)"
            )
    if not args.no_coverage:
        from . import coverage

        for report in coverage.run_coverage_suite():
            failures.extend(report.violations)
    if not args.no_schedverify:
        from . import schedverify

        # the extraction cross-check traces on the full-device ring
        if _have_virtual_devices(8):
            for name, violations in schedverify.run_schedverify_suite():
                failures.extend(violations)
        else:
            for name, violations in [(
                "model", schedverify.verify_protocol())]:
                failures.extend(violations)
            notes.append(
                "schedverify extraction skipped: backend already "
                "initialized with < 8 devices (model check still ran; "
                "tools/check_contracts.py --dma re-proves with virtual "
                "devices)"
            )
    if not args.no_elastic:
        # the elastic checks build 4-device sub-meshes
        if _have_virtual_devices(4):
            from ..elastic.verify import run_elastic_suite

            for name, violations in run_elastic_suite():
                failures.extend(f"{name}: {v}" for v in violations)
        else:
            notes.append(
                "elastic checks skipped: backend already initialized "
                "with < 4 devices (tools/check_contracts.py --elastic "
                "runs them with virtual devices)"
            )
    if not args.no_gate:
        if args.gate_full:
            current = perfgate.collect_current()
        else:
            current = perfgate.collect_current(strategies=None,
                                               compiled=False)
        report = perfgate.run_gate(current)
        failures.extend(str(f) for f in report.findings)
    for line in failures:
        print(line)
    for line in notes:
        print(f"note: {line}")
    print(f"{len(failures)} finding(s)" if failures else "clean")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
