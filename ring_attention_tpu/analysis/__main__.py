"""Self-run: ``python -m ring_attention_tpu.analysis``.

Lints the whole package tree, runs the f32 accumulator-dtype audit
(unless ``--no-audit``), and runs the perf-observatory gate (unless
``--no-gate``): benchmark-history trend checks plus the arithmetic
comms-reference table against ``docs/perf_baseline.json``.  The default
gate pass compiles nothing; ``--gate-full`` adds the collective
fingerprint and the reference-step compiled cost/memory signals (what
``tools/perf_gate.py --check`` runs).  Exit status 0 = clean.

The ``-m`` form imports the package ``__init__`` chain (which needs
jax); on a host without jax, run the lint as a plain script instead:
``python ring_attention_tpu/analysis/lint.py``.  The full
collective-contract suite needs virtual devices and lives in
``tools/check_contracts.py``.
"""

from __future__ import annotations

import argparse

from .lint import lint_package
from . import perfgate, recompile


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ring_attention_tpu.analysis",
        description="lint the package tree + audit kernel accumulator "
                    "dtypes + run the perf-observatory gate",
    )
    parser.add_argument("--no-audit", action="store_true",
                        help="skip the (jax-importing) f32 accumulator audit")
    parser.add_argument("--no-gate", action="store_true",
                        help="skip the perf gate (history + comms baseline)")
    parser.add_argument("--gate-full", action="store_true",
                        help="gate on the full CPU signal set (fingerprint "
                             "+ reference-step compile) — pays compiles; "
                             "the default gates only the compile-free "
                             "signals")
    args = parser.parse_args(argv)

    failures = [str(v) for v in lint_package()]
    if not args.no_audit:
        failures.extend(recompile.audit_accumulator_dtypes())
    if not args.no_gate:
        if args.gate_full:
            current = perfgate.collect_current()
        else:
            current = perfgate.collect_current(strategies=None,
                                               compiled=False)
        report = perfgate.run_gate(current)
        failures.extend(str(f) for f in report.findings)
    for line in failures:
        print(line)
    print(f"{len(failures)} finding(s)" if failures else "clean")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
