"""Self-run: ``python -m ring_attention_tpu.analysis``.

Lints the whole package tree and, unless ``--no-audit``, runs the f32
accumulator-dtype audit.  Exit status 0 = clean.  The ``-m`` form imports
the package ``__init__`` chain (which needs jax); on a host without jax,
run the lint as a plain script instead:
``python ring_attention_tpu/analysis/lint.py``.  The full
collective-contract suite needs virtual devices and lives in
``tools/check_contracts.py``.
"""

from __future__ import annotations

import argparse

from .lint import lint_package
from . import recompile


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ring_attention_tpu.analysis",
        description="lint the package tree + audit kernel accumulator dtypes",
    )
    parser.add_argument("--no-audit", action="store_true",
                        help="skip the (jax-importing) f32 accumulator audit")
    args = parser.parse_args(argv)

    failures = [str(v) for v in lint_package()]
    if not args.no_audit:
        failures.extend(recompile.audit_accumulator_dtypes())
    for line in failures:
        print(line)
    print(f"{len(failures)} finding(s)" if failures else "clean")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
