"""Declarative collective/HLO contracts for every sequence-parallel entry.

Ring attention's value proposition IS a communication contract: exactly
``ring - 1`` collective-permutes per forward (Liu et al.; Striped Attention
changes only the permutation, not the count), ``2*ring - 1`` in backward
(the kv counter-rotation — ``ring - 1`` after XLA drops the unused final
rotate — plus the full ``ring``-hop dkv circulation back to its owner), and
the hybrid factoring must cut the hop count by the Ulysses degree while
adding exactly two all-to-alls per tensor leg.  Before this module those
invariants lived as scattered one-off HLO pins; here they are ONE
declarative table (:data:`CONTRACTS`), verified two ways for every strategy
x mesh shape:

  - **optimized HLO** (the hot path: unrolled Pallas hop loop, or the XLA
    path for gather/all-to-all strategies): exact instruction counts per
    collective kind, source/target-pair and replica-group *axis* checks
    (a ring permute must keep every non-ring mesh coordinate fixed), and
    the global rule that any collective kind the contract does not declare
    must not appear at all — an accidental ``all-gather`` of O(seq)
    activations fails loudly;
  - **jaxpr structure** (the scanned XLA path): collective counts with
    scan bodies multiplied by their trip count, plus the rule that no
    collective may sit inside a ``lax.cond`` branch (a data-dependent
    collective schedule deadlocks SPMD programs).

Count expressions are strings evaluated over the mesh dims
(``ring`` / ``ulysses`` / ``world`` / ``passes`` / ``data``) so the table
reads as documentation (docs/static_analysis.md renders it directly).

The contracts pin the *base* path: unsegmented, unmasked, unidirectional,
full passes — the configuration every other variant adds collectives onto.
All checks run on CPU (``--xla_force_host_platform_device_count``); the
compiled collective sequence is backend-independent at this level.
"""

from __future__ import annotations

import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Any

import numpy as np

# ---------------------------------------------------------------------------
# The declarative table
# ---------------------------------------------------------------------------

CONTRACTS: dict[str, dict[str, Any]] = {
    "ring": {
        "description": "KV rotation: one ppermute per hop, nothing else",
        "impl": "pallas",
        "mesh": "plain",
        "axes": {"collective-permute": "seq"},
        "hlo": {
            "fwd": {"collective-permute": "ring - 1"},
            "fwdbwd": {"collective-permute": "3 * ring - 2"},
        },
        "scan": {
            "fwd": {"ppermute": "passes"},
            "fwdbwd": {"ppermute": "3 * passes"},
        },
    },
    "striped": {
        "description": "balanced-causal ring: permutation changes, count "
                       "does not (Striped Attention, arXiv 2311.09431)",
        "impl": "pallas",
        "mesh": "plain",
        "striped": True,
        "axes": {"collective-permute": "seq"},
        "hlo": {
            "fwd": {"collective-permute": "ring - 1"},
            "fwdbwd": {"collective-permute": "3 * ring - 2"},
        },
        "scan": {
            "fwd": {"ppermute": "passes"},
            "fwdbwd": {"ppermute": "3 * passes"},
        },
    },
    "counter": {
        "description": "TokenRing counter-rotation (arXiv 2412.20501): the "
                       "Q+(acc,m,l) pack rotates one ring direction while "
                       "KV rotates the other (permute pairs in BOTH "
                       "directions); backward circulates only the q-side "
                       "pack with KV/dKV resident — fwd pays one extra "
                       "collective (the out/lse catch-up) and the backward "
                       "repays it: 2*ring per step vs the baseline 3*ring-2",
        "impl": "pallas",
        "mesh": "plain",
        "ring_kwargs": {"counter_rotate": True},
        "both_directions": True,
        "axes": {"collective-permute": "seq"},
        "hlo": {
            "fwd": {"collective-permute": "ring"},
            "fwdbwd": {"collective-permute": "2 * ring"},
        },
        "scan": {
            # the single-lax.scan body covers two hops (one Q-rotation,
            # one KV-rotation) + the out/lse catch-up; backward is one
            # uniform ppermute per hop, landing home at full circulation
            "fwd": {"ppermute": "2 * (passes // 2) + 1"},
            "fwdbwd": {"ppermute": "2 * (passes // 2) + 1 + passes"},
        },
    },
    "ring_compressed": {
        "description": "int8-compressed KV hops: per-token absmax values + "
                       "bitcast f32 scales in ONE payload — hop count "
                       "identical to the ring contract, bytes/hop "
                       "(d+4)/(4d) of the f32 ring's",
        "impl": "pallas",
        "mesh": "plain",
        "ring_kwargs": {"hop_compression": "int8"},
        "axes": {"collective-permute": "seq"},
        "hlo": {
            "fwd": {"collective-permute": "ring - 1"},
            "fwdbwd": {"collective-permute": "3 * ring - 2"},
        },
        "scan": {
            "fwd": {"ppermute": "passes"},
            "fwdbwd": {"ppermute": "3 * passes"},
        },
        "hop_bytes": {
            # every forward rotation moves the (2, b, hk, chunk, d+4) int8
            # handle; backward recirculates exact kv + f32 dkv (its own
            # larger payloads), so the pin is forward-only
            "fwd": {
                "min": "2 * b * kv_heads * chunk * (dim_head + 4)",
                "max": "2 * b * kv_heads * chunk * (dim_head + 4)",
            },
        },
    },
    "counter_compressed": {
        "description": "counter-rotation with int8 KV hops: counts match "
                       "the counter contract exactly; the smallest "
                       "circulating payload is the compressed KV handle",
        "impl": "pallas",
        "mesh": "plain",
        "ring_kwargs": {"counter_rotate": True, "hop_compression": "int8"},
        "both_directions": True,
        "axes": {"collective-permute": "seq"},
        "hlo": {
            "fwd": {"collective-permute": "ring"},
            "fwdbwd": {"collective-permute": "2 * ring"},
        },
        "scan": {
            "fwd": {"ppermute": "2 * (passes // 2) + 1"},
            "fwdbwd": {"ppermute": "2 * (passes // 2) + 1 + passes"},
        },
        "hop_bytes": {
            "fwd": {
                "min": "2 * b * kv_heads * chunk * (dim_head + 4)",
                "max": "4 * b * heads * chunk * (2 * dim_head + 2)",
            },
        },
    },
    "counter_q8": {
        "description": "counter-rotation with int8 hops feeding the int8 "
                       "COMPUTE kernels directly (PR 13, dequant-free "
                       "composition, docs/precision.md): the collective "
                       "schedule is IDENTICAL to counter_compressed — the "
                       "quantized matmuls change what the kernels read, "
                       "never what the ring moves — and the payload still "
                       "circulates as one int8 array per hop",
        "impl": "pallas",
        "mesh": "plain",
        "ring_kwargs": {"counter_rotate": True, "hop_compression": "int8",
                        "compute_dtype": "int8"},
        "both_directions": True,
        "axes": {"collective-permute": "seq"},
        "hlo": {
            "fwd": {"collective-permute": "ring"},
            "fwdbwd": {"collective-permute": "2 * ring"},
        },
        "hop_bytes": {
            "fwd": {
                "min": "2 * b * kv_heads * chunk * (dim_head + 4)",
                "max": "4 * b * heads * chunk * (2 * dim_head + 2)",
            },
        },
    },
    "zigzag": {
        "description": "Llama-3 CP: gather K and V once; grads flow back "
                       "through the gather transpose (reduce-scatter)",
        "impl": "xla",
        "mesh": "plain",
        "axes": {"all-gather": "seq", "reduce-scatter": "seq"},
        "hlo": {
            "fwd": {"all-gather": "2"},
            "fwdbwd": {"all-gather": "2", "reduce-scatter": "2"},
        },
    },
    "ulysses": {
        "description": "head-parallel: two all-to-alls per tensor leg "
                       "(q/k/v in, out back; bwd transposes combine to 3)",
        "impl": "xla",
        "mesh": "plain",
        "axes": {"all-to-all": "seq"},
        "hlo": {
            "fwd": {"all-to-all": "4"},
            "fwdbwd": {"all-to-all": "7"},
        },
    },
    "ulysses_gqa": {
        "description": "small-hk GQA: real kv heads ship ONCE (all-gather) "
                       "and expand locally — never world/gcd repeated "
                       "all-to-all copies",
        "impl": "xla",
        "mesh": "plain",
        "kv_heads": 2,
        "directions": ("fwd",),
        "axes": {"all-to-all": "seq", "all-gather": "seq"},
        "hlo": {
            "fwd": {"all-to-all": "2", "all-gather": "2"},
        },
    },
    "hybrid": {
        "description": "Ulysses x Ring factoring: all-to-alls on the inner "
                       "axis only, ppermutes on the outer axis only, "
                       "ulysses-x fewer hops than a pure ring at equal world",
        "impl": "pallas",
        "mesh": "factored",
        "axes": {
            "collective-permute": "ring",
            "all-to-all": "ulysses",
            "all-gather": "ulysses",
        },
        "hlo": {
            "fwd": {"all-to-all": "4", "collective-permute": "ring - 1"},
            "fwdbwd": {"all-to-all": "7", "collective-permute": "3 * ring - 2"},
        },
        "scan": {
            "fwd": {"ppermute": "passes", "all_to_all": "4"},
            "fwdbwd": {"ppermute": "3 * passes", "all_to_all": "8"},
        },
    },
    "blockwise_ffn": {
        "description": "chunked feedforward (Ring Attention's blockwise "
                       "FFN, arXiv 2310.01889): chunks split WITHIN each "
                       "sequence shard, so the rematted scan adds ZERO "
                       "collectives — forward has none at all, backward "
                       "has exactly the dense FFN's two weight-grad "
                       "all-reduces",
        "impl": "xla",
        "mesh": "plain",
        "axes": {},
        "hlo": {
            "fwd": {},
            "fwdbwd": {"all-reduce": "2"},
        },
    },
    "tree_decode": {
        "description": "tree-attention decode merge: pmax + two psums, "
                       "nothing touches the O(seq) cache shards",
        "impl": "xla",
        "mesh": "plain",
        "directions": ("fwd",),
        "axes": {"all-reduce": "seq"},
        "hlo": {
            "fwd": {"all-reduce": "3"},
        },
        "scan": {
            "fwd": {"pmax": "1", "psum": "2"},
        },
    },
}

# Collective kinds tracked in optimized HLO.  Any kind present in a
# program but absent from its contract's expectation dict is a violation
# (the "no undeclared collective in the hot path" rule).
HLO_COLLECTIVE_KINDS = (
    "all-gather",
    "all-to-all",
    "collective-permute",
    "all-reduce",
    "reduce-scatter",
    "collective-broadcast",
)

# jaxpr-level collective primitive names (the traced contract).
JAXPR_COLLECTIVE_PRIMS = {
    "ppermute",
    "all_to_all",
    "all_gather",
    "all_gather_invariant",
    "psum",
    "psum_invariant",
    "pmax",
    "pmin",
    "reduce_scatter",
    "psum_scatter",
}

_HLO_COLLECTIVE_RE = re.compile(
    r"%?(" + "|".join(HLO_COLLECTIVE_KINDS) + r")(?:-start)?[.\d]* = "
)
_PPERMUTE_PAIRS_RE = re.compile(
    r"collective-permute[^\n]*source_target_pairs=\{([0-9,{} ]*)\}"
)
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[0-9, ]+\}(?:,\{[0-9, ]+\})*)\}")
# iota (v2) form some XLA builds print instead of brace lists:
#   replica_groups=[2,4]<=[8]  or  [4,2]<=[2,4]T(1,0)
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)


def _parse_replica_groups(line: str) -> list[list[int]] | None | str:
    """Replica groups of one HLO instruction line: a list of groups, None
    when the instruction carries no ``replica_groups=`` attribute at all
    (scalar/degenerate form), or an error string for a format this parser
    does not recognize — callers must surface that loudly, never skip it
    (a silently unparsed group would turn the axis rule into a no-op)."""
    gm = _GROUPS_RE.search(line)
    if gm:
        return [
            [int(x) for x in g.split(",")]
            for g in re.findall(r"\{([0-9, ]+)\}", gm.group(1))
        ]
    im = _GROUPS_IOTA_RE.search(line)
    if im:
        ng, gs = int(im.group(1)), int(im.group(2))
        dims = [int(x) for x in im.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if im.group(4):
            ids = ids.transpose([int(x) for x in im.group(4).split(",")])
        return ids.reshape(ng, gs).tolist()
    if "replica_groups=" in line:
        return line.split("replica_groups=", 1)[1][:40]
    return None


# ---------------------------------------------------------------------------
# HLO-side helpers (shared with the test-suite pins)
# ---------------------------------------------------------------------------


def hlo_collective_sequence(txt: str) -> list[str]:
    """Collective kinds in program order — the telemetry pin's signature:
    an instrumented program must issue the same sequence as its base."""
    return [m.group(1) for m in _HLO_COLLECTIVE_RE.finditer(txt)]


def hlo_collective_counts(txt: str) -> dict[str, int]:
    """Collective instruction counts per kind in optimized HLO text."""
    return dict(Counter(hlo_collective_sequence(txt)))


def hlo_ppermute_pairs(txt: str) -> list[list[tuple[int, int]]]:
    """Per-instruction ``source_target_pairs`` of every collective-permute."""
    return [
        [(int(a), int(b)) for a, b in re.findall(r"\{(\d+),(\d+)\}", m.group(1))]
        for m in _PPERMUTE_PAIRS_RE.finditer(txt)
    ]


def _device_coords(device_id: int, mesh_shape: tuple[int, ...]) -> tuple[int, ...]:
    return tuple(int(c) for c in np.unravel_index(device_id, mesh_shape))


def check_pairs_axis(
    pairs: list[list[tuple[int, int]]],
    mesh_shape: tuple[int, ...],
    axis_index: int,
    axis_name: str,
) -> list[str]:
    """Every source->target pair must change ONLY the given mesh axis."""
    out = []
    for inst, ps in enumerate(pairs):
        if not ps:
            out.append(
                f"collective-permute #{inst}: empty source_target_pairs "
                f"[rule: {axis_name}-axis-only]"
            )
        for s, t in ps:
            cs, ct = _device_coords(s, mesh_shape), _device_coords(t, mesh_shape)
            fixed_ok = all(
                cs[i] == ct[i] for i in range(len(mesh_shape)) if i != axis_index
            )
            if not fixed_ok or s == t:
                out.append(
                    f"collective-permute #{inst}: pair {s}->{t} leaves the "
                    f"{axis_name} axis (coords {cs}->{ct}) "
                    f"[rule: {axis_name}-axis-only]"
                )
    return out


def check_groups_axis(
    txt: str,
    kind: str,
    mesh_shape: tuple[int, ...],
    axis_index: int,
    axis_name: str,
) -> list[str]:
    """Replica groups of ``kind`` instructions must each span exactly the
    given mesh axis (all other coordinates fixed within a group)."""
    out = []
    inst_re = re.compile(r"%?" + re.escape(kind) + r"(?:-start)?[.\d]* = [^\n]*")
    for inst, line in enumerate(inst_re.findall(txt)):
        groups = _parse_replica_groups(line)
        if groups is None:
            continue  # scalar/degenerate form without explicit groups
        if isinstance(groups, str):
            out.append(
                f"{kind} #{inst}: unrecognized replica_groups format "
                f"{groups!r} — cannot verify the {axis_name} axis rule "
                f"[rule: {axis_name}-axis-only]"
            )
            continue
        for g in groups:
            coords = [_device_coords(d, mesh_shape) for d in g]
            for i in range(len(mesh_shape)):
                if i == axis_index:
                    continue
                if len({c[i] for c in coords}) != 1:
                    out.append(
                        f"{kind} #{inst}: group {g} spans mesh axis {i}, "
                        f"not only {axis_name} [rule: {axis_name}-axis-only]"
                    )
            if len(g) != mesh_shape[axis_index]:
                out.append(
                    f"{kind} #{inst}: group {g} does not cover the full "
                    f"{axis_name} axis (size {mesh_shape[axis_index]}) "
                    f"[rule: {axis_name}-axis-only]"
                )
    return out


# ---------------------------------------------------------------------------
# jaxpr-side helpers
# ---------------------------------------------------------------------------


@dataclass
class JaxprCollectives:
    """Scan-aware collective counts from a traced program."""

    counts: dict[str, int] = field(default_factory=dict)
    in_cond: list[str] = field(default_factory=list)  # prims under lax.cond
    in_while: list[str] = field(default_factory=list)  # prims under lax.while
    # bytes of each ppermute's payload (one entry per traced instruction,
    # NOT multiplied by scan trip counts): the backend-independent
    # bytes-per-hop signature the compression contracts pin
    ppermute_bytes: list[int] = field(default_factory=list)

    @property
    def dynamic(self) -> bool:
        """A while-loop body issues collectives: the trip count is unknown
        statically, so no count expression can verify the program."""
        return bool(self.in_while)


def _sub_jaxprs(value):
    import jax

    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            stack.extend(v)


def jaxpr_collectives(closed_jaxpr) -> JaxprCollectives:
    """Walk a (closed) jaxpr counting collective primitives, multiplying
    counts inside ``lax.scan`` bodies by the trip count and recording any
    collective that sits inside a ``lax.cond`` branch (a divergent
    collective schedule — the SPMD deadlock hazard this codebase keeps
    its rotations outside conds to avoid) or a ``lax.while_loop`` body
    (trip count unknown statically — no count expression can verify the
    program, so the checkers fail it rather than undercount)."""
    res = JaxprCollectives(counts=Counter())

    def walk(jaxpr, mult: int, in_cond: bool, in_while: bool) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in JAXPR_COLLECTIVE_PRIMS:
                res.counts[name] += mult
                if in_cond:
                    res.in_cond.append(name)
                if in_while:
                    res.in_while.append(name)
                if name == "ppermute":
                    aval = eqn.invars[0].aval
                    res.ppermute_bytes.append(
                        int(np.prod(aval.shape)) * aval.dtype.itemsize
                    )
            if name == "scan":
                walk(eqn.params["jaxpr"].jaxpr,
                     mult * int(eqn.params["length"]), in_cond, in_while)
            elif name == "cond":
                for br in eqn.params["branches"]:
                    walk(br.jaxpr, mult, True, in_while)
            elif name == "while":
                for key in ("body_jaxpr", "cond_jaxpr"):
                    walk(eqn.params[key].jaxpr, mult, in_cond, True)
            else:
                for v in eqn.params.values():
                    for sub in _sub_jaxprs(v):
                        walk(sub, mult, in_cond, in_while)

    walk(closed_jaxpr.jaxpr, 1, False, False)
    res.counts = dict(res.counts)
    return res


# ---------------------------------------------------------------------------
# Contract evaluation
# ---------------------------------------------------------------------------


def expected_counts(strategy: str, direction: str, dims: dict[str, int],
                    table: str = "hlo") -> dict[str, int]:
    """Evaluate the contract table's count expressions for one strategy."""
    contract = CONTRACTS[strategy]
    exprs = contract.get(table, {}).get(direction)
    if exprs is None:
        raise KeyError(f"{strategy} declares no {table!r} contract for "
                       f"{direction!r}")
    ns = dict(dims)
    return {
        kind: int(eval(expr, {"__builtins__": {}}, ns))  # noqa: S307 - table-only
        for kind, expr in exprs.items()
    }


@dataclass
class ContractReport:
    strategy: str
    direction: str
    impl: str
    mesh_shape: tuple[int, ...]
    dims: dict[str, int]
    counts: dict[str, int] = field(default_factory=dict)
    expected: dict[str, int] = field(default_factory=dict)
    jaxpr_counts: dict[str, int] = field(default_factory=dict)
    violations: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return {
            "strategy": self.strategy,
            "direction": self.direction,
            "impl": self.impl,
            "mesh_shape": list(self.mesh_shape),
            "dims": self.dims,
            "counts": self.counts,
            "expected": self.expected,
            "jaxpr_counts": self.jaxpr_counts,
            "ok": self.ok,
            "violations": self.violations,
        }


def _mesh_dims(mesh) -> dict[str, int]:
    from ..parallel.mesh import (
        DCN_DATA_AXIS,
        RING_AXIS,
        SEQ_AXIS,
        ULYSSES_AXIS,
        seq_world,
    )

    shape = dict(mesh.shape)
    ring = shape.get(RING_AXIS) or shape.get(SEQ_AXIS) or 1
    return {
        "data": shape.get("data", 1),
        "dcn": shape.get(DCN_DATA_AXIS, 1),
        "ring": ring,
        "ulysses": shape.get(ULYSSES_AXIS, 1),
        "world": seq_world(mesh),
        "passes": ring,
    }


def default_mesh(strategy: str):
    """The canonical CPU mesh for a strategy: all devices on the sequence
    axis (factored with ulysses=2 for hybrid)."""
    import jax

    from ..parallel.mesh import create_mesh

    n = len(jax.devices())
    if CONTRACTS[strategy].get("mesh") == "factored":
        return create_mesh(ulysses_size=2, ring_size=n // 2)
    return create_mesh(ring_size=n)


def build_entry(strategy: str, mesh, *, b: int = 1, heads: int = 8,
                seq: int = 64, dim_head: int = 8, impl: str | None = None):
    """(fn, args, dims): the strategy's functional core wrapped in
    ``compat.shard_map`` over ``mesh``, ready to lower.  ``fn`` takes
    ``(q, k, v)`` global arrays; tiny shapes — these programs exist to be
    compiled and inspected, not run."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.hybrid import hybrid_attention
    from ..parallel.mesh import (
        RING_AXIS,
        SEQ_AXIS,
        ULYSSES_AXIS,
        data_partition,
        is_factored,
        seq_partition,
    )
    from ..parallel.ring import ring_flash_attention
    from ..parallel.tree_decode import tree_attn_decode
    from ..parallel.ulysses import ulysses_attention
    from ..parallel.zigzag import zigzag_attention
    from ..utils import compat

    contract = CONTRACTS[strategy]
    impl = impl or contract["impl"]
    kv_heads = contract.get("kv_heads", heads)
    striped = contract.get("striped", False)
    dims = _mesh_dims(mesh)
    # shape dims join the namespace so hop-byte expressions read like the
    # payload formulas they pin ("chunk" = the ring-leg KV block length)
    dims.update(
        b=b, heads=heads, kv_heads=kv_heads, seq=seq, dim_head=dim_head,
        chunk=seq // dims["world"] * dims["ulysses"],
    )
    if contract.get("mesh") == "factored" and not is_factored(mesh):
        raise ValueError(f"{strategy} needs a factored (data, ring, ulysses) "
                         "mesh — create_mesh(ulysses_size=...)")
    if contract.get("mesh") == "plain" and is_factored(mesh):
        raise ValueError(f"{strategy} runs on a plain (data, seq) mesh")

    rng = np.random.default_rng(0)
    # the batch must tile the full data-parallel degree (both tiers of a
    # hierarchical mesh)
    b = b * dims["data"] * dims["dcn"]

    def mk(h, n=seq):
        return jnp.asarray(rng.standard_normal((b, h, n, dim_head)),
                           jnp.float32)

    dspec = data_partition(mesh)
    spec = P(dspec, None, seq_partition(mesh), None)
    rep = P(dspec, None, None, None)
    bucket = max(seq // dims["world"] // 2, 4)

    if strategy in ("ring", "striped", "counter", "ring_compressed",
                    "counter_compressed", "counter_q8"):
        ring_kwargs = contract.get("ring_kwargs", {})

        def core(q, k, v):
            return ring_flash_attention(
                q, k, v, None, SEQ_AXIS, causal=True, striped=striped,
                bucket_size=bucket, impl=impl, **ring_kwargs,
            )
        in_specs = (spec, spec, spec)
        out_specs = spec
        args = (mk(heads), mk(kv_heads), mk(kv_heads))
    elif strategy == "zigzag":
        def core(q, k, v):
            return zigzag_attention(
                q, k, v, SEQ_AXIS, causal=True, bucket_size=bucket, impl=impl,
            )
        in_specs = (spec, spec, spec)
        out_specs = spec
        args = (mk(heads), mk(kv_heads), mk(kv_heads))
    elif strategy in ("ulysses", "ulysses_gqa"):
        def core(q, k, v):
            return ulysses_attention(
                q, k, v, SEQ_AXIS, causal=True, bucket_size=bucket, impl=impl,
            )
        in_specs = (spec, spec, spec)
        out_specs = spec
        args = (mk(heads), mk(kv_heads), mk(kv_heads))
    elif strategy == "hybrid":
        def core(q, k, v):
            return hybrid_attention(
                q, k, v, None, ULYSSES_AXIS, RING_AXIS, causal=True,
                bucket_size=bucket, impl=impl,
            )
        in_specs = (spec, spec, spec)
        out_specs = spec
        args = (mk(heads), mk(kv_heads), mk(kv_heads))
    elif strategy == "tree_decode":
        def core(q, k, v):
            return tree_attn_decode(
                q, k, v, axis_name=SEQ_AXIS, bucket_size=bucket, impl=impl,
            )
        in_specs = (rep, spec, spec)
        out_specs = rep
        args = (mk(heads, 1), mk(kv_heads), mk(kv_heads))
    elif strategy == "blockwise_ffn":
        # the one auto-sharded (GSPMD) row: the chunked FeedForward runs
        # under the partitioner like the model path does, NOT inside
        # shard_map — the contract pins what the partitioner inserts.
        # fn(x, w_in, w_out) keeps build_entry's uniform 3-arg shape so
        # _direction_fn's (0, 1, 2) grads produce the weight all-reduces.
        import jax

        from ..models.layers import FeedForward

        world = dims["world"]
        ff = FeedForward(
            dim=dim_head, mult=4, chunk_size=max(seq // world // 2, 1),
            seq_shards=world, mesh=mesh,
        )
        x = jnp.asarray(rng.standard_normal((b, seq, dim_head)), jnp.float32)
        params = ff.init(jax.random.PRNGKey(0), x)
        gamma = params["params"]["RMSNorm_0"]["gamma"]

        def ffn(x, w_in, w_out):
            p = {"params": {
                "RMSNorm_0": {"gamma": gamma},
                "Dense_0": {"kernel": w_in},
                "Dense_1": {"kernel": w_out},
            }}
            return ff.apply(p, x)

        x = jax.device_put(x, NamedSharding(
            mesh, P(dspec, seq_partition(mesh), None)
        ))
        return ffn, (
            x,
            params["params"]["Dense_0"]["kernel"],
            params["params"]["Dense_1"]["kernel"],
        ), dims
    else:
        raise KeyError(f"unknown strategy {strategy!r}; "
                       f"known: {sorted(CONTRACTS)}")

    fn = compat.shard_map(
        core, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=(impl != "pallas"),
    )
    return fn, args, dims


def _direction_fn(fn, direction: str):
    import jax

    if direction == "fwd":
        return fn
    if direction == "fwdbwd":
        def grads(q, k, v):
            return jax.grad(
                lambda q, k, v: fn(q, k, v).sum(), argnums=(0, 1, 2)
            )(q, k, v)
        return grads
    raise ValueError(f"unknown direction {direction!r}")


def verify_hlo(strategy: str, direction: str, txt: str,
               dims: dict[str, int], mesh_shape: tuple[int, ...],
               axis_names: list[str]) -> list[str]:
    """Check one compiled program's optimized-HLO text against a
    strategy's contract: exact counts for every declared collective kind,
    zero for every undeclared kind, axis discipline for permute pairs and
    replica groups.  Returns one-line violations (empty = contract holds).

    This is the shared core behind :func:`check_strategy`, the test-suite
    pins, and negative-case toys — anything that can produce HLO text can
    be held to a contract.
    """
    contract = CONTRACTS[strategy]
    counts = hlo_collective_counts(txt)
    expected = expected_counts(strategy, direction, dims)
    violations: list[str] = []

    for kind in HLO_COLLECTIVE_KINDS:
        got = counts.get(kind, 0)
        want = expected.get(kind, 0)
        if got != want:
            expr = contract["hlo"][direction].get(kind, "0 (undeclared)")
            violations.append(
                f"{strategy}/{direction}: {kind} x{got}, contract says "
                f"{want} ({expr!r} at {dims_str(dims)}) "
                f"[rule: collective-contract]"
            )

    for kind, axis in contract.get("axes", {}).items():
        if axis not in axis_names:
            continue
        axis_index = axis_names.index(axis)
        if kind == "collective-permute":
            violations.extend(check_pairs_axis(
                hlo_ppermute_pairs(txt), mesh_shape, axis_index, axis,
            ))
        else:
            violations.extend(check_groups_axis(
                txt, kind, mesh_shape, axis_index, axis,
            ))

    if contract.get("both_directions"):
        axis = contract["axes"]["collective-permute"]
        if axis in axis_names:
            axis_index = axis_names.index(axis)
            size = mesh_shape[axis_index]
            shifts = set()
            for ps in hlo_ppermute_pairs(txt):
                for s, t in ps:
                    cs = _device_coords(s, mesh_shape)
                    ct = _device_coords(t, mesh_shape)
                    shifts.add((ct[axis_index] - cs[axis_index]) % size)
            if size > 1 and not {1, size - 1} <= shifts:
                violations.append(
                    f"{strategy}/{direction}: permute shifts {sorted(shifts)} "
                    f"do not cover both ring directions (+1 and -1) — the "
                    f"counter-rotation must load both full-duplex link "
                    f"directions [rule: both-directions]"
                )
    return violations


def check_hop_bytes(strategy: str, direction: str, dims: dict[str, int],
                    ppermute_bytes: list[int]) -> list[str]:
    """Pin the smallest/largest circulating ppermute payload against the
    contract's declared bytes-per-hop expressions (jaxpr-level avals —
    backend-independent, immune to the CPU runtime's dtype promotions)."""
    contract = CONTRACTS[strategy]
    exprs = contract.get("hop_bytes", {}).get(direction)
    if not exprs:
        return []
    if not ppermute_bytes:
        return [f"{strategy}/{direction}: no ppermute payloads found but "
                f"hop_bytes declared [rule: hop-bytes]"]
    out = []
    got = {"min": min(ppermute_bytes), "max": max(ppermute_bytes)}
    for bound, expr in exprs.items():
        want = int(eval(expr, {"__builtins__": {}}, dict(dims)))  # noqa: S307 - table-only
        if got[bound] != want:
            out.append(
                f"{strategy}/{direction}: {bound} ppermute payload "
                f"{got[bound]} bytes, contract says {want} ({expr!r} at "
                f"{dims_str(dims)}) [rule: hop-bytes]"
            )
    return out


def check_strategy(strategy: str, mesh=None, *, directions=None,
                   **shape_kw) -> list[ContractReport]:
    """Verify one strategy's collective contract on a mesh.

    For each direction the entry point is compiled and its optimized HLO
    checked against the declarative table: exact counts per declared
    collective kind, zero for every undeclared kind, axis discipline for
    permute pairs and replica groups — plus the jaxpr-structure rules
    (scan-aware counts where declared; never a collective inside a
    ``lax.cond`` branch).  Returns one :class:`ContractReport` per
    direction; a report with a non-empty ``violations`` list failed.
    """
    import jax

    from ..utils import compat

    contract = CONTRACTS[strategy]
    if mesh is None:
        mesh = default_mesh(strategy)
    if directions is None:
        directions = contract.get("directions", ("fwd", "fwdbwd"))
    mesh_shape = tuple(mesh.shape.values())
    axis_names = list(mesh.shape.keys())

    fn, args, dims = build_entry(strategy, mesh, **shape_kw)
    reports = []
    for direction in directions:
        dfn = _direction_fn(fn, direction)
        report = ContractReport(
            strategy=strategy, direction=direction, impl=contract["impl"],
            mesh_shape=mesh_shape, dims=dims,
        )
        txt = compat.jit(dfn).lower(*args).compile().as_text()
        report.counts = hlo_collective_counts(txt)
        report.expected = expected_counts(strategy, direction, dims)
        report.violations.extend(verify_hlo(
            strategy, direction, txt, dims, mesh_shape, axis_names,
        ))

        # traced structure: scan-aware counts + the no-collective-in-cond rule
        jc = jaxpr_collectives(jax.make_jaxpr(dfn)(*args))
        report.jaxpr_counts = jc.counts
        report.violations.extend(check_hop_bytes(
            strategy, direction, dims, jc.ppermute_bytes,
        ))
        if jc.in_cond:
            report.violations.append(
                f"{strategy}/{direction}: collective(s) {sorted(set(jc.in_cond))} "
                f"inside a lax.cond branch — data-dependent collective "
                f"schedules deadlock SPMD programs [rule: no-cond-collective]"
            )
        if jc.dynamic:
            report.violations.append(
                f"{strategy}/{direction}: collective(s) "
                f"{sorted(set(jc.in_while))} inside a lax.while_loop body — "
                f"trip count unknown statically, collective counts "
                f"unverifiable [rule: no-while-collective]"
            )
        reports.append(report)
    return reports


def check_scan_contract(strategy: str, mesh=None, *, directions=None,
                        **shape_kw) -> list[ContractReport]:
    """The traced (``impl="xla"``, scanned-hop) side of a strategy's
    contract: jaxpr collective counts with scan multipliers."""
    import jax

    contract = dict(CONTRACTS[strategy])
    if "scan" not in contract:
        raise KeyError(f"{strategy} declares no scan contract")
    if mesh is None:
        mesh = default_mesh(strategy)
    if directions is None:
        directions = tuple(contract["scan"])

    # rebuild the entry on the scanned XLA path
    fn, args, dims = build_entry(strategy, mesh, impl="xla", **shape_kw)

    reports = []
    for direction in directions:
        dfn = _direction_fn(fn, direction)
        report = ContractReport(
            strategy=strategy, direction=direction, impl="xla",
            mesh_shape=tuple(mesh.shape.values()), dims=dims,
        )
        jc = jaxpr_collectives(jax.make_jaxpr(dfn)(*args))
        report.jaxpr_counts = jc.counts
        report.expected = expected_counts(strategy, direction, dims,
                                          table="scan")
        for prim, want in report.expected.items():
            got = jc.counts.get(prim, 0)
            if got != want:
                expr = CONTRACTS[strategy]["scan"][direction][prim]
                report.violations.append(
                    f"{strategy}/{direction} (traced): {prim} x{got}, "
                    f"contract says {want} ({expr!r} at {dims_str(dims)}) "
                    f"[rule: collective-contract]"
                )
        if jc.in_cond:
            report.violations.append(
                f"{strategy}/{direction} (traced): collective(s) "
                f"{sorted(set(jc.in_cond))} inside a lax.cond branch "
                f"[rule: no-cond-collective]"
            )
        if jc.dynamic:
            report.violations.append(
                f"{strategy}/{direction} (traced): collective(s) "
                f"{sorted(set(jc.in_while))} inside a lax.while_loop body — "
                f"trip count unknown statically [rule: no-while-collective]"
            )
        reports.append(report)
    return reports


# The fused-ring row (ops/pallas_ring.py::fused_ring_remote) pins a
# DIFFERENT surface from the scan-path contracts above: hops are in-kernel
# remote DMAs, so the proof counts Mosaic DMA/semaphore primitives from the
# traced kernel body instead of HLO collectives.  The counts are structural
# (static ``pl.when`` branches, a once-traced ``fori_loop`` body), so they
# are ring-size and shard-size independent.  They were hand-derived here
# through PR 18 (dma_start 14, dma_wait 14, signal 3, wait 2, barrier 1);
# since the protocol verifier landed they are DERIVED from the declared
# schedule — the per-row ``sites`` fields of ops/pallas_ring.py::PROTOCOL,
# summed by schedverify.derived_fused_counts() — so the contract pin and
# the model-checked protocol cannot disagree silently.  The zero-ppermute
# pin (the launch-free-hops claim itself) rides along in the derivation.
FUSED_RING_PRIMS = (
    "dma_start", "dma_wait", "semaphore_signal", "semaphore_wait",
    "get_barrier_semaphore", "ppermute",
)


def _derived_fused_expected() -> dict[str, int]:
    from .schedverify import derived_fused_counts

    return derived_fused_counts()


def __getattr__(name: str):
    # FUSED_RING_EXPECTED stays importable (tests pin against it) but is
    # computed from the verified PROTOCOL table, not hand-maintained.
    if name == "FUSED_RING_EXPECTED":
        return _derived_fused_expected()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def jaxpr_primitive_counts(closed_jaxpr, names) -> dict[str, int]:
    """Exhaustive primitive counts from a traced program, descending into
    every sub-jaxpr a param carries (scan/cond/while bodies, shard_map,
    pallas_call kernels) — unlike :func:`jaxpr_collectives` there is no
    scan multiplication; this counts traced instructions."""
    counts: Counter = Counter()

    def walk(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in names:
                counts[eqn.primitive.name] += 1
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub)

    walk(closed_jaxpr.jaxpr)
    return dict(counts)


def trace_fused_ring(*, quantized: bool = False, b: int = 1, heads: int = 4,
                     kv_heads: int = 2, seq: int = 256, dim_head: int = 16):
    """Trace the single-launch remote kernel under ``shard_map`` on the
    full-device CPU ring — the shared feed for the fused contract row AND
    schedverify's jaxpr extraction.  Returns ``(closed_jaxpr, dims)``;
    make_jaxpr only, nothing compiles or runs."""
    import jax
    import jax.numpy as jnp

    from ..ops import pallas_ring
    from ..ops import quant as _quant
    from ..parallel.mesh import SEQ_AXIS, data_partition, seq_partition
    from ..utils import compat
    from jax.sharding import PartitionSpec as P

    mesh = default_mesh("ring")
    dims = _mesh_dims(mesh)
    ring = dims["ring"]
    n_local = seq // ring
    dims.update(b=b, heads=heads, kv_heads=kv_heads, seq=seq,
                dim_head=dim_head, chunk=n_local)
    rng = np.random.default_rng(0)
    b_full = b * dims["data"] * dims["dcn"]

    def mk(h):
        return jnp.asarray(rng.standard_normal((b_full, h, seq, dim_head)),
                           jnp.float32)

    def core(q, k, v):
        his = jnp.full((ring,), n_local, jnp.int32)
        los = jnp.full((ring,), -n_local, jnp.int32)
        works = jnp.ones((ring,), jnp.int32)
        # per-axis MESH coordinates — this mesh is multi-axis (data/dcn
        # around the ring), exactly the shape where a ring-rank-only
        # LOGICAL id would address the wrong replica group
        nbr_coords = pallas_ring.neighbor_mesh_coords(SEQ_AXIS, ring)
        payload = (_quant.pack_kv(k, v, v_block=n_local)
                   if quantized else None)
        out, _ = pallas_ring.fused_ring_remote(
            q, k, v, his=his, los=los, works=works, nbr_coords=nbr_coords,
            scale=dim_head ** -0.5, payload=payload,
        )
        return out

    spec = P(data_partition(mesh), None, seq_partition(mesh), None)
    fn = compat.shard_map(
        core, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(fn)(mk(heads), mk(kv_heads), mk(kv_heads))
    return jaxpr, dims


def check_fused_ring_contract(
    *, quantized: bool = False, b: int = 1, heads: int = 4,
    kv_heads: int = 2, seq: int = 256, dim_head: int = 16,
) -> ContractReport:
    """The fused-ring contract row: trace the single-launch remote kernel
    under ``shard_map`` on the full-device CPU ring and hold its traced
    body to the schedverify-derived expected counts — the in-kernel remote
    copies and semaphore handshakes declared by the verified PROTOCOL
    table, and zero ``ppermute``s (the scan-path ring's per-hop collective
    has no business in the fused forward).  The ``quantized`` variant
    feeds PR 13's packed int8 payload through the same kernel and must
    produce IDENTICAL counts: scales ride the KV buffer, never their own
    copy."""
    jaxpr, dims = trace_fused_ring(
        quantized=quantized, b=b, heads=heads, kv_heads=kv_heads, seq=seq,
        dim_head=dim_head,
    )
    mesh = default_mesh("ring")
    counted = jaxpr_primitive_counts(jaxpr, FUSED_RING_PRIMS)

    report = ContractReport(
        strategy="fused_ring_q8" if quantized else "fused_ring",
        direction="fwd", impl="fused",
        mesh_shape=tuple(mesh.shape.values()), dims=dims,
        # zeros stay explicit: "ppermute": 0 IS the launch-free-hops pin
        counts={p: counted.get(p, 0) for p in FUSED_RING_PRIMS},
        expected=_derived_fused_expected(),
    )
    for prim, want in report.expected.items():
        got = report.counts.get(prim, 0)
        if got != want:
            rule = ("launch-free-hops" if prim == "ppermute"
                    else "fused-ring-dma")
            report.violations.append(
                f"{report.strategy}/fwd (traced kernel): {prim} x{got}, "
                f"contract says {want} at {dims_str(dims)} [rule: {rule}]"
            )
    return report


def check_hybrid_hop_reduction(world: int | None = None, ulysses: int = 2,
                               **shape_kw) -> ContractReport:
    """The tentpole relation, proven from two compiled programs: at equal
    sequence-parallel world, the hybrid factoring's ring hop count is
    exactly ``ulysses``-x smaller (``world/ulysses - 1`` vs ``world - 1``)."""
    import jax

    from ..parallel.mesh import create_mesh
    from ..utils import compat

    if world is None:
        world = len(jax.devices())
    hmesh = create_mesh(ulysses_size=ulysses, ring_size=world // ulysses)
    rmesh = create_mesh(ring_size=world)

    hfn, hargs, hdims = build_entry("hybrid", hmesh, **shape_kw)
    rfn, rargs, rdims = build_entry("ring", rmesh, **shape_kw)
    hops_h = hlo_collective_counts(
        compat.jit(hfn).lower(*hargs).compile().as_text()
    ).get("collective-permute", 0)
    hops_r = hlo_collective_counts(
        compat.jit(rfn).lower(*rargs).compile().as_text()
    ).get("collective-permute", 0)

    report = ContractReport(
        strategy="hybrid_vs_ring", direction="fwd", impl="pallas",
        mesh_shape=tuple(hmesh.shape.values()),
        dims={**hdims, "pure_ring_world": world},
        counts={"hybrid_hops": hops_h, "pure_ring_hops": hops_r},
        expected={"hybrid_hops": world // ulysses - 1,
                  "pure_ring_hops": world - 1},
    )
    if hops_r != world - 1:
        report.violations.append(
            f"pure ring at world {world}: {hops_r} hops, contract says "
            f"{world - 1} [rule: hop-reduction]"
        )
    if hops_h != world // ulysses - 1:
        report.violations.append(
            f"hybrid at world {world} (ulysses {ulysses}): {hops_h} hops, "
            f"contract says {world // ulysses - 1} [rule: hop-reduction]"
        )
    if (hops_h + 1) * ulysses != hops_r + 1:
        report.violations.append(
            f"hybrid hop chain ({hops_h + 1} rotations incl. the elided "
            f"last) is not ulysses-x ({ulysses}) shorter than the pure "
            f"ring's ({hops_r + 1}) [rule: hop-reduction]"
        )
    return report


def check_counter_collective_budget(**shape_kw) -> ContractReport:
    """The counter-rotation acceptance pin, proven from compiled programs:
    a counter-rotated train step (fwd + bwd) issues NO MORE collectives
    than the unidirectional baseline's — ``2 * ring`` vs ``3 * ring - 2``
    (fwd alone pays one extra for the out/lse catch-up, ``ring`` vs
    ``ring - 1``; the backward's resident-KV schedule repays it with
    ``ring`` vs ``2 * ring - 1``)."""
    import jax

    from ..utils import compat

    mesh = default_mesh("ring")
    ring = _mesh_dims(mesh)["ring"]

    def permutes(strategy, direction):
        fn, args, _ = build_entry(strategy, mesh, **shape_kw)
        dfn = _direction_fn(fn, direction)
        txt = compat.jit(dfn).lower(*args).compile().as_text()
        return hlo_collective_counts(txt).get("collective-permute", 0)

    base_fwd = permutes("ring", "fwd")
    base_step = permutes("ring", "fwdbwd")
    ctr_fwd = permutes("counter", "fwd")
    ctr_step = permutes("counter", "fwdbwd")

    report = ContractReport(
        strategy="counter_vs_ring", direction="fwdbwd", impl="pallas",
        mesh_shape=tuple(mesh.shape.values()), dims={"ring": ring},
        counts={"counter_fwd": ctr_fwd, "counter_step": ctr_step,
                "baseline_fwd": base_fwd, "baseline_step": base_step},
        expected={"counter_fwd": ring, "counter_step": 2 * ring,
                  "baseline_fwd": ring - 1, "baseline_step": 3 * ring - 2},
    )
    for key, want in report.expected.items():
        if report.counts[key] != want:
            report.violations.append(
                f"{key}: {report.counts[key]} collective-permutes, contract "
                f"says {want} at ring={ring} [rule: counter-budget]"
            )
    if ctr_step > base_step:
        report.violations.append(
            f"counter-rotated step issues {ctr_step} collective-permutes, "
            f"MORE than the unidirectional baseline's {base_step} "
            f"[rule: counter-budget]"
        )
    return report


def hlo_dcn_isolation(
    txt: str, mesh_shape: tuple[int, ...], axis_names: list[str]
) -> list[str]:
    """The pod-scale placement proof: ZERO sequence-parallel collectives
    cross the ``dcn_data`` axis in optimized HLO.

    Every collective-permute pair and every all-to-all / all-gather /
    reduce-scatter replica group must keep the dcn coordinate fixed —
    a ring hop or head all-to-all that touches two dcn groups is riding
    the slow inter-slice links TASP (arXiv 2509.26541) places sequence
    parallelism to avoid.  ``all-reduce`` is exempt: the once-per-step
    gradient reduction is the ONE collective that legitimately spans DCN.
    Returns one-line violations.
    """
    from ..parallel.mesh import DCN_DATA_AXIS

    if DCN_DATA_AXIS not in axis_names:
        return [f"mesh axes {axis_names} carry no {DCN_DATA_AXIS} axis — "
                f"nothing to prove [rule: dcn-isolation]"]
    dcn_i = axis_names.index(DCN_DATA_AXIS)
    out: list[str] = []
    for inst, pairs in enumerate(hlo_ppermute_pairs(txt)):
        for s, t in pairs:
            cs = _device_coords(s, mesh_shape)
            ct = _device_coords(t, mesh_shape)
            if cs[dcn_i] != ct[dcn_i]:
                out.append(
                    f"collective-permute #{inst}: pair {s}->{t} crosses "
                    f"the dcn_data axis (coords {cs}->{ct}) — a ring hop "
                    f"over DCN [rule: dcn-isolation]"
                )
    for kind in ("all-to-all", "all-gather", "reduce-scatter"):
        inst_re = re.compile(
            r"%?" + re.escape(kind) + r"(?:-start)?[.\d]* = [^\n]*"
        )
        for inst, line in enumerate(inst_re.findall(txt)):
            groups = _parse_replica_groups(line)
            if groups is None:
                continue
            if isinstance(groups, str):
                out.append(
                    f"{kind} #{inst}: unrecognized replica_groups format "
                    f"{groups!r} — cannot verify dcn isolation "
                    f"[rule: dcn-isolation]"
                )
                continue
            for g in groups:
                coords = {_device_coords(d, mesh_shape)[dcn_i] for d in g}
                if len(coords) > 1:
                    out.append(
                        f"{kind} #{inst}: group {g} spans dcn_data "
                        f"coordinates {sorted(coords)} [rule: dcn-isolation]"
                    )
    return out


def jaxpr_collective_axis_names(closed_jaxpr) -> dict[str, set]:
    """Axis names each collective primitive binds in a traced program —
    the jaxpr half of the dcn-isolation proof (an ``axis_name`` is the
    mesh axis the collective moves data over)."""
    res: dict[str, set] = {}

    def walk(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in JAXPR_COLLECTIVE_PRIMS:
                axes = eqn.params.get("axis_name", ())
                if not isinstance(axes, (tuple, list)):
                    axes = (axes,)
                res.setdefault(name, set()).update(str(a) for a in axes)
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub)

    walk(closed_jaxpr.jaxpr)
    return res


def check_dcn_isolation(
    *, dcn: int = 2, ulysses: int = 2, directions=None, **shape_kw
) -> list[ContractReport]:
    """The hierarchical-mesh contract rows: the ring and hybrid entries
    compiled over a ``(dcn_data, data, ...)`` mesh hold their ordinary
    collective contracts AND provably issue zero sequence-parallel
    collectives over the dcn axis — from optimized HLO
    (:func:`hlo_dcn_isolation`) and from the jaxpr walk
    (:func:`jaxpr_collective_axis_names`).  Rows: ``ring_dcn`` always,
    ``hybrid_dcn`` when the per-group world still factors as
    ring x ulysses."""
    import jax

    from ..parallel.mesh import DCN_DATA_AXIS, create_mesh
    from ..utils import compat

    n = len(jax.devices())
    if n % dcn or n // dcn < 2:
        raise ValueError(
            f"check_dcn_isolation: need >= {2 * dcn} devices factorable "
            f"by dcn={dcn}, have {n}"
        )
    inner = n // dcn
    cases = [("ring", create_mesh(dcn_data_size=dcn, ring_size=inner))]
    if inner % ulysses == 0 and inner // ulysses >= 2:
        cases.append((
            "hybrid",
            create_mesh(dcn_data_size=dcn, ring_size=inner // ulysses,
                        ulysses_size=ulysses),
        ))
    reports: list[ContractReport] = []
    for strategy, mesh in cases:
        mesh_shape = tuple(mesh.shape.values())
        axis_names = list(mesh.shape.keys())
        fn, args, dims = build_entry(strategy, mesh, **shape_kw)
        dirs = directions or CONTRACTS[strategy].get(
            "directions", ("fwd", "fwdbwd")
        )
        for direction in dirs:
            dfn = _direction_fn(fn, direction)
            report = ContractReport(
                strategy=f"{strategy}_dcn", direction=direction,
                impl=CONTRACTS[strategy]["impl"], mesh_shape=mesh_shape,
                dims=dims,
            )
            txt = compat.jit(dfn).lower(*args).compile().as_text()
            report.counts = hlo_collective_counts(txt)
            report.expected = expected_counts(strategy, direction, dims)
            # the ordinary contract (exact counts, axis discipline, no
            # undeclared kinds) still holds at the dcn factoring...
            report.violations.extend(verify_hlo(
                strategy, direction, txt, dims, mesh_shape, axis_names,
            ))
            # ...plus the hierarchical placement rule itself
            report.violations.extend(
                hlo_dcn_isolation(txt, mesh_shape, axis_names)
            )
            axes_by_prim = jaxpr_collective_axis_names(
                jax.make_jaxpr(dfn)(*args)
            )
            report.jaxpr_counts = {
                prim: sorted(axes) for prim, axes in axes_by_prim.items()
            }
            for prim, axes in axes_by_prim.items():
                if DCN_DATA_AXIS in axes:
                    report.violations.append(
                        f"{strategy}_dcn/{direction} (traced): {prim} "
                        f"binds the {DCN_DATA_AXIS} axis — sequence "
                        f"parallelism crossed DCN [rule: dcn-isolation]"
                    )
            reports.append(report)
    return reports


def dcn_collective_fingerprint(*, dcn: int = 2, ulysses: int = 2) -> dict:
    """The multihost-dryrun comms signature for the bench JSON (phase
    0e): per-row forward collective counts over the hierarchical
    ``(dcn_data, ...)`` mesh, plus the machine-checked verdict that no
    sequence-parallel collective crossed the dcn axis.  CPU-runnable —
    it lands even on wedged-TPU rounds, and ``analysis/perfgate.py``
    gates it exactly like the flat-mesh fingerprint."""
    out: dict[str, Any] = {}
    ok = True
    for report in check_dcn_isolation(
        dcn=dcn, ulysses=ulysses, directions=("fwd",)
    ):
        out[report.strategy] = {
            k.replace("collective-permute", "ppermute")
             .replace("all-to-all", "all_to_all")
             .replace("all-gather", "all_gather")
             .replace("all-reduce", "all_reduce"): v
            for k, v in sorted(report.counts.items())
        }
        ok = ok and report.ok
    out["dcn_ok"] = ok
    return out


def dims_str(dims: dict[str, int]) -> str:
    return ", ".join(f"{k}={v}" for k, v in sorted(dims.items()))


def run_contract_suite(strategies=None, *, scan: bool = True,
                       **shape_kw) -> list[ContractReport]:
    """Every strategy's contract on its canonical CPU mesh, plus the
    hybrid-vs-ring hop-reduction relation.  The CLI and the bench
    fingerprint both run exactly this."""
    if strategies is None or strategies == "all":
        strategies = list(CONTRACTS)
    reports: list[ContractReport] = []
    for strategy in strategies:
        reports.extend(check_strategy(strategy, **shape_kw))
        if scan and "scan" in CONTRACTS[strategy]:
            reports.extend(check_scan_contract(strategy, **shape_kw))
    if "hybrid" in strategies and "ring" in strategies:
        reports.append(check_hybrid_hop_reduction(**shape_kw))
    if "counter" in strategies and "ring" in strategies:
        reports.append(check_counter_collective_budget(**shape_kw))
    if "ring" in strategies:
        import jax

        if len(jax.devices()) >= 4:
            reports.extend(check_dcn_isolation(**shape_kw))
        reports.append(check_fused_ring_contract())
        reports.append(check_fused_ring_contract(quantized=True))
    return reports


def collective_fingerprint(
    strategies=("ring", "ulysses", "hybrid", "counter", "ring_compressed",
                "counter_q8", "blockwise_ffn"),
) -> dict:
    """Compact comms signature for the bench JSON: per-strategy forward
    collective counts from compiled HLO, so a perf trajectory catches a
    hop-count or accidental-gather regression even when tokens/sec moves
    for other reasons.  The counter-rotation and int8-compressed ring
    variants ride along so a comms regression in either shows up on a
    wedged-TPU round too (the CPU fingerprint is the primary signal,
    ROADMAP item 5)."""
    out: dict[str, Any] = {}
    ok = True
    for strategy in strategies:
        reports = check_strategy(strategy, directions=("fwd",))
        rep = reports[0]
        out[strategy] = {
            k.replace("collective-permute", "ppermute")
             .replace("all-to-all", "all_to_all")
             .replace("all-gather", "all_gather")
             .replace("all-reduce", "all_reduce"): v
            for k, v in sorted(rep.counts.items())
        }
        ok = ok and rep.ok
    # the fused-ring rows speak Mosaic primitives, not HLO collectives:
    # in-kernel remote-copy/semaphore counts with the zero-ppermute pin
    for quantized in (False, True):
        rep = check_fused_ring_contract(quantized=quantized)
        out[rep.strategy] = dict(sorted(rep.counts.items()))
        ok = ok and rep.ok
    out["contract_ok"] = ok
    return out
