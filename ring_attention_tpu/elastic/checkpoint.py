"""Sharded, async, manifest-committed checkpoints with elastic restore.

PR 1's :class:`~ring_attention_tpu.utils.checkpoint.CheckpointManager`
writes one monolithic ``arrays.npz`` per step from the main thread and
can only resume at the identical device count.  At ring-attention scale
that is three separate walls: the full state must fit one host buffer,
the train loop stalls for the whole serialization, and a preempted job
that comes back on a different slice shape cannot restart at all.  This
manager removes all three:

**Sharded layout.**  Each step directory holds one ``shard_dNNN.npz``
per addressable-shard group (the device that owns the shard — replicated
leaves are stored once, by their first holder), with every leaf entry
stored as its raw bytes (dtype-agnostic: bf16 and any future ml_dtypes
kind round-trip bit-exactly).  A SHA-256 per shard file is recorded in
the manifest.

**Manifest commit.**  The manifest (step, mesh descriptor, per-leaf
shape/dtype/sharding spec, shard index table, shard digests) is the LAST
file written into the pid-stamped staging directory, which is then
``os.replace``d into place — one atomic rename commits the whole step.
A death at ANY instant leaves either the previous checkpoint or the new
one fully valid, never a torn mix: no committed step directory can lack
its manifest, and a half-written staging dir (dead writer pid) is swept
by the next save.  The chaos harness (:mod:`.chaos`) plants hard-death
points at every window of this protocol, and ``tests/test_elastic.py``
kills real processes at each of them.

**Async, double-buffered saves.**  ``save()`` snapshots the state to
host memory synchronously (the only part that must not race the next
step's donated buffers) and does file I/O + hashing on a background
thread; the train loop overlaps the write with the next steps and a
background failure surfaces on the next ``save()``/``wait()`` instead of
vanishing.

**Elastic restore.**  ``restore()`` rebuilds each leaf at the CURRENT
mesh with ``jax.make_array_from_callback``: every target shard is
assembled on host by global-position gather/scatter from whichever old
shard files overlap it, streaming one shard at a time — peak host memory
is one target shard plus one old shard, never the global array.  The
same code path serves same-mesh resume (target index == stored index,
one copy) and re-mesh resume (4 -> 2 or 2 -> 4 devices); values are
restored bit-exactly either way, so the loss trajectory continues within
reduction-order noise (pinned in ``tests/test_elastic.py``).  Corrupt or
truncated shard files fail the digest check and fall back (one warning)
to the newest older intact step, exactly like PR 1's manager.

**Multi-process (pod-scale) protocol.**  Under ``jax.distributed``
(``jax.process_count() > 1``, shared checkpoint filesystem) the same
manager coordinates every process:

- shard ownership is GLOBAL: the owner of each distinct shard region is
  the lowest-id device holding it (``leaf.global_shards``), and each
  process writes only the regions its addressable devices own — replicas
  are stored once cluster-wide, write sets are disjoint by construction;
- all processes write into ONE deterministic staging directory; each
  records its shard files + digests in a ``shards_pNNNNN.json`` sidecar;
- process 0 merges the sidecars into the manifest and commits it LAST —
  behind a cross-process barrier (the ``jax.distributed`` coordinator's
  KV barrier, not a device collective, so async-save threads never race
  the training step's collectives) — with the same single atomic rename.
  A kill of ANY worker at ANY instant leaves the previous-or-new
  invariant intact: a dead peer turns the barrier into a one-line
  ``BarrierTimeout`` on the survivors (never an eternal hang), and the
  half-written staging dir is swept later;
- restore is read-only and per-process: every process assembles only its
  addressable target shards from whichever stored shards overlap them,
  so a checkpoint written by 2 processes restores on 1 (and vice versa)
  through the identical gather/scatter path, bit-exactly
  (``tests/test_multihost.py`` pins 2 -> 1 and 1 -> 2).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import warnings
from typing import Any, Callable

import jax

from ..utils.checkpoint import (
    CheckpointCorruptError,
    CheckpointStructureError,
    _fsync_dir,
    _sha256,
)
from ..utils import tracing
from ..utils.resilience import DirectoryLock, pid_alive
from . import chaos

_STEP_PREFIX = "step_"
_MANIFEST = "manifest.json"
MANIFEST_FORMAT = "elastic-ckpt"
MANIFEST_VERSION = 1

# manifest keys every reader requires; load_manifest rejects anything less
_REQUIRED_KEYS = (
    "format", "version", "step", "mesh", "treedef", "leaf_count",
    "leaves", "files",
)


def _np():
    import numpy as np

    return np


def _np_dtype(name: str):
    """Resolve a dtype name, including ml_dtypes kinds (bfloat16...)."""
    np = _np()
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _spec_to_json(sharding) -> list | None:
    """PartitionSpec of a NamedSharding as JSON (None for other kinds)."""
    from jax.sharding import NamedSharding

    if not isinstance(sharding, NamedSharding):
        return None
    out: list = []
    for entry in tuple(sharding.spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(e) for e in entry])
        else:
            out.append(str(entry))
    return out


def _norm_index(index, shape) -> list[list[int]]:
    """A shard's index (tuple of slices) as [[start, stop], ...]."""
    out = []
    for slc, dim in zip(index, shape):
        start, stop, step = slc.indices(dim)
        if step != 1:
            raise ValueError(f"strided shard index unsupported: {slc}")
        out.append([int(start), int(stop)])
    return out


def load_manifest(path: str) -> dict:
    """Read + schema-validate one ``manifest.json``; raises
    :class:`CheckpointCorruptError` on unreadable/unknown manifests (the
    restore path treats both as "that step never completed")."""
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(f"unreadable manifest {path} ({e})") from e
    if manifest.get("format") != MANIFEST_FORMAT or manifest.get(
        "version"
    ) != MANIFEST_VERSION:
        raise CheckpointCorruptError(
            f"{path}: not an elastic checkpoint manifest "
            f"(format={manifest.get('format')!r} "
            f"version={manifest.get('version')!r}; this reader understands "
            f"{MANIFEST_FORMAT}/{MANIFEST_VERSION})"
        )
    missing = [k for k in _REQUIRED_KEYS if k not in manifest]
    if missing:
        raise CheckpointCorruptError(
            f"{path}: manifest missing required keys {missing}"
        )
    if len(manifest["leaves"]) != manifest["leaf_count"]:
        raise CheckpointCorruptError(
            f"{path}: leaf table length {len(manifest['leaves'])} != "
            f"leaf_count {manifest['leaf_count']}"
        )
    return manifest


class AsyncSaveError(RuntimeError):
    """A background checkpoint write failed; carries the original error
    and is raised on the NEXT ``save()``/``wait()`` so the failure cannot
    silently cost every subsequent checkpoint too."""


class BarrierTimeout(RuntimeError):
    """A cross-process checkpoint barrier expired — a peer process died
    (or wedged) mid-protocol.  The save fails with THIS one-line error on
    every survivor instead of hanging them; the half-written staging
    directory is invisible to ``all_steps`` and swept by a later save."""


def _distributed_client():
    """The jax.distributed coordinator's KV-store client, or None when
    the process runs standalone.  Its ``wait_at_barrier`` is a host-side
    rendezvous — safe from the async-save background thread, where a
    device collective would interleave with the training step's."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — older jax layouts: no client
        return None


def cross_process_barrier(name: str, *, timeout_s: float = 600.0) -> None:
    """Block until every process reaches barrier ``name`` (unique per
    rendezvous).  Single-process: a no-op.  A peer that never arrives
    turns into :class:`BarrierTimeout` after ``timeout_s``."""
    if jax.process_count() <= 1:
        return
    tracer = tracing.get_tracer()
    # the wait is a span (its duration IS the straggler signal: the
    # survivor of a dead peer shows one long barrier/wait ending in
    # BarrierTimeout); the EXIT is a rendezvous stamp — every process
    # leaves the same barrier at nearly the same true instant, which is
    # what the merger's clock-offset correction aligns on
    with tracer.span("barrier/wait", barrier=name, timeout_s=timeout_s):
        client = _distributed_client()
        if client is None:
            # no coordinator client exposed on this build: the
            # device-level barrier still rendezvouses (main thread only
            # — documented)
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
        else:
            try:
                client.wait_at_barrier(name, int(timeout_s * 1000))
            except Exception as e:  # noqa: BLE001 — backend types
                raise BarrierTimeout(
                    f"cross-process barrier {name!r} expired after "
                    f"{timeout_s:.0f}s — a peer process died or wedged "
                    f"mid-checkpoint ({type(e).__name__}: {e})"
                ) from e
    tracer.rendezvous(name)


class ElasticCheckpointManager:
    """Sharded async checkpoints in ``<directory>/step_<8 digits>/``.

    See the module docstring for the commit protocol — including the
    multi-process one: under ``jax.distributed`` every process constructs
    the SAME manager over a shared filesystem, each writes only its
    addressable shard groups, and process 0 commits the manifest last
    behind a cross-process barrier (``barrier_timeout_s`` bounds how long
    a survivor waits on a dead peer).
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        keep: int = 3,
        async_save: bool = True,
        lock_stale_age: float = 30.0,
        lock_timeout: float = 600.0,
        barrier_timeout_s: float = 600.0,
    ) -> None:
        if keep < 1:
            raise ValueError(
                f"ElasticCheckpointManager: keep must be >= 1, got {keep}"
            )
        self.directory = os.fspath(os.path.abspath(directory))
        self.keep = keep
        self.async_save = async_save
        self.barrier_timeout_s = float(
            os.environ.get("RING_ATTN_ELASTIC_BARRIER_S")
            or barrier_timeout_s
        )
        self._proc = int(jax.process_index())
        self._nproc = int(jax.process_count())
        # barrier ids must be unique per rendezvous: saves/restores run in
        # lockstep across processes, so a per-manager counter agrees
        self._sync = 0
        os.makedirs(self.directory, exist_ok=True)
        self._dirlock = DirectoryLock(
            self.directory, stale_age=lock_stale_age
        )
        # generous: a multi-GB shard write + hashing legitimately holds
        # a competing manager's save out for minutes
        self.lock_timeout = lock_timeout
        self._inflight: threading.Thread | None = None
        self._error: BaseException | None = None
        self.last_resume: dict | None = None
        self.last_manifest: dict | None = None

    def _barrier(self, tag: str) -> None:
        cross_process_barrier(
            f"elastic:{os.path.basename(self.directory)}:{tag}",
            timeout_s=self.barrier_timeout_s,
        )

    @property
    def _mp_lock_budget(self) -> float:
        """Process 0's directory-lock wait inside the multi-process
        protocol: bounded by HALF the peers' barrier budget.  A worker
        killed mid-commit leaves the lock held by a dead pid, and the
        stale takeover must wait out ``stale_age`` — unbounded, process
        0 would sit in that wait while every peer's rendezvous deadline
        expired one by one.  Bounded, process 0 degrades (restore skips
        the advisory sweep) or fails (save) BEFORE the barrier does, so
        the whole cluster sees one coherent outcome."""
        return min(self.lock_timeout, max(self.barrier_timeout_s / 2.0, 1.0))

    # -- directory bookkeeping ----------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    def all_steps(self) -> list[int]:
        """Committed steps (manifest present), ascending."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            if not name.startswith(_STEP_PREFIX) or "." in name:
                continue
            try:
                step = int(name[len(_STEP_PREFIX):])
            except ValueError:
                continue
            if os.path.isfile(
                os.path.join(self.directory, name, _MANIFEST)
            ):
                steps.append(step)
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_manifest(self) -> dict | None:
        """The newest intact step's manifest (digests NOT verified —
        this is the cheap pre-restore peek re-mesh planning needs), or
        None.  Steps with unreadable manifests are skipped."""
        for step in reversed(self.all_steps()):
            try:
                return load_manifest(
                    os.path.join(self._step_dir(step), _MANIFEST)
                )
            except CheckpointCorruptError:
                continue
        return None

    def _sweep(self) -> None:
        """Delete dead writers' staging dirs and stale ``.old`` backups
        (recovering a backup whose live step vanished).  Staging dirs are
        pid-stamped; a live pid's dir belongs to a concurrent writer and
        is left alone."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for name in names:
            path = os.path.join(self.directory, name)
            if ".writing-" in name:
                try:
                    writer = int(name.rsplit("-", 1)[-1])
                except ValueError:
                    # unparsable writer suffix: same safety rule as the
                    # monolithic manager's sweep — only delete past a
                    # minimum age (it might be a live writer from a
                    # manager version with another naming scheme)
                    try:
                        age = time.time() - os.path.getmtime(path)  # ra: allow(RA014 mtime age against the filesystem wall clock, not an emitted timestamp)
                    except OSError:
                        continue
                    if age >= 60.0:
                        shutil.rmtree(path, ignore_errors=True)
                    continue
                if writer != os.getpid() and pid_alive(writer):
                    continue
                shutil.rmtree(path, ignore_errors=True)
            elif name.endswith(".old"):
                live = path[: -len(".old")]
                if os.path.isfile(os.path.join(live, _MANIFEST)):
                    shutil.rmtree(path, ignore_errors=True)
                elif os.path.isfile(os.path.join(path, _MANIFEST)):
                    shutil.rmtree(live, ignore_errors=True)
                    try:
                        os.replace(path, live)
                    except OSError:
                        pass

    def _prune(self) -> None:
        for step in self.all_steps()[: -self.keep]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)

    # -- snapshot (synchronous half of an async save) -----------------

    def _leaf_shards(self, leaf) -> list[dict]:
        """This process's owned shard payloads of one array leaf.

        Single-process: every distinct shard region once (replicas
        deduped by index).  Multi-process: the owner of a region is the
        LOWEST-id device holding it anywhere in the cluster
        (``global_shards`` exposes every region's index; only the
        addressable ones carry data) — so each region is stored exactly
        once cluster-wide and the per-process write sets are disjoint.
        """
        np = _np()
        if self._nproc > 1:
            groups: dict[tuple, list] = {}
            for shard in leaf.global_shards:
                index = tuple(
                    tuple(s.indices(d))
                    for s, d in zip(shard.index, leaf.shape)
                )
                groups.setdefault(index, []).append(shard)
            out = []
            for shards in groups.values():
                owner = min(
                    shards, key=lambda s: int(getattr(s.device, "id", 0))
                )
                if owner.data is None:  # another process's region
                    continue
                arr = np.ascontiguousarray(np.asarray(owner.data))
                out.append({
                    "owner": int(getattr(owner.device, "id", 0)),
                    "index": _norm_index(owner.index, leaf.shape),
                    "bytes": np.frombuffer(arr.tobytes(), np.uint8),
                })
            return out
        seen: dict[tuple, Any] = {}
        for shard in leaf.addressable_shards:
            index = tuple(
                tuple(s.indices(d))
                for s, d in zip(shard.index, leaf.shape)
            )
            if index in seen:  # replicated copy: store once
                continue
            seen[index] = shard
        return [{
            "owner": int(getattr(shard.device, "id", 0)),
            "index": _norm_index(shard.index, leaf.shape),
            "bytes": np.frombuffer(
                np.ascontiguousarray(np.asarray(shard.data)).tobytes(),
                np.uint8,
            ),
        } for shard in seen.values()]

    def _snapshot(self, state: Any) -> dict:
        """Copy every leaf's unique shards to host memory.

        This runs on the caller's thread BEFORE save returns: once it
        completes, the background writer holds its own host buffers and
        the train loop may donate/overwrite the device arrays freely —
        the double-buffer boundary.
        """
        np = _np()
        leaves, treedef = jax.tree_util.tree_flatten(state)
        mesh = None
        snap_leaves = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                sharding = leaf.sharding
                from jax.sharding import NamedSharding

                if isinstance(sharding, NamedSharding) and mesh is None:
                    mesh = sharding.mesh
                snap_leaves.append({
                    "shape": [int(d) for d in leaf.shape],
                    "dtype": str(leaf.dtype),
                    "spec": _spec_to_json(sharding),
                    "shards": self._leaf_shards(leaf),
                })
            else:
                arr = np.ascontiguousarray(np.asarray(leaf))
                # a host-side value is replicated by construction:
                # process 0 stores the one copy
                shards = [] if self._proc else [{
                    "owner": 0,
                    "index": [[0, int(d)] for d in arr.shape],
                    "bytes": np.frombuffer(arr.tobytes(), np.uint8),
                }]
                snap_leaves.append({
                    "shape": [int(d) for d in arr.shape],
                    "dtype": str(arr.dtype),
                    "spec": None,
                    "shards": shards,
                })
        from ..parallel.mesh import mesh_descriptor

        return {
            "treedef": str(treedef),
            "leaves": snap_leaves,
            "mesh": mesh_descriptor(mesh),
            "devices": int(jax.device_count()),
        }

    # -- write (background half) --------------------------------------

    def _stage_shards(self, stage: str, snap: dict) -> tuple[list, dict]:
        """Write THIS process's shard payloads into ``stage``: one
        ``shard_dNNN.npz`` per owner device, fsync'd and digested.
        Returns ``(leaf_table, files)`` — the per-leaf shard entries and
        per-file digests this process contributes to the manifest."""
        np = _np()
        groups: dict[str, dict[str, Any]] = {}
        leaf_table = []
        for i, leaf in enumerate(snap["leaves"]):
            entries = []
            for j, shard in enumerate(leaf["shards"]):
                fname = f"shard_d{shard['owner']:03d}.npz"
                key = f"L{i:05d}_{j:03d}"
                groups.setdefault(fname, {})[key] = shard["bytes"]
                entries.append({
                    "file": fname,
                    "key": key,
                    "index": shard["index"],
                })
            leaf_table.append({
                "shape": leaf["shape"],
                "dtype": leaf["dtype"],
                "spec": leaf["spec"],
                "shards": entries,
            })
        files = {}
        tracer = tracing.get_tracer()
        with tracer.span("ckpt/stage", files=len(groups)):
            for fname in sorted(groups):
                path = os.path.join(stage, fname)
                with open(path, "wb") as f:
                    np.savez(f, **groups[fname])
                    f.flush()
                    os.fsync(f.fileno())
                with tracer.span("ckpt/hash", file=fname):
                    digest = _sha256(path)
                files[fname] = {
                    "sha256": digest,
                    "bytes": os.path.getsize(path),
                }
                # chaos: die with SOME shard files durable and the
                # manifest absent — the torn-write window the commit
                # protocol must make unobservable
                chaos.chaos_point(chaos.KILL_MID_SHARD)
        return leaf_table, files

    def _commit(self, step: int, stage: str, final: str,
                leaf_table: list, files: dict, snap: dict) -> None:
        """Write the manifest LAST, fsync, then the one atomic rename."""
        with tracing.get_tracer().span("ckpt/commit", step=int(step)):
            self._commit_impl(step, stage, final, leaf_table, files, snap)

    def _commit_impl(self, step: int, stage: str, final: str,
                     leaf_table: list, files: dict, snap: dict) -> None:
        manifest = {
            "format": MANIFEST_FORMAT,
            "version": MANIFEST_VERSION,
            "step": int(step),
            "mesh": snap["mesh"],
            "devices": snap["devices"],
            "process_count": self._nproc,
            "treedef": snap["treedef"],
            "leaf_count": len(leaf_table),
            "leaves": leaf_table,
            "files": files,
        }
        man_path = os.path.join(stage, _MANIFEST)
        with open(man_path, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(stage)
        # chaos: die with a COMPLETE staging dir, commit rename
        # not yet executed — next boot must resume the previous
        # step and sweep this one
        chaos.chaos_point(chaos.KILL_PRE_COMMIT)
        backup = None
        if os.path.isdir(final):
            backup = final + ".old"
            shutil.rmtree(backup, ignore_errors=True)
            os.replace(final, backup)
        os.replace(stage, final)  # THE commit: one atomic rename
        _fsync_dir(self.directory)
        # chaos: die right after the commit — next boot must see
        # THIS step as valid, with only .old debris to sweep
        chaos.chaos_point(chaos.KILL_POST_COMMIT)
        if backup is not None:
            shutil.rmtree(backup, ignore_errors=True)

    def _write(self, step: int, snap: dict) -> str:
        with tracing.get_tracer().span(
            "ckpt/save", step=int(step), nproc=self._nproc
        ):
            return self._write_impl(step, snap)

    def _write_impl(self, step: int, snap: dict) -> str:
        if self._nproc > 1:
            return self._write_multiprocess(step, snap)
        with self._dirlock.locked(timeout=self.lock_timeout):
            self._sweep()
            final = self._step_dir(step)
            stage = f"{final}.writing-{os.getpid()}"
            shutil.rmtree(stage, ignore_errors=True)
            os.makedirs(stage)
            try:
                leaf_table, files = self._stage_shards(stage, snap)
                self._commit(step, stage, final, leaf_table, files, snap)
            except BaseException:
                shutil.rmtree(stage, ignore_errors=True)
                raise
            self._prune()
            return final

    def _write_multiprocess(self, step: int, snap: dict) -> str:
        """The pod-scale writer: every process stages its own shard
        groups into ONE shared staging directory, then process 0 merges
        the per-process sidecars into the manifest and commits — the
        manifest is still the last byte written before the one rename, so
        the previous-or-new invariant holds under a kill of ANY worker at
        ANY instant (a dead peer costs the survivors a
        :class:`BarrierTimeout`, never a torn checkpoint)."""
        sync = snap["sync"]
        final = self._step_dir(step)
        # deterministic shared name: every process must agree on it
        # without communicating (swept age-based if a whole save dies)
        stage = f"{final}.writing-mp"
        if self._proc == 0:
            # only process 0 takes the cross-manager directory lock: the
            # in-job coordination is the barriers, and N processes
            # contending one lock for a cooperative write would deadlock
            self._dirlock.acquire(timeout=self._mp_lock_budget)
        try:
            if self._proc == 0:
                self._sweep()
                shutil.rmtree(stage, ignore_errors=True)
                os.makedirs(stage)
            self._barrier(f"s{sync}:staged")
            leaf_table, files = self._stage_shards(stage, snap)
            sidecar = os.path.join(stage, f"shards_p{self._proc:05d}.json")
            with open(sidecar, "w") as f:
                json.dump({"leaves": leaf_table, "files": files}, f)
                f.flush()
                os.fsync(f.fileno())
            # every process's shard files + sidecar durable before the
            # manifest can exist
            self._barrier(f"s{sync}:shards")
            if self._proc == 0:
                merged_leaves, merged_files = self._merge_sidecars(
                    stage, snap
                )
                self._commit(
                    step, stage, final, merged_leaves, merged_files, snap
                )
            # no process returns (and possibly starts the next save)
            # until the commit rename happened
            self._barrier(f"s{sync}:committed")
            if self._proc == 0:
                self._prune()
            return final
        except BaseException:
            if self._proc == 0:
                shutil.rmtree(stage, ignore_errors=True)
            raise
        finally:
            if self._proc == 0:
                self._dirlock.release()

    def _merge_sidecars(self, stage: str, snap: dict) -> tuple[list, dict]:
        """Join every process's sidecar into one manifest leaf table:
        per-leaf shard entries concatenated in process order (owner
        regions are disjoint by construction), digests unioned."""
        n_leaves = len(snap["leaves"])
        merged = [{
            "shape": leaf["shape"],
            "dtype": leaf["dtype"],
            "spec": leaf["spec"],
            "shards": [],
        } for leaf in snap["leaves"]]
        files: dict[str, Any] = {}
        for proc in range(self._nproc):
            path = os.path.join(stage, f"shards_p{proc:05d}.json")
            try:
                with open(path) as f:
                    side = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise CheckpointCorruptError(
                    f"process {proc} sidecar unreadable mid-commit "
                    f"({e}) — peer died after its shards barrier?"
                ) from e
            if len(side["leaves"]) != n_leaves:
                raise CheckpointCorruptError(
                    f"process {proc} sidecar records "
                    f"{len(side['leaves'])} leaves, expected {n_leaves}"
                )
            for mine, theirs in zip(merged, side["leaves"]):
                mine["shards"].extend(theirs["shards"])
            files.update(side["files"])
            os.remove(path)  # sidecars never land in the committed step
        return merged, files

    def _write_guarded(self, step: int, snap: dict) -> None:
        try:
            self._write(step, snap)
        except BaseException as e:  # noqa: BLE001 — re-raised on next save/wait
            self._error = e

    # -- public save/wait ---------------------------------------------

    def wait(self) -> None:
        """Block until the in-flight background save (if any) finishes;
        re-raise its failure as :class:`AsyncSaveError`."""
        t, self._inflight = self._inflight, None
        if t is not None:
            t.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise AsyncSaveError(
                f"background checkpoint save failed: "
                f"{type(err).__name__}: {err}"
            ) from err

    def save(self, step: int, state: Any, *, block: bool | None = None) -> None:
        """Checkpoint ``state`` as step ``step``.

        Blocks only for the host snapshot (and for a still-running
        PREVIOUS save — the write depth is one, double-buffered); the
        file I/O, hashing, and commit run on a background thread unless
        ``block=True`` (or the manager was built ``async_save=False``).
        """
        self.wait()
        with tracing.get_tracer().span("ckpt/snapshot", step=int(step)):
            snap = self._snapshot(state)
        # barrier-id generation: every process calls save in lockstep, so
        # a per-manager counter names the same rendezvous on all of them
        self._sync += 1
        snap["sync"] = f"{self._sync}:{step}"
        sync = (not self.async_save) if block is None else block
        if sync:
            self._write(step, snap)
            return
        t = threading.Thread(
            target=self._write_guarded, args=(step, snap),
            name=f"elastic-ckpt-save-{step}", daemon=True,
        )
        self._inflight = t
        t.start()

    def close(self) -> None:
        """Flush the in-flight save (call at clean shutdown / drain)."""
        self.wait()

    # -- restore -------------------------------------------------------

    def _target_sharding(self, ref, mesh):
        from jax.sharding import NamedSharding, PartitionSpec

        if isinstance(ref, jax.Array):
            if isinstance(ref.sharding, NamedSharding):
                return ref.sharding
            if mesh is not None:
                return NamedSharding(mesh, PartitionSpec())
            return ref.sharding
        if mesh is not None:
            return NamedSharding(mesh, PartitionSpec())
        return None

    def _load_step(self, step: int, template: Any, mesh) -> Any:
        np = _np()
        path = self._step_dir(step)
        manifest = load_manifest(os.path.join(path, _MANIFEST))
        if manifest["step"] != step:
            raise CheckpointCorruptError(
                f"step {step}: manifest records step {manifest['step']}"
            )
        for fname, meta in manifest["files"].items():
            fpath = os.path.join(path, fname)
            try:
                digest = _sha256(fpath)
            except OSError as e:
                raise CheckpointCorruptError(
                    f"step {step}: unreadable shard file {fname} ({e})"
                ) from e
            if digest != meta.get("sha256"):
                raise CheckpointCorruptError(
                    f"step {step}: shard file {fname} checksum mismatch "
                    f"(truncated or corrupted write)"
                )

        t_leaves, t_treedef = jax.tree_util.tree_flatten(template)
        if manifest["treedef"] != str(t_treedef) or manifest[
            "leaf_count"
        ] != len(t_leaves):
            raise CheckpointStructureError(
                f"step {step}: saved state structure does not match the "
                f"restore template (did the model or optimizer definition "
                f"change?).\n  saved:    {manifest['leaf_count']} leaves, "
                f"{manifest['treedef']}\n  template: {len(t_leaves)} "
                f"leaves, {t_treedef}"
            )

        handles: dict[str, Any] = {}

        def entry(fname: str, key: str):
            if fname not in handles:
                handles[fname] = np.load(os.path.join(path, fname))
            try:
                return handles[fname][key]
            except KeyError as e:
                raise CheckpointCorruptError(
                    f"step {step}: shard entry {key} missing from {fname}"
                ) from e

        def assemble(leaf_i: int, leaf_man: dict, target: list[list[int]]):
            """Gather/scatter ONE target shard from the old shard files
            overlapping it — streaming, one old shard at a time."""
            dtype = _np_dtype(leaf_man["dtype"])
            tshape = tuple(hi - lo for lo, hi in target)
            buf = np.empty(tshape, dtype)
            covered = 0
            for shard in leaf_man["shards"]:
                old = shard["index"]
                inter = [
                    (max(tl, ol), min(th, oh))
                    for (tl, th), (ol, oh) in zip(target, old)
                ]
                if any(lo >= hi for lo, hi in inter):
                    continue
                raw = entry(shard["file"], shard["key"])
                oshape = tuple(hi - lo for lo, hi in old)
                arr = np.ndarray(oshape, dtype, buffer=raw.tobytes())
                src = tuple(
                    slice(lo - ol, hi - ol)
                    for (lo, hi), (ol, _) in zip(inter, old)
                )
                dst = tuple(
                    slice(lo - tl, hi - tl)
                    for (lo, hi), (tl, _) in zip(inter, target)
                )
                buf[dst] = arr[src]
                vol = 1
                for lo, hi in inter:
                    vol *= hi - lo
                covered += vol
            want = 1
            for d in tshape:
                want *= d
            if covered != want:
                raise CheckpointCorruptError(
                    f"step {step}: leaf {leaf_i} region {target} only "
                    f"{covered}/{want} elements covered by stored shards"
                )
            return buf

        try:
            out = []
            for i, (ref, leaf_man) in enumerate(
                zip(t_leaves, manifest["leaves"])
            ):
                # chaos: die mid-resume — the checkpoint is read-only
                # here, so a killed resume must leave it fully intact
                chaos.chaos_point(chaos.KILL_MID_RESUME)
                shape = tuple(leaf_man["shape"])
                if isinstance(ref, jax.Array) and shape != tuple(ref.shape):
                    raise CheckpointStructureError(
                        f"step {step}: leaf {i} shape {shape} != "
                        f"template {tuple(ref.shape)}"
                    )
                full = [[0, int(d)] for d in shape]
                if not isinstance(ref, jax.Array):
                    arr = assemble(i, leaf_man, full)
                    out.append(arr if arr.shape else arr[()])
                    continue
                dtype = _np_dtype(leaf_man["dtype"])
                sharding = self._target_sharding(ref, mesh)
                want_dtype = ref.dtype

                def cb(index, _i=i, _man=leaf_man, _shape=shape,
                       _dtype=dtype, _want=want_dtype):
                    target = _norm_index(index, _shape)
                    buf = assemble(_i, _man, target)
                    if _dtype != _want:
                        buf = buf.astype(_want)
                    return buf

                if sharding is None or (
                    not getattr(ref, "_committed", True) and mesh is None
                ):
                    import jax.numpy as jnp

                    arr = assemble(i, leaf_man, full)
                    if dtype != want_dtype:
                        arr = arr.astype(want_dtype)
                    out.append(jnp.asarray(arr))
                else:
                    out.append(jax.make_array_from_callback(
                        shape, sharding, cb
                    ))
        finally:
            for h in handles.values():
                h.close()
        return jax.tree_util.tree_unflatten(t_treedef, out), manifest

    def restore(
        self, template: Any, *, mesh=None, step: int | None = None
    ) -> tuple[Any, int] | None:
        """Restore the newest intact checkpoint (or exactly ``step``) at
        the CURRENT mesh/template shardings — re-meshing from whatever
        factoring the checkpoint was written under.

        Returns ``(state, step)`` or None (nothing intact on disk);
        corrupt steps warn once each and fall back; structure mismatches
        raise (fallback would hit the same mismatch).  ``mesh`` supplies
        the placement for template leaves without an explicit
        ``NamedSharding`` (restored replicated over it).
        """
        with tracing.get_tracer().span(
            "ckpt/restore", nproc=self._nproc,
            **({"step": int(step)} if step is not None else {}),
        ):
            return self._restore_traced(template, mesh=mesh, step=step)

    def _restore_traced(
        self, template: Any, *, mesh=None, step: int | None = None
    ) -> tuple[Any, int] | None:
        from ..utils.resilience import LockTimeout

        if self._nproc > 1:
            # multi-process read: process 0 sweeps (under the lock), then
            # everyone reads the same shared directory — the step choice
            # is deterministic (same files, same fallback rule), and the
            # trailing barrier keeps any process from starting the next
            # save while a peer is still mid-read
            self._sync += 1
            if self._proc == 0:
                try:
                    with self._dirlock.locked(timeout=self._mp_lock_budget):
                        self._sweep()
                except LockTimeout:
                    warnings.warn(
                        f"ElasticCheckpointManager: directory lock "
                        f"{self._dirlock.path} stuck; skipping the "
                        f"pre-restore sweep",
                        stacklevel=2,
                    )
            self._barrier(f"r{self._sync}:swept")
            try:
                return self._restore_unlocked(template, mesh, step)
            finally:
                self._barrier(f"r{self._sync}:read")

        # held for the whole read: the sweep recovers .old debris even
        # when the dead writer died holding the lock, and a concurrent
        # manager's prune cannot delete a step mid-digest-read; a stuck
        # lock degrades to the unlocked read with one warning
        try:
            with self._dirlock.locked(timeout=self.lock_timeout):
                self._sweep()
                return self._restore_unlocked(template, mesh, step)
        except LockTimeout:
            warnings.warn(
                f"ElasticCheckpointManager: directory lock "
                f"{self._dirlock.path} stuck; restoring WITHOUT the lock "
                f"(a concurrent prune could race this read)",
                stacklevel=2,
            )
            return self._restore_unlocked(template, mesh, step)

    def _restore_unlocked(
        self, template: Any, mesh, step: int | None
    ) -> tuple[Any, int] | None:
        if step is not None and not os.path.isdir(self._step_dir(step)):
            raise FileNotFoundError(
                f"ElasticCheckpointManager: no checkpoint for step {step} "
                f"in {self.directory} (existing steps: {self.all_steps()})"
            )
        candidates = [step] if step is not None else list(
            reversed(self.all_steps())
        )
        for s in candidates:
            try:
                state, manifest = self._load_step(s, template, mesh)
            except CheckpointCorruptError as e:
                if step is not None:
                    # an EXPLICITLY requested corrupt step raises: a
                    # None return reads as "cold start" and would
                    # silently reinitialize over the named history
                    raise
                warnings.warn(
                    f"ElasticCheckpointManager: skipping corrupt "
                    f"checkpoint ({e}); falling back to the previous step",
                    stacklevel=2,
                )
                continue
            self.last_manifest = manifest
            return state, s
        return None

    def resume_or_init(
        self,
        init_fn: Callable[[], Any],
        *,
        mesh=None,
        seq_len: int | None = None,
    ) -> tuple[Any, int]:
        """The one-call elastic resume: build fresh state, restore over
        it if anything intact is on disk, and report what happened in
        ``self.last_resume`` (step, old/new mesh descriptors, re-mesh
        flag, one-line diagnostics — the resume banner callers print).

        ``seq_len`` (when given) is revalidated against the current
        mesh's sequence world — a re-mesh that breaks divisibility fails
        HERE with a one-line diagnostic, not 40 layers deep in a
        padding mismatch.
        """
        from ..parallel.mesh import mesh_descriptor, validate_seq_len

        if seq_len is not None:
            validate_seq_len(seq_len, mesh)
        state = init_fn()
        restored = self.restore(state, mesh=mesh)
        if restored is None:
            self.last_resume = None
            return state, 0
        state, step = restored
        old_mesh = self.last_manifest.get("mesh")
        new_mesh = mesh_descriptor(mesh)
        remeshed = old_mesh != new_mesh
        diags = []
        if remeshed:
            def _fmt(d):
                if not d:
                    return "unmeshed"
                return "x".join(
                    f"{a}={s}" for a, s in zip(d["axes"], d["shape"])
                )

            diags.append(
                f"re-mesh resume: checkpoint step {step} written at "
                f"{_fmt(old_mesh)}, restored at {_fmt(new_mesh)} "
                f"(resharded shard-streaming load; values bit-exact)"
            )
        self.last_resume = {
            "step": step,
            "old_mesh": old_mesh,
            "new_mesh": new_mesh,
            "remeshed": remeshed,
            "diagnostics": diags,
        }
        return state, step + 1
