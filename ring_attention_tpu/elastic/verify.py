"""Machine-checked contracts for the elastic checkpoint layer.

Run via ``tools/check_contracts.py --elastic`` (and the analysis
self-run): CPU-only, virtual devices, no hardware.  Four checks, each
returning one-line violations like the memory/coverage suites:

- **manifest round-trip** — a saved step's manifest re-reads through
  :func:`~.checkpoint.load_manifest` schema-validated, JSON round-trips
  byte-stably, records the mesh descriptor and per-leaf dtype/spec, and
  its shard digests match the files on disk.
- **resharded == direct** — a state saved on a ring-4 mesh and restored
  on a ring-2 mesh is bit-identical (values AND dtypes) to the same
  state saved and restored natively on the ring-2 mesh — the re-mesh
  gather/scatter adds or loses nothing.
- **corrupt shard falls back** — truncating one shard file of the newest
  step makes restore fall back (one warning) to the previous intact
  step, never return torn data.
- **commit protocol debris** — a dead writer's staging directory is
  invisible to ``all_steps`` and swept by the next save; a live writer's
  is left alone.
"""

from __future__ import annotations

import json
import os
import tempfile
import warnings

import jax
import jax.numpy as jnp

from . import chaos
from .checkpoint import ElasticCheckpointManager, load_manifest


def _mesh(n: int):
    from ..parallel.mesh import create_mesh

    return create_mesh(ring_size=n, devices=jax.devices()[:n])


def _state(mesh) -> dict:
    """A small but representative pytree: a seq-sharded f32, a
    seq-sharded bf16 (the raw-bytes dtype path), a replicated matrix,
    and a scalar step counter."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import seq_partition

    rng = np.random.default_rng(7)
    seq = NamedSharding(mesh, P(None, seq_partition(mesh)))
    rep = NamedSharding(mesh, P())
    return {
        "acts": jax.device_put(
            jnp.asarray(rng.normal(size=(2, 32, 3)), jnp.float32),
            NamedSharding(mesh, P(None, seq_partition(mesh), None)),
        ),
        "kv": jax.device_put(
            jnp.asarray(rng.normal(size=(4, 16)), jnp.bfloat16), seq
        ),
        "w": jax.device_put(
            jnp.asarray(rng.normal(size=(8, 8)), jnp.float32), rep
        ),
        "count": jax.device_put(jnp.asarray(11, jnp.int32), rep),
    }


def _values(state) -> list:
    return [jax.device_get(x) for x in jax.tree_util.tree_leaves(state)]


def _bit_equal(a, b) -> bool:
    import numpy as np

    return a.dtype == b.dtype and a.shape == b.shape and bool(
        np.array_equal(
            np.ascontiguousarray(a).reshape(-1).view(np.uint8),
            np.ascontiguousarray(b).reshape(-1).view(np.uint8),
        )
    )


def check_manifest_roundtrip() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(4)
        state = _state(mesh)
        mgr = ElasticCheckpointManager(tmp, async_save=False)
        mgr.save(3, state)
        man_path = os.path.join(mgr._step_dir(3), "manifest.json")
        try:
            manifest = load_manifest(man_path)
        except Exception as e:  # noqa: BLE001 — a violation, not a crash
            return [f"manifest failed to load: {e}"]
        # JSON round-trip stability: what we re-serialize is what's there
        rt = json.loads(json.dumps(manifest))
        if rt != manifest:
            violations.append("manifest does not JSON round-trip stably")
        if manifest["step"] != 3:
            violations.append(f"manifest step {manifest['step']} != 3")
        md = manifest["mesh"]
        if not md or "seq" not in md["axes"]:
            violations.append(f"manifest mesh descriptor wrong: {md}")
        dtypes = {leaf["dtype"] for leaf in manifest["leaves"]}
        if "bfloat16" not in dtypes:
            violations.append(
                f"bf16 leaf dtype not recorded (saw {sorted(dtypes)})"
            )
        specs = [leaf["spec"] for leaf in manifest["leaves"]]
        if not any(s and "seq" in str(s) for s in specs):
            violations.append(
                f"no per-leaf sharding spec records the seq axis: {specs}"
            )
        sharded = [leaf for leaf in manifest["leaves"]
                   if len(leaf["shards"]) > 1]
        if not sharded:
            violations.append(
                "no leaf stored as multiple shards on a 4-way mesh"
            )
        from ..utils.checkpoint import _sha256

        for fname, meta in manifest["files"].items():
            digest = _sha256(os.path.join(mgr._step_dir(3), fname))
            if digest != meta["sha256"]:
                violations.append(
                    f"manifest digest for {fname} does not match disk"
                )
    return violations


def check_reshard_equals_direct() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as big, \
            tempfile.TemporaryDirectory() as small:
        mesh4, mesh2 = _mesh(4), _mesh(2)
        state4 = _state(mesh4)
        state2 = _state(mesh2)  # same values, natively on the small mesh
        ElasticCheckpointManager(big, async_save=False).save(1, state4)
        ElasticCheckpointManager(small, async_save=False).save(1, state2)

        template = _state(mesh2)
        resharded = ElasticCheckpointManager(big).restore(
            template, mesh=mesh2
        )
        direct = ElasticCheckpointManager(small).restore(
            template, mesh=mesh2
        )
        if resharded is None or direct is None:
            return ["restore returned None for an intact checkpoint"]
        for i, (a, b, orig) in enumerate(zip(
            _values(resharded[0]), _values(direct[0]), _values(state4)
        )):
            if not _bit_equal(a, b):
                violations.append(
                    f"leaf {i}: resharded (4->2) load != direct load at "
                    f"the new mesh (dtype {a.dtype} vs {b.dtype})"
                )
            if not _bit_equal(a, orig):
                violations.append(
                    f"leaf {i}: resharded load != original values"
                )
        # and the restored leaves actually live on the NEW mesh
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            resharded[0]
        )[0]:
            from jax.sharding import NamedSharding

            if isinstance(leaf, jax.Array) and isinstance(
                leaf.sharding, NamedSharding
            ):
                if dict(leaf.sharding.mesh.shape).get("seq") not in (None, 2):
                    violations.append(
                        f"{path}: restored onto mesh "
                        f"{dict(leaf.sharding.mesh.shape)}, want seq=2"
                    )
    return violations


def check_corrupt_shard_falls_back() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(4)
        mgr = ElasticCheckpointManager(tmp, async_save=False)
        good = _state(mesh)
        mgr.save(1, good)
        mgr.save(2, _state(mesh))
        step2 = mgr._step_dir(2)
        shard = sorted(
            n for n in os.listdir(step2) if n.startswith("shard_")
        )[0]
        chaos.corrupt_file(os.path.join(step2, shard), "truncate")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            restored = mgr.restore(_state(mesh), mesh=mesh)
        if restored is None:
            return ["corrupt newest step: restore found nothing at all"]
        if restored[1] != 1:
            violations.append(
                f"corrupt newest step: restored step {restored[1]}, "
                f"want fallback to 1"
            )
        if not any("corrupt" in str(w.message) for w in caught):
            violations.append("fallback happened without its warning")
        for i, (a, b) in enumerate(zip(
            _values(restored[0]), _values(good)
        )):
            if not _bit_equal(a, b):
                violations.append(f"fallback leaf {i} != step-1 values")
    return violations


def check_commit_debris() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(2)
        mgr = ElasticCheckpointManager(tmp, async_save=False)
        mgr.save(1, _state(mesh))
        dead = os.path.join(tmp, "step_00000005.writing-999999999")
        os.makedirs(dead)
        live = os.path.join(tmp, f"step_00000006.writing-{os.getpid()}")
        os.makedirs(live)
        if mgr.all_steps() != [1]:
            violations.append(
                f"staging dirs leaked into all_steps: {mgr.all_steps()}"
            )
        mgr.save(2, _state(mesh))  # save sweeps first
        if os.path.isdir(dead):
            violations.append("dead writer's staging dir survived sweep")
        # our own pid counts as "this process's leftover" and is swept;
        # only a DIFFERENT live pid must survive — fake one with the
        # parent pid (alive, not us)
        ppid = os.getppid()
        other = os.path.join(tmp, f"step_00000007.writing-{ppid}")
        os.makedirs(other, exist_ok=True)
        mgr.save(3, _state(mesh))
        if ppid > 0 and not os.path.isdir(other):
            violations.append(
                "live concurrent writer's staging dir was deleted"
            )
        if mgr.all_steps() != [1, 2, 3]:
            violations.append(f"steps after sweeps: {mgr.all_steps()}")
    return violations


def run_elastic_suite() -> list[tuple[str, list[str]]]:
    """Every elastic contract as ``(name, violations)`` rows (the
    check_contracts CLI table shape)."""
    return [
        ("elastic/manifest_roundtrip", check_manifest_roundtrip()),
        ("elastic/reshard_equals_direct", check_reshard_equals_direct()),
        ("elastic/corrupt_shard_fallback", check_corrupt_shard_falls_back()),
        ("elastic/commit_debris_sweep", check_commit_debris()),
    ]


def _main() -> int:
    checks = run_elastic_suite()
    bad = 0
    for name, violations in checks:
        status = "ok  " if not violations else "FAIL"
        print(f"{status} {name}")  # ra: allow(RA006 suite CLI output)
        for v in violations:
            print(f"     {v}")  # ra: allow(RA006 suite CLI output)
        bad += bool(violations)
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(_main())
