"""Machine-checked contracts for the elastic checkpoint layer.

Run via ``tools/check_contracts.py --elastic`` (and the analysis
self-run): CPU-only, virtual devices, no hardware.  Seven checks, each
returning one-line violations like the memory/coverage suites:

- **manifest round-trip** — a saved step's manifest re-reads through
  :func:`~.checkpoint.load_manifest` schema-validated, JSON round-trips
  byte-stably, records the mesh descriptor and per-leaf dtype/spec, and
  its shard digests match the files on disk.
- **resharded == direct** — a state saved on a ring-4 mesh and restored
  on a ring-2 mesh is bit-identical (values AND dtypes) to the same
  state saved and restored natively on the ring-2 mesh — the re-mesh
  gather/scatter adds or loses nothing.
- **corrupt shard falls back** — truncating one shard file of the newest
  step makes restore fall back (one warning) to the previous intact
  step, never return torn data.
- **commit protocol debris** — a dead writer's staging directory is
  invisible to ``all_steps`` and swept by the next save; a live writer's
  is left alone.

Plus the multi-process rows (``multiprocess=True`` — the default for the
CLI; they spawn real two-process ``jax.distributed`` clusters and cost
tens of seconds, so the in-process test tier skips them):

- **mp barrier** — two spawned processes rendezvous at
  :func:`~.checkpoint.cross_process_barrier`; a missing peer converts to
  a one-line :class:`~.checkpoint.BarrierTimeout` within the budget,
  never a hang.
- **mp commit round-trip** — a two-process cluster saves a sharded
  state (each process writes only its shard group, process 0 commits the
  manifest last); a ONE-process restore of that checkpoint is bit-exact
  — the 2 -> 1 elastic path.
- **mp restore grow** — a single-process save restores bit-exactly on a
  spawned two-process cluster — the 1 -> 2 path.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import warnings

import jax
import jax.numpy as jnp

from . import chaos
from .checkpoint import ElasticCheckpointManager, load_manifest


def _mesh(n: int):
    from ..parallel.mesh import create_mesh

    return create_mesh(ring_size=n, devices=jax.devices()[:n])


def _state(mesh) -> dict:
    """A small but representative pytree: a seq-sharded f32, a
    seq-sharded bf16 (the raw-bytes dtype path), a replicated matrix,
    and a scalar step counter."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import seq_partition

    rng = np.random.default_rng(7)
    seq = NamedSharding(mesh, P(None, seq_partition(mesh)))
    rep = NamedSharding(mesh, P())
    return {
        "acts": jax.device_put(
            jnp.asarray(rng.normal(size=(2, 32, 3)), jnp.float32),
            NamedSharding(mesh, P(None, seq_partition(mesh), None)),
        ),
        "kv": jax.device_put(
            jnp.asarray(rng.normal(size=(4, 16)), jnp.bfloat16), seq
        ),
        "w": jax.device_put(
            jnp.asarray(rng.normal(size=(8, 8)), jnp.float32), rep
        ),
        "count": jax.device_put(jnp.asarray(11, jnp.int32), rep),
    }


def _values(state) -> list:
    return [jax.device_get(x) for x in jax.tree_util.tree_leaves(state)]


def _bit_equal(a, b) -> bool:
    import numpy as np

    return a.dtype == b.dtype and a.shape == b.shape and bool(
        np.array_equal(
            np.ascontiguousarray(a).reshape(-1).view(np.uint8),
            np.ascontiguousarray(b).reshape(-1).view(np.uint8),
        )
    )


def check_manifest_roundtrip() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(4)
        state = _state(mesh)
        mgr = ElasticCheckpointManager(tmp, async_save=False)
        mgr.save(3, state)
        man_path = os.path.join(mgr._step_dir(3), "manifest.json")
        try:
            manifest = load_manifest(man_path)
        except Exception as e:  # noqa: BLE001 — a violation, not a crash
            return [f"manifest failed to load: {e}"]
        # JSON round-trip stability: what we re-serialize is what's there
        rt = json.loads(json.dumps(manifest))
        if rt != manifest:
            violations.append("manifest does not JSON round-trip stably")
        if manifest["step"] != 3:
            violations.append(f"manifest step {manifest['step']} != 3")
        md = manifest["mesh"]
        if not md or "seq" not in md["axes"]:
            violations.append(f"manifest mesh descriptor wrong: {md}")
        dtypes = {leaf["dtype"] for leaf in manifest["leaves"]}
        if "bfloat16" not in dtypes:
            violations.append(
                f"bf16 leaf dtype not recorded (saw {sorted(dtypes)})"
            )
        specs = [leaf["spec"] for leaf in manifest["leaves"]]
        if not any(s and "seq" in str(s) for s in specs):
            violations.append(
                f"no per-leaf sharding spec records the seq axis: {specs}"
            )
        sharded = [leaf for leaf in manifest["leaves"]
                   if len(leaf["shards"]) > 1]
        if not sharded:
            violations.append(
                "no leaf stored as multiple shards on a 4-way mesh"
            )
        from ..utils.checkpoint import _sha256

        for fname, meta in manifest["files"].items():
            digest = _sha256(os.path.join(mgr._step_dir(3), fname))
            if digest != meta["sha256"]:
                violations.append(
                    f"manifest digest for {fname} does not match disk"
                )
    return violations


def check_reshard_equals_direct() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as big, \
            tempfile.TemporaryDirectory() as small:
        mesh4, mesh2 = _mesh(4), _mesh(2)
        state4 = _state(mesh4)
        state2 = _state(mesh2)  # same values, natively on the small mesh
        ElasticCheckpointManager(big, async_save=False).save(1, state4)
        ElasticCheckpointManager(small, async_save=False).save(1, state2)

        template = _state(mesh2)
        resharded = ElasticCheckpointManager(big).restore(
            template, mesh=mesh2
        )
        direct = ElasticCheckpointManager(small).restore(
            template, mesh=mesh2
        )
        if resharded is None or direct is None:
            return ["restore returned None for an intact checkpoint"]
        for i, (a, b, orig) in enumerate(zip(
            _values(resharded[0]), _values(direct[0]), _values(state4)
        )):
            if not _bit_equal(a, b):
                violations.append(
                    f"leaf {i}: resharded (4->2) load != direct load at "
                    f"the new mesh (dtype {a.dtype} vs {b.dtype})"
                )
            if not _bit_equal(a, orig):
                violations.append(
                    f"leaf {i}: resharded load != original values"
                )
        # and the restored leaves actually live on the NEW mesh
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            resharded[0]
        )[0]:
            from jax.sharding import NamedSharding

            if isinstance(leaf, jax.Array) and isinstance(
                leaf.sharding, NamedSharding
            ):
                if dict(leaf.sharding.mesh.shape).get("seq") not in (None, 2):
                    violations.append(
                        f"{path}: restored onto mesh "
                        f"{dict(leaf.sharding.mesh.shape)}, want seq=2"
                    )
    return violations


def check_corrupt_shard_falls_back() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(4)
        mgr = ElasticCheckpointManager(tmp, async_save=False)
        good = _state(mesh)
        mgr.save(1, good)
        mgr.save(2, _state(mesh))
        step2 = mgr._step_dir(2)
        shard = sorted(
            n for n in os.listdir(step2) if n.startswith("shard_")
        )[0]
        chaos.corrupt_file(os.path.join(step2, shard), "truncate")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            restored = mgr.restore(_state(mesh), mesh=mesh)
        if restored is None:
            return ["corrupt newest step: restore found nothing at all"]
        if restored[1] != 1:
            violations.append(
                f"corrupt newest step: restored step {restored[1]}, "
                f"want fallback to 1"
            )
        if not any("corrupt" in str(w.message) for w in caught):
            violations.append("fallback happened without its warning")
        for i, (a, b) in enumerate(zip(
            _values(restored[0]), _values(good)
        )):
            if not _bit_equal(a, b):
                violations.append(f"fallback leaf {i} != step-1 values")
    return violations


def check_commit_debris() -> list[str]:
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(2)
        mgr = ElasticCheckpointManager(tmp, async_save=False)
        mgr.save(1, _state(mesh))
        dead = os.path.join(tmp, "step_00000005.writing-999999999")
        os.makedirs(dead)
        live = os.path.join(tmp, f"step_00000006.writing-{os.getpid()}")
        os.makedirs(live)
        if mgr.all_steps() != [1]:
            violations.append(
                f"staging dirs leaked into all_steps: {mgr.all_steps()}"
            )
        mgr.save(2, _state(mesh))  # save sweeps first
        if os.path.isdir(dead):
            violations.append("dead writer's staging dir survived sweep")
        # our own pid counts as "this process's leftover" and is swept;
        # only a DIFFERENT live pid must survive — fake one with the
        # parent pid (alive, not us)
        ppid = os.getppid()
        other = os.path.join(tmp, f"step_00000007.writing-{ppid}")
        os.makedirs(other, exist_ok=True)
        mgr.save(3, _state(mesh))
        if ppid > 0 and not os.path.isdir(other):
            violations.append(
                "live concurrent writer's staging dir was deleted"
            )
        if mgr.all_steps() != [1, 2, 3]:
            violations.append(f"steps after sweeps: {mgr.all_steps()}")
    return violations


# ---------------------------------------------------------------------------
# Multi-process rows: real two-process jax.distributed clusters
# ---------------------------------------------------------------------------

# deterministic state every worker and the parent can reconstruct without
# communicating: the bit-exactness oracle of the mp round-trip rows
def _mp_values():
    import numpy as np

    try:
        from ml_dtypes import bfloat16
    except ImportError:  # pragma: no cover - ml_dtypes rides with jax
        bfloat16 = np.float32
    return {
        "x": np.arange(4 * 16, dtype=np.float32).reshape(4, 16),
        "kv": (np.arange(2 * 16, dtype=np.float32) / 7).astype(
            bfloat16
        ).reshape(2, 16),
        "w": np.arange(9, dtype=np.float32).reshape(3, 3),
    }


def _mp_place(mesh):
    """The oracle values placed on ``mesh``: rank-2 leaves batch-over-
    data x seq-over-ring (each process passes its local rows), ``w``
    replicated."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import seq_sharding

    values = _mp_values()
    proc, nproc = jax.process_index(), jax.process_count()

    def rows(full):
        if nproc <= 1:
            return full
        per = full.shape[0] // nproc
        return full[proc * per:(proc + 1) * per]

    def place2d(full):
        sharding = seq_sharding(mesh)
        if nproc <= 1:
            return jax.device_put(full, sharding)
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(rows(full))
        )

    state = {
        "x": place2d(values["x"]),
        "kv": place2d(values["kv"]),
    }
    if nproc <= 1:
        state["w"] = jax.device_put(
            values["w"], NamedSharding(mesh, P())
        )
    else:
        state["w"] = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P()), values["w"]
        )
    return state


# the worker bootstrap must set the virtual-device count BEFORE the
# package (and therefore jax) imports — a ``python -c`` shim, not ``-m``
_WORKER_BOOTSTRAP = (
    "import os, sys;"
    "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS', '')"
    " + ' --xla_force_host_platform_device_count='"
    " + os.environ.get('RING_ATTN_CHAOS_DEVICES', '2');"
    "from ring_attention_tpu.elastic.verify import _main;"
    "sys.exit(_main(sys.argv[1:]))"
)


def _spawn_cluster(mode: str, directory: str | None,
                   *, timeout: float = 300.0) -> list:
    """Two spawned verify workers joined into one jax.distributed
    cluster (2 virtual devices each)."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    argv = [sys.executable, "-c", _WORKER_BOOTSTRAP, "--mp-worker", mode]
    if directory is not None:
        argv += ["--dir", directory]
    worker = chaos.ChaosWorker(argv, cwd=repo, timeout=timeout)
    return worker.run_cluster(processes=2, devices_per_process=2)


def _cluster_violations(mode: str, results) -> list[str]:
    out = []
    for pid, r in enumerate(results):
        if r.returncode != 0:
            out.append(
                f"{mode}: worker {pid} exited {r.returncode}: "
                f"{(r.stdout or '')[-300:]}"
            )
        elif f"MPV-OK {mode} {pid}" not in (r.stdout or ""):
            out.append(
                f"{mode}: worker {pid} produced no MPV-OK line: "
                f"{(r.stdout or '')[-300:]}"
            )
    return out


def check_mp_barrier() -> list[str]:
    """Two spawned processes rendezvous at the cross-process barrier, a
    lonely waiter times out with a one-line BarrierTimeout inside its
    budget, and both still exit cleanly."""
    return _cluster_violations("barrier", _spawn_cluster("barrier", None))


def check_mp_commit_roundtrip() -> list[str]:
    """A two-process cluster saves; a ONE-process (this process) restore
    is bit-exact — shard files from both processes, manifest committed by
    process 0, 2 -> 1 re-scatter adds/loses nothing."""
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        results = _spawn_cluster("save", tmp)
        violations.extend(_cluster_violations("save", results))
        if violations:
            return violations
        mgr = ElasticCheckpointManager(tmp)
        manifest = mgr.latest_manifest()
        if manifest is None:
            return ["save: cluster committed no manifest"]
        if manifest.get("process_count") != 2:
            violations.append(
                f"manifest process_count {manifest.get('process_count')}"
                f" != 2"
            )
        step_dir = mgr._step_dir(manifest["step"])
        shard_files = [n for n in os.listdir(step_dir)
                       if n.startswith("shard_")]
        if len(shard_files) < 4:
            violations.append(
                f"expected shard files from both processes' devices, "
                f"found {sorted(shard_files)}"
            )
        if any(n.startswith("shards_p") for n in os.listdir(step_dir)):
            violations.append("sidecar leaked into the committed step")
        mesh = _mesh(4)
        template = jax.tree_util.tree_map(
            lambda x: x * 0, _mp_place(mesh)
        )
        restored = mgr.restore(template, mesh=mesh)
        if restored is None:
            return violations + ["restore of the cluster's save found nothing"]
        for key, want in _mp_values().items():
            got = jax.device_get(restored[0][key])
            if not _bit_equal(got, want):
                violations.append(
                    f"2->1 restore: leaf {key} not bit-exact "
                    f"(dtype {got.dtype} vs {want.dtype})"
                )
    return violations


def check_mp_restore_grow() -> list[str]:
    """This process saves; a spawned two-process cluster restores the
    checkpoint bit-exactly — the 1 -> 2 path."""
    violations: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        mesh = _mesh(2)
        ElasticCheckpointManager(tmp, async_save=False).save(
            7, _mp_place(mesh)
        )
        results = _spawn_cluster("restore", tmp)
        violations.extend(_cluster_violations("restore", results))
    return violations


def run_elastic_suite(
    *, multiprocess: bool = False
) -> list[tuple[str, list[str]]]:
    """Every elastic contract as ``(name, violations)`` rows (the
    check_contracts CLI table shape).  ``multiprocess=True`` appends the
    spawned two-process rows (barrier semantics + both directions of the
    cross-process-count round-trip) — the CLI default; the in-process
    test tier and the analysis self-run skip them for time."""
    checks = [
        ("elastic/manifest_roundtrip", check_manifest_roundtrip()),
        ("elastic/reshard_equals_direct", check_reshard_equals_direct()),
        ("elastic/corrupt_shard_fallback", check_corrupt_shard_falls_back()),
        ("elastic/commit_debris_sweep", check_commit_debris()),
    ]
    if multiprocess:
        checks += [
            ("elastic/mp_barrier", check_mp_barrier()),
            ("elastic/mp_commit_roundtrip", check_mp_commit_roundtrip()),
            ("elastic/mp_restore_grow", check_mp_restore_grow()),
        ]
    return checks


# ---------------------------------------------------------------------------
# The spawned worker (one process of a verify cluster)
# ---------------------------------------------------------------------------


def _mp_worker(mode: str, directory: str | None) -> int:
    from .checkpoint import BarrierTimeout, cross_process_barrier

    cluster = chaos.cluster_from_env()
    assert cluster is not None, "worker needs RING_ATTN_CLUSTER"
    pid, nproc, port = cluster

    from ..parallel.mesh import create_mesh, initialize_multihost

    initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc, process_id=pid,
    )
    if mode == "barrier":
        cross_process_barrier("verify:meet", timeout_s=60)
        # lonely waiter: only process 0 shows up; its wait must convert
        # to BarrierTimeout inside the budget while the peer idles
        if pid == 0:
            try:
                cross_process_barrier("verify:lonely", timeout_s=3)
                print(  # ra: allow(RA006 suite CLI output)
                    f"MPV-FAIL {mode} {pid}: lonely barrier did not "
                    f"time out"
                )
                return 1
            except BarrierTimeout:
                pass
        else:
            import time

            time.sleep(5)  # stay alive past the peer's timeout window
        cross_process_barrier("verify:done", timeout_s=60)
    else:
        mesh = create_mesh(
            dcn_data_size=nproc,
            ring_size=len(jax.devices()) // nproc,
        )
        state = _mp_place(mesh)
        mgr = ElasticCheckpointManager(
            directory, async_save=False, barrier_timeout_s=60
        )
        if mode == "save":
            mgr.save(5, state)
        elif mode == "restore":
            template = jax.tree_util.tree_map(lambda x: x * 0, state)
            restored = mgr.restore(template, mesh=mesh)
            assert restored is not None, "nothing to restore"
            for key, ref in state.items():
                got = restored[0][key]
                for mine, theirs in zip(
                    sorted(got.addressable_shards,
                           key=lambda s: str(s.index)),
                    sorted(ref.addressable_shards,
                           key=lambda s: str(s.index)),
                ):
                    if not _bit_equal(
                        jax.device_get(mine.data),
                        jax.device_get(theirs.data),
                    ):
                        print(  # ra: allow(RA006 suite CLI output)
                            f"MPV-FAIL {mode} {pid}: leaf {key} "
                            f"shard {mine.index} differs"
                        )
                        return 1
        else:
            raise SystemExit(f"unknown --mp-worker mode {mode!r}")
    print(f"MPV-OK {mode} {pid}")  # ra: allow(RA006 suite CLI output)
    return 0


def _main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--mp-worker", default=None)
    ap.add_argument("--dir", default=None)
    ap.add_argument("--multiprocess", action="store_true")
    args = ap.parse_args(argv)
    if args.mp_worker:
        return _mp_worker(args.mp_worker, args.dir)
    checks = run_elastic_suite(multiprocess=args.multiprocess)
    bad = 0
    for name, violations in checks:
        status = "ok  " if not violations else "FAIL"
        print(f"{status} {name}")  # ra: allow(RA006 suite CLI output)
        for v in violations:
            print(f"     {v}")  # ra: allow(RA006 suite CLI output)
        bad += bool(violations)
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(_main())
