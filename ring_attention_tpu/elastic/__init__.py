"""Elastic training runtime: survive preemption, resume at any world.

Three cooperating pieces (docs/resilience.md is the operator guide):

- :class:`ElasticCheckpointManager` — sharded, async, double-buffered
  checkpoints committed by one atomic manifest rename; restore
  re-scatters every leaf onto the CURRENT mesh, so a job can come back
  at a different device count (``elastic/checkpoint.py``).
- :class:`PreemptionGuard` — SIGTERM/SIGINT becomes a drain: finish the
  in-flight step, save, dump a FlightRecorder incident, exit cleanly
  (``elastic/preemption.py``).
- :mod:`~ring_attention_tpu.elastic.chaos` — the process-level fault
  harness that proves both: hard-death points inside the commit
  protocol, injected delays for wedge simulation, file corruption, and
  a multi-process virtual-device runner (``elastic/chaos.py``).

``tools/check_contracts.py --elastic`` runs the machine-checked
contracts (``elastic/verify.py``).
"""

from . import chaos
from .checkpoint import (
    AsyncSaveError,
    BarrierTimeout,
    ElasticCheckpointManager,
    MANIFEST_FORMAT,
    MANIFEST_VERSION,
    cross_process_barrier,
    load_manifest,
)
from .preemption import PREEMPT_FAULT, PreemptionGuard, broadcast_drain
from .verify import run_elastic_suite
from .watchdog import WATCHDOG_EXIT_CODE, Watchdog

__all__ = [
    "AsyncSaveError",
    "BarrierTimeout",
    "ElasticCheckpointManager",
    "MANIFEST_FORMAT",
    "MANIFEST_VERSION",
    "PREEMPT_FAULT",
    "PreemptionGuard",
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "broadcast_drain",
    "chaos",
    "cross_process_barrier",
    "load_manifest",
    "run_elastic_suite",
]
