"""Preemption-safe drain: catch the signal, finish the step, save, exit.

Cloud TPU preemption is a SIGTERM with a grace window; an unhandled one
kills the process mid-step and costs everything since the last
checkpoint.  :class:`PreemptionGuard` turns it into a *drain*: the
handler only sets a flag, the training loop finishes its in-flight step,
checks the flag at the step boundary, saves a final checkpoint (flushing
any in-flight async write), dumps a FlightRecorder incident (the
preemption arrives with its recent loss/grad trajectory attached), and
exits cleanly — the restarted job resumes one step later, possibly at a
different device count (``elastic/checkpoint.py``).

The guard is also drivable WITHOUT a real signal through the fault
injector (:data:`PREEMPT_FAULT` — ``resilience.inject("preempt_now")``),
so every drain path is testable in-process, and a second signal while
draining escalates to ``KeyboardInterrupt`` (the operator's "no really,
die now").

**Cluster-wide drain.**  On a multi-host pod the platform preempts ONE
host; a drain that stops only that host leaves the others wedged in the
next step's collectives waiting for a peer that will never arrive.
:func:`broadcast_drain` turns any host's local flag into everyone's: one
tiny compiled OR-reduction over the per-process flags, invoked from the
step-boundary host hook (``make_train_step(on_step_end=...)``) — the
TRAIN step's compiled program is untouched (the existing
zero-extra-collectives HLO pin in ``tests/test_elastic.py`` covers it),
and the broadcast's own program is one scalar all-gather per checked
boundary, compiled once.  ``should_stop_cluster()`` is the drop-in
cluster form of ``should_stop()``; every process then drains through the
SAME save (the multi-process checkpoint barrier needs all of them) and
exits cleanly.
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Callable

from ..utils import tracing
from ..utils.resilience import get_injector

# fault-injector name that simulates a preemption signal (chaos harness)
PREEMPT_FAULT = "preempt_now"

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def broadcast_drain(local: bool) -> bool:
    """Global OR of every process's drain flag.

    Single-process: the identity (no program runs at all).  Multi-process:
    one scalar per process all-gathers through a tiny jitted program
    (compiled once, reused every boundary) and any process's True drains
    the whole cluster.  Runs from the HOST side of the step boundary —
    never inside the train step's compiled program, whose collective
    sequence stays byte-identical (the ``on_step_end`` HLO pin).
    """
    import jax

    if jax.process_count() <= 1:
        return bool(local)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray(bool(local), dtype=np.bool_)
    )
    return bool(np.any(flags))


class PreemptionGuard:
    """Context manager that converts SIGTERM/SIGINT into a drain flag.

    ::

        with PreemptionGuard() as guard:
            for step in range(start, steps):
                state = train_step(state)
                if guard.should_stop():
                    guard.drain(lambda: mgr.save(step, state, block=True),
                                recorder=recorder, step=step)
                    break

    Installation is a no-op (with a recorded reason) outside the main
    thread — Python only delivers signals there — so a guard created in
    a worker thread degrades to the fault-injector path instead of
    crashing.  Handlers are restored on exit, and a signal that arrives
    while NO guard is active keeps the interpreter's default behavior.
    """

    def __init__(
        self,
        *,
        signals: tuple = DEFAULT_SIGNALS,
        on_preempt: Callable[[str], None] | None = None,
    ) -> None:
        self.signals = tuple(signals)
        self.on_preempt = on_preempt
        self.signal_name: str | None = None
        self.drained = False
        self._requested = threading.Event()
        self._previous: dict[int, Any] = {}
        self._installed = False
        self.install_error: str | None = None

    # -- handler lifecycle --------------------------------------------

    def _handler(self, signum, frame) -> None:
        if self._requested.is_set():
            # second signal while draining: escalate — the operator (or
            # the platform's kill -9 precursor) wants out NOW
            raise KeyboardInterrupt(
                f"second {signal.Signals(signum).name} during drain"
            )
        self.signal_name = signal.Signals(signum).name
        self._requested.set()
        if self.on_preempt is not None:
            try:
                self.on_preempt(self.signal_name)
            except Exception:  # noqa: BLE001 — a callback bug must not
                pass           # break the drain itself

    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            self.install_error = (
                "PreemptionGuard: signal handlers only install on the "
                "main thread; falling back to the fault-injector path"
            )
            return self
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> "PreemptionGuard":
        if self._installed:
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._previous.clear()
            self._installed = False
        return self

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- the drain flag -----------------------------------------------

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def should_stop(self) -> bool:
        """Check at every step boundary: True once a preemption signal
        landed OR the :data:`PREEMPT_FAULT` fault is armed (the chaos
        harness's signal-free simulation)."""
        if self._requested.is_set():
            return True
        if get_injector().armed(PREEMPT_FAULT):
            self.signal_name = self.signal_name or "injected"
            self._requested.set()
            return True
        return False

    def should_stop_cluster(self, every: int = 1, step: int = 0) -> bool:
        """The cluster form of :meth:`should_stop`: a drain signal on ANY
        process drains every process (:func:`broadcast_drain`).  The
        whole pod must leave together — the multi-process checkpoint
        commit and the next step's collectives both need all peers.

        ``every``/``step`` thin the broadcast to every ``every``-th step
        boundary when one scalar all-gather per step is too chatty (a
        drain — even the locally-signalled process's own — then acts at
        the next aligned boundary, costing at most ``every - 1`` extra
        steps of the grace window; the alignment rule must be identical
        on every process or the all-gather itself would lose a peer).  A
        process that observed a peer's drain this way reports
        ``signal_name == "peer"``.
        """
        local = self.should_stop()
        if every > 1 and step % every:
            return False
        if broadcast_drain(local):
            if not local:
                self.signal_name = self.signal_name or "peer"
                self._requested.set()
            return True
        return False

    def drain(
        self,
        save_fn: Callable[[], Any] | None = None,
        *,
        recorder=None,
        step: int | None = None,
    ) -> None:
        """The orderly exit: run ``save_fn`` (the final synchronous
        checkpoint), then dump a ``preemption`` FlightRecorder incident
        carrying the signal name and step.  Save-before-dump: the
        checkpoint is the part that saves the run; the incident is
        diagnostics.  Idempotent (``drained`` latches)."""
        if self.drained:
            return
        self.drained = True
        with tracing.get_tracer().span(
            "preempt/drain",
            signal=self.signal_name or "unknown",
            **({"step": step} if step is not None else {}),
        ):
            try:
                if save_fn is not None:
                    save_fn()
            finally:
                if recorder is not None:
                    recorder.dump(
                        "preemption",
                        signal=self.signal_name or "unknown",
                        **({"step": step} if step is not None else {}),
                    )
