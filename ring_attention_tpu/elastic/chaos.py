"""Process-level chaos harness: kill, delay, and corrupt on purpose.

PR 1's :class:`~ring_attention_tpu.utils.resilience.FaultInjector` raises
exceptions at armed injection points — the right shape for NaN grads and
truncated files, but a *preemption* is not an exception: the process is
gone mid-syscall, ``finally`` blocks never run, and whatever was on disk
at that instant is what the next process finds.  This module extends the
injector to that level, in four pieces, all CPU-testable with virtual
devices (``tests/test_elastic.py``):

- :func:`chaos_point` — a named hard-death point: when its fault is armed
  (optionally for a specific step), the process dies via ``os._exit``
  with :data:`CHAOS_EXIT_CODE` — no cleanup, no atexit, no flushing, the
  closest a single call can get to SIGKILL/preemption.  The elastic
  checkpointer plants these at every commit-protocol window (mid-shard,
  pre-manifest-commit, post-commit, mid-resume).
- :func:`arm_from_env` — process-level arming: a parent test/driver sets
  ``RING_ATTN_CHAOS="elastic_kill_mid_shard,kill_at_step=3"`` and the
  child worker arms those faults at startup, so multi-process chaos runs
  need no RPC into the victim.
- :func:`delay_tap` / :func:`hang` — injected delay: a ``pure_callback``
  sleep gate spliced into a jitted step simulates a hung collective /
  wedged device (the 5/5-round BENCH wedge) from inside the compiled
  program, so ``with_retries`` timeout ladders and the bench probe's
  hard deadline are exercisable without hardware.
- :func:`corrupt_file` — truncation/garbage corruption of a shard file,
  for the corrupted-checkpoint fallback matrix.

The multi-process runner (:class:`ChaosWorker`) spawns a training worker
as a real OS process on virtual CPU devices
(``--xla_force_host_platform_device_count``), with chaos faults in its
environment — kill it anywhere, restart it at any device count, and the
parent asserts what the elastic runtime promised: a valid checkpoint and
a loss trajectory that continues where the dead process left off.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Any, Iterable, Mapping, Sequence

from ..utils import tracing
from ..utils.resilience import get_injector

# exit code a chaos_point death uses: distinguishable from a crash (1),
# a signal death (negative returncode), and success (0)
CHAOS_EXIT_CODE = 113

# environment variable arm_from_env reads: comma-separated
# ``name`` / ``name=value`` fault specs
CHAOS_ENV = "RING_ATTN_CHAOS"

# cluster spec a spawned worker reads at startup to join a
# jax.distributed cluster: "<process_id>:<num_processes>:<port>"
CLUSTER_ENV = "RING_ATTN_CLUSTER"

# the elastic checkpointer's planted kill points (elastic/checkpoint.py)
KILL_MID_SHARD = "elastic_kill_mid_shard"
KILL_PRE_COMMIT = "elastic_kill_pre_commit"
KILL_POST_COMMIT = "elastic_kill_post_commit"
KILL_MID_RESUME = "elastic_kill_mid_resume"
KILL_AT_STEP = "kill_at_step"


def chaos_point(name: str, *, step: int | None = None) -> None:
    """Die here — hard — when fault ``name`` is armed.

    ``os._exit`` skips every Python-level cleanup (atexit, finally,
    buffered writes), which is the point: the on-disk state the next
    process observes is whatever the commit protocol had made durable at
    this exact line.  When the armed value is an integer and ``step`` is
    given, death fires only when they match (``kill_at_step=3`` kills
    step 3, not every step).
    """
    inj = get_injector()
    if not inj.armed(name):
        return
    value = inj.value(name)
    if step is not None and value is not True:
        try:
            if int(value) != step:
                return
        except (TypeError, ValueError):
            pass
    # the kill instant + flushed open spans are O_APPEND span-file
    # writes: durable the moment they return, so the merged cluster
    # timeline names the victim and the fault even though os._exit
    # skips every normal shutdown path
    try:
        tracer = tracing.get_tracer()
        tracer.instant(
            "chaos/kill", fault=name, exit_code=CHAOS_EXIT_CODE,
            **({"step": step} if step is not None else {}),
        )
        tracer.flush_open("chaos_kill")
    except Exception:  # noqa: BLE001 — diagnostics must not save the victim
        pass
    # one line of evidence for the parent's log, then nothing runs after
    sys.stderr.write(f"chaos: dying at {name}"
                     + (f" (step {step})" if step is not None else "") + "\n")
    sys.stderr.flush()
    os._exit(CHAOS_EXIT_CODE)


def arm_from_env(environ: Mapping[str, str] | None = None) -> list[str]:
    """Arm every fault named in :data:`CHAOS_ENV` (``name`` or
    ``name=value``, comma-separated) on the process-global injector.
    Returns the armed names — call once at worker startup.  Values
    parse as int when they look like one (step indices, delays)."""
    spec = (environ if environ is not None else os.environ).get(CHAOS_ENV, "")
    armed: list[str] = []
    inj = get_injector()
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, raw = item.partition("=")
        value: Any = True
        if raw:
            try:
                value = int(raw)
            except ValueError:
                try:
                    value = float(raw)
                except ValueError:
                    value = raw
        inj.arm(name, value)
        armed.append(name)
    if armed:
        # the fault window opens HERE: armed -> chaos/kill is the span
        # of exposure the incident reconstruction annotates
        tracing.get_tracer().instant("chaos/armed", faults=",".join(armed))
    return armed


def cluster_from_env(
    environ: Mapping[str, str] | None = None,
) -> tuple[int, int, str] | None:
    """Parse :data:`CLUSTER_ENV` (``"<pid>:<nproc>:<port>"``) into
    ``(process_id, num_processes, port)``, or None when the worker runs
    standalone.  The spawned-worker half of :meth:`ChaosWorker.run_cluster`:
    call at startup and feed ``initialize_multihost``."""
    spec = (environ if environ is not None else os.environ).get(
        CLUSTER_ENV, ""
    )
    if not spec:
        return None
    try:
        pid, nproc, port = spec.split(":")
        return int(pid), int(nproc), port
    except ValueError as e:
        raise ValueError(
            f"{CLUSTER_ENV}={spec!r}: want '<process_id>:<num_processes>"
            f":<port>'"
        ) from e


def hang(name: str = "hang_collective") -> float:
    """Host-side injected delay: sleep for the armed value (seconds) and
    return how long was slept (0.0 when disarmed)."""
    inj = get_injector()
    if not inj.armed(name):
        return 0.0
    delay = float(inj.value(name, 0.0) or 0.0)
    if delay > 0:
        time.sleep(delay)
    return delay


def delay_tap(x, name: str = "hang_collective"):
    """Multiply ``x`` by 1.0 through a ``pure_callback`` that sleeps for
    the armed delay — under jit, at RUN time.

    Splice this onto a collective's operand (or any tensor on the step's
    critical path) and the compiled step stalls for the armed duration:
    the wedged-collective simulation.  Like
    :func:`~...utils.resilience.nan_tap`, the armed/disarmed decision is
    fetched from the host each run, so the SAME compiled step can be
    healthy for k steps and hang at exactly step k.

    Multi-process caveat: in a ``jax.distributed`` SPMD program the
    callback of a replicated value executes only on the process holding
    its first shard — process 0.  Arm the wedge THERE; every peer then
    wedges inside its own (real) cross-process collective waiting for
    process 0's contribution, which is the symmetric cluster-wide stall
    the watchdog pin wants (``tests/test_multihost.py``).  A wedge
    armed on a non-zero process silently no-ops in-graph.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def factor() -> np.ndarray:
        hang(name)
        return np.float32(1.0)

    f = jax.pure_callback(
        factor, jax.ShapeDtypeStruct((), jnp.float32),
        vmap_method="broadcast_all",
    )
    return x * f.astype(x.dtype)


def corrupt_file(path: str, mode: str = "truncate") -> None:
    """Corrupt one file the way real failures do: ``"truncate"`` cuts it
    mid-byte (power loss / preemption mid-write), ``"garbage"`` flips a
    block in the middle (bit rot / torn sector)."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif mode == "garbage":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            f.write(b"\xde\xad\xbe\xef" * 8)
    else:
        raise ValueError(
            f"corrupt_file: mode must be 'truncate' or 'garbage', got {mode!r}"
        )


def free_port() -> int:
    """A free localhost TCP port for a spawned cluster's coordinator."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ChaosWorker:
    """Spawn-and-kill driver for a training worker on virtual CPU devices.

    One instance = one worker *command* (script + args); each
    :meth:`run` is a fresh OS process with its own device count and
    chaos faults — the parent-side half of the kill-anywhere matrix::

        w = ChaosWorker([sys.executable, "tests/elastic_worker.py",
                         "--ckpt-dir", d])
        r = w.run(devices=4, chaos={"kill_at_step": 3})   # dies at step 3
        assert r.returncode == CHAOS_EXIT_CODE
        r = w.run(devices=2)                              # re-mesh resume
        assert r.returncode == 0

    :meth:`run_cluster` is the pod-scale form: N worker processes join
    ONE ``jax.distributed`` cluster (coordinator on a free localhost
    port, spec delivered via :data:`CLUSTER_ENV`) and the chaos faults
    arm in exactly ONE victim — kill one worker of a live cluster at any
    commit window, then :meth:`run` restarts at the surviving process
    count and the elastic checkpoint re-meshes (``tests/test_multihost.py``
    drives the full matrix).
    """

    def __init__(
        self,
        argv: Sequence[str],
        *,
        cwd: str | None = None,
        timeout: float = 300.0,
    ) -> None:
        self.argv = list(argv)
        self.cwd = cwd
        self.timeout = timeout

    def run(
        self,
        *,
        devices: int,
        chaos: Mapping[str, Any] | Iterable[str] | None = None,
        extra_env: Mapping[str, str] | None = None,
        extra_args: Sequence[str] = (),
    ) -> subprocess.CompletedProcess:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the worker owns its device count
        env["JAX_PLATFORMS"] = "cpu"
        env["RING_ATTN_CHAOS_DEVICES"] = str(devices)
        if chaos:
            items = (chaos.items() if isinstance(chaos, Mapping)
                     else ((c, True) for c in chaos))
            env[CHAOS_ENV] = ",".join(
                name if value is True else f"{name}={value}"
                for name, value in items
            )
        else:
            env.pop(CHAOS_ENV, None)
        if extra_env:
            env.update(extra_env)
        return subprocess.run(
            self.argv + list(extra_args),
            capture_output=True, text=True, env=env, cwd=self.cwd,
            timeout=self.timeout,
        )

    def run_cluster(
        self,
        *,
        processes: int,
        devices_per_process: int,
        chaos: Mapping[str, Any] | Iterable[str] | None = None,
        chaos_process: int = 0,
        extra_env: Mapping[str, str] | None = None,
        extra_args: Sequence[str] = (),
        port: int | None = None,
    ) -> list[subprocess.CompletedProcess]:
        """One live multi-process cluster run; chaos arms in ONE victim.

        Spawns ``processes`` copies of the worker command, each owning
        ``devices_per_process`` virtual CPU devices, joined through a
        ``jax.distributed`` coordinator on a localhost port.  The chaos
        faults land only in ``chaos_process``'s environment — the other
        workers run clean and must convert the victim's death into a
        bounded error (checkpoint barrier timeout), never a hang.

        Returns one :class:`subprocess.CompletedProcess` per worker, in
        process order.  Outputs are reaped PER WORKER even when some hang
        past the timeout (those report ``returncode=None``-style kill
        codes with whatever partial output they produced) — misattributed
        logs are how multi-process failures become undebuggable.
        """
        port = port or free_port()
        env_base = dict(os.environ)
        env_base.pop("XLA_FLAGS", None)
        env_base["JAX_PLATFORMS"] = "cpu"
        env_base["RING_ATTN_CHAOS_DEVICES"] = str(devices_per_process)
        if extra_env:
            env_base.update(extra_env)
        procs = []
        for pid in range(processes):
            env = dict(env_base)
            env[CLUSTER_ENV] = f"{pid}:{processes}:{port}"
            if chaos and pid == chaos_process:
                items = (chaos.items() if isinstance(chaos, Mapping)
                         else ((c, True) for c in chaos))
                env[CHAOS_ENV] = ",".join(
                    name if value is True else f"{name}={value}"
                    for name, value in items
                )
            else:
                env.pop(CHAOS_ENV, None)
            procs.append(subprocess.Popen(
                self.argv + list(extra_args),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=self.cwd,
            ))
        results: list[subprocess.CompletedProcess | None] = [None] * processes
        deadline = time.monotonic() + self.timeout  # ra: allow(RA014 deadline arithmetic over worker reaping, not an emitted timestamp)
        try:
            for pid, p in enumerate(procs):
                budget = max(deadline - time.monotonic(), 0.01)  # ra: allow(RA014 deadline arithmetic over worker reaping, not an emitted timestamp)
                try:
                    out, _ = p.communicate(timeout=budget)
                except subprocess.TimeoutExpired:
                    continue  # reaped (with partial output) below
                results[pid] = subprocess.CompletedProcess(
                    p.args, p.returncode, out, ""
                )
        finally:
            for pid, p in enumerate(procs):
                if results[pid] is not None:
                    continue
                p.kill()
                try:
                    out, _ = p.communicate(timeout=10)
                except Exception:  # noqa: BLE001 — corpse refuses to talk
                    out = ""
                results[pid] = subprocess.CompletedProcess(
                    p.args, p.returncode if p.returncode is not None
                    else -9, out, ""
                )
        return list(results)
