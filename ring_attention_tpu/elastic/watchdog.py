"""Heartbeat watchdog: a wedged collective becomes a deadline abort.

The observed failure mode on this image's TPU tunnel (5/5 BENCH rounds)
and on any real pod that loses a host mid-step is not a crash but a
*wedge*: one process blocks forever inside a collective whose peer will
never arrive, `finally` blocks never run, and the job burns its
reservation doing nothing.  Python cannot interrupt a thread stuck in a
C extension, so the only honest conversion is: a watchdog THREAD watches
a heartbeat the training loop touches at every step boundary, and when
the heartbeat goes stale past the deadline it (1) dumps a
FlightRecorder ``watchdog_abort`` incident — the wedge arrives with the
run's recent trajectory and the stalled step number attached — and then
(2) hard-exits the process with :data:`WATCHDOG_EXIT_CODE`, so the
supervisor restarts it and the elastic checkpoint resumes the run.  The
simulation knob is :func:`~.chaos.delay_tap` (an armed in-graph sleep
wedges the SAME compiled step the healthy rounds ran), and
``tests/test_elastic.py`` / ``tests/test_multihost.py`` pin both the
incident dump and the exit code.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable

from ..utils import tracing

# distinguishable from success (0), a crash (1), a signal death
# (negative), and a chaos_point death (113)
WATCHDOG_EXIT_CODE = 114


class Watchdog:
    """Deadline abort for wedged steps.

    ::

        dog = Watchdog(deadline_s=300, recorder=recorder).start()
        for step in range(start, steps):
            state = train_step(state)       # may wedge forever
            dog.beat(step)                  # step boundary reached
        dog.stop()

    ``beat()`` is a single monotonic-clock store — cheap enough for
    every boundary.  The watchdog only arms AFTER the first beat (the
    first step legitimately pays minutes of XLA compilation; pass
    ``arm_immediately=True`` to cover the compile window too, with a
    correspondingly generous deadline).  ``abort`` is injectable for
    in-process tests; the default dumps the incident and calls
    ``os._exit(WATCHDOG_EXIT_CODE)`` — no cleanup, because the wedged
    main thread would never run it anyway.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        recorder=None,
        abort: Callable[[str], None] | None = None,
        poll_s: float | None = None,
        arm_immediately: bool = False,
    ) -> None:
        if deadline_s <= 0:
            raise ValueError(
                f"Watchdog: deadline_s must be > 0, got {deadline_s}"
            )
        self.deadline_s = float(deadline_s)
        self.recorder = recorder
        self._abort = abort
        self.poll_s = poll_s if poll_s is not None else min(
            max(deadline_s / 10.0, 0.05), 5.0
        )
        self._last = tracing.monotonic() if arm_immediately else None
        self._step: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.fired = False

    # -- the loop-side API --------------------------------------------

    def beat(self, step: int | None = None) -> None:
        """The training loop reached a step boundary: reset the clock.
        With a tracer configured, each beat is an instant row — the
        cluster timeline's per-process liveness track (the gap before a
        wedge is visible straggler evidence)."""
        self._step = step
        self._last = tracing.monotonic()
        tracing.get_tracer().instant(
            "watchdog/beat", **({"step": step} if step is not None else {})
        )

    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._watch, name="elastic-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the watcher thread -------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._last is None:
                continue  # not armed until the first beat
            stale = tracing.monotonic() - self._last
            if stale < self.deadline_s:
                continue
            self.fired = True
            after = (
                f"step {self._step}" if self._step is not None
                else "the last beat"
            )
            message = (
                f"watchdog: no heartbeat for {stale:.1f}s (deadline "
                f"{self.deadline_s:.0f}s) after {after} — a collective "
                f"is wedged (dead peer / hung device); aborting so the "
                f"supervisor can restart from the last checkpoint"
            )
            # the abort instant + flushed open spans are O_APPEND writes
            # — durable before os._exit, so the merged cluster timeline
            # names this process and what it was stuck inside even
            # though no normal shutdown will ever run here
            try:
                tracer = tracing.get_tracer()
                tracer.instant(
                    "watchdog/abort", stale_s=round(stale, 1),
                    deadline_s=self.deadline_s,
                    exit_code=WATCHDOG_EXIT_CODE,
                    **({"step": self._step}
                       if self._step is not None else {}),
                )
                tracer.flush_open("watchdog_abort")
            except Exception:  # noqa: BLE001 — diagnostics must not
                pass           # block the abort itself
            if self.recorder is not None:
                try:
                    self.recorder.dump(
                        "watchdog_abort",
                        stale_s=round(stale, 1),
                        deadline_s=self.deadline_s,
                        **({"step": self._step}
                           if self._step is not None else {}),
                    )
                except Exception:  # noqa: BLE001 — diagnostics must not
                    pass           # block the abort itself
            if self._abort is not None:
                self._abort(message)
                return
            sys.stderr.write(message + "\n")
            sys.stderr.flush()
            os._exit(WATCHDOG_EXIT_CODE)
            return
