"""Transformer building blocks: RMSNorm and (blockwise) FeedForward.

TPU-native equivalents of the reference's ``RMSNorm``
(ref ``ring_attention.py:470-477``: ``F.normalize(x) * sqrt(dim) * gamma``)
and ``FeedForward`` (ref ``ring_attention.py:479-486``: prenorm -> Dense(mult*dim)
-> GELU -> Dense(dim)).  Norm statistics are computed in float32 regardless
of activation dtype, then cast back — the standard TPU mixed-precision
recipe.

Beyond the reference: ``FeedForward(chunk_size=...)`` is the *blockwise
feedforward* half of Ring Attention (arXiv 2310.01889 §3 — the paper pairs
blockwise attention with a blockwise FFN precisely so activation memory,
not compute, stops being the context-length ceiling).  The
prenorm -> Dense -> GELU -> Dense block runs as a rematted ``lax.scan``
over sequence chunks, so the ``(seq, mult*dim)`` intermediate only ever
exists at chunk extent — forward AND backward (the per-chunk remat makes
the grad pass recompute one chunk at a time).  Chunks are taken per
sequence *shard* (``seq_shards``), so under a sequence-parallel mesh every
scan step keeps all devices busy and the scan adds ZERO collectives
(pinned by the ``blockwise_ffn`` row of ``analysis/contracts.py``).
See ``docs/memory.md``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class RMSNorm(nn.Module):
    dim: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        gamma = self.param("gamma", nn.initializers.ones, (self.dim,))
        xf = x.astype(jnp.float32)
        rms = jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-12)
        return ((xf / rms) * gamma).astype(x.dtype)


class FeedForward(nn.Module):
    dim: int
    mult: int = 4
    dtype: jnp.dtype | None = None
    # blockwise feedforward: run the block as a rematted scan over sequence
    # chunks of this size so at most (b, chunk, mult*dim) of the
    # intermediate exists at once.  None = dense single-shot block; short
    # sequences (<= chunk per shard) and shapes that cannot split
    # shard-aligned (decode steps) fall back to the dense block, which is
    # value-identical
    chunk_size: int | None = None
    # sequence-shard count of the incoming layout: chunks are taken WITHIN
    # each shard so no scan slice crosses a device boundary
    seq_shards: int = 1
    mesh: Mesh | None = None

    def setup(self):
        # explicit names pin the param tree to the original @nn.compact
        # auto-naming, so checkpoints and shared-params parity predate the
        # chunked path
        self.norm = RMSNorm(self.dim, name="RMSNorm_0")
        self.proj_in = nn.Dense(
            self.dim * self.mult, use_bias=False, dtype=self.dtype,
            name="Dense_0",
        )
        self.proj_out = nn.Dense(
            self.dim, use_bias=False, dtype=self.dtype, name="Dense_1",
        )

    def _block(self, x: jax.Array) -> jax.Array:
        # "ffn_in" is the remat-policy name for the post-norm input (see
        # models/remat.py save_ffn_inputs); the mult*dim intermediate is
        # deliberately unnamed — no policy may keep it
        h = checkpoint_name(self.norm(x), "ffn_in")
        # exact (erf) gelu: the reference's nn.GELU() default
        # (ref ring_attention.py:484); the tanh approximation would be the
        # one avoidable numeric divergence in cross-framework parity
        h = nn.gelu(self.proj_in(h), approximate=False)
        return self.proj_out(h)

    def __call__(self, x: jax.Array) -> jax.Array:
        n = x.shape[1]
        shards = max(self.seq_shards, 1)
        c = self.chunk_size
        if c is not None and c > 0 and n % shards == 0:
            c = min(c, n // shards)
            if 0 < c < n // shards:
                return self._chunked(x, c, shards)
        return self._block(x)

    def _chunked(self, x: jax.Array, c: int, shards: int) -> jax.Array:
        """The blockwise path: (b, n, d) -> (nc, b, shards, c, d) chunks
        scanned through a rematted block.

        The shard axis is split out FIRST so chunk i of the scan is the
        concatenation of every device's chunk i — each step keeps the full
        sequence-parallel world busy on its own c positions, and the
        reshape/transpose stay local to each device (position-local math
        needs no cross-shard data).  A shard length that does not divide
        by ``c`` is padded up and the pad rows sliced back off (the FFN is
        position-local, so pad outputs are garbage nobody reads)."""
        b, n, d = x.shape
        n_local = n // shards
        pad = (-n_local) % c
        xs = x.reshape(b, shards, n_local, d)
        if pad:
            xs = jnp.pad(xs, ((0, 0), (0, 0), (0, pad), (0, 0)))
        nc = (n_local + pad) // c
        xs = xs.reshape(b, shards, nc, c, d).transpose(2, 0, 1, 3, 4)
        if self.mesh is not None and shards > 1:
            # keep the shard axis on the sequence mesh axes through the
            # scan: without the constraint the partitioner is free to
            # gather the whole sequence onto every device
            from ..parallel.mesh import data_partition, seq_partition

            xs = lax.with_sharding_constraint(
                xs, NamedSharding(
                    self.mesh,
                    P(None, data_partition(self.mesh),
                      seq_partition(self.mesh), None, None),
                )
            )

        def body(mdl, carry, x_c):
            return carry, mdl._block(x_c)

        scan = nn.scan(
            nn.remat(body, prevent_cse=False),
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )
        _, ys = scan(self, None, xs)
        out = ys.transpose(1, 2, 0, 3, 4).reshape(b, shards, nc * c, d)
        if pad:
            out = out[:, :, :n_local]
        out = out.reshape(b, n, d)
        if self.mesh is not None and shards > 1:
            from ..parallel.mesh import data_partition, seq_partition

            out = lax.with_sharding_constraint(
                out, NamedSharding(
                    self.mesh, P(data_partition(self.mesh),
                                 seq_partition(self.mesh), None)
                )
            )
        return out
