"""Transformer building blocks: RMSNorm and FeedForward.

TPU-native equivalents of the reference's ``RMSNorm``
(ref ``ring_attention.py:470-477``: ``F.normalize(x) * sqrt(dim) * gamma``)
and ``FeedForward`` (ref ``ring_attention.py:479-486``: prenorm -> Dense(mult*dim)
-> GELU -> Dense(dim)).  Norm statistics are computed in float32 regardless
of activation dtype, then cast back — the standard TPU mixed-precision
recipe.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class RMSNorm(nn.Module):
    dim: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        gamma = self.param("gamma", nn.initializers.ones, (self.dim,))
        xf = x.astype(jnp.float32)
        rms = jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-12)
        return ((xf / rms) * gamma).astype(x.dtype)


class FeedForward(nn.Module):
    dim: int
    mult: int = 4
    dtype: jnp.dtype | None = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = RMSNorm(self.dim)(x)
        h = nn.Dense(self.dim * self.mult, use_bias=False, dtype=self.dtype)(x)
        # exact (erf) gelu: the reference's nn.GELU() default
        # (ref ring_attention.py:484); the tanh approximation would be the
        # one avoidable numeric divergence in cross-framework parity
        h = nn.gelu(h, approximate=False)
        return nn.Dense(self.dim, use_bias=False, dtype=self.dtype)(h)
