"""Named rematerialization policies for RingTransformer layers.

``remat=True`` trades recompute for activation memory; WHAT the remat is
allowed to keep is the policy, and the binary ``remat_policy in (None,
"save_attn")`` switch this module replaces could not express the choices
that matter at million-token context (LWM-lineage
``get_gradient_checkpoint_policy``, SNIPPETS.md [1]).  Each registry entry
maps a stable name to a ``jax.checkpoint_policies`` policy over the named
residuals this codebase tags:

- ``flash_out`` / ``flash_lse`` — each layer's attention output + the
  online-softmax log-sum-exp (tagged in ``parallel/ring.py`` /
  ``ops/flash.py`` / ``ops/pallas_flash.py``): saving them lets the
  backward skip re-running the O(n^2) ring scan for the cost of
  ``(b, n, dim)`` + f32 ``(b, h, n)`` per layer.
- ``ffn_in`` — the post-norm FeedForward input (tagged in
  ``models/layers.py``): saving it elides the RMSNorm recompute in the
  FFN backward for ``(b, n, dim)`` per layer; the ``mult*dim``
  intermediate is NEVER saveable by name — with ``ff_chunk_size`` it never
  exists at full sequence extent at all (docs/memory.md).

The table (policy -> what the backward recomputes):

=========================  ==============================================
``nothing_saveable``       everything (block inputs only — the default
                           ``remat=True`` behavior, maximum memory savings)
``everything_saveable``    nothing (remat becomes a no-op; A/B baseline)
``checkpoint_dots``        elementwise ops only (matmul outputs saved)
``checkpoint_dots_no_batch``  as above, skipping batched dots
``save_attn``              the FFN and the attention residual recompute,
                           but NOT the ring scan (flash_out/lse saved)
``save_ffn_inputs``        everything except the per-layer RMSNorm feeding
                           the FFN (ffn_in saved)
``save_attn_and_ffn_inputs``  union of the two named policies
``offload_attn``           as ``save_attn``, but the saved residuals live
                           in host memory (``pinned_host``) instead of
                           HBM; degrades to ``save_attn`` on backends
                           without an addressable host space (jax 0.4.x
                           CPU — see ``utils/compat.host_memory_kind``)
=========================  ==============================================

Policies are per-layer selectable on ``RingTransformer`` (a tuple of names
mirrors ``max_lookback_seq_len``) and from ``examples/train.py
--remat-policy``; the recompute signature of each is HLO-pinned in
``tests/test_memory.py``.
"""

from __future__ import annotations

import jax

_ATTN_NAMES = ("flash_out", "flash_lse")
_FFN_NAMES = ("ffn_in",)


def _named(*names):
    return jax.checkpoint_policies.save_only_these_names(*names)


def _offload_attn():
    """save_attn with the residuals parked in host memory when the backend
    has one; plain save_attn otherwise (the graceful-degradation contract
    every compat shim here follows)."""
    from ..utils import compat

    kind = compat.host_memory_kind()
    fn = getattr(
        jax.checkpoint_policies, "save_and_offload_only_these_names", None
    )
    if kind is None or fn is None:
        return _named(*_ATTN_NAMES)
    return fn(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=list(_ATTN_NAMES),
        offload_src="device",
        offload_dst=kind,
    )


# name -> zero-arg factory returning a jax.checkpoint policy.  Factories
# (not policy objects) because offload_attn probes the backend and the
# probe must not run at import time.
REMAT_POLICIES = {
    "nothing_saveable": lambda: jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": lambda: jax.checkpoint_policies.everything_saveable,
    "checkpoint_dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    "checkpoint_dots_no_batch": (
        lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    ),
    "save_attn": lambda: _named(*_ATTN_NAMES),
    "save_ffn_inputs": lambda: _named(*_FFN_NAMES),
    "save_attn_and_ffn_inputs": lambda: _named(*_ATTN_NAMES, *_FFN_NAMES),
    "offload_attn": _offload_attn,
}


def resolve_remat_policy(name: str | None):
    """Policy object for a registry name (None -> None, plain full-block
    remat).  Raises ``ValueError`` naming every valid policy — the
    validation the old ``assert`` version lost under ``python -O``."""
    if name is None:
        return None
    try:
        factory = REMAT_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown remat_policy {name!r}; valid policies: "
            f"{', '.join(sorted(REMAT_POLICIES))} (or None for plain "
            f"full-block remat)"
        ) from None
    return factory()
