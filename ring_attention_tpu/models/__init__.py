from .attention import RingAttention
from .layers import FeedForward, RMSNorm
from .remat import REMAT_POLICIES, resolve_remat_policy
from .transformer import RingTransformer

__all__ = [
    "RingAttention",
    "FeedForward",
    "RMSNorm",
    "RingTransformer",
    "REMAT_POLICIES",
    "resolve_remat_policy",
]
