from .attention import RingAttention
from .layers import FeedForward, RMSNorm
from .transformer import RingTransformer

__all__ = ["RingAttention", "FeedForward", "RMSNorm", "RingTransformer"]
