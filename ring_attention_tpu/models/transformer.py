"""RingTransformer: end-to-end causal LM over a sharded sequence.

TPU-native equivalent of the reference's ``RingTransformer``
(ref ``ring_attention.py:488-685``): token embedding, depth x
(RingAttention + FeedForward) residual blocks, final RMSNorm + logits, and
autoregressive cross-entropy with label auto-shift and pad-label masking
(ref ``ring_attention.py:599-615``).

Sharding is decided once at the model top (pad -> stripe -> sharding
constraint) and the attention layers run pre-sharded (the reference
similarly passes ``auto_shard_seq=False`` down to layers,
ref ``ring_attention.py:565``).  Per-layer ``max_lookback_seq_len`` gives
local -> global attention over depth (ref ``ring_attention.py:546-561``).

Beyond the reference: an incremental decoding path — ``init_cache`` /
``decode_step`` / ``generate`` — running tree-attention decoding against a
ring-sharded KV cache (the reference only ships the standalone collective,
ref ``tree_attn_decoding.py``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import PAD_SEGMENT_ID
from ..parallel.mesh import (
    SEQ_AXIS,
    ULYSSES_AXIS,
    data_partition,
    is_factored,
    seq_partition,
    seq_world,
)
from ..parallel.sharding import (
    layout_for,
    layout_permute,
    layout_unpermute,
    pad_to_multiple,
)
from ..utils.validate import check_tokens_input
from .attention import RingAttention
from .. import masks as mask_algebra
from .layers import FeedForward, RMSNorm
from .remat import REMAT_POLICIES, resolve_remat_policy


def _position_nll(
    logits: jax.Array,  # (..., vocab), any float dtype
    labels: jax.Array,  # (...)
    valid: jax.Array,  # (...) bool
) -> jax.Array:
    """Per-position negative log likelihood, zero where invalid.

    ``nll = logsumexp - chosen logit`` in f32: the same value as
    ``log_softmax`` + gather without materializing a second
    ``(..., vocab)`` f32 array.  THE loss math shared by the dense and
    chunked CE paths — the chunked path's value-identity guarantee
    depends on both calling exactly this."""
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    chosen = jnp.take_along_axis(
        lf, jnp.where(valid, labels, 0)[..., None], axis=-1
    )[..., 0]
    return jnp.where(valid, lse - chosen, 0.0)


class RingTransformer(nn.Module):
    num_tokens: int
    dim: int
    depth: int
    causal: bool = False
    # mask-algebra expression (ring_attention_tpu.masks), forwarded to
    # every attention layer: ``causal=True`` is sugar for
    # ``mask=Causal()``; a tuple selects per layer (mirroring
    # max_lookback_seq_len — e.g. local-window layers below a global
    # one).  Certified at trace time per layer; mutually exclusive with
    # causal=True and max_lookback_seq_len (see RingAttention.mask)
    mask: mask_algebra.Mask | tuple[mask_algebra.Mask | None, ...] | None = None
    heads: int = 8
    dim_head: int = 64
    kv_heads: int | None = None
    bucket_size: int = 512
    striped: bool = False
    use_ring: bool = True
    force_regular_attn: bool = False
    rotary: bool = True
    softclamp_value: float | None = None
    # int -> same lookback every layer; tuple -> per layer (None = global)
    max_lookback_seq_len: int | tuple[int | None, ...] | None = None
    ff_mult: int = 4
    ignore_index: int = -1
    auto_shard: bool = True
    mesh: Mesh | None = None
    use_pallas: bool = False
    # kernel-path selection with graceful degradation, forwarded to every
    # RingAttention layer (see models/attention.py ``impl``): "fused" |
    # "pallas" | "xla" | "auto"; None keeps the explicit use_pallas switch
    impl: str | None = None
    # see RingAttention.pallas_head_chunks (program-size escape hatch)
    pallas_head_chunks: int | None = None
    # see RingAttention.quantize_cache (int8 decode KV cache)
    quantize_cache: bool = False
    # size each layer's decode cache to its lookback window instead of
    # max_len (local decode only): a layer with max_lookback_seq_len=W
    # stores and reads O(W) cache rows per step regardless of context
    # length — the decode-side payoff of the local->global layer ladder.
    # The cache is a ring buffer (writes at pos % size); exactness is
    # untouched because those layers never attend past their window
    windowed_cache: bool = False
    # "ring" | "zigzag" | "ulysses" | "hybrid" (Ulysses x Ring factored
    # mesh, create_mesh(ulysses_size=U) — see docs/hybrid_parallelism.md)
    sequence_parallel: str = "ring"
    ring_bidirectional: bool = False  # see RingAttention.ring_bidirectional
    ring_dkv_dtype: str | None = None  # see RingAttention.ring_dkv_dtype
    # see RingAttention.ring_counter_rotate / ring_hop_compression
    ring_counter_rotate: bool = False
    ring_hop_compression: str | None = None
    # see RingAttention.compute_dtype: "int8" runs every layer's forward
    # QK^T/PV on int8 operands (pallas path, ring/hybrid/local), backward
    # bf16 from exact residuals (docs/precision.md)
    compute_dtype: str | None = None
    # rematerialize each block in backward: trades recompute for activation
    # memory — the standard recipe for quarter-million-token training.
    # NOTE: requires the train step to be jit-compiled (jax.checkpoint over
    # shard_map has no eager path)
    remat: bool = False
    # remat refinement: which intermediates each rematted block may KEEP
    # instead of recomputing — a name from models/remat.py REMAT_POLICIES
    # ("save_attn" saves flash_out/flash_lse so the backward skips the
    # O(n^2) ring scan; "save_ffn_inputs" elides the FFN norm recompute;
    # "offload_attn" parks the attn residuals in host memory; see
    # docs/memory.md for the full table).  A tuple selects per layer
    # (mirroring max_lookback_seq_len); None = plain full-block remat.
    remat_policy: str | tuple[str | None, ...] | None = None
    # blockwise feedforward (Ring Attention §3, arXiv 2310.01889): run each
    # FeedForward as a rematted scan over sequence chunks of this size so
    # the (seq, mult*dim) intermediate never exists at full sequence
    # extent — the memory-axis twin of loss_chunk_size (docs/memory.md).
    # Chunks split WITHIN each sequence shard, so the scan adds zero
    # collectives (pinned: analysis/contracts.py "blockwise_ffn" row).
    # None = dense FFN; shard lengths that don't divide are padded.
    ff_chunk_size: int | None = None
    # chunked cross-entropy: compute the loss as a rematted lax.scan over
    # sequence chunks of this size, so at most (b, chunk, vocab) logits
    # ever materialize — at a real LM vocab the full logits tensor is the
    # long-context memory wall.  None = single dense logits+CE (fine for
    # small vocab).  The full memory story (why, when, and how this
    # composes with ff_chunk_size / remat_policy / offload) lives in
    # docs/memory.md.
    loss_chunk_size: int | None = None
    dtype: jnp.dtype | None = None

    def setup(self):
        # a negative chunk size used to surface as an obscure shape error
        # deep inside pad_to_multiple, and 0 silently disabled chunking via
        # the falsy check in __call__ — validate once, loudly, up front
        if self.loss_chunk_size is not None and self.loss_chunk_size <= 0:
            raise ValueError(
                f"RingTransformer: loss_chunk_size must be None or a "
                f"positive int, got {self.loss_chunk_size!r} (None disables "
                f"chunking; 0 would silently disable it, a negative value "
                f"breaks padding)"
            )
        if self.ff_chunk_size is not None and self.ff_chunk_size <= 0:
            raise ValueError(
                f"RingTransformer: ff_chunk_size must be None or a positive "
                f"int, got {self.ff_chunk_size!r} (None disables the "
                f"blockwise feedforward; any positive size works — shard "
                f"lengths that don't divide are padded)"
            )
        policies = self._remat_policies()
        self.embed = nn.Embed(self.num_tokens, self.dim, dtype=self.dtype)
        # flax-lifted remat (NOT raw jax.checkpoint: param creation during
        # init is a side effect that would leak tracers out of the
        # checkpointed trace); one lifted class per layer so the policy is
        # per-layer selectable
        if self.remat:
            attn_classes = [
                nn.remat(RingAttention, policy=resolve_remat_policy(p))
                for p in policies
            ]
            ff_classes = [
                nn.remat(FeedForward, policy=resolve_remat_policy(p))
                for p in policies
            ]
        else:
            attn_classes = [RingAttention] * self.depth
            ff_classes = [FeedForward] * self.depth
        self.attn_layers = [
            attn_cls(
                dim=self.dim,
                heads=self.heads,
                dim_head=self.dim_head,
                kv_heads=self.kv_heads,
                causal=self.causal,
                striped=self.striped and self._ring_size() > 1,
                bucket_size=self.bucket_size,
                use_ring=self.use_ring,
                force_regular_attn=self.force_regular_attn,
                rotary=self.rotary,
                softclamp_value=self.softclamp_value,
                max_lookback_seq_len=lookback,
                mask=layer_mask,
                auto_shard=False,  # sharded once at model top
                mesh=self.mesh,
                use_pallas=self.use_pallas,
                impl=self.impl,
                pallas_head_chunks=self.pallas_head_chunks,
                quantize_cache=self.quantize_cache,
                sequence_parallel=self.sequence_parallel,
                ring_bidirectional=self.ring_bidirectional,
                ring_dkv_dtype=self.ring_dkv_dtype,
                ring_counter_rotate=self.ring_counter_rotate,
                ring_hop_compression=self.ring_hop_compression,
                compute_dtype=self.compute_dtype,
                dtype=self.dtype,
            )
            for attn_cls, lookback, layer_mask in zip(
                attn_classes, self._lookbacks(), self._masks()
            )
        ]
        self.ff_layers = [
            ff_cls(
                self.dim, self.ff_mult, dtype=self.dtype,
                chunk_size=self.ff_chunk_size,
                seq_shards=self._ring_size(),
                mesh=self.mesh if self.auto_shard else None,
            )
            for ff_cls in ff_classes
        ]
        self.final_norm = RMSNorm(self.dim)
        self.to_logits = nn.Dense(self.num_tokens, use_bias=False, dtype=self.dtype)

    def _ring_size(self) -> int:
        """Total sequence-parallel world (both axes of a factored mesh)."""
        if self.mesh is None or not self.use_ring or self.force_regular_attn:
            return 1
        return seq_world(self.mesh)

    def _ulysses_size(self) -> int:
        if self.mesh is None or not is_factored(self.mesh):
            return 1
        return self.mesh.shape[ULYSSES_AXIS]

    def _layout(self) -> tuple[str, int]:
        """(scheme, factor) of the model-top sequence permutation — the
        shared derivation (``parallel/sharding.py::layout_for``), so the
        model top and every attention layer agree by construction."""
        return layout_for(
            self.sequence_parallel, self.striped, self._ring_size(),
            self._ulysses_size(),
        )

    def _lookbacks(self) -> tuple[int | None, ...]:
        lb = self.max_lookback_seq_len
        if not isinstance(lb, tuple):
            lb = (lb,) * self.depth
        assert len(lb) == self.depth
        return lb

    def _masks(self) -> tuple[mask_algebra.Mask | None, ...]:
        m = self.mask
        if not isinstance(m, tuple):
            m = (m,) * self.depth
        if len(m) != self.depth:
            raise ValueError(
                f"RingTransformer: mask tuple has {len(m)} entries for "
                f"depth {self.depth} (one mask per layer, or a single "
                f"mask for all layers)"
            )
        return m

    def _eff_causal(self) -> bool:
        """Whether every layer's attention is causal — the property the
        pad-mask synthesis and the zig-zag assert actually rely on
        (``causal=True`` or a mask whose kernel form is causal)."""
        if self.mask is None:
            return self.causal
        return all(
            mask_algebra.kernel_form(m).causal if m is not None
            else self.causal
            for m in self._masks()
        )

    def _remat_policies(self) -> tuple[str | None, ...]:
        """Per-layer remat-policy names, validated against the registry
        (models/remat.py) — a ValueError here lists every valid name, where
        the old ``assert`` vanished under ``python -O``."""
        p = self.remat_policy
        if not isinstance(p, tuple):
            p = (p,) * self.depth
        if len(p) != self.depth:
            raise ValueError(
                f"RingTransformer: remat_policy tuple has {len(p)} entries "
                f"for depth {self.depth} (one policy name per layer, or a "
                f"single name for all layers)"
            )
        for name in p:
            if name is not None and name not in REMAT_POLICIES:
                raise ValueError(
                    f"RingTransformer: unknown remat_policy {name!r}; valid "
                    f"policies: {', '.join(sorted(REMAT_POLICIES))} (or "
                    f"None for plain full-block remat)"
                )
        return p

    def __call__(
        self,
        tokens: jax.Array,
        mask: jax.Array | None = None,
        return_loss: bool = False,
        example_mask: jax.Array | None = None,
        segment_ids: jax.Array | None = None,
    ) -> jax.Array:
        """``tokens: (b, n)`` int32 -> logits ``(b, n, num_tokens)`` or scalar loss.

        ``example_mask: (b,)`` marks valid batch rows: the static-shape
        answer to the reference's variable per-rank batch
        (``all_gather_variable_dim``, ref ``distributed.py:58-84``,
        exercised by ``assert_attn.py:81-82``) — ragged data-parallel
        shards are padded to a common batch and the pad rows drop out of
        the loss here.

        ``segment_ids: (b, n)`` int document ids pack multiple documents
        into one sequence: every attention layer masks (and where possible
        skips) cross-document attention, and the loss drops positions
        whose label crosses a document boundary (the first token of each
        packed document is never predicted from the previous document).
        See ``docs/packing.md``.
        """
        check_tokens_input("RingTransformer", tokens)
        segment_same = None
        if return_loss:
            labels = tokens[:, 1:]
            tokens = tokens[:, :-1]
            if segment_ids is not None:
                # label at position i is token i+1: valid only when both
                # sit in the same document (no loss on each doc's first
                # token — it would be "predicted" from the previous doc)
                segment_same = segment_ids[:, 1:] == segment_ids[:, :-1]
                segment_ids = segment_ids[:, :-1]

        ring = self._ring_size()
        n_orig = tokens.shape[1]
        scheme, factor = self._layout()
        zigzag = self.sequence_parallel == "zigzag" and ring > 1
        if zigzag:
            assert self._eff_causal(), "zig-zag CP is causal-only"

        if ring > 1 and self.auto_shard:
            pad_mult = 2 * ring if zigzag else ring
            tokens, _ = pad_to_multiple(tokens, pad_mult)
            padded = tokens.shape[1] != n_orig
            if padded and mask is None and not self._eff_causal():
                # non-causal: real tokens must not attend to the pad slots,
                # so synthesize a key-padding mask (ref ring_attention.py:211-219);
                # causal needs none — pad sits after every real query and the
                # padded output rows are sliced off below.
                mask = jnp.arange(tokens.shape[1])[None, :] < n_orig
                mask = jnp.broadcast_to(mask, tokens.shape)
            tokens = layout_permute(tokens, scheme, factor)
            tokens = lax.with_sharding_constraint(
                tokens, NamedSharding(
                    self.mesh, P(data_partition(self.mesh), seq_partition(self.mesh))
                )
            )
            if mask is not None:
                mask, _ = pad_to_multiple(mask, pad_mult, value=False)
                mask = layout_permute(mask, scheme, factor)
            if segment_ids is not None:
                # pad slots get PAD_SEGMENT_ID: their own "document",
                # attending nothing real (models/attention.py does the
                # same for its per-layer padding)
                segment_ids, _ = pad_to_multiple(segment_ids, pad_mult,
                                                 value=PAD_SEGMENT_ID)
                segment_ids = layout_permute(segment_ids, scheme, factor)

        x = self.embed(tokens)
        if ring > 1 and self.auto_shard:
            x = lax.with_sharding_constraint(
                x, NamedSharding(
                    self.mesh, P(data_partition(self.mesh), seq_partition(self.mesh), None)
                )
            )

        for attn, ff in zip(self.attn_layers, self.ff_layers):
            x = attn(x, mask, segment_ids) + x
            x = ff(x) + x

        x = self.final_norm(x)

        if return_loss and self.loss_chunk_size:
            # the (b, n, vocab) logits never materialize: un-permute the
            # (b, n, dim) features instead (CE is position-local, so the
            # layout permutation only has to line features up with labels)
            # and scan the projection+CE over sequence chunks
            if ring > 1 and self.auto_shard:
                x = layout_unpermute(x, scheme, factor)
                x = x[:, :n_orig]
            return self._chunked_ce(
                x, labels,
                self._valid_labels(labels, example_mask, segment_same),
            )

        logits = self.to_logits(x)

        if ring > 1 and self.auto_shard:
            logits = layout_unpermute(logits, scheme, factor)
            logits = logits[:, :n_orig]

        if not return_loss:
            return logits

        # Cross-entropy with ignore_index (ref ring_attention.py:664-673)
        valid = self._valid_labels(labels, example_mask, segment_same)
        nll = _position_nll(logits, labels, valid)
        return nll.sum() / jnp.maximum(valid.sum(), 1)

    def _valid_labels(
        self,
        labels: jax.Array,
        example_mask: jax.Array | None,
        segment_same: jax.Array | None = None,
    ) -> jax.Array:
        """Which (b, n) label slots count toward the loss — the ONE place
        the ignore_index / example_mask / packed-boundary rule lives (both
        CE paths use it).  ``segment_same`` marks labels living in the same
        document as the token predicting them."""
        valid = labels != self.ignore_index
        if example_mask is not None:
            valid = valid & example_mask[:, None]
        if segment_same is not None:
            valid = valid & segment_same
        return valid

    def _chunked_ce(
        self,
        x: jax.Array,  # (b, n, dim) final-norm features
        labels: jax.Array,  # (b, n)
        valid: jax.Array,  # (b, n) bool, from _valid_labels
    ) -> jax.Array:
        """Cross-entropy as a rematted scan over sequence chunks.

        Peak memory is one chunk's logits ``(b, chunk, vocab)`` — forward
        AND backward (the remat recomputes each chunk's projection in the
        grad pass; dW accumulates across scan steps).  Value-identical to
        the dense path (same f32 lse-minus-chosen per position)."""
        b, n, _ = x.shape
        # clamp: padding a short sequence UP to the chunk size would make
        # peak memory/compute strictly worse than the dense path
        c = min(self.loss_chunk_size, n)
        x, _ = pad_to_multiple(x, c)
        labels, _ = pad_to_multiple(labels, c)
        valid, _ = pad_to_multiple(valid, c, value=False)
        nc = x.shape[1] // c
        xs = (
            x.reshape(b, nc, c, x.shape[-1]).transpose(1, 0, 2, 3),
            labels.reshape(b, nc, c).transpose(1, 0, 2),
            valid.reshape(b, nc, c).transpose(1, 0, 2),
        )

        def body(mdl, carry, inp):
            x_c, lab_c, val_c = inp
            nll = _position_nll(mdl.to_logits(x_c), lab_c, val_c)
            s, cnt = carry
            return (s + nll.sum(), cnt + val_c.sum()), None

        scan = nn.scan(
            nn.remat(body, prevent_cse=False),
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )
        (total, count), _ = scan(
            self, (jnp.float32(0.0), jnp.int32(0)), xs
        )
        return total / jnp.maximum(count, 1)

    # ------------------------------------------------------------------
    # Incremental decoding
    # ------------------------------------------------------------------

    def init_cache(self, batch: int, max_len: int) -> dict[str, Any]:
        """Fresh KV cache pytree; ``max_len`` must divide over the ring.

        With ``quantize_cache`` each per-layer entry is a
        ``(values int8, scales f32)`` tuple (see
        ``RingAttention.quantize_cache``); otherwise a dense array in the
        model dtype."""
        ring = self._ring_size()
        assert max_len % max(ring, 1) == 0
        if ring > 1 and self.mesh is not None and is_factored(self.mesh):
            raise NotImplementedError(
                "ring-sharded decode runs on a plain (data, seq) mesh; the "
                "factored hybrid mesh is a training/forward layout — decode "
                "with create_mesh(ring_size=...)"
            )
        if self.windowed_cache:
            assert ring <= 1, (
                "windowed_cache is a local-decode optimization; the "
                "ring-sharded cache uses absolute positions"
            )
        kvh = self.kv_heads or self.heads
        dtype = self.dtype or jnp.float32

        def make_entry(size):
            shape = (batch, kvh, size, self.dim_head)
            if self.quantize_cache:
                entry = (
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:3], jnp.float32),
                )
                if ring > 1:
                    entry = (
                        jax.device_put(entry[0], NamedSharding(
                            self.mesh, P(data_partition(self.mesh), None, SEQ_AXIS, None))),
                        jax.device_put(entry[1], NamedSharding(
                            self.mesh, P(data_partition(self.mesh), None, SEQ_AXIS))),
                    )
                return entry
            entry = jnp.zeros(shape, dtype)
            if ring > 1:
                entry = jax.device_put(entry, NamedSharding(
                    self.mesh, P(data_partition(self.mesh), None, SEQ_AXIS, None)))
            return entry

        sizes = [
            min(max_len, lb) if self.windowed_cache and lb is not None
            else max_len
            for lb in self._lookbacks()
        ]
        return {
            "k": [make_entry(s) for s in sizes],
            "v": [make_entry(s) for s in sizes],
        }

    def decode_step(
        self,
        token: jax.Array,  # (b,) int32 — token at position `pos`
        cache: dict[str, Any],
        pos: jax.Array,  # scalar int32
    ) -> tuple[jax.Array, dict[str, Any]]:
        """Next-token logits given the token at ``pos`` and the cache of
        positions ``[0, pos)``.  Returns ``(logits (b, vocab), new_cache)``."""
        x = self.embed(token[:, None])
        new_k, new_v = [], []
        for i, (attn, ff) in enumerate(zip(self.attn_layers, self.ff_layers)):
            a, ck, cv = attn.decode_step(x, cache["k"][i], cache["v"][i], pos)
            new_k.append(ck)
            new_v.append(cv)
            x = a + x
            x = ff(x) + x
        x = self.final_norm(x)
        logits = self.to_logits(x)[:, 0]
        return logits, {"k": new_k, "v": new_v}

    def prefill(
        self,
        tokens: jax.Array,  # (b, n) int32
        cache: dict[str, Any],
    ) -> tuple[jax.Array, dict[str, Any]]:
        """One causal pass over the prompt, filling cache positions [0, n).

        Returns ``(last_logits (b, vocab), cache)`` — n flash-prefilled
        positions instead of n sequential decode steps."""
        x = self.embed(tokens)
        new_k, new_v = [], []
        for i, (attn, ff) in enumerate(zip(self.attn_layers, self.ff_layers)):
            a, ck, cv = attn.prefill(x, cache["k"][i], cache["v"][i])
            new_k.append(ck)
            new_v.append(cv)
            x = a + x
            x = ff(x) + x
        x = self.final_norm(x)
        logits = self.to_logits(x)[:, -1]
        return logits, {"k": new_k, "v": new_v}

    def generate(
        self,
        prompt: jax.Array,  # (b, n) int32
        max_len: int,
        num_steps: int,
        *,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        rng: jax.Array | None = None,
    ) -> jax.Array:
        """One prefill pass over the prompt, then emit ``num_steps`` new
        tokens.  Returns ``(b, num_steps)``.

        The decode loop is a single ``nn.scan`` with the KV cache as carry,
        so the jitted program holds ONE decode-step body regardless of
        ``num_steps`` (compile time is O(1) in generation length, not O(n)
        as a Python loop of traced steps would be).

        ``temperature == 0.0`` (default) is greedy argmax; otherwise
        categorical sampling at the given temperature, truncated to the
        ``top_k`` highest-probability tokens and/or the ``top_p`` nucleus
        (smallest probability mass >= top_p), driven by ``rng`` (which must
        then be provided).
        """
        b, n = prompt.shape
        assert n >= 1, "generate needs a non-empty prompt"
        assert num_steps >= 1, "generate needs num_steps >= 1"
        assert n + num_steps - 1 <= max_len, "cache too small for prompt + steps"
        if temperature > 0.0 and rng is None:
            raise ValueError("generate: temperature > 0 needs an rng key")
        if temperature <= 0.0 and (top_k is not None or top_p is not None):
            raise ValueError(
                "generate: top_k/top_p need temperature > 0 (greedy mode "
                "would silently ignore them)"
            )
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"generate: top_p must be in (0, 1], got {top_p}")
        if rng is None:  # unused (greedy) but keeps the carry pytree uniform
            rng = jax.random.PRNGKey(0)

        def sample(logits, key):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # temperature first: the nucleus must be computed on the SAME
            # distribution that is sampled (the standard ordering)
            logits = logits.astype(jnp.float32) / temperature
            if top_k is not None:
                kth = lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p is not None:
                # nucleus: keep the smallest prefix of descending-prob
                # tokens whose mass reaches top_p (always >= 1 token, since
                # each token's threshold tests the mass *before* it and
                # top_p > 0 is validated above)
                sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
                probs = jax.nn.softmax(sorted_logits, axis=-1)
                mass_before = jnp.cumsum(probs, axis=-1) - probs
                cut = jnp.sum(mass_before < top_p, axis=-1, keepdims=True)
                thresh = jnp.take_along_axis(sorted_logits, cut - 1, axis=-1)
                logits = jnp.where(logits < thresh, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1).astype(
                jnp.int32
            )

        cache = self.init_cache(b, max_len)
        logits, cache = self.prefill(prompt, cache)
        rng, key = jax.random.split(rng)
        tok = sample(logits, key)
        if num_steps == 1:
            return tok[:, None]

        def body(mdl, carry, _):
            tok, cache, pos, rng = carry
            logits, cache = mdl.decode_step(tok, cache, pos)
            rng, key = jax.random.split(rng)
            nxt = sample(logits, key)
            return (nxt, cache, pos + 1, rng), nxt

        scan = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False},
            length=num_steps - 1,
        )
        _, rest = scan(self, (tok, cache, jnp.int32(n), rng), None)
        return jnp.concatenate([tok[:, None], rest.T], axis=1)
