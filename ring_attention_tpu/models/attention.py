"""RingAttention module: the user-facing attention layer.

TPU-native equivalent of the reference's ``RingAttention``
(ref ``ring_attention.py:283-466``): prenorm + fused qkv projection, GQA head
split, shard-aware rotary, and dispatch to the ring path (``shard_map`` +
``lax.ppermute``) or a single-device oracle (``force_regular_attn``).

Auto-sharding follows the reference's model-top recipe (pad -> stripe ->
shard, ref ``ring_attention.py:389-403``) but expressed as layouts: a pure
stripe permutation plus a ``NamedSharding`` constraint; XLA inserts the
minimal collective instead of a hand-written all-gather
(cf. ``sharded_batch_to_sharded_seq``, ref ``ring_attention.py:223-262``).

Beyond the reference: ``decode_step`` — single-token incremental decoding
against a KV cache sharded over the ring, merged with tree attention
(the reference ships ``tree_attn_decode`` standalone only,
ref ``tree_attn_decoding.py:23-103``).
"""

from __future__ import annotations

import math
import warnings

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import masks as mask_algebra
from ..ops.attention import PAD_SEGMENT_ID, default_attention
from ..ops.flash import flash_attention
from ..ops.pallas_flash import (
    QuantizedKV,
    _doc_runtime_ids,
    dequantize_kv_cache as _dequantize,
    pallas_flash_attention,
    pallas_flash_decode,
    pallas_flash_decode_q8,
    quantize_kv_cache,
)
from ..ops.rotary import apply_rotary, hybrid_positions, ring_positions, rotary_freqs
from ..parallel.hybrid import hybrid_attention
from ..parallel.mesh import (
    RING_AXIS,
    SEQ_AXIS,
    ULYSSES_AXIS,
    data_partition,
    is_factored,
    seq_partition,
    seq_world,
)
from ..parallel.ring import ring_flash_attention
from ..parallel.sharding import (
    layout_for,
    layout_permute,
    layout_unpermute,
    pad_seq_and_mask,
    pad_to_multiple,
)
from ..parallel.tree_decode import tree_attn_decode
from ..parallel.ulysses import ulysses_attention
from ..parallel.zigzag import zigzag_attention, zigzag_positions
from ..utils import compat
from ..utils.validate import check_model_input
from .layers import RMSNorm


class RingAttention(nn.Module):
    """Sequence-parallel attention layer.

    Attributes mirror the reference constructor (ref
    ``ring_attention.py:284-337``); ``kv_heads`` expresses GQA directly
    (the reference's ``heads // num_grouped_query_heads``).
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    kv_heads: int | None = None
    causal: bool = False
    # mask-algebra expression (ring_attention_tpu.masks): the general
    # form of the masking knobs — ``causal=True`` is sugar for
    # ``mask=Causal()``, ``mask=Causal() & SlidingWindow(w)`` replaces
    # ``max_lookback_seq_len=w``, ``... & DocumentMask(starts)`` declares
    # a packed layout, ``... & Segments()`` requires runtime segment_ids.
    # The lowering is certified sound/tight/complete at trace time
    # against the mask's own oracle (masks.require_certified, cached);
    # expressions beyond the kernel surface raise MaskLoweringError
    # naming the supported forms.  Mutually exclusive with causal=True
    # and max_lookback_seq_len (compose them into the mask instead)
    mask: mask_algebra.Mask | None = None
    striped: bool = False
    bucket_size: int = 512
    use_ring: bool = True
    force_regular_attn: bool = False
    rotary: bool = True
    rotary_theta: float = 10000.0
    softclamp_value: float | None = None
    max_lookback_seq_len: int | None = None
    auto_shard: bool = False
    mesh: Mesh | None = None
    use_pallas: bool = False
    # kernel-path selection with graceful degradation (overrides use_pallas
    # when set): "fused" | "pallas" | "xla" | "auto".  "auto" resolves
    # through utils/resilience.py at trace time — the fused ring
    # (ops/pallas_ring.py: one launch, in-kernel remote KV DMA) when its
    # probe passes, else the scan-path Pallas kernels, else the XLA flash
    # path, with a one-shot warning and a queryable degradation record.
    # "fused" applies to the "ring" strategy and the hybrid outer ring;
    # other strategies run it as "pallas".  use_pallas remains as the
    # explicit legacy switch.
    impl: str | None = None
    # split the (non-ring) pallas launch into this many per-head-group
    # kernel programs — bit-identical results; the escape hatch for
    # compiler/relay program-size limits at large heads x seq (see
    # ops/pallas_flash.py pallas_flash_attention)
    pallas_head_chunks: int | None = None
    # store the decode KV cache as per-token-absmax int8 (+ f32 scales):
    # 1.88x fewer cache HBM bytes per decode step at d=64 — the binding
    # resource at long context — for ~1% output error (see
    # ops/pallas_flash.py QuantizedKV).  Cache entries become
    # (values int8, scales f32) tuples; decode attends via the q8 kernel
    # (use_pallas) or a dequantized oracle fallback
    quantize_cache: bool = False
    # context-parallel scheme over the seq mesh axis (or axes):
    #   "ring"    — KV rotation (+ striped load balance); the reference's core
    #   "zigzag"  — Llama-3 chunk pairing + all-gathered KV (causal only)
    #   "ulysses" — all-to-all head parallelism (not in the reference)
    #   "hybrid"  — Ulysses x Ring 2-D factoring: all-to-all over the inner
    #               `ulysses` mesh axis, ring over the outer `ring` axis —
    #               ulysses_size x fewer ring hops at equal world size;
    #               requires a factored mesh (create_mesh(ulysses_size=U))
    sequence_parallel: str = "ring"
    # circulate KV halves in opposite ring directions (full-duplex ICI);
    # applies when the local shard length is even, unidirectional with a
    # warning otherwise (odd shards only arise from padding edge cases)
    ring_bidirectional: bool = False
    # dtype for the circulating dk/dv ring accumulators in the backward:
    # None = float32 (exact); "bfloat16" halves backward ring bandwidth
    # (ref ring_flash_attention_cuda.py:255-260) at bf16 round-off cost
    ring_dkv_dtype: str | None = None
    # TokenRing counter-rotation (arXiv 2412.20501): circulate the Q shard
    # + its online-softmax accumulators one ring direction while the KV
    # stream rotates the other — each full-duplex ICI direction carries
    # about half the rotation traffic, and the backward drops the
    # circulating dkv payload entirely (parallel/ring.py::_counter_fwd).
    # Supersedes ring_bidirectional (the two schedules cannot compose —
    # docs/ring_overlap.md); applies to the pure ring and the hybrid
    # outer ring alike
    ring_counter_rotate: bool = False
    # "int8": ship each forward KV hop as per-token absmax int8 values +
    # bitcast f32 scales in one payload — same hop count, ~dtype_bytes *
    # d/(d+4)-x fewer bytes per hop; quantized once at ring entry, f32
    # accumulators untouched (parallel/collectives.quantize_ring_payload)
    ring_hop_compression: str | None = None
    # "int8": run the forward's QK^T and PV matmuls on int8 operands
    # (v5e/v5p MXUs run int8 at ~2x bf16 peak) with per-row q/k and
    # per-KV-block v absmax scales, f32 (acc, m, l) untouched; the
    # backward stays bf16 from the exact residuals this round.  Pallas
    # kernels only — requires impl="pallas"/use_pallas on the "ring" or
    # "hybrid" strategies (or the local path); composes with
    # ring_hop_compression="int8" into the dequant-free ring
    # (docs/precision.md)
    compute_dtype: str | None = None
    dtype: jnp.dtype | None = None

    def setup(self):
        h, kvh, dh = self.heads, self._kv_heads(), self.dim_head
        self.prenorm = RMSNorm(self.dim)
        self.to_qkv = nn.Dense(
            (h + 2 * kvh) * dh, use_bias=False, dtype=self.dtype
        )
        self.to_out = nn.Dense(self.dim, use_bias=False, dtype=self.dtype)

    def _kv_heads(self) -> int:
        kvh = self.kv_heads or self.heads
        assert self.heads % kvh == 0
        return kvh

    def _mask_form(self) -> mask_algebra.KernelForm | None:
        """The algebra mask resolved onto the kernel knobs (or None).
        Raises on conflicting legacy knobs and on masks beyond the
        kernel surface (MaskLoweringError names the supported forms)."""
        if self.mask is None:
            return None
        if self.causal:
            raise ValueError(
                "RingAttention: mask= replaces causal=True (causal=True "
                "is sugar for mask=Causal()); set only one"
            )
        if self.max_lookback_seq_len is not None:
            raise ValueError(
                "RingAttention: mask= replaces max_lookback_seq_len — "
                "compose SlidingWindow(w) into the mask instead"
            )
        return mask_algebra.kernel_form(self.mask)

    def _eff_causal(self) -> bool:
        form = self._mask_form()
        return self.causal if form is None else form.causal

    def _eff_lookback(self) -> int | None:
        form = self._mask_form()
        return self.max_lookback_seq_len if form is None else form.window

    def _certify_mask(self, n: int) -> None:
        """Trace-time certificate for the grids this call's strategy
        lowers the mask to — proven on first use, cached by (mask,
        shape, blocks, strategy, layout) next to the compile cache."""
        if self.mask is None:
            return
        ring = (self.use_ring and not self.force_regular_attn
                and self._ring_size() > 1)
        if not ring:
            strategy, ring_size = "single", 1
        elif self.sequence_parallel == "hybrid":
            strategy = ("counter" if self.ring_counter_rotate else "ring")
            ring_size = self._ring_size() // self._ulysses_size()
        elif self.sequence_parallel == "ring":
            strategy = ("counter" if self.ring_counter_rotate else "ring")
            ring_size = self._ring_size()
        else:  # ulysses attends the full span locally; zigzag is
            strategy, ring_size = "single", 1  # causal-only (own row)
        passes = None
        if strategy in ("ring", "counter") and ring_size > 1:
            _, _, _, passes = self._ring_leg(n // ring_size)
        mask_algebra.require_certified(
            self.mask,
            mask_algebra.spec_for_call(
                strategy, n=n, ring=ring_size, striped=self.striped,
                passes=passes,
            ),
        )

    def _kernel_impl(self) -> str:
        """Resolve the kernel path for this call (trace time, cached probe):
        "fused" | "pallas" | "xla".  Counter-rotation has no fused form
        (the alternating Q/KV schedule cannot ride one launch), so a
        resolved "fused" degrades to the scan-path Pallas ring there."""
        if self.impl is None:
            resolved = "pallas" if self.use_pallas else "xla"
        else:
            from ..utils import resilience

            resolved = resilience.resolve_ring_impl(self.impl)
        if resolved == "fused" and self.ring_counter_rotate:
            return "pallas"
        return resolved

    def _use_pallas(self) -> bool:
        """True when this call runs on Pallas kernels (scan-path or fused)."""
        return self._kernel_impl() in ("pallas", "fused")

    def _compute_dtype(self) -> str | None:
        """Validated int8-compute knob for this call.

        ``"int8"`` needs the Pallas kernels (the XLA/oracle paths have no
        int8 matmul form) and a strategy that lowers onto them — the
        local path, "ring", or "hybrid".  A config that silently ran the
        quantized model at bf16 would misreport every perf number, so
        mismatches raise rather than degrade (docs/precision.md)."""
        if self.compute_dtype is None:
            return None
        if self.compute_dtype != "int8":
            raise ValueError(
                f"RingAttention: compute_dtype={self.compute_dtype!r}; "
                'supported values are None and "int8"'
            )
        if self.force_regular_attn or not self._use_pallas():
            raise ValueError(
                'compute_dtype="int8" runs on the Pallas kernels only — '
                "set impl=\"pallas\"/use_pallas=True (and drop "
                "force_regular_attn)"
            )
        if (self._ring_size() > 1 and self.use_ring
                and self.sequence_parallel not in ("ring", "hybrid")):
            raise ValueError(
                f'compute_dtype="int8" supports the "ring" and "hybrid" '
                f"strategies (and the local path); got "
                f'sequence_parallel="{self.sequence_parallel}"'
            )
        return "int8"

    def _ring_size(self) -> int:
        """Total sequence-parallel world (over BOTH axes of a factored mesh)."""
        if self.mesh is None:
            return 1
        return seq_world(self.mesh)

    def _ulysses_size(self) -> int:
        if self.mesh is None or not is_factored(self.mesh):
            return 1
        return self.mesh.shape[ULYSSES_AXIS]

    def _layout(self) -> tuple[str, int]:
        """(scheme, factor) for the model-top sequence permutation — the
        shared derivation (``parallel/sharding.py::layout_for``), so this
        layer and ``RingTransformer`` can never disagree."""
        return layout_for(
            self.sequence_parallel, self.striped, self._ring_size(),
            self._ulysses_size(),
        )

    def _check_mesh(self) -> None:
        factored = self.mesh is not None and is_factored(self.mesh)
        if self.sequence_parallel == "hybrid" and not factored:
            raise ValueError(
                'sequence_parallel="hybrid" needs a factored mesh — build '
                "it with create_mesh(ulysses_size=U, ring_size=R)"
            )
        if self.sequence_parallel != "hybrid" and factored:
            raise ValueError(
                f'sequence_parallel="{self.sequence_parallel}" runs on a '
                "plain (data, seq) mesh; the factored (data, ring, ulysses) "
                'mesh is for sequence_parallel="hybrid"'
            )

    def _bidirectional(self, n_local: int) -> bool:
        """Bidirectional streams need an even local shard; warn on the
        silent unidirectional fallback so benchmarks aren't misread."""
        if self.ring_bidirectional and n_local % 2:
            warnings.warn(
                f"ring_bidirectional requested but the per-device sequence "
                f"length ({n_local}) is odd; running the unidirectional ring",
                stacklevel=3,
            )
            return False
        return self.ring_bidirectional

    def _project_qkv(self, x: jax.Array):
        """prenorm + fused qkv -> heads-major (b, h|hk, n, dh)."""
        h, kvh, dh = self.heads, self._kv_heads(), self.dim_head
        qkv = self.to_qkv(self.prenorm(x))
        q, k, v = jnp.split(qkv, [h * dh, (h + kvh) * dh], axis=-1)
        b, n, _ = x.shape
        q = q.reshape(b, n, h, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, n, kvh, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, n, kvh, dh).transpose(0, 2, 1, 3)
        return q, k, v

    def __call__(
        self,
        x: jax.Array,
        mask: jax.Array | None = None,
        segment_ids: jax.Array | None = None,
    ) -> jax.Array:
        """``x: (b, n, dim)`` -> ``(b, n, dim)``.

        When ``auto_shard`` is set, ``x`` is a global (unsharded-layout)
        array: it is padded to the ring size, stripe-permuted if ``striped``,
        and constrained onto the ``(data, seq)`` mesh; the inverse is applied
        to the output (ref ``ring_attention.py:389-403,458-464``).

        ``segment_ids: (b, n)`` int document ids enable packed-sequence
        attention (cross-document attention masked; whole tiles/hops
        skipped where possible — see ``docs/packing.md``).  Padding added
        by ``auto_shard`` gets ``PAD_SEGMENT_ID``, which matches no real
        document.
        """
        check_model_input("RingAttention", x, self.dim)
        ring = self.use_ring and not self.force_regular_attn and self._ring_size() > 1
        assert self.sequence_parallel in ("ring", "zigzag", "ulysses", "hybrid")
        if ring:
            self._check_mesh()
        if self.sequence_parallel == "zigzag":
            assert self._eff_causal(), "zig-zag CP is causal-only (ref zig_zag_attention.py:102-103)"
            assert self._eff_lookback() is None, "lookback not supported with zigzag"

        form = self._mask_form()
        if form is not None:
            if form.needs_segment_ids and segment_ids is None:
                raise ValueError(
                    "RingAttention: the mask includes Segments() — pass "
                    "the runtime segment_ids array"
                )
            if form.doc_starts is not None:
                if segment_ids is not None:
                    raise ValueError(
                        "RingAttention: the mask declares a DocumentMask "
                        "layout AND segment_ids were passed — declare "
                        "one packing"
                    )
                if ring:
                    # sequence-parallel paths realize the declared layout
                    # as runtime ids (padded/permuted/rotated by the
                    # existing proven machinery); the local Pallas path
                    # keeps doc_starts for its trace-time compact grid
                    segment_ids = _doc_runtime_ids(
                        form.doc_starts, x.shape[1], x.shape[0]
                    )

        n_orig = x.shape[1]
        scheme, factor = self._layout()
        if ring and self.auto_shard:
            pad_mult = (
                2 * self._ring_size()
                if self.sequence_parallel == "zigzag"
                else self._ring_size()
            )
            x, mask, n_orig = pad_seq_and_mask(x, mask, pad_mult)
            if segment_ids is not None:
                segment_ids, _ = pad_to_multiple(
                    segment_ids, pad_mult, value=PAD_SEGMENT_ID
                )
            x = layout_permute(x, scheme, factor)
            if mask is not None:
                mask = layout_permute(mask, scheme, factor)
            if segment_ids is not None:
                segment_ids = layout_permute(segment_ids, scheme, factor)
            x = lax.with_sharding_constraint(
                x, NamedSharding(
                    self.mesh, P(data_partition(self.mesh), seq_partition(self.mesh), None)
                )
            )

        q, k, v = self._project_qkv(x)
        b, n, _ = x.shape
        self._certify_mask(n)

        if self._eff_causal():
            mask = None  # ref asserts causal and key-pad mask are exclusive

        if ring:
            out = self._sp_attend(q, k, v, mask, segment_ids)
        else:
            out = self._local_attend(q, k, v, mask, segment_ids)

        out = out.transpose(0, 2, 1, 3).reshape(b, n, self.heads * self.dim_head)
        out = self.to_out(out)

        if ring and self.auto_shard:
            out = layout_unpermute(out, scheme, factor)
            out = out[:, :n_orig]
        return out

    def _local_attend(self, q, k, v, mask, segment_ids=None):
        n = q.shape[2]
        if self.rotary:
            freqs = rotary_freqs(jnp.arange(n), self.dim_head, self.rotary_theta)
            q = apply_rotary(q, freqs)
            k = apply_rotary(k, freqs)
        window = self._eff_lookback()
        causal = self._eff_causal()
        # a mask-declared packing: doc_starts feed the Pallas compact
        # grid directly; the XLA/oracle paths realize them as runtime ids
        form = self._mask_form()
        doc_starts = (form.doc_starts
                      if form is not None and segment_ids is None else None)
        doc_ids = (None if doc_starts is None
                   else _doc_runtime_ids(doc_starts, n, q.shape[0]))
        if self.force_regular_attn and window is None:
            return default_attention(
                q, k, v, mask, causal=causal,
                softclamp_value=self.softclamp_value,
                segment_ids=segment_ids if doc_ids is None else doc_ids,
            )
        if self._use_pallas():
            return pallas_flash_attention(
                q, k, v, mask, causal=causal, window=window,
                softclamp_value=self.softclamp_value,
                head_chunks=self.pallas_head_chunks,
                segment_ids=segment_ids, doc_starts=doc_starts,
                compute_dtype=self._compute_dtype(),
            )
        return flash_attention(
            q, k, v, mask, causal=causal, bucket_size=self.bucket_size,
            window=window, softclamp_value=self.softclamp_value,
            segment_ids=segment_ids if doc_ids is None else doc_ids,
        )

    def _sp_attend(self, q, k, v, mask, segment_ids=None):
        """Dispatch to the configured context-parallel scheme."""
        ring_size = self._ring_size()
        n = q.shape[2]
        mult = 2 * ring_size if self.sequence_parallel == "zigzag" else ring_size
        assert n % mult == 0, (
            f"sequence {n} must divide over {mult} ({self.sequence_parallel}); "
            "use auto_shard=True to pad"
        )
        if self.sequence_parallel == "zigzag":
            return self._zigzag_attend(q, k, v, segment_ids)
        if self.sequence_parallel == "ulysses":
            return self._ulysses_attend(q, k, v, mask, segment_ids)
        if self.sequence_parallel == "hybrid":
            return self._hybrid_attend(q, k, v, mask, segment_ids)
        return self._ring_attend(q, k, v, mask, segment_ids)

    def _seg_spec(self, segment_ids):
        """shard_map spec for an optional (b, n) per-token operand, on the
        plain or factored sequence axes."""
        if segment_ids is None:
            return P()
        return P(data_partition(self.mesh), seq_partition(self.mesh))

    def _ring_leg(self, n_chunk: int):
        """Ring-leg knobs for chunks of length ``n_chunk`` — the whole
        local shard for the pure ring, the post-all-to-all chunk for
        hybrid.  Returns ``(bucket, bidirectional, window,
        max_ring_passes)``; the ONE copy of the bucket-fit and lookback
        hop-skip arithmetic, so the two ring callers cannot drift."""
        # per-hop flash tile: largest divisor of the chunk <= bucket_size
        bucket = min(self.bucket_size, n_chunk)
        while n_chunk % bucket:
            bucket -= 1
        bidirectional = self._bidirectional(n_chunk)
        max_ring_passes = None
        window = None
        lookback = self._eff_lookback()
        if lookback is not None:
            assert self._eff_causal(), (
                "max_lookback_seq_len requires causal attention "
                "(ref ring_flash_attention.py:99)"
            )
            window = lookback
            if not self.striped:
                # contiguous layout: distant hops carry no in-window keys,
                # so cover ceil((window-1)/n_chunk) earlier chunks plus our
                # own (exact — the reference truncates early rows at bucket
                # granularity, ring_flash_attention.py:95-103)
                max_ring_passes = math.ceil((lookback - 1) / n_chunk) + 1
            # striped layout: windows are exact too (per-hop band lower
            # offsets, parallel/ring.py), but striping interleaves tokens
            # so every hop holds some in-window keys — all passes run.
            # Prefer non-striped for windowed attention: the window itself
            # balances causal load and allows hop skipping.
        return bucket, bidirectional, window, max_ring_passes

    def _zigzag_attend(self, q, k, v, segment_ids=None):
        ring_size = self._ring_size()
        n_local = q.shape[2] // ring_size

        def core(q, k, v, seg):
            if self.rotary:
                rank = lax.axis_index(SEQ_AXIS)
                pos = zigzag_positions(n_local, rank, ring_size)
                freqs = rotary_freqs(pos, self.dim_head, self.rotary_theta)
                q = apply_rotary(q, freqs)
                k = apply_rotary(k, freqs)
            return zigzag_attention(
                q, k, v, SEQ_AXIS,
                bucket_size=self.bucket_size,
                softclamp_value=self.softclamp_value,
                impl="pallas" if self._use_pallas() else "xla",
                segment_ids=seg,
            )

        qspec = P(data_partition(self.mesh), None, SEQ_AXIS, None)
        return compat.shard_map(
            core, mesh=self.mesh,
            in_specs=(qspec, qspec, qspec, self._seg_spec(segment_ids)),
            out_specs=qspec,
            check_vma=not self._use_pallas(),
        )(q, k, v, segment_ids)

    def _ulysses_attend(self, q, k, v, mask, segment_ids=None):
        ring_size = self._ring_size()
        n_local = q.shape[2] // ring_size

        def core(q, k, v, mask, seg):
            if self.rotary:
                rank = lax.axis_index(SEQ_AXIS)
                pos = ring_positions(n_local, rank, striped=False, world=ring_size)
                freqs = rotary_freqs(pos, self.dim_head, self.rotary_theta)
                q = apply_rotary(q, freqs)
                k = apply_rotary(k, freqs)
            return ulysses_attention(
                q, k, v, SEQ_AXIS,
                causal=self._eff_causal(),
                kv_mask=mask,
                bucket_size=self.bucket_size,
                window=self._eff_lookback(),
                softclamp_value=self.softclamp_value,
                impl="pallas" if self._use_pallas() else "xla",
                segment_ids=seg,
            )

        qspec = P(data_partition(self.mesh), None, SEQ_AXIS, None)
        mspec = P(data_partition(self.mesh), SEQ_AXIS) if mask is not None else P()
        return compat.shard_map(
            core, mesh=self.mesh,
            in_specs=(qspec, qspec, qspec, mspec, self._seg_spec(segment_ids)),
            out_specs=qspec,
            check_vma=not self._use_pallas(),
        )(q, k, v, mask, segment_ids)

    def _hybrid_attend(self, q, k, v, mask, segment_ids=None):
        """Ulysses x Ring 2-D factoring over the (data, ring, ulysses) mesh.

        Rotary runs on the resident (pre-all-to-all) shard with positions
        from the combined rank — the all-to-all only *moves* rotated
        tokens, so the ring leg sees exactly the positions a pure ring of
        ``ring_size`` devices would.  The ring-leg knobs (bucket, window,
        bidirectional streams) are sized against the post-all-to-all chunk
        ``n / ring_size``, which is what the ring actually attends.
        """
        ulysses = self._ulysses_size()
        ring_size = self._ring_size() // ulysses
        n = q.shape[2]
        n_local = n // (ulysses * ring_size)  # resident shard
        n_ring = n // ring_size  # post-all-to-all ring chunk
        bucket, bidirectional, window, max_ring_passes = self._ring_leg(n_ring)

        def core(q, k, v, mask, seg):
            if self.rotary:
                pos = hybrid_positions(
                    n_local,
                    lax.axis_index(ULYSSES_AXIS),
                    lax.axis_index(RING_AXIS),
                    ulysses=ulysses, ring=ring_size, striped=self.striped,
                )
                freqs = rotary_freqs(pos, self.dim_head, self.rotary_theta)
                q_r = apply_rotary(q, freqs)
                k_r = apply_rotary(k, freqs)
            else:
                q_r, k_r = q, k
            return hybrid_attention(
                q_r, k_r, v, mask, ULYSSES_AXIS, RING_AXIS,
                causal=self._eff_causal(), striped=self.striped,
                bucket_size=bucket, max_ring_passes=max_ring_passes,
                window=window, softclamp_value=self.softclamp_value,
                impl=self._kernel_impl(),
                bidirectional=bidirectional,
                dkv_dtype=self.ring_dkv_dtype,
                segment_ids=seg,
                counter_rotate=self.ring_counter_rotate,
                hop_compression=self.ring_hop_compression,
                compute_dtype=self._compute_dtype(),
            )

        qspec = P(data_partition(self.mesh), None, seq_partition(self.mesh), None)
        mspec = self._seg_spec(mask)
        return compat.shard_map(
            core,
            mesh=self.mesh,
            in_specs=(qspec, qspec, qspec, mspec, self._seg_spec(segment_ids)),
            out_specs=qspec,
            check_vma=not self._use_pallas(),
        )(q, k, v, mask, segment_ids)

    def _ring_attend(self, q, k, v, mask, segment_ids=None):
        ring_size = self._ring_size()
        n = q.shape[2]
        n_local = n // ring_size
        bucket, bidirectional, window, max_ring_passes = self._ring_leg(n_local)

        def core(q, k, v, mask, seg):
            rank = lax.axis_index(SEQ_AXIS)
            if self.rotary:
                pos = ring_positions(
                    n_local, rank, striped=self.striped, world=ring_size
                )
                freqs = rotary_freqs(pos, self.dim_head, self.rotary_theta)
                q_r = apply_rotary(q, freqs)
                k_r = apply_rotary(k, freqs)
            else:
                q_r, k_r = q, k
            return ring_flash_attention(
                q_r, k_r, v, mask, SEQ_AXIS,
                self._eff_causal(), self.striped,
                bucket, max_ring_passes, window,
                self.softclamp_value, None,
                self._kernel_impl(),
                bidirectional, self.ring_dkv_dtype,
                segment_ids=seg,
                counter_rotate=self.ring_counter_rotate,
                hop_compression=self.ring_hop_compression,
                compute_dtype=self._compute_dtype(),
            )

        qspec = P(data_partition(self.mesh), None, SEQ_AXIS, None)
        mspec = P(data_partition(self.mesh), SEQ_AXIS) if mask is not None else P()
        return compat.shard_map(
            core,
            mesh=self.mesh,
            in_specs=(qspec, qspec, qspec, mspec, self._seg_spec(segment_ids)),
            out_specs=qspec,
            # pallas_call with device-varying scalars trips jax's vma
            # checker; jax suggests check_vma=False as the workaround
            check_vma=not self._use_pallas(),
        )(q, k, v, mask, segment_ids)

    # ------------------------------------------------------------------
    # Incremental decoding (beyond reference parity)
    # ------------------------------------------------------------------

    def decode_step(
        self,
        x: jax.Array,  # (b, 1, dim) — the new token's activation
        cache_k: jax.Array,  # (b, hk, max_len, dh); sharded over seq if ring
        cache_v: jax.Array,
        pos: jax.Array,  # scalar int32: index the new token occupies
    ) -> tuple[jax.Array, jax.Array, jax.Array]:
        """One token of autoregressive decoding against a KV cache.

        Writes this token's K/V at ``pos`` and attends positions
        ``[0, pos]`` — restricted to the last ``max_lookback_seq_len``
        positions when the layer has a lookback window, matching the
        training-time forward.  With a mesh, the cache is sharded
        contiguously over the ``seq`` axis and the shard partials merge via
        tree attention (``parallel/tree_decode.py``); decode layout is
        always contiguous regardless of how training was striped, since
        positions are explicit.  Returns ``(out (b,1,dim), cache_k, cache_v)``.
        """
        q, k, v = self._project_qkv(x)
        if self.rotary:
            freqs = rotary_freqs(
                jnp.reshape(pos, (1,)), self.dim_head, self.rotary_theta
            )
            q = apply_rotary(q, freqs)
            k = apply_rotary(k, freqs)

        ring = self.use_ring and not self.force_regular_attn and self._ring_size() > 1
        # the local cache is a ring buffer: writes land at pos % size and
        # slot validity comes from _buffer_mask.  A full-length cache
        # (size > every pos) reduces exactly to the plain layout, and a
        # window-sized cache (size >= max_lookback_seq_len) stores only the
        # window — O(W) decode memory/bandwidth instead of O(max_len) for
        # lookback layers (see RingTransformer.windowed_cache)
        if not ring and self.quantize_cache:
            size = cache_k[0].shape[2]
            cache_k, cache_v = self._quantized_write(
                cache_k, cache_v, k, v, pos % size
            )
            kv = QuantizedKV(*cache_k, *cache_v)
            kv_mask = self._buffer_mask(size, pos, x.shape[0])
            if self._use_pallas():
                out, _ = pallas_flash_decode_q8(
                    q, kv, kv_mask, softclamp_value=self.softclamp_value,
                )
            else:
                k_deq, v_deq = _dequantize(kv, q.dtype)
                out = default_attention(
                    q, k_deq, v_deq, kv_mask,
                    softclamp_value=self.softclamp_value,
                )
        elif not ring:
            size = cache_k.shape[2]
            slot = pos % size
            cache_k = lax.dynamic_update_slice_in_dim(cache_k, k.astype(cache_k.dtype), slot, axis=2)
            cache_v = lax.dynamic_update_slice_in_dim(cache_v, v.astype(cache_v.dtype), slot, axis=2)
            kv_mask = self._buffer_mask(size, pos, x.shape[0])
            if self._use_pallas():
                # single-sweep decode kernel: each cache byte read once per
                # kv head, normalized output written in-kernel
                out, _ = pallas_flash_decode(
                    q, cache_k, cache_v, kv_mask,
                    softclamp_value=self.softclamp_value,
                )
            else:
                out = default_attention(
                    q, cache_k, cache_v, kv_mask,
                    softclamp_value=self.softclamp_value,
                )
        else:
            out, cache_k, cache_v = self._ring_decode(q, k, v, cache_k, cache_v, pos)

        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], 1, -1)
        return self.to_out(out), cache_k, cache_v

    @staticmethod
    def _quantized_write(cache_k, cache_v, k, v, pos):
        """Quantize this step's K/V rows and write values + scales at
        ``pos``.  Cache entries are ``(values int8, scales f32)`` tuples."""
        kq, ks, vq, vs = quantize_kv_cache(k, v)
        (k_qc, k_sc), (v_qc, v_sc) = cache_k, cache_v

        def wr(c, new, axis):
            return lax.dynamic_update_slice_in_dim(
                c, new.astype(c.dtype), pos, axis=axis
            )

        return (
            (wr(k_qc, kq, 2), wr(k_sc, ks, 2)),
            (wr(v_qc, vq, 2), wr(v_sc, vs, 2)),
        )

    def _decode_mask(self, idx: jax.Array, pos: jax.Array, batch: int) -> jax.Array:
        """Valid-cache-slot mask for a decode step: ``[0, pos]``, windowed to
        the last ``max_lookback_seq_len`` tokens when configured.  ``idx``
        are absolute token positions (the ring path's contiguous shards)."""
        keep = idx <= pos
        lookback = self._eff_lookback()
        if lookback is not None:
            keep = keep & (idx > pos - lookback)
        return jnp.broadcast_to(keep[None, :], (batch, idx.shape[0]))

    def _buffer_mask(self, size: int, pos: jax.Array, batch: int) -> jax.Array:
        """Valid-slot mask for a ring-buffer cache of ``size`` slots.

        Slot ``s`` holds the most recent position ``p_s <= pos`` with
        ``p_s ≡ s (mod size)``; a slot is valid when that position exists
        (``p_s >= 0``) and sits inside the lookback window.  With
        ``size > pos`` this reduces to the plain ``idx <= pos`` mask, so
        the local decode path uses it unconditionally."""
        s = jnp.arange(size)
        p = pos - ((pos - s) % size)
        keep = p >= 0
        lookback = self._eff_lookback()
        if lookback is not None:
            keep = keep & (p > pos - lookback)
        return jnp.broadcast_to(keep[None, :], (batch, size))

    def prefill(
        self,
        x: jax.Array,  # (b, n, dim) — the whole prompt
        cache_k: jax.Array,  # (b, hk, max_len, dh)
        cache_v: jax.Array,
    ) -> tuple[jax.Array, jax.Array, jax.Array]:
        """Process a whole prompt in one causal pass and fill cache[0:n].

        One O(n^2)-FLOPs flash pass instead of n decode steps; the written
        K/V are rotary-applied exactly as ``decode_step`` writes them, so
        decoding can continue from position ``n``.  With a mesh, the prompt
        is padded onto the ring and attention runs sequence-parallel
        (contiguous layout, like the decode cache) — per-device memory
        scales as n/ring, same as the training forward.  Returns
        ``(out (b,n,dim), cache_k, cache_v)``.
        """
        n = x.shape[1]
        size = (cache_k[0] if self.quantize_cache else cache_k).shape[2]
        if n > size:
            # window-sized ring-buffer cache: only the last `size` rows
            # survive (valid when the cache covers the lookback window —
            # decode steps never look further back than that).  Not an
            # assert: under python -O a silently-truncated global-attention
            # cache would produce wrong logits with no error
            if (self._eff_lookback() is None
                    or size < self._eff_lookback()):
                raise ValueError(
                    f"prefill: prompt ({n}) longer than the cache ({size}) "
                    f"is only valid for a window-sized cache covering "
                    f"max_lookback_seq_len ({self._eff_lookback()})"
                )
        q, k, v = self._project_qkv(x)
        if self.rotary:
            freqs = rotary_freqs(jnp.arange(n), self.dim_head, self.rotary_theta)
            q = apply_rotary(q, freqs)
            k = apply_rotary(k, freqs)

        ring = self.use_ring and not self.force_regular_attn and self._ring_size() > 1
        if ring:
            out = self._ring_prefill_attend(q, k, v)
        else:
            out = flash_attention(
                q, k, v, causal=True, bucket_size=self.bucket_size,
                window=self._eff_lookback(),
                softclamp_value=self.softclamp_value,
            )
        if n > size:
            # keep the last `size` rows, rolled into ring-buffer slot
            # order: cache[s] = row at position p ≡ s (mod size)
            k_rows = jnp.roll(k[:, :, n - size:], n % size, axis=2)
            v_rows = jnp.roll(v[:, :, n - size:], n % size, axis=2)
        else:
            k_rows, v_rows = k, v  # slots [0, n) are the positions [0, n)
        if self.quantize_cache:
            # attention over the prompt ran on the exact K/V above; only
            # the cache (what later decode steps read) is quantized
            cache_k, cache_v = self._quantized_write(
                cache_k, cache_v, k_rows, v_rows, 0
            )
        else:
            zeros = (0, 0, 0, 0)
            cache_k = lax.dynamic_update_slice(cache_k, k_rows.astype(cache_k.dtype), zeros)
            cache_v = lax.dynamic_update_slice(cache_v, v_rows.astype(cache_v.dtype), zeros)

        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], n, -1)
        return self.to_out(out), cache_k, cache_v

    def _ring_prefill_attend(self, q, k, v):
        """Ring attention over the prompt in contiguous (cache) layout.

        Rotary is already applied (global positions), so the shard_map core
        calls the ring collective directly; right-padding to the ring size
        is invisible under causal masking (pad keys sit after every real
        query) and padded output rows are sliced off.
        """
        if is_factored(self.mesh):
            raise NotImplementedError(
                "ring-sharded prefill/decode runs on a plain (data, seq) "
                "mesh; the factored hybrid mesh is a training/forward "
                "layout — decode with create_mesh(ring_size=...)"
            )
        ring_size = self._ring_size()
        n = q.shape[2]
        pad = (-n) % ring_size
        if pad:
            widths = [(0, 0), (0, 0), (0, pad), (0, 0)]
            q = jnp.pad(q, widths)
            k = jnp.pad(k, widths)
            v = jnp.pad(v, widths)
        n_local = (n + pad) // ring_size
        bucket = max(min(self.bucket_size, n_local), 1)
        while n_local % bucket:
            bucket -= 1

        bidirectional = self._bidirectional(n_local)
        max_ring_passes = None
        window = None
        if self._eff_lookback() is not None:
            window = self._eff_lookback()
            max_ring_passes = math.ceil((window - 1) / n_local) + 1

        def core(q, k, v):
            return ring_flash_attention(
                q, k, v, None, SEQ_AXIS,
                True, False,  # causal, contiguous (non-striped) layout
                bucket, max_ring_passes, window,
                self.softclamp_value, None,
                self._kernel_impl(),
                bidirectional, self.ring_dkv_dtype,
                counter_rotate=self.ring_counter_rotate,
                hop_compression=self.ring_hop_compression,
            )

        qspec = P(data_partition(self.mesh), None, SEQ_AXIS, None)
        out = compat.shard_map(
            core,
            mesh=self.mesh,
            in_specs=(qspec, qspec, qspec),
            out_specs=qspec,
            check_vma=not self._use_pallas(),
        )(q, k, v)
        return out[:, :, :n]

    def _ring_decode(self, q, k, v, cache_k, cache_v, pos):
        if is_factored(self.mesh):
            raise NotImplementedError(
                "ring-sharded decode runs on a plain (data, seq) mesh; the "
                "factored hybrid mesh is a training/forward layout — decode "
                "with create_mesh(ring_size=...)"
            )
        ring_size = self._ring_size()
        quant = self.quantize_cache
        n_local = (cache_k[0] if quant else cache_k).shape[2] // ring_size

        def core(q, k, v, cache_k, cache_v, pos):
            rank = lax.axis_index(SEQ_AXIS)
            owner = pos // n_local
            local_pos = pos % n_local

            def write(c, new):
                return lax.dynamic_update_slice_in_dim(
                    c, new.astype(c.dtype), local_pos, axis=2
                )

            if quant:
                kq, ks, vq, vs = quantize_kv_cache(k, v)
                cache_k = lax.cond(
                    rank == owner,
                    lambda c: (write(c[0], kq), write(c[1], ks)),
                    lambda c: c, cache_k,
                )
                cache_v = lax.cond(
                    rank == owner,
                    lambda c: (write(c[0], vq), write(c[1], vs)),
                    lambda c: c, cache_v,
                )
            else:
                cache_k = lax.cond(
                    rank == owner, lambda c: write(c, k), lambda c: c, cache_k
                )
                cache_v = lax.cond(
                    rank == owner, lambda c: write(c, v), lambda c: c, cache_v
                )
            idx = rank * n_local + jnp.arange(n_local)
            kv_mask = self._decode_mask(idx, pos, q.shape[0])
            if quant:
                kvq = QuantizedKV(*cache_k, *cache_v)
                # impl="xla" dequantizes inside tree_attn_decode
                out = tree_attn_decode(
                    q, None, None, kv_mask,
                    axis_name=SEQ_AXIS,
                    softclamp_value=self.softclamp_value,
                    impl=None if self._use_pallas() else "xla",
                    kv_quantized=kvq,
                )
            else:
                out = tree_attn_decode(
                    q, cache_k, cache_v, kv_mask,
                    axis_name=SEQ_AXIS,
                    softclamp_value=self.softclamp_value,
                    impl="pallas" if self._use_pallas() else "xla",
                )
            return out, cache_k, cache_v

        cspec = P(data_partition(self.mesh), None, SEQ_AXIS, None)
        sspec = P(data_partition(self.mesh), None, SEQ_AXIS)
        cache_spec = (cspec, sspec) if quant else cspec
        rep = P(data_partition(self.mesh), None, None, None)
        return compat.shard_map(
            core,
            mesh=self.mesh,
            in_specs=(rep, rep, rep, cache_spec, cache_spec, P()),
            out_specs=(rep, cache_spec, cache_spec),
            check_vma=not self._use_pallas(),
        )(q, k, v, cache_k, cache_v, pos)
