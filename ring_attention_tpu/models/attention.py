"""RingAttention module: the user-facing attention layer.

TPU-native equivalent of the reference's ``RingAttention``
(ref ``ring_attention.py:283-466``): prenorm + fused qkv projection, GQA head
split, shard-aware rotary, and dispatch to the ring path (``shard_map`` +
``lax.ppermute``) or a single-device oracle (``force_regular_attn``).

Auto-sharding follows the reference's model-top recipe (pad -> stripe ->
shard, ref ``ring_attention.py:389-403``) but expressed as layouts: a pure
stripe permutation plus a ``NamedSharding`` constraint; XLA inserts the
minimal collective instead of a hand-written all-gather
(cf. ``sharded_batch_to_sharded_seq``, ref ``ring_attention.py:223-262``).
"""

from __future__ import annotations

import math
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import default_attention
from ..ops.flash import flash_attention
from ..ops.pallas_flash import pallas_flash_attention
from ..ops.rotary import apply_rotary, ring_positions, rotary_freqs
from ..parallel.mesh import DATA_AXIS, SEQ_AXIS
from ..parallel.ring import ring_flash_attention
from ..parallel.sharding import pad_seq_and_mask, stripe_permute, stripe_unpermute
from .layers import RMSNorm


class RingAttention(nn.Module):
    """Sequence-parallel attention layer.

    Attributes mirror the reference constructor (ref
    ``ring_attention.py:284-337``); ``kv_heads`` expresses GQA directly
    (the reference's ``heads // num_grouped_query_heads``).
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    kv_heads: int | None = None
    causal: bool = False
    striped: bool = False
    bucket_size: int = 512
    use_ring: bool = True
    force_regular_attn: bool = False
    rotary: bool = True
    rotary_theta: float = 10000.0
    softclamp_value: float | None = None
    max_lookback_seq_len: int | None = None
    auto_shard: bool = False
    mesh: Mesh | None = None
    use_pallas: bool = False
    dtype: jnp.dtype | None = None

    def _kv_heads(self) -> int:
        kvh = self.kv_heads or self.heads
        assert self.heads % kvh == 0
        return kvh

    def _ring_size(self) -> int:
        if self.mesh is None:
            return 1
        return self.mesh.shape[SEQ_AXIS]

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: jax.Array | None = None,
    ) -> jax.Array:
        """``x: (b, n, dim)`` -> ``(b, n, dim)``.

        When ``auto_shard`` is set, ``x`` is a global (unsharded-layout)
        array: it is padded to the ring size, stripe-permuted if ``striped``,
        and constrained onto the ``(data, seq)`` mesh; the inverse is applied
        to the output (ref ``ring_attention.py:389-403,458-464``).
        """
        h, kvh, dh = self.heads, self._kv_heads(), self.dim_head
        ring = self.use_ring and not self.force_regular_attn and self._ring_size() > 1

        n_orig = x.shape[1]
        if ring and self.auto_shard:
            x, mask, n_orig = pad_seq_and_mask(x, mask, self._ring_size())
            if self.striped:
                x = stripe_permute(x, self._ring_size())
                if mask is not None:
                    mask = stripe_permute(mask, self._ring_size())
            x = lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, P(DATA_AXIS, SEQ_AXIS, None))
            )

        normed = RMSNorm(self.dim)(x)
        qkv = nn.Dense((h + 2 * kvh) * dh, use_bias=False, dtype=self.dtype)(normed)
        q, k, v = jnp.split(qkv, [h * dh, (h + kvh) * dh], axis=-1)

        b, n, _ = x.shape
        q = q.reshape(b, n, h, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, n, kvh, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, n, kvh, dh).transpose(0, 2, 1, 3)

        if self.causal:
            mask = None  # ref asserts causal and key-pad mask are exclusive

        if ring:
            out = self._ring_attend(q, k, v, mask)
        else:
            out = self._local_attend(q, k, v, mask)

        out = out.transpose(0, 2, 1, 3).reshape(b, n, h * dh)
        out = nn.Dense(self.dim, use_bias=False, dtype=self.dtype)(out)

        if ring and self.auto_shard:
            if self.striped:
                out = stripe_unpermute(out, self._ring_size())
            out = out[:, :n_orig]
        return out

    def _local_attend(self, q, k, v, mask):
        n = q.shape[2]
        if self.rotary:
            freqs = rotary_freqs(jnp.arange(n), self.dim_head, self.rotary_theta)
            q = apply_rotary(q, freqs)
            k = apply_rotary(k, freqs)
        window = self.max_lookback_seq_len
        if self.force_regular_attn and window is None:
            return default_attention(
                q, k, v, mask, causal=self.causal,
                softclamp_value=self.softclamp_value,
            )
        if self.use_pallas:
            return pallas_flash_attention(
                q, k, v, mask, causal=self.causal, window=window,
                softclamp_value=self.softclamp_value,
            )
        return flash_attention(
            q, k, v, mask, causal=self.causal, bucket_size=self.bucket_size,
            window=window, softclamp_value=self.softclamp_value,
        )

    def _ring_attend(self, q, k, v, mask):
        ring_size = self._ring_size()
        n = q.shape[2]
        assert n % ring_size == 0, (
            f"sequence {n} must divide over ring {ring_size}; "
            "use auto_shard=True to pad"
        )
        n_local = n // ring_size
        # per-hop flash tile: largest divisor of the local shard <= bucket_size
        bucket = min(self.bucket_size, n_local)
        while n_local % bucket:
            bucket -= 1

        max_ring_passes = None
        window = None
        lookback = self.max_lookback_seq_len
        if lookback is not None:
            assert self.causal, (
                "max_lookback_seq_len requires causal attention "
                "(ref ring_flash_attention.py:99)"
            )
            if self.striped:
                # striped layout has no contiguous local band; approximate at
                # hop granularity like the reference (ring_flash_attention.py:95-103)
                max_ring_passes = math.ceil(lookback / n_local)
            else:
                # exact sliding window: a query at local row 0 must still see
                # window-1 tokens back, so cover ceil((window-1)/n_local)
                # earlier shards plus its own (tighter than the reference,
                # which truncates early rows at bucket granularity)
                window = lookback
                max_ring_passes = math.ceil((lookback - 1) / n_local) + 1

        def core(q, k, v, mask):
            rank = lax.axis_index(SEQ_AXIS)
            if self.rotary:
                pos = ring_positions(
                    n_local, rank, striped=self.striped, world=ring_size
                )
                freqs = rotary_freqs(pos, self.dim_head, self.rotary_theta)
                q_r = apply_rotary(q, freqs)
                k_r = apply_rotary(k, freqs)
            else:
                q_r, k_r = q, k
            return ring_flash_attention(
                q_r, k_r, v, mask, SEQ_AXIS,
                self.causal, self.striped,
                bucket, max_ring_passes, window,
                self.softclamp_value, None,
                "pallas" if self.use_pallas else "xla",
            )

        qspec = P(DATA_AXIS, None, SEQ_AXIS, None)
        mspec = P(DATA_AXIS, SEQ_AXIS) if mask is not None else P()
        return jax.shard_map(
            core,
            mesh=self.mesh,
            in_specs=(qspec, qspec, qspec, mspec),
            out_specs=qspec,
            # pallas_call with device-varying scalars trips jax's vma
            # checker; jax suggests check_vma=False as the workaround
            check_vma=not self.use_pallas,
        )(q, k, v, mask)
