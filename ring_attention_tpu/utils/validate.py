"""Runtime shape/dtype validation for the public API surface.

TPU-native analogue of the reference's beartype/jaxtyping layer
(``tensor_typing.py:11-20``, applied to the public functions at
``ring_attention.py:47,284`` and ``ring_flash_attention.py:391``): every
public attention entry point checks its argument layout up front and raises
a one-line ``ValueError`` naming the function and the offending shape —
instead of failing deep inside an einsum (or silently computing nonsense
on a transposed layout).

Checks run at trace time on static shape/dtype metadata only — zero
runtime cost under ``jit``.
"""

from __future__ import annotations

import jax.numpy as jnp

_LAYOUT = "(batch, heads, seq, dim_head)"


def _shape(x) -> tuple:
    return tuple(getattr(x, "shape", ()))


def check_attention_args(
    fn: str,
    q,
    k,
    v,
    kv_mask=None,
    *,
    equal_qkv_len: bool = False,
) -> None:
    """Validate a ``q/k/v (+ kv_mask)`` attention call.

    Layout contract (package-wide): ``q: (b, h, n, d)``,
    ``k, v: (b, hk, n, d)`` with ``h`` a multiple of ``hk`` (GQA),
    ``kv_mask: (b, n_kv)`` boolean.
    """
    for name, x in (("q", q), ("k", k), ("v", v)):
        if getattr(x, "ndim", None) != 4:
            raise ValueError(
                f"{fn}: {name} must be 4-D {_LAYOUT}, got shape {_shape(x)}"
            )
        if not jnp.issubdtype(x.dtype, jnp.floating):
            raise ValueError(
                f"{fn}: {name} must be floating point, got dtype {x.dtype}"
            )

    b, h, nq, d = q.shape
    if k.shape != v.shape:
        raise ValueError(
            f"{fn}: k and v must have identical shapes, got k={_shape(k)} "
            f"v={_shape(v)}"
        )
    bk, hk, nk, dk = k.shape
    if bk != b or dk != d:
        raise ValueError(
            f"{fn}: q {_shape(q)} and k {_shape(k)} disagree on batch/dim_head "
            f"— expected layout {_LAYOUT}; a (batch, seq, heads, dim) call "
            "usually trips this"
        )
    if hk > h or h % hk:
        raise ValueError(
            f"{fn}: query heads ({h}) must be a positive multiple of kv heads "
            f"({hk}) for GQA, got q={_shape(q)} k={_shape(k)} — expected layout "
            f"{_LAYOUT}; a (batch, seq, heads, dim) call usually trips this"
        )
    if equal_qkv_len and nq != nk:
        raise ValueError(
            f"{fn}: q and k must share the sequence length, got nq={nq} nk={nk}"
        )
    if kv_mask is not None:
        if getattr(kv_mask, "ndim", None) != 2 or kv_mask.shape != (b, nk):
            raise ValueError(
                f"{fn}: kv_mask must be (batch, n_kv) = ({b}, {nk}), got "
                f"shape {_shape(kv_mask)}"
            )


def check_segment_ids(fn: str, q, k, q_seg, kv_seg) -> None:
    """Validate packed-sequence segment ids against a q/k pair.

    Contract: ``q_seg: (b, nq)`` and ``kv_seg: (b, nk)`` integer document
    ids (real ids >= 0; -1 marks padding).
    """
    b, _, nq, _ = q.shape
    nk = k.shape[2]
    for name, seg, n in (("q", q_seg, nq), ("kv", kv_seg, nk)):
        if getattr(seg, "ndim", None) != 2 or seg.shape != (b, n):
            raise ValueError(
                f"{fn}: {name} segment_ids must be (batch, n) = ({b}, {n}), "
                f"got shape {_shape(seg)} — a single (b, n) array needs "
                f"nq == nk; pass a (q_ids, kv_ids) pair otherwise"
            )
        if not jnp.issubdtype(seg.dtype, jnp.integer):
            raise ValueError(
                f"{fn}: {name} segment_ids must be integers, got {seg.dtype}"
            )


def check_model_input(fn: str, x, dim: int) -> None:
    """Validate a module call ``x: (b, n, dim)``."""
    if getattr(x, "ndim", None) != 3 or x.shape[-1] != dim:
        raise ValueError(
            f"{fn}: x must be (batch, seq, dim={dim}), got shape {_shape(x)}"
        )


def check_tokens_input(fn: str, x) -> None:
    """Validate a transformer call ``tokens: (b, n)`` integer ids."""
    if getattr(x, "ndim", None) != 2:
        raise ValueError(
            f"{fn}: tokens must be (batch, seq) integer ids, got shape {_shape(x)}"
        )
    if not jnp.issubdtype(x.dtype, jnp.integer):
        raise ValueError(
            f"{fn}: tokens must be integer ids, got dtype {x.dtype}"
        )
