"""Distributed checkpoint / resume.

The reference has **no** checkpoint story — ``state_dict`` is used only to
clone weights inside its parity tests (SURVEY §5; ref ``assert.py:81``).
Training at ring-attention sequence lengths without resumability is not
operable, so this framework ships a thin wrapper over Orbax (the TPU-native
checkpoint layer): sharded arrays are written/restored per-shard with their
``NamedSharding`` preserved, so a (data, seq) mesh job resumes in place.
"""

from __future__ import annotations

import os
from typing import Any

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str | os.PathLike, state: Any, *, force: bool = False) -> None:
    """Write a pytree (params / optimizer state / step counter) to ``path``.

    Arrays keep their shardings; call from every process in a multi-host
    setup (orbax coordinates the write).  Refuses to overwrite an existing
    checkpoint unless ``force=True`` (orbax's safe default) — use distinct
    step-numbered paths for periodic saves.
    """
    ckptr = _checkpointer()
    ckptr.save(os.fspath(os.path.abspath(path)), state, force=force)
    ckptr.wait_until_finished()


def restore_checkpoint(
    path: str | os.PathLike, template: Any, *, mesh=None
) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``template`` supplies structure/shapes/shardings — typically the
    freshly-initialized state (or ``jax.eval_shape`` of it with shardings
    attached) — so each shard lands on the right device.

    Restored arrays are *committed* to their shardings.  When the state
    will feed a ``shard_map``/``pjit`` program over a mesh, pass ``mesh``:
    leaves without an explicit ``NamedSharding`` in the template are then
    restored replicated over that mesh (the right default for parameters;
    single-device-committed arrays would otherwise be rejected by a
    multi-device jit).
    """
    import orbax.checkpoint as ocp
    from jax.sharding import NamedSharding, PartitionSpec

    def to_restore_type(x):
        if isinstance(x, jax.Array):
            if mesh is not None and not isinstance(x.sharding, NamedSharding):
                return jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=NamedSharding(mesh, PartitionSpec())
                )
            return ocp.utils.to_shape_dtype_struct(x)
        return x

    template = jax.tree.map(to_restore_type, template)
    return _checkpointer().restore(os.fspath(os.path.abspath(path)), template)
