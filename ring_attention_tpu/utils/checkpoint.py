"""Distributed checkpoint / resume.

The reference has **no** checkpoint story — ``state_dict`` is used only to
clone weights inside its parity tests (SURVEY §5; ref ``assert.py:81``).
Training at ring-attention sequence lengths without resumability is not
operable, so this framework ships two layers:

- :func:`save_checkpoint` / :func:`restore_checkpoint` — a thin wrapper
  over Orbax (the TPU-native checkpoint layer): sharded arrays are
  written/restored per-shard with their ``NamedSharding`` preserved, so a
  (data, seq) mesh job resumes in place.
- :class:`CheckpointManager` — the preemption-safe periodic-save loop
  around it (part of the resilience layer, see ``docs/resilience.md``):
  atomic write-then-rename saves, keep-last-N retention, checksum-verified
  restore that detects a truncated/partial checkpoint (the file a
  preempted host leaves behind) and falls back to the previous good step,
  and :meth:`CheckpointManager.resume_or_init` as the one-call resume
  story for training loops (``examples/train.py``).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import warnings
from typing import Any, Callable

import jax

from .resilience import DirectoryLock, pid_alive


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str | os.PathLike, state: Any, *, force: bool = False) -> None:
    """Write a pytree (params / optimizer state / step counter) to ``path``.

    Arrays keep their shardings; call from every process in a multi-host
    setup (orbax coordinates the write).  Refuses to overwrite an existing
    checkpoint unless ``force=True`` (orbax's safe default) — use distinct
    step-numbered paths for periodic saves.
    """
    ckptr = _checkpointer()
    ckptr.save(os.fspath(os.path.abspath(path)), state, force=force)
    ckptr.wait_until_finished()


def restore_checkpoint(
    path: str | os.PathLike, template: Any, *, mesh=None
) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``template`` supplies structure/shapes/shardings — typically the
    freshly-initialized state (or ``jax.eval_shape`` of it with shardings
    attached) — so each shard lands on the right device.

    Restored arrays are *committed* to their shardings.  When the state
    will feed a ``shard_map``/``pjit`` program over a mesh, pass ``mesh``:
    leaves without an explicit ``NamedSharding`` in the template are then
    restored replicated over that mesh (the right default for parameters;
    single-device-committed arrays would otherwise be rejected by a
    multi-device jit).
    """
    import orbax.checkpoint as ocp
    from jax.sharding import NamedSharding, PartitionSpec

    def to_restore_type(x):
        if isinstance(x, jax.Array):
            if mesh is not None and not isinstance(x.sharding, NamedSharding):
                return jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=NamedSharding(mesh, PartitionSpec())
                )
            return ocp.utils.to_shape_dtype_struct(x)
        return x

    template = jax.tree.map(to_restore_type, template)
    return _checkpointer().restore(os.fspath(os.path.abspath(path)), template)


# ----------------------------------------------------------------------
# Preemption-safe periodic checkpointing (resilience layer)
# ----------------------------------------------------------------------

_STEP_PREFIX = "step_"
_ARRAYS = "arrays.npz"
_MANIFEST = "manifest.json"
_FORMAT = 1


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (truncated write,
    checksum mismatch, unreadable manifest).  Restore treats this as
    "that step never completed" and falls back to the previous one."""


class CheckpointStructureError(RuntimeError):
    """The saved state's pytree structure does not match the restore
    template — typically the optimizer or model definition changed between
    save and restore.  NOT a corruption: falling back to an older step
    would hit the same mismatch, so this raises immediately with both
    structures named instead of surfacing as a cryptic tree-map error."""


def _state_leaves(state: Any):
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(state)
    return [np.asarray(jax.device_get(leaf)) for leaf in leaves], treedef


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # not supported on every platform/filesystem; rename still lands


class CheckpointManager:
    """Atomic, retained, checksum-verified step checkpoints in a directory.

    Layout: ``<directory>/step_<8 digits>/{arrays.npz, manifest.json}``.
    Saves write into a hidden temp directory and ``os.replace`` it into
    place, so a checkpoint either exists completely or not at all — a
    preemption mid-write leaves only a temp directory that the next save
    sweeps away, never a half-readable ``step_*``.  The manifest carries a
    SHA-256 of the array payload; restore verifies it and silently (one
    warning) falls back to the newest older step on any integrity failure.

    This manager targets the single-process case (CPU mesh / one-host TPU:
    every device's shards are addressable) — it gathers every leaf to one
    host.  Multi-host jobs use
    :class:`~ring_attention_tpu.elastic.ElasticCheckpointManager`, whose
    multi-process protocol writes one shard group per process and commits
    the manifest behind a cross-process barrier (docs/resilience.md) —
    this manager refuses ``jax.process_count() > 1`` rather than writing
    per-host files that look like full checkpoints.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        keep: int = 3,
        lock_stale_age: float = 30.0,
        lock_timeout: float = 600.0,
    ):
        if keep < 1:
            raise ValueError(f"CheckpointManager: keep must be >= 1, got {keep}")
        self.directory = os.fspath(os.path.abspath(directory))
        self.keep = keep
        # how long a save waits on a competing manager's critical
        # section before failing loudly: generous by default — a
        # multi-GB serialize+fsync+prune can legitimately hold the lock
        # for minutes, and converting that into a crash would be worse
        # than the race the lock fixes
        self.lock_timeout = lock_timeout
        os.makedirs(self.directory, exist_ok=True)
        # two managers on one directory (a restarted job racing its own
        # not-yet-dead predecessor, or a sweep racing a save) serialize
        # their save/prune/sweep sections through the watcher-protocol
        # lock: atomic mkdir acquisition, pid-stamped, takeover only when
        # the holder pid is dead AND the lock is at least lock_stale_age
        # seconds old (utils/resilience.DirectoryLock)
        self._dirlock = DirectoryLock(
            self.directory, stale_age=lock_stale_age
        )

    # -- directory bookkeeping ---------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    def all_steps(self) -> list[int]:
        """Completed (renamed-into-place) steps, ascending."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # a temp dir with no parsable pid suffix must still be this old
    # before the sweep deletes it (it might be a live writer from a
    # manager version with another naming scheme)
    _TMP_MIN_AGE_S = 60.0

    def _sweep_tmp(self) -> None:
        """Clean up after a preempted save: delete half-written temp dirs
        whose WRITER IS DEAD, and RECOVER a ``step_*.old`` backup whose
        live step vanished (the crash landed between rename-aside and
        rename-into-place — the backup is a complete, verified
        checkpoint).

        Temp dirs are pid-stamped (``.tmp-step_NNNNNNNN-<pid>``); a dir
        whose pid is still alive belongs to a CONCURRENT writer mid-save
        and is left alone — the pre-fix sweep deleted it, so two managers
        on one directory could destroy each other's in-flight saves.  A
        dir with no parsable pid is deleted only past a minimum age.
        """
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for name in names:
            path = os.path.join(self.directory, name)
            if name.startswith(".tmp-"):
                try:
                    writer = int(name.rsplit("-", 1)[-1])
                except ValueError:
                    writer = None
                if writer is not None and writer != os.getpid() and pid_alive(writer):
                    continue  # live concurrent writer: not ours to sweep
                if writer is None:
                    try:
                        age = time.time() - os.stat(path).st_mtime  # ra: allow(RA014 mtime age against the filesystem wall clock, not an emitted timestamp)
                    except OSError:
                        continue
                    if age < self._TMP_MIN_AGE_S:
                        continue
                shutil.rmtree(path, ignore_errors=True)
            elif name.endswith(".old"):
                live = path[: -len(".old")]
                if os.path.isdir(live):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    try:
                        os.replace(path, live)
                    except OSError:
                        pass

    # -- save ---------------------------------------------------------

    def save(self, step: int, state: Any) -> str:
        """Write ``state`` (any pytree of arrays) as step ``step``.

        Atomic: the ``step_*`` directory appears only after every byte
        (including the checksum manifest) is on disk.  Existing data for
        the same step is replaced.  Retention then deletes all but the
        newest ``keep`` steps.  Returns the final checkpoint path.
        """
        import numpy as np

        if jax.process_count() > 1:
            raise RuntimeError(
                "CheckpointManager is single-process (it gathers every "
                "leaf to one host); multi-host jobs use "
                "ring_attention_tpu.elastic.ElasticCheckpointManager — "
                "each process writes its own shard group and process 0 "
                "commits the manifest behind a cross-process barrier"
            )
        leaves, treedef = _state_leaves(state)
        with self._dirlock.locked(timeout=self.lock_timeout):
            return self._save_locked(step, leaves, treedef, np)

    def _save_locked(self, step: int, leaves, treedef, np) -> str:
        # under the directory lock: sweep, write, rename, prune are one
        # critical section, so a concurrent manager's prune can never
        # interleave with this save's rename window
        self._sweep_tmp()
        final = self._step_dir(step)
        tmp = os.path.join(
            self.directory, f".tmp-{_STEP_PREFIX}{step:08d}-{os.getpid()}"
        )
        os.makedirs(tmp)
        try:
            npz_path = os.path.join(tmp, _ARRAYS)
            with open(npz_path, "wb") as f:
                np.savez(f, **{f"leaf_{i:05d}": a for i, a in enumerate(leaves)})
                f.flush()
                os.fsync(f.fileno())
            manifest = {
                "format": _FORMAT,
                "step": int(step),
                "leaf_count": len(leaves),
                "treedef": str(treedef),
                "shapes": [list(a.shape) for a in leaves],
                "dtypes": [str(a.dtype) for a in leaves],
                "sha256": _sha256(npz_path),
            }
            man_path = os.path.join(tmp, _MANIFEST)
            with open(man_path, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            # re-save of an existing step stays atomic: the old intact
            # checkpoint is renamed aside (not deleted) until the new one
            # is in place, so no preemption point loses both
            backup = None
            if os.path.isdir(final):
                backup = final + ".old"
                shutil.rmtree(backup, ignore_errors=True)
                os.replace(final, backup)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
            if backup is not None:
                shutil.rmtree(backup, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        return final

    def _prune(self) -> None:
        for step in self.all_steps()[: -self.keep]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)

    # -- restore ------------------------------------------------------

    def _load_step(self, step: int, template: Any) -> Any:
        import numpy as np

        path = self._step_dir(step)
        man_path = os.path.join(path, _MANIFEST)
        npz_path = os.path.join(path, _ARRAYS)
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"step {step}: unreadable manifest ({e})"
            ) from e
        if manifest.get("format") != _FORMAT:
            raise CheckpointCorruptError(
                f"step {step}: unknown checkpoint format "
                f"{manifest.get('format')!r}"
            )
        try:
            digest = _sha256(npz_path)
        except OSError as e:
            raise CheckpointCorruptError(
                f"step {step}: unreadable array payload ({e})"
            ) from e
        if digest != manifest.get("sha256"):
            raise CheckpointCorruptError(
                f"step {step}: array payload checksum mismatch "
                f"(truncated or partially-written checkpoint)"
            )

        t_leaves, t_treedef = jax.tree_util.tree_flatten(template)
        if manifest.get("treedef") != str(t_treedef) or manifest.get(
            "leaf_count"
        ) != len(t_leaves):
            raise CheckpointStructureError(
                f"step {step}: saved state structure does not match the "
                f"restore template (did the model or optimizer definition "
                f"change?).\n  saved:    {manifest.get('leaf_count')} leaves, "
                f"{manifest.get('treedef')}\n  template: {len(t_leaves)} "
                f"leaves, {t_treedef}"
            )
        try:
            with np.load(npz_path) as z:
                loaded = [z[f"leaf_{i:05d}"] for i in range(len(t_leaves))]
        except Exception as e:  # zipfile/np raise several types on truncation
            raise CheckpointCorruptError(
                f"step {step}: failed to read arrays ({e})"
            ) from e

        out = []
        for i, (arr, ref) in enumerate(zip(loaded, t_leaves)):
            if isinstance(ref, jax.Array) and tuple(arr.shape) != tuple(
                ref.shape
            ):
                raise CheckpointStructureError(
                    f"step {step}: leaf {i} shape {tuple(arr.shape)} != "
                    f"template {tuple(ref.shape)}"
                )
            if isinstance(ref, jax.Array):
                if getattr(ref, "_committed", True):
                    # committed template (e.g. device_put / sharding-
                    # constrained onto a mesh): restore to the same sharding
                    out.append(
                        jax.device_put(arr.astype(ref.dtype), ref.sharding)
                    )
                else:
                    # uncommitted template (plain computation output, e.g.
                    # model.init): keep it uncommitted so a later jit may
                    # co-locate it with mesh-sharded arguments
                    import jax.numpy as jnp

                    out.append(jnp.asarray(arr.astype(ref.dtype)))
            else:
                out.append(arr)
        return jax.tree_util.tree_unflatten(t_treedef, out)

    def restore(
        self, template: Any, *, step: int | None = None
    ) -> tuple[Any, int] | None:
        """Restore the newest intact checkpoint (or exactly ``step``).

        ``template`` supplies structure/shapes/dtypes/shardings — typically
        the freshly-initialized state.  Returns ``(state, step)``, or
        ``None`` when the directory holds no checkpoint at all (missing,
        empty, or only corrupt steps — each corrupt step warns once and is
        skipped).  A structure mismatch raises
        :class:`CheckpointStructureError` instead of falling back: older
        steps share the saved structure, so fallback would mask a real
        code/checkpoint incompatibility.
        """
        from .resilience import LockTimeout

        # restore holds the directory lock: (a) the pre-listing sweep
        # recovers an orphaned .old backup even when the dead writer
        # died HOLDING the lock (blocking acquire takes a stale lock
        # over once pid-dead + stale_age), and (b) a concurrent
        # manager's prune can no longer delete a step mid-digest-read.
        # On a genuinely stuck lock, degrade to the unlocked read (one
        # warning): restore is read-only and availability wins.
        try:
            with self._dirlock.locked(timeout=self.lock_timeout):
                self._sweep_tmp()
                return self._restore_unlocked(template, step)
        except LockTimeout:
            warnings.warn(
                f"CheckpointManager: directory lock {self._dirlock.path} "
                f"stuck; restoring WITHOUT the lock (a concurrent prune "
                f"could race this read)",
                stacklevel=2,
            )
            return self._restore_unlocked(template, step)

    def _restore_unlocked(
        self, template: Any, step: int | None
    ) -> tuple[Any, int] | None:
        if step is not None and not os.path.isdir(self._step_dir(step)):
            # absent is not corrupt: an explicitly-requested step that was
            # never written (or already pruned) must not warn "corrupt"
            # and pretend a fallback happened
            raise FileNotFoundError(
                f"CheckpointManager: no checkpoint for step {step} in "
                f"{self.directory} (existing steps: {self.all_steps()})"
            )
        candidates = [step] if step is not None else list(
            reversed(self.all_steps())
        )
        for s in candidates:
            try:
                return self._load_step(s, template), s
            except CheckpointCorruptError as e:
                if step is not None:
                    # an EXPLICITLY requested step that is corrupt must
                    # raise, not warn-and-return-None: callers treat
                    # None as "cold start", which would silently
                    # reinitialize over the history the operator named
                    raise
                warnings.warn(
                    f"CheckpointManager: skipping corrupt checkpoint "
                    f"({e}); falling back to the previous step",
                    stacklevel=2,
                )
                continue
        return None

    def resume_or_init(
        self, init_fn: Callable[[], Any]
    ) -> tuple[Any, int]:
        """The one-call resume story for a training loop.

        ``init_fn()`` builds the fresh state (also used as the restore
        template).  Returns ``(state, start_step)``: the restored state
        with the step AFTER the checkpointed one, or the fresh state with
        step 0 when nothing (intact) is on disk::

            mgr = CheckpointManager(args.ckpt_dir, keep=3)
            state, start = mgr.resume_or_init(make_initial_state)
            for step in range(start, args.steps):
                state = train(state)
                if step % save_every == 0:
                    mgr.save(step, state)
        """
        state = init_fn()
        restored = self.restore(state)
        if restored is None:
            return state, 0
        state, step = restored
        return state, step + 1
