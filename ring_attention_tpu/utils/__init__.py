from .checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    CheckpointStructureError,
    restore_checkpoint,
    save_checkpoint,
)
from .profiling import StepTimer, annotate, trace
from .benchtime import enable_compile_cache, fetch_rtt, timed_chained
from .telemetry import (
    MetricsLogger,
    Telemetry,
    TrainMetrics,
    achieved_mfu,
    attention_logit_summaries,
    device_peak_tflops,
    flash_attention_flops,
    init_train_metrics,
    read_metrics,
    ring_comms_accounting,
    telemetry,
    transformer_step_flops,
)
from .train import StepStats, init_step_stats, make_train_step, shard_optimizer_state
from .validate import check_attention_args, check_model_input, check_tokens_input

__all__ = [
    "enable_compile_cache",
    "make_train_step",
    "shard_optimizer_state",
    "StepStats",
    "init_step_stats",
    "restore_checkpoint",
    "save_checkpoint",
    "CheckpointManager",
    "CheckpointCorruptError",
    "CheckpointStructureError",
    "StepTimer",
    "trace",
    "annotate",
    "MetricsLogger",
    "Telemetry",
    "TrainMetrics",
    "telemetry",
    "init_train_metrics",
    "read_metrics",
    "achieved_mfu",
    "attention_logit_summaries",
    "device_peak_tflops",
    "flash_attention_flops",
    "transformer_step_flops",
    "ring_comms_accounting",
    "check_attention_args",
    "check_model_input",
    "check_tokens_input",
    "fetch_rtt",
    "timed_chained",
]
