from .checkpoint import restore_checkpoint, save_checkpoint
from .profiling import StepTimer, trace

__all__ = ["restore_checkpoint", "save_checkpoint", "StepTimer", "trace"]
