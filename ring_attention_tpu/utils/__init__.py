from .checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    CheckpointStructureError,
    restore_checkpoint,
    save_checkpoint,
)
from .profiling import StepTimer, trace
from .benchtime import enable_compile_cache, fetch_rtt, timed_chained
from .train import StepStats, init_step_stats, make_train_step, shard_optimizer_state
from .validate import check_attention_args, check_model_input, check_tokens_input

__all__ = [
    "enable_compile_cache",
    "make_train_step",
    "shard_optimizer_state",
    "StepStats",
    "init_step_stats",
    "restore_checkpoint",
    "save_checkpoint",
    "CheckpointManager",
    "CheckpointCorruptError",
    "CheckpointStructureError",
    "StepTimer",
    "trace",
    "check_attention_args",
    "check_model_input",
    "check_tokens_input",
    "fetch_rtt",
    "timed_chained",
]
