"""Resilience layer: retries, fault injection, and kernel degradation.

Ring attention's value is multi-hour runs over million-token contexts on
many chips — exactly the regime where a single NaN step, preempted host,
or wedged device kills hours of work.  This repo's own hardware log
records three consecutive zero-window bench rounds (>=44h of TPU-tunnel
wedge, docs/hardware_log.md rounds 3-5) with no retry machinery anywhere
in the tree.  This module turns those lessons into framework code, in
three pieces used across ``utils/train.py`` (guarded step),
``utils/checkpoint.py`` (preemption-safe saves), ``ops``/``models``
(kernel fallback), ``bench.py``, and ``tools/``:

- :func:`with_retries` — timeout + exponential-backoff wrapper for
  callables that can hang (device probes through a wedged tunnel) or
  fail transiently (relay 500s).
- :class:`FaultInjector` / :func:`inject` — the test harness's hook for
  forcing the failures the resilience machinery exists to survive
  (NaN grads, truncated checkpoints, Pallas compile errors, hung
  probes), so every degradation path is exercised on the CPU mesh.
- :class:`DegradationRecord` + :func:`pallas_available` /
  :func:`resolve_attention_impl` — graceful kernel degradation:
  ``impl="auto"`` callers get the Pallas path when it compiles and a
  one-shot-warned, queryable fallback to the XLA path when it doesn't.

Everything here is host-side Python (no jax transforms are applied to
this module's code), so it composes with jit-compiled callers by running
at trace/dispatch time.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
import warnings
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Sequence

try:
    from . import tracing as _tracing
except ImportError:  # standalone file-path load (tools, bench parent)
    _tracing = None


def _tracer():
    """The active span tracer, or None on a standalone file-path load
    where the relative import (and hence span emission) is unavailable."""
    return _tracing.get_tracer() if _tracing is not None else None


# ----------------------------------------------------------------------
# Fault injection
# ----------------------------------------------------------------------


class InjectedFault(RuntimeError):
    """Raised by :meth:`FaultInjector.check` at an armed injection point."""


class FaultInjector:
    """Process-global registry of armed faults.

    Production code calls :meth:`check`/:meth:`armed` at its injection
    points; tests arm faults with :func:`inject` (a context manager, so a
    failing assertion can never leave a fault armed for the next test).
    Armed faults may carry a payload (:meth:`value`) — e.g. the step index
    at which to poison gradients.
    """

    def __init__(self) -> None:
        self._faults: dict[str, Any] = {}
        self._lock = threading.Lock()

    def arm(self, name: str, value: Any = True) -> None:
        with self._lock:
            self._faults[name] = value

    def disarm(self, name: str) -> None:
        with self._lock:
            self._faults.pop(name, None)

    def armed(self, name: str) -> bool:
        with self._lock:
            return name in self._faults

    def value(self, name: str, default: Any = None) -> Any:
        with self._lock:
            return self._faults.get(name, default)

    def check(self, name: str) -> None:
        """Raise :class:`InjectedFault` when ``name`` is armed (no-op
        otherwise) — the one-line injection point for failure paths."""
        if self.armed(name):
            raise InjectedFault(f"injected fault: {name}")

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()


_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR


@contextmanager
def inject(name: str, value: Any = True) -> Iterator[FaultInjector]:
    """Arm fault ``name`` for the duration of the block (always disarmed
    on exit, even when the block raises).

    Exit drains pending JAX runtime effects first: jitted computations
    dispatch asynchronously, so a ``pure_callback`` injection point
    (:func:`nan_tap`) inside a step launched in the block could otherwise
    execute AFTER the block disarmed the fault — the injection would
    silently miss.  ``jax.effects_barrier()`` guarantees every callback
    from inside the block observed the armed state.
    """
    _INJECTOR.arm(name, value)
    try:
        yield _INJECTOR
    finally:
        try:
            import jax

            jax.effects_barrier()
        except Exception:  # noqa: BLE001 — jax absent/old: nothing to drain
            pass
        _INJECTOR.disarm(name)


# ----------------------------------------------------------------------
# Retry / timeout / backoff
# ----------------------------------------------------------------------


class RetryTimeout(TimeoutError):
    """A single attempt exceeded its timeout budget."""


class RetryError(RuntimeError):
    """All attempts failed; ``last`` holds the final attempt's exception."""

    def __init__(self, message: str, last: BaseException | None = None):
        super().__init__(message)
        self.last = last


# Callbacks fired when a with_retries ladder exhausts all attempts —
# the flight-recorder hook (telemetry.FlightRecorder.install): a probe or
# save that died after its last retry dumps the run's recent trajectory
# alongside the RetryError, instead of surfacing as a bare exception.
# Kept as a module-level registry (like DegradationRecord's listeners) so
# this module stays stdlib-only and import-free of telemetry.
_failure_listeners: list[Callable[[str, str], None]] = []
_failure_lock = threading.Lock()


def add_failure_listener(callback: Callable[[str, str], None]) -> None:
    """Register ``callback(where, error)`` to run when a
    :func:`with_retries` call exhausts its attempts (idempotent per
    callback).  Callback failures are swallowed — diagnostics must never
    mask the retried operation's own error.  Pair with
    :func:`remove_failure_listener` for listeners whose lifetime is
    shorter than the process (``FlightRecorder.uninstall`` does)."""
    with _failure_lock:
        if callback not in _failure_listeners:
            _failure_listeners.append(callback)


def remove_failure_listener(callback: Callable[[str, str], None]) -> None:
    """Unregister a failure listener (no-op when absent)."""
    with _failure_lock:
        if callback in _failure_listeners:
            _failure_listeners.remove(callback)


def _notify_failure(where: str, error: BaseException | None) -> None:
    text = (
        f"{type(error).__name__}: {error}" if error is not None else "unknown"
    )
    with _failure_lock:
        listeners = tuple(_failure_listeners)
    for cb in listeners:
        try:
            cb(where, text)
        except Exception:  # noqa: BLE001 — see add_failure_listener
            pass


def _call_with_timeout(fn: Callable[[], Any], timeout: float) -> Any:
    """Run ``fn()`` with a hard wall-clock budget.

    The callable runs in a daemon thread; on timeout the thread is
    abandoned (Python offers no safe cross-thread kill) and
    :class:`RetryTimeout` is raised.  Callables that own external
    resources should therefore enforce their own inner timeout too
    (e.g. ``subprocess.run(timeout=...)`` kills the child) — this wrapper
    is the backstop for the observed wedge mode where even the probe's
    bookkeeping hangs.
    """
    result: list[Any] = []
    error: list[BaseException] = []

    def run() -> None:
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            error.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise RetryTimeout(f"attempt still running after {timeout:.1f}s")
    if error:
        raise error[0]
    return result[0]


def with_retries(
    fn: Callable[[], Any],
    *,
    timeout: float | None = None,
    backoff: float = 1.0,
    max_attempts: int = 3,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Callable[[int, BaseException], None] | None = None,
) -> Any:
    """Call ``fn()`` with per-attempt ``timeout`` and exponential backoff.

    Attempt ``i`` (0-based) that fails with ``retry_on`` (or times out)
    is followed by ``sleep(backoff * 2**i)`` before the next attempt;
    after ``max_attempts`` failures a :class:`RetryError` carrying the
    last exception is raised.  ``sleep`` and ``on_retry`` are injectable
    for tests (and for callers that want to log each retry).

    ``timeout=None`` disables the wall-clock guard (pure retry/backoff);
    otherwise each attempt gets its own ``timeout`` seconds — see
    :func:`_call_with_timeout` for the abandonment caveat on hung
    callables.
    """
    if max_attempts < 1:
        raise ValueError(f"with_retries: max_attempts must be >= 1, got {max_attempts}")
    if backoff < 0:
        raise ValueError(f"with_retries: backoff must be >= 0, got {backoff}")
    last: BaseException | None = None
    for attempt in range(max_attempts):
        try:
            if timeout is None:
                return fn()
            return _call_with_timeout(fn, timeout)
        except (RetryTimeout, *retry_on) as e:  # noqa: B030
            last = e
            if on_retry is not None:
                on_retry(attempt, e)
            if attempt + 1 < max_attempts:
                sleep(backoff * (2**attempt))
    _notify_failure(getattr(fn, "__name__", None) or "callable", last)
    raise RetryError(
        f"with_retries: all {max_attempts} attempts failed "
        f"(last: {type(last).__name__}: {last})",
        last,
    )


# ----------------------------------------------------------------------
# Cross-process directory lock (watcher protocol, in-library form)
# ----------------------------------------------------------------------


def pid_alive(pid: int) -> bool:
    """Best-effort liveness check for a local pid (signal 0 probe).

    ``EPERM`` counts as alive (the process exists, we just can't signal
    it); any other failure counts as dead.  This is the takeover predicate
    of the TPU window watcher's lock protocol (tools/tpu_window_watch.sh),
    shared here so checkpoint managers apply the same rule.
    """
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except PermissionError:
        return True
    except OSError:
        return False
    return True


class LockTimeout(TimeoutError):
    """A :class:`DirectoryLock` could not be acquired within its budget."""


class DirectoryLock:
    """Atomic cross-process lock on a directory, with stale takeover.

    The watcher shell protocol (PR 1, ``tools/tpu_window_watch.sh``),
    ported to library code: acquisition is ``os.mkdir`` of a lock
    directory (atomic-exclusive on every POSIX filesystem) followed by a
    pid stamp inside it, so a held lock always names its holder.  A
    SIGKILLed holder (no cleanup ran) must not block the directory
    forever: a contender may take over only when the pid file exists,
    the pid is **dead**, AND the lock is at least ``stale_age`` seconds
    old — and the takeover renames the stale lock aside first, so of N
    concurrent contenders exactly one wins the rename and the losers
    retry cleanly (a plain ``rmtree`` could delete the winner's freshly
    acquired lock).

    Used by ``utils/checkpoint.py`` and ``elastic/checkpoint.py`` so two
    managers on one directory serialize their save/prune/sweep sections
    instead of interleaving (one manager's retention pass deleting the
    step another just renamed into place).
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        name: str = ".ckpt.lock",
        *,
        stale_age: float = 30.0,
        poll: float = 0.05,
    ) -> None:
        self.path = os.path.join(os.fspath(directory), name)
        self.stale_age = stale_age
        self.poll = poll
        self._held = False
        # within-process serialization: the filesystem lock is per
        # PROCESS (one pid stamp), so two threads of one process — the
        # async checkpoint writer and a concurrent restore's sweep —
        # must contend here first; without this, thread B would see
        # _held, "acquire" a lock thread A holds, and release it out
        # from under A's critical section
        self._tlock = threading.Lock()

    def _try_acquire(self) -> bool:
        try:
            os.mkdir(self.path)
        except FileExistsError:
            return False
        except FileNotFoundError:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            return self._try_acquire()
        with open(os.path.join(self.path, "pid"), "w") as f:
            f.write(str(os.getpid()))
        self._held = True
        return True

    def _takeover_if_stale(self) -> None:
        try:
            with open(os.path.join(self.path, "pid")) as f:
                holder = int(f.read().strip())
        except (OSError, ValueError):
            # no/garbled pid stamp: a holder that died between mkdir and
            # the stamp write (SIGKILL, ENOSPC).  The age rule below is
            # the only takeover predicate left — a healthy acquirer
            # stamps within milliseconds, so an unstamped lock past
            # stale_age is debris, not a writer
            holder = None
        try:
            age = time.time() - os.stat(self.path).st_mtime  # ra: allow(RA014 mtime age against the filesystem wall clock, not an emitted timestamp)
        except OSError:
            return  # lock vanished between checks: next acquire retries
        if (holder is not None and pid_alive(holder)) or age < self.stale_age:
            return
        aside = f"{self.path}.stale-{os.getpid()}"
        try:
            os.rename(self.path, aside)  # one winner among N contenders
        except OSError:
            return  # another contender won the rename; retry acquire
        shutil.rmtree(aside, ignore_errors=True)

    def acquire(self, timeout: float | None = 60.0) -> bool:
        """Acquire, blocking up to ``timeout`` seconds (None = forever;
        0 = one nonblocking attempt).  Returns True when held; raises
        :class:`LockTimeout` when the budget runs out.  NOT re-entrant:
        a thread that already holds the lock must not re-acquire it."""
        tracer = _tracer()
        if tracer is None or not tracer.enabled:
            return self._acquire(timeout)
        # the lock-wait span IS the straggler signal: a process stuck
        # behind a dead holder shows up on the cluster timeline as one
        # long lock/acquire span (errored with LockTimeout if it loses)
        with tracer.span("lock/acquire", path=self.path,
                         timeout=timeout) as sp:
            got = self._acquire(timeout)
            sp.set(held=got)
            return got

    def _acquire(self, timeout: float | None) -> bool:
        # ONE deadline covers both waits: the in-process tlock and the
        # filesystem loop share the budget (counting it twice would let
        # acquire(600) block for 20 minutes)
        deadline = None if timeout is None else time.monotonic() + timeout  # ra: allow(RA014 deadline arithmetic; the acquire() span records the wait)
        # within-process contention first: a sibling thread holding the
        # filesystem lock is contention, not ownership
        if timeout == 0:
            if not self._tlock.acquire(blocking=False):
                return False
        elif timeout is None:
            self._tlock.acquire()
        else:
            if not self._tlock.acquire(timeout=timeout):
                raise LockTimeout(
                    f"DirectoryLock: {self.path} held by another thread "
                    f"of this process after {timeout:.1f}s"
                )
        try:
            first = True
            while True:
                if self._try_acquire():
                    return True
                self._takeover_if_stale()
                if deadline is not None and time.monotonic() >= deadline:  # ra: allow(RA014 deadline arithmetic; the acquire() span records the wait)
                    # nonblocking mode still deserves one retry AFTER the
                    # takeover: a stale lock (dead holder) must not make
                    # a timeout=0 acquire fail when the dir is free now
                    if first and self._try_acquire():
                        return True
                    if timeout == 0:
                        self._tlock.release()
                        return False
                    raise LockTimeout(
                        f"DirectoryLock: {self.path} still held after "
                        f"{timeout:.1f}s (holder pid in {self.path}/pid)"
                    )
                first = False
                time.sleep(self.poll)
        except BaseException:
            if not self._held:
                self._tlock.release()
            raise

    def release(self) -> None:
        if not self._held:
            return
        self._held = False
        shutil.rmtree(self.path, ignore_errors=True)
        self._tlock.release()

    @contextmanager
    def locked(self, timeout: float | None = 60.0) -> Iterator[bool]:
        """``with lock.locked():`` — acquire/release around a block.
        With ``timeout=0`` the block still runs when the lock is busy,
        and the yielded bool says whether it is actually held (callers
        use this for optional housekeeping: skip the sweep, never block
        a restore on another process's save)."""
        got = self.acquire(timeout)
        try:
            yield got
        finally:
            if got:
                self.release()


# ----------------------------------------------------------------------
# Graceful kernel degradation
# ----------------------------------------------------------------------


@dataclass
class DegradationEvent:
    component: str
    reason: str
    time: float = field(default_factory=time.time)


class DegradationRecord:
    """Queryable record of components that fell back to a degraded path.

    The first failure of a component emits ONE ``UserWarning`` (multi-hour
    runs must not drown their logs in per-step warnings); every failure is
    appended to :meth:`events` so operators and tests can ask exactly what
    degraded and why.
    """

    def __init__(self) -> None:
        self._events: list[DegradationEvent] = []
        self._degraded: set[str] = set()
        self._listeners: list[Callable[[str, str], None]] = []
        self._lock = threading.Lock()

    def add_listener(self, callback: Callable[[str, str], None]) -> None:
        """Register ``callback(component, reason)`` to run on every
        recorded degradation (idempotent per callback).  This is how the
        telemetry layer turns silent ``impl="auto"`` fallbacks into metric
        rows without this module importing it (this file must stay
        stdlib-only and loadable standalone — see bench.py)."""
        with self._lock:
            if callback not in self._listeners:
                self._listeners.append(callback)

    def remove_listener(self, callback: Callable[[str, str], None]) -> None:
        """Unregister a listener (no-op when absent) — for listeners
        whose lifetime is shorter than the process, e.g. a
        ``FlightRecorder`` bound to one run's directory."""
        with self._lock:
            if callback in self._listeners:
                self._listeners.remove(callback)

    def record(self, component: str, reason: BaseException | str) -> None:
        text = f"{type(reason).__name__}: {reason}" if isinstance(
            reason, BaseException
        ) else str(reason)
        with self._lock:
            first = component not in self._degraded
            self._degraded.add(component)
            self._events.append(DegradationEvent(component, text))
            listeners = tuple(self._listeners)
        for cb in listeners:
            try:
                cb(component, text)
            except Exception:  # noqa: BLE001 — telemetry must never break
                pass           # the degradation path it observes
        if first:
            warnings.warn(
                f"resilience: {component} degraded, falling back "
                f"({text}); further occurrences are recorded silently — "
                f"see ring_attention_tpu.utils.resilience.degradation.events()",
                stacklevel=3,
            )

    def is_degraded(self, component: str) -> bool:
        with self._lock:
            return component in self._degraded

    def events(self) -> Sequence[DegradationEvent]:
        with self._lock:
            return tuple(self._events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._degraded.clear()


degradation = DegradationRecord()

# component name shared by the probe, the ops dispatcher, and the models
PALLAS_COMPONENT = "pallas_flash"
# fault name the injection harness arms to force the Pallas path to fail
PALLAS_FAULT = "pallas_fail"

_pallas_probe: bool | None = None
_pallas_probe_lock = threading.Lock()


class _PallasNotApplicable(Exception):
    """The backend has no real Pallas path (non-TPU): ``auto`` resolves to
    XLA *silently* — nothing degraded, the fast path never existed here.
    Interpret-mode Pallas would "work" on CPU but is pure-Python slow;
    choosing it over the XLA flash path would be a pessimization, not a
    fallback."""


def _probe_pallas() -> None:
    """Compile-and-run a minimal real (non-interpret) Pallas flash call.

    Raises whatever the Pallas path raises on this backend — lowering
    errors, Mosaic rejections, missing plugin — which is exactly the
    signal ``impl="auto"`` needs BEFORE a caller's outer jit bakes the
    kernel choice in.  Raises :class:`_PallasNotApplicable` on non-TPU
    backends (see its docstring); the injected :data:`PALLAS_FAULT` is
    checked first so CI can exercise the degradation path anywhere.
    """
    get_injector().check(PALLAS_FAULT)
    import jax

    if jax.devices()[0].platform != "tpu":
        raise _PallasNotApplicable(
            f"backend {jax.devices()[0].platform!r} has no Mosaic path"
        )
    import jax.numpy as jnp

    from ..ops.pallas_flash import pallas_flash_attention

    q = jnp.zeros((1, 1, 128, 64), jnp.float32)
    out = pallas_flash_attention(q, q, q, causal=True, interpret=False)
    jax.block_until_ready(out)


def pallas_available(*, refresh: bool = False) -> bool:
    """True when the real Pallas kernel path works on this backend.

    The probe runs once per process (cached).  A non-TPU backend returns
    False silently (not a degradation — see :class:`_PallasNotApplicable`);
    a TPU whose kernels fail records a :data:`PALLAS_COMPONENT`
    degradation with a one-shot warning.  Pass ``refresh=True`` to
    re-probe (tests; or after an operator fixes the environment
    mid-process).
    """
    global _pallas_probe
    with _pallas_probe_lock:
        if _pallas_probe is not None and not refresh:
            return _pallas_probe
        try:
            _probe_pallas()
            _pallas_probe = True
        except _PallasNotApplicable:
            _pallas_probe = False
        except Exception as e:  # noqa: BLE001 — any failure means degrade
            degradation.record(PALLAS_COMPONENT, e)
            _pallas_probe = False
        return _pallas_probe


def resolve_attention_impl(impl: str | None) -> str:
    """Resolve a requested attention impl to a concrete one.

    ``"xla"``/``None`` and ``"pallas"`` pass through (an explicit request
    must fail loudly, never silently degrade); ``"auto"`` returns
    ``"pallas"`` when the probe passes and the component has not been
    marked degraded, else ``"xla"``.  Resolution happens at trace time,
    so an outer ``jax.jit`` compiles exactly one path.
    """
    if impl in (None, "xla"):
        return "xla"
    if impl == "pallas":
        return "pallas"
    if impl == "auto":
        if degradation.is_degraded(PALLAS_COMPONENT):
            return "xla"
        return "pallas" if pallas_available() else "xla"
    raise ValueError(
        f"resolve_attention_impl: impl must be 'auto', 'pallas', 'xla' or "
        f"None, got {impl!r}"
    )


# fused-ring kernel (ops/pallas_ring.py): component name shared by the
# probe, parallel/ring.py's dispatcher, and the models
FUSED_COMPONENT = "fused_ring"
# fault name the injection harness arms to force the fused path to fail
FUSED_FAULT = "fused_fail"

_fused_probe: bool | None = None


def remote_copy_supported() -> bool:
    """Does this jax expose the in-kernel remote-DMA surface the fused
    ring's ICI tier needs (``pltpu.make_async_remote_copy`` + semaphore
    primitives)?  Cheap attribute check, no compilation."""
    from ..ops.pallas_ring import remote_supported

    return remote_supported()


def _probe_fused() -> None:
    """Compile-and-run a minimal real (non-interpret) fused-ring launch.

    Unlike the plain Pallas probe, a non-TPU backend here is a RECORDED
    degradation, not a silent miss: ``impl="auto"`` via
    :func:`resolve_ring_impl` promises the launch-free fused forward, and
    falling back to the scan-path ring (per-hop launches + ppermutes) is
    a real performance property change operators must be able to query.
    The injected :data:`FUSED_FAULT` is checked first so CI can exercise
    the degradation path anywhere.
    """
    get_injector().check(FUSED_FAULT)
    import jax

    if not remote_copy_supported():
        raise RuntimeError(
            "jax.experimental.pallas.tpu lacks the remote-DMA surface "
            "(make_async_remote_copy / semaphore primitives) — the fused "
            "ring cannot circulate KV in-kernel on this jax version"
        )
    if jax.devices()[0].platform != "tpu":
        raise RuntimeError(
            f"backend {jax.devices()[0].platform!r} runs the fused ring "
            "in interpret mode only — degrading to the scan-path ring"
        )
    import jax.numpy as jnp

    from ..ops.pallas_ring import fused_ring_local

    q = jnp.zeros((1, 1, 128, 64), jnp.float32)
    out, _ = fused_ring_local(
        q, q, q,
        origins=jnp.zeros((1,), jnp.int32),
        his=jnp.zeros((1,), jnp.int32),
        los=jnp.full((1,), -128, jnp.int32),
        works=jnp.ones((1,), jnp.int32),
        n_local=128, interpret=False,
    )
    jax.block_until_ready(out)


# ICI tier of the fused ring: its OWN component, because its failure
# mode is softer — fused_ring_local (gather + the same single launch)
# still honors the "fused" contract, so a remote-tier Mosaic rejection
# or VMEM overflow degrades one tier, not all the way to the scan ring.
FUSED_REMOTE_COMPONENT = "fused_ring_remote"
# fault name the injection harness arms to force the remote tier to fail
FUSED_REMOTE_FAULT = "fused_remote_fail"

_fused_remote_probe: bool | None = None


def _probe_fused_remote() -> None:
    """Compile-and-run a minimal ``fused_ring_remote`` launch — the tier
    the TPU model path actually prefers, which the local-tier probe never
    touches.  A one-device ring under ``shard_map`` exercises the whole
    remote surface (ANY-space HBM buffers, barrier + grant semaphores,
    MESH-coordinate device ids, the async-copy staging pipeline) without
    needing a second chip; a Mosaic rejection here must become a recorded
    degradation, not a hard failure on the first model step."""
    get_injector().check(FUSED_REMOTE_FAULT)
    import jax

    if not remote_copy_supported():
        raise RuntimeError(
            "jax.experimental.pallas.tpu lacks the remote-DMA surface "
            "(make_async_remote_copy / semaphore primitives) — the fused "
            "ring cannot circulate KV in-kernel on this jax version"
        )
    if jax.devices()[0].platform != "tpu":
        raise RuntimeError(
            f"backend {jax.devices()[0].platform!r} cannot execute "
            "in-kernel remote DMA — remote tier degrades to the "
            "gather-based fused_ring_local"
        )
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec

    from . import compat
    from ..ops.pallas_ring import fused_ring_remote

    n = 128

    def core(q, k, v):
        # Hardcoded (2, 1) self-coordinates, NOT neighbor_mesh_coords:
        # this probe may run at trace time inside a model's shard_map,
        # where the ambient axis env still holds the OUTER mesh axes —
        # introspecting it here would leak outer tracers into this
        # self-contained one-axis launch.  On a one-device ring both
        # neighbors are rank 0 anyway.
        coords = jnp.zeros((2, 1), jnp.int32)
        return fused_ring_remote(
            q, k, v,
            his=jnp.zeros((1,), jnp.int32),
            los=jnp.full((1,), -n, jnp.int32),
            works=jnp.ones((1,), jnp.int32),
            nbr_coords=coords,
        )[0]

    mesh = Mesh(np.array(jax.devices()[:1]), ("fused_probe",))
    fn = compat.shard_map(
        core, mesh=mesh,
        in_specs=(PartitionSpec(),) * 3, out_specs=PartitionSpec(),
        check_vma=False,
    )
    q = jnp.zeros((1, 1, n, 64), jnp.float32)
    jax.block_until_ready(compat.jit(fn)(q, q, q))


def fused_remote_available(*, refresh: bool = False) -> bool:
    """True when the fused ring's in-kernel remote-DMA tier works here.

    Probed once per process (cached, same lock discipline as
    :func:`fused_ring_available`).  Every failure records a
    :data:`FUSED_REMOTE_COMPONENT` degradation with a one-shot warning;
    ``parallel/ring.py::_ring_fwd_fused`` consults this before choosing
    the remote tier and falls back to ``fused_ring_local`` — still the
    single-launch fused forward, just gather-fed."""
    global _fused_remote_probe
    with _pallas_probe_lock:
        if _fused_remote_probe is not None and not refresh:
            return _fused_remote_probe
        try:
            _probe_fused_remote()
            _fused_remote_probe = True
        except Exception as e:  # noqa: BLE001 — any failure means degrade
            degradation.record(FUSED_REMOTE_COMPONENT, e)
            _fused_remote_probe = False
        return _fused_remote_probe


def fused_ring_available(*, refresh: bool = False) -> bool:
    """True when the real fused-ring kernel path works on this backend.

    Probed once per process (cached, same lock discipline as
    :func:`pallas_available`).  EVERY failure — CPU/interpret backend,
    missing remote-copy support, Mosaic rejection, armed fault — records
    a :data:`FUSED_COMPONENT` degradation with a one-shot warning (see
    :func:`_probe_fused` for why non-TPU is not silent here)."""
    global _fused_probe
    with _pallas_probe_lock:
        if _fused_probe is not None and not refresh:
            return _fused_probe
        try:
            _probe_fused()
            _fused_probe = True
        except Exception as e:  # noqa: BLE001 — any failure means degrade
            degradation.record(FUSED_COMPONENT, e)
            _fused_probe = False
        return _fused_probe


def resolve_ring_impl(impl: str | None) -> str:
    """Resolve a requested RING impl (superset of the attention impls).

    ``"fused"`` returns ``"fused"`` when the probe passes, else records
    the degradation (in the probe) and re-resolves as ``"auto"`` through
    :func:`resolve_attention_impl` — the scan-path ring at the best
    per-hop compute tier available.  ``"auto"`` prefers the fused tier,
    then degrades the same way.  ``"xla"``/``"pallas"``/``None`` pass
    through unchanged (explicit scan-path requests stay scan-path).

    Note the asymmetry with :func:`ring_flash_attention`: calling it with
    a literal ``impl="fused"`` always RUNS the fused kernel (interpret
    mode on CPU — the parity-test tier); resolution here is the
    model-level seam where interpret-mode would be a silent pessimization
    rather than a test fixture.
    """
    if impl == "fused":
        return "fused" if fused_ring_available() else (
            resolve_attention_impl("auto")
        )
    if impl == "auto":
        if (not degradation.is_degraded(FUSED_COMPONENT)
                and fused_ring_available()):
            return "fused"
        return resolve_attention_impl("auto")
    if impl in (None, "xla", "pallas"):
        return resolve_attention_impl(impl)
    raise ValueError(
        f"resolve_ring_impl: impl must be 'auto', 'fused', 'pallas', "
        f"'xla' or None, got {impl!r}"
    )


def reset(*, probe: bool = True) -> None:
    """Test-harness hook: clear armed faults, degradation state, and
    (optionally) the cached Pallas/fused-ring probe results."""
    global _pallas_probe, _fused_probe, _fused_remote_probe
    _INJECTOR.clear()
    degradation.reset()
    if probe:
        with _pallas_probe_lock:
            _pallas_probe = None
            _fused_probe = None
            _fused_remote_probe = None


# ----------------------------------------------------------------------
# NaN-grad injection tap (jit-compatible)
# ----------------------------------------------------------------------


def nan_tap(x, name: str = "nan_loss"):
    """Multiply ``x`` by NaN when fault ``name`` is armed — under jit.

    The armed/disarmed decision is fetched at RUN time through
    ``jax.pure_callback`` (a trace-time Python check would be baked into
    the compiled step and could never fire "at step k"), so a test can run
    a compiled train step normally for k steps, arm the fault for exactly
    one step, and assert the guarded step skipped it.  Production code
    pays one scalar host callback only if it opts in by wrapping its loss
    with :func:`faulty_loss`.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def factor() -> np.ndarray:
        return np.float32(np.nan if _INJECTOR.armed(name) else 1.0)

    f = jax.pure_callback(
        factor, jax.ShapeDtypeStruct((), jnp.float32), vmap_method="broadcast_all"
    )
    return x * f.astype(x.dtype)


def faulty_loss(loss_fn: Callable[..., Any], name: str = "nan_loss"):
    """Wrap ``loss_fn`` with a :func:`nan_tap` on its scalar output, so the
    fault-injection harness can poison the loss (and therefore every
    gradient) of an arbitrary training step."""

    def wrapped(*args, **kwargs):
        return nan_tap(loss_fn(*args, **kwargs), name)

    return wrapped
