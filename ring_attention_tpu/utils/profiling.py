"""Profiling and throughput measurement.

The reference has no timers or profiler hooks at all (SURVEY §5).  On TPU
the platform profiler (XProf via ``jax.profiler``) is the ground truth for
MXU utilization and ICI overlap; this module adds the two things a training
loop actually calls: a trace context and a step-throughput meter.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA/TPU profile viewable in XProf/TensorBoard.

    >>> with trace("/tmp/profile"):
    ...     step(...)  # traced region
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclass
class StepTimer:
    """Wall-clock throughput meter for a training/decoding loop.

    Blocks on the supplied result each step so async dispatch doesn't hide
    device time; reports steps/s and tokens/s over a sliding window.
    """

    tokens_per_step: int = 0
    window: int = 20
    _times: list = field(default_factory=list)

    def step(self, result=None) -> None:
        if result is not None:
            jax.block_until_ready(result)
        self._times.append(time.perf_counter())
        if len(self._times) > self.window + 1:
            self._times.pop(0)

    @property
    def steps_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        span = self._times[-1] - self._times[0]
        return (len(self._times) - 1) / span if span > 0 else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self.steps_per_sec * self.tokens_per_step
