"""Profiling and throughput measurement.

The reference has no timers or profiler hooks at all (SURVEY §5).  On TPU
the platform profiler (XProf via ``jax.profiler``) is the ground truth for
MXU utilization and ICI overlap; this module adds the pieces a training
loop actually calls: a trace context, named annotations, and a
step-throughput meter.  The hot paths across ``parallel/`` and ``ops/``
are wrapped in stable ``jax.named_scope`` names (``ring/hop{i}``,
``ulysses/a2a_in``, ``hybrid/inner``, ``tree_decode/gather``, …) so an
XProf capture attributes device time to stages — ``tools/trace_report.py``
renders the resulting per-stage table.
"""

from __future__ import annotations

import contextlib
import statistics
import time
import warnings
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA/TPU profile viewable in XProf/TensorBoard.

    >>> with trace("/tmp/profile"):
    ...     step(...)  # traced region
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Host-side timeline annotation (``jax.profiler.TraceAnnotation``).

    Marks a span on the HOST trace line — dispatch loops, data loading,
    checkpoint saves.  For naming *device* time inside jitted code use
    ``jax.named_scope`` (applied throughout ``parallel/`` and ``ops/``);
    the two compose: a host annotation around a ``step()`` call brackets
    the device ops the named scopes attribute.

    >>> with annotate("train/step"):
    ...     loss = step(...)
    """
    return jax.profiler.TraceAnnotation(name)


@dataclass
class StepTimer:
    """Wall-clock throughput meter for a training/decoding loop.

    Blocks on the supplied result each step so async dispatch doesn't hide
    device time; reports steps/s and tokens/s over a sliding window, plus
    p50/p95 per-step latency (the tail is what a wedged collective or a
    slow host callback shows up in first — the mean hides it).

    Timestamps come from ``time.perf_counter`` (monotonic by contract); a
    non-increasing reading anyway — a suspended VM, a broken clock shim —
    resets the window instead of poisoning every rate until it scrolls
    out (``clock_anomalies`` counts the resets).
    """

    tokens_per_step: int = 0
    window: int = 20
    clock_anomalies: int = 0
    _times: list = field(default_factory=list)
    _warned_no_tokens: bool = field(default=False, repr=False)

    def step(self, result=None) -> None:
        if result is not None:
            jax.block_until_ready(result)
            if self.tokens_per_step == 0 and not self._warned_no_tokens:
                # tokens_per_sec would read 0.0 forever — say so ONCE
                # instead of letting a dashboard trend a silent zero
                self._warned_no_tokens = True
                warnings.warn(
                    "StepTimer.step() called with a result but "
                    "tokens_per_step is unset — tokens_per_sec will report "
                    "0.0; construct StepTimer(tokens_per_step=...) to get "
                    "throughput",
                    stacklevel=2,
                )
        now = time.perf_counter()
        if self._times and now <= self._times[-1]:
            self.clock_anomalies += 1
            self._times.clear()
        self._times.append(now)
        if len(self._times) > self.window + 1:
            self._times.pop(0)

    def _deltas(self) -> list[float]:
        return [
            b - a for a, b in zip(self._times, self._times[1:])
        ]

    @property
    def steps_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        span = self._times[-1] - self._times[0]
        return (len(self._times) - 1) / span if span > 0 else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self.steps_per_sec * self.tokens_per_step

    @property
    def step_ms_p50(self) -> float:
        """Median per-step latency (ms) over the window; 0.0 until two
        steps have been recorded."""
        deltas = self._deltas()
        if not deltas:
            return 0.0
        return statistics.median(deltas) * 1e3

    @property
    def step_ms_p95(self) -> float:
        """95th-percentile per-step latency (ms) over the window (linear
        interpolation; equals the max for windows under ~20 steps)."""
        deltas = self._deltas()
        if not deltas:
            return 0.0
        if len(deltas) == 1:
            return deltas[0] * 1e3
        deltas = sorted(deltas)
        pos = 0.95 * (len(deltas) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(deltas) - 1)
        frac = pos - lo
        return (deltas[lo] * (1 - frac) + deltas[hi] * frac) * 1e3
