"""Profiling: trace capture, step throughput, and the measured-overlap
observatory.

The reference has no timers or profiler hooks at all (SURVEY §5).  On TPU
the platform profiler (XProf via ``jax.profiler``) is the ground truth for
MXU utilization and ICI overlap; this module adds the pieces a training
loop actually calls — a trace context, named annotations, a
step-throughput meter — plus the **reader** side: a stdlib-only parser
for the ``.xplane.pb`` captures the profiler writes, a per-hop/per-stage
timeline reconstruction keyed on the stack's stable ``jax.named_scope``
names (``ring/hop{i}``, ``ring/rotate{i}``, ``ulysses/a2a_in``, …), and a
*measured* compute/transfer overlap fraction to sit next to the analytic
one from ``telemetry.ring_comms_accounting`` — Ring Attention's whole
premise ("KV hops hide under blockwise compute") as a number read off the
hardware timeline, not a model (docs/observability.md §Observatory).

Like ``telemetry.py``/``resilience.py``, this module is stdlib-only at
module level (jax is imported inside functions), so ``tools/
trace_report.py`` can load it by file path on a box where jax cannot
import.  The xplane parser is a ~150-line protobuf wire-format reader —
the TensorFlow proto stubs this image lacks are NOT required: op events
carry HLO instruction names and a ``program_id``, the ``/host:metadata``
plane embeds each program's ``HloProto``, and joining the two recovers
the full ``op_name`` scope path for every event.
"""

from __future__ import annotations

import contextlib
import glob
import os
import re
import statistics
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Iterator, NamedTuple

try:
    from .tracing import perf_counter as _perf_counter
except ImportError:  # standalone file-path load (tools/trace_report.py)
    _perf_counter = time.perf_counter


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA/TPU profile viewable in XProf/TensorBoard.

    Goes through ``utils/compat.profiler_trace`` so the jax-0.4.x
    entry-point differences stay in the shim (docs/observability.md).

    >>> with trace("/tmp/profile"):
    ...     step(...)  # traced region
    """
    try:
        from . import compat

        cm = compat.profiler_trace(logdir)
    except ImportError:  # standalone file-path load (tools/)
        import jax

        cm = jax.profiler.trace(logdir)
    with cm:
        yield


def annotate(name: str):
    """Host-side timeline annotation (``jax.profiler.TraceAnnotation``).

    Marks a span on the HOST trace line — dispatch loops, data loading,
    checkpoint saves.  For naming *device* time inside jitted code use
    ``jax.named_scope`` (applied throughout ``parallel/`` and ``ops/``);
    the two compose: a host annotation around a ``step()`` call brackets
    the device ops the named scopes attribute.

    >>> with annotate("train/step"):
    ...     loss = step(...)
    """
    import jax

    return jax.profiler.TraceAnnotation(name)


@dataclass
class StepTimer:
    """Wall-clock throughput meter for a training/decoding loop.

    Blocks on the supplied result each step so async dispatch doesn't hide
    device time; reports steps/s and tokens/s over a sliding window, plus
    p50/p95 per-step latency (the tail is what a wedged collective or a
    slow host callback shows up in first — the mean hides it).

    Timestamps come from ``time.perf_counter`` (monotonic by contract); a
    non-increasing reading anyway — a suspended VM, a broken clock shim —
    resets the window instead of poisoning every rate until it scrolls
    out (``clock_anomalies`` counts the resets).
    """

    tokens_per_step: int = 0
    window: int = 20
    clock_anomalies: int = 0
    _times: list = field(default_factory=list)
    _warned_no_tokens: bool = field(default=False, repr=False)

    def step(self, result=None) -> None:
        if result is not None:
            import jax

            jax.block_until_ready(result)
            if self.tokens_per_step == 0 and not self._warned_no_tokens:
                # tokens_per_sec would read 0.0 forever — say so ONCE
                # instead of letting a dashboard trend a silent zero
                self._warned_no_tokens = True
                warnings.warn(
                    "StepTimer.step() called with a result but "
                    "tokens_per_step is unset — tokens_per_sec will report "
                    "0.0; construct StepTimer(tokens_per_step=...) to get "
                    "throughput",
                    stacklevel=2,
                )
        now = _perf_counter()
        if self._times and now <= self._times[-1]:
            self.clock_anomalies += 1
            self._times.clear()
        self._times.append(now)
        if len(self._times) > self.window + 1:
            self._times.pop(0)

    def _deltas(self) -> list[float]:
        return [
            b - a for a, b in zip(self._times, self._times[1:])
        ]

    @property
    def steps_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        span = self._times[-1] - self._times[0]
        return (len(self._times) - 1) / span if span > 0 else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self.steps_per_sec * self.tokens_per_step

    @property
    def step_ms_p50(self) -> float:
        """Median per-step latency (ms) over the window; 0.0 until two
        steps have been recorded."""
        deltas = self._deltas()
        if not deltas:
            return 0.0
        return statistics.median(deltas) * 1e3

    @property
    def step_ms_p95(self) -> float:
        """95th-percentile per-step latency (ms) over the window (linear
        interpolation; equals the max for windows under ~20 steps)."""
        deltas = self._deltas()
        if not deltas:
            return 0.0
        return percentile([d * 1e3 for d in deltas], 0.95)


def percentile(values: list[float], q: float) -> float:
    """Linear-interpolation percentile (numpy 'linear' method), 0.0 on
    empty input — shared by the timer, the timeline, and trace_report."""
    if not values:
        return 0.0
    values = sorted(values)
    if len(values) == 1:
        return values[0]
    pos = q * (len(values) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(values) - 1)
    frac = pos - lo
    return values[lo] * (1 - frac) + values[hi] * frac


# ----------------------------------------------------------------------
# xplane.pb wire-format parser (stdlib-only)
# ----------------------------------------------------------------------
#
# Field numbers below are the stable public schema of
# tensorflow/tsl/profiler/protobuf/xplane.proto and xla/service/hlo.proto
# (unchanged across every TF/XLA release this stack can meet).  Only the
# fields the observatory needs are decoded; unknown fields are skipped by
# wire type, so schema additions cannot break the reader.


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s  # ra: allow(RA012 protobuf varint 7-bit payload mask, not quantization)
        if not b & 0x80:
            return r, i
        s += 7


def _wire_fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield ``(field_number, wire_type, value)`` triples of one message.

    wire type 0 -> int, 2 -> bytes, 1/5 -> raw 8/4 bytes.  Groups (3/4)
    do not occur in these protos; an unknown type aborts the message
    rather than guessing at framing.
    """
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # unknown framing: stop decoding this message
            return
        yield fn, wt, v


def _hlo_scopes(hlo_proto: bytes) -> dict[str, str]:
    """``{instruction_name: op_name}`` from a serialized ``HloProto``.

    HloProto.hlo_module=1 -> HloModuleProto.computations=3 ->
    HloComputationProto.instructions=2 -> HloInstructionProto.name=1 /
    .metadata=7 -> OpMetadata.op_name=2 (the ``jit(f)/…/ring/hop0/…``
    scope path the named_scope annotations put there).
    """
    out: dict[str, str] = {}
    for fn, _, module in _wire_fields(hlo_proto):
        if fn != 1:
            continue
        for mfn, _, comp in _wire_fields(module):
            if mfn != 3:
                continue
            for cfn, _, instr in _wire_fields(comp):
                if cfn != 2:
                    continue
                name = scope = ""
                for ifn, _, val in _wire_fields(instr):
                    if ifn == 1:
                        name = val.decode(errors="replace")
                    elif ifn == 7:
                        for ofn, _, oval in _wire_fields(val):
                            if ofn == 2:
                                scope = oval.decode(errors="replace")
                if name and scope:
                    out[name] = scope
    return out


class OpEvent(NamedTuple):
    """One profiled op occurrence with its resolved scope path."""

    plane: str
    line: str
    name: str       # HLO instruction name ("dot.14", "collective-permute.4")
    scope: str      # op_name metadata path ("" when the join found none)
    stage: str      # stage label from STAGES ("other" when unmatched)
    kind: str       # "compute" | "transfer" | "other"
    start_ns: int
    dur_ns: int

    @property
    def end_ns(self) -> int:
        return self.start_ns + self.dur_ns


# stage buckets keyed on the stable scope/kernel names threaded through
# parallel/ and ops/ (docs/observability.md §4): (needle, label, kind),
# first match wins.  "transfer" = inter-device payload movement the ring
# schedule wants hidden under "compute".
STAGES: list[tuple[str, str, str]] = [
    ("ring/rotate", "ring kv rotation", "transfer"),
    ("ring/catchup", "ring dkv catch-up", "transfer"),
    ("ring/bwd", "ring backward", "compute"),
    ("ring/hop", "ring hop compute", "compute"),
    # fused ring (ops/pallas_ring.py): the CPU-degradable local tier's
    # KV gather is transfer; the single launch itself is compute — its
    # in-kernel remote DMAs never surface as separate timeline ops, which
    # is exactly the launch-free-hops property (docs/ring_overlap.md)
    ("ring/fused_gather", "fused ring kv gather", "transfer"),
    ("ring/fused", "fused ring kernel", "compute"),
    ("kv_head_reshard", "gqa kv reshard", "transfer"),
    ("ulysses/a2a", "ulysses all-to-all", "transfer"),
    ("ulysses/flash", "ulysses local flash", "compute"),
    ("hybrid/a2a", "hybrid all-to-all", "transfer"),
    ("hybrid/inner", "hybrid inner ring", "compute"),
    ("zigzag/gather", "zigzag gather", "transfer"),
    ("zigzag/", "zigzag", "compute"),
    ("tree_decode/gather", "tree-decode merge", "transfer"),
    ("tree_decode/", "tree-decode local", "compute"),
    ("flash_bwd", "flash backward kernel", "compute"),  # pallas kernel name
    ("flash/bwd", "flash backward", "compute"),  # XLA-path named_scope
    ("flash_decode", "flash decode kernel", "compute"),
    ("flash", "flash forward kernel", "compute"),
]

# instruction-name prefixes that are payload movement even when no scope
# attributed them (an unattributed collective is itself a finding — RA004
# lints the source side of this)
_COLLECTIVE_PREFIXES = (
    "collective-permute", "all-to-all", "all-gather", "all-reduce",
    "reduce-scatter", "collective-broadcast",
)

_HOP_RE = re.compile(r"ring/(?:bwd_)?hop(\d+)")
_ROTATE_RE = re.compile(r"ring/rotate(\d+)")


def stage_of(name: str, scope: str = "") -> tuple[str, str]:
    """``(label, kind)`` for an op: scope needles first (first match in
    STAGES wins), then the bare-collective fallback, else ``other``."""
    hay = (scope or name).lower()
    for needle, label, kind in STAGES:
        if needle in hay:
            return label, kind
    if name.startswith(_COLLECTIVE_PREFIXES):
        return "unattributed collective", "transfer"
    return "other", "other"


def _xplane_paths(path: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(
            glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True),
            key=os.path.getmtime,
        )
    return [path]


def read_xplane_events(path: str) -> tuple[list[OpEvent], str]:
    """Parse the newest ``*.xplane.pb`` under ``path`` (or the file
    itself) into resolved :class:`OpEvent` rows.

    Returns ``(events, note)`` — ``note`` is a human-readable degradation
    reason when nothing could be parsed (missing capture, no op events),
    empty on success.  Never raises on malformed input: the timeline is a
    diagnostic, not a gate.
    """
    paths = _xplane_paths(path)
    if not paths:
        return [], f"no .xplane.pb under {path}"
    try:
        data = open(paths[-1], "rb").read()
    except OSError as e:
        return [], f"unreadable capture: {e}"
    # program_id -> {instruction: scope}; module_name -> same (fallback)
    scopes_by_id: dict[int, dict[str, str]] = {}
    scopes_by_module: dict[str, dict[str, str]] = {}
    op_planes: list[bytes] = []
    try:
        for fn, _, plane in _wire_fields(data):
            if fn != 1:
                continue
            pname = ""
            for pfn, _, pval in _wire_fields(plane):
                if pfn == 2:
                    pname = pval.decode(errors="replace")
                    break
            if "metadata" in pname:
                _index_metadata_plane(plane, scopes_by_id, scopes_by_module)
            else:
                op_planes.append(plane)
        events: list[OpEvent] = []
        for plane in op_planes:
            events.extend(
                _plane_events(plane, scopes_by_id, scopes_by_module)
            )
    except (IndexError, ValueError, OverflowError) as e:
        # a capture truncated mid-write (killed profiler — the wedge mode
        # this repo knows well) degrades to a note, never a traceback
        return [], (
            f"malformed capture {paths[-1]}: {type(e).__name__}: {e}"
        )
    if not events:
        return [], f"no op events parsed from {paths[-1]}"
    return events, ""


def _index_metadata_plane(
    plane: bytes,
    by_id: dict[int, dict[str, str]],
    by_module: dict[str, dict[str, str]],
) -> None:
    """The ``/host:metadata`` plane: each event-metadata entry is one
    profiled program; its ``hlo_proto`` stat holds the serialized
    HloProto whose OpMetadata carries the named_scope paths."""
    for pfn, _, entry in _wire_fields(plane):
        if pfn != 4:  # event_metadata map entry
            continue
        for efn, _, meta in _wire_fields(entry):
            if efn != 2:  # XEventMetadata
                continue
            meta_id = None
            module_name = ""
            blobs: list[bytes] = []
            for mfn, mwt, mval in _wire_fields(meta):
                if mfn == 1 and mwt == 0:
                    meta_id = mval
                elif mfn == 2:
                    module_name = mval.decode(errors="replace")
                elif mfn in (3, 5):
                    # field 3: raw metadata bytes; field 5: XStat whose
                    # bytes_value (field 6) carries the proto — both
                    # spellings exist in the wild
                    if mfn == 3:
                        blobs.append(mval)
                    else:
                        for sfn, _, sval in _wire_fields(mval):
                            if sfn == 6:
                                blobs.append(sval)
            for blob in blobs:
                scopes = _hlo_scopes(blob)
                if not scopes:
                    continue
                if meta_id is not None:
                    by_id.setdefault(meta_id, {}).update(scopes)
                if module_name:
                    by_module.setdefault(module_name, {}).update(scopes)


def _plane_events(
    plane: bytes,
    by_id: dict[int, dict[str, str]],
    by_module: dict[str, dict[str, str]],
) -> list[OpEvent]:
    pname = ""
    metas: dict[int, str] = {}
    stat_names: dict[int, str] = {}
    lines: list[bytes] = []
    for pfn, _, pval in _wire_fields(plane):
        if pfn == 2:
            pname = pval.decode(errors="replace")
        elif pfn == 3:
            lines.append(pval)
        elif pfn == 4:  # event_metadata map entry -> id, name
            mid, mname = None, ""
            for efn, ewt, meta in _wire_fields(pval):
                if efn == 1 and ewt == 0:  # map key == metadata id
                    mid = meta
                elif efn == 2:  # XEventMetadata
                    for mfn, mwt, mval in _wire_fields(meta):
                        if mfn == 1 and mwt == 0:
                            mid = mval
                        elif mfn == 2:
                            mname = mval.decode(errors="replace")
            if mid is not None:
                metas[mid] = mname
        elif pfn == 5:  # stat_metadata map entry -> id, name
            sid, sname = None, ""
            for efn, _, meta in _wire_fields(pval):
                if efn == 1:
                    sid = meta
                elif efn == 2:
                    for mfn, mwt, mval in _wire_fields(meta):
                        if mfn == 1 and mwt == 0:
                            sid = mval
                        elif mfn == 2:
                            sname = mval.decode(errors="replace")
            if sid is not None:
                stat_names[sid] = sname
    out: list[OpEvent] = []
    parsed_lines: list[tuple[str, int, list[bytes]]] = []
    for line_buf in lines:
        lname = ""
        ts_ns = 0
        evs: list[bytes] = []
        for lfn, lwt, lval in _wire_fields(line_buf):
            if lfn == 2:
                lname = lval.decode(errors="replace")
            elif lfn == 3 and lwt == 0:
                ts_ns = lval
            elif lfn == 4:
                evs.append(lval)
        parsed_lines.append((lname, ts_ns, evs))
    # device planes (TPU) carry an "XLA Ops" line plus DERIVED lines
    # (step, framework-name-scope) describing the same wall-clock spans;
    # counting both would double every op.  When a plane has op lines,
    # only they enter the timeline; CPU planes (one thunk line per
    # thread, no derived lines) keep everything.
    op_lines = [pl for pl in parsed_lines if "XLA Ops" in pl[0]]
    if op_lines:
        parsed_lines = op_lines
    for lname, ts_ns, evs in parsed_lines:
        for ev in evs:
            mid = None
            offset_ps = dur_ps = 0
            program_id = None
            module_ref = None
            for efn, ewt, eval_ in _wire_fields(ev):
                if efn == 1 and ewt == 0:
                    mid = eval_
                elif efn == 2 and ewt == 0:
                    offset_ps = eval_
                elif efn == 3 and ewt == 0:
                    dur_ps = eval_
                elif efn == 4:  # XStat
                    smid = None
                    val = None
                    for sfn, swt, sval in _wire_fields(eval_):
                        if sfn == 1 and swt == 0:
                            smid = sval
                        elif sfn in (3, 4, 7) and swt == 0:
                            val = sval
                    sname = stat_names.get(smid, "")
                    if sname == "program_id":
                        program_id = val
                    elif sname == "hlo_module" and val is not None:
                        module_ref = stat_names.get(val, "")
            name = metas.get(mid, "")
            if not name or not dur_ps:
                continue
            if program_id is None and not module_ref:
                # only HLO-attributed op events enter the timeline: host
                # python-tracer/TraceMe spans (a dispatch wrapper named
                # after the jitted fn, a ThreadpoolListener) would
                # otherwise bucket as compute and corrupt busy time and
                # the measured overlap (the needle match runs on NAMES
                # when no scope resolves)
                continue
            scope = ""
            if program_id is not None and program_id in by_id:
                scope = by_id[program_id].get(name, "")
            if not scope and module_ref and module_ref in by_module:
                scope = by_module[module_ref].get(name, "")
            if not scope and len(by_module) == 1:
                scope = next(iter(by_module.values())).get(name, "")
            label, kind = stage_of(name, scope)
            out.append(OpEvent(
                plane=pname, line=lname, name=name, scope=scope,
                stage=label, kind=kind,
                start_ns=ts_ns * 1000 + offset_ps,  # both in picoseconds
                dur_ns=dur_ps,
            ))
    # start/dur computed in ps above; convert once here so one unit rules
    return [
        e._replace(start_ns=e.start_ns // 1000, dur_ns=max(e.dur_ns // 1000, 1))
        for e in out
    ]


# ----------------------------------------------------------------------
# Timeline reconstruction + measured overlap
# ----------------------------------------------------------------------


def stage_timeline(
    events: list[OpEvent], *, ring_size: int | None = None
) -> dict[str, Any]:
    """Per-stage/per-hop timeline over one capture.

    Returns::

        {"stages": [{"stage", "kind", "events", "busy_ms",
                     "p50_ms", "p95_ms"}, ...],        # busy-desc order
         "hops":   [{"hop", "compute_ms", "transfer_ms",
                     "samples"}, ...],                  # hop index order
         "total_busy_ms": float}

    ``p50/p95`` are over stage *instances* — one sample per (line, stage,
    hop-index) group, i.e. per device-thread occurrence — not per HLO op,
    so a hop that fragments into 40 fusions still reads as one latency
    sample.  ``hops`` resolves per-hop indices into the compute-vs-
    transfer table the overlap story is about: the unrolled Pallas path
    carries static ``ring/hop{i}`` / ``ring/rotate{i}`` scope indices; the
    XLA scan path re-runs ONE set of instructions per hop, so its indices
    are reconstructed temporally — on each timeline line, hop ``i`` is
    whatever runs after the line's ``i``-th completed KV rotation (an
    approximation when a thread pool interleaves devices on one line, so
    ``hops`` rows carry their ``samples`` count for sanity).

    A capture should normally cover ONE step (the xprof_capture
    practice); for a multi-step capture pass ``ring_size`` so hop indices
    fold modulo the ring and each step contributes its own latency
    sample (hop-index DECREASES on a line mark the step boundary —
    without ``ring_size`` the scan path's temporal counter keeps
    growing and a multi-step capture reads as one long hop sequence).
    """
    instances: dict[tuple, float] = {}
    stage_events: dict[str, int] = {}
    stage_kind: dict[str, str] = {}
    hop_busy: dict[int, dict[str, float]] = {}
    hop_samples: dict[int, int] = {}
    rotations_seen: dict[tuple[str, str], int] = {}
    prev_hop: dict[tuple[str, str], int] = {}
    cycles: dict[tuple[str, str], int] = {}
    for ev in sorted(events, key=lambda e: e.start_ns):
        if ev.stage == "other":
            continue
        line_key = (ev.plane, ev.line)
        hop = None
        m = _HOP_RE.search(ev.scope) or _ROTATE_RE.search(ev.scope)
        if m:
            hop = int(m.group(1))
        elif ev.stage == "ring kv rotation":
            hop = rotations_seen.get(line_key, 0)
            if ev.name.startswith("collective-permute"):
                # the permute op itself advances the line's hop counter;
                # its satellite copies/converts stay on the same index
                rotations_seen[line_key] = hop + 1
        elif ev.stage in ("ring hop compute", "ring backward"):
            hop = rotations_seen.get(line_key, 0)
        cycle = 0
        if hop is not None:
            if ring_size:
                hop %= ring_size
            # a hop index going BACKWARDS on a line = a new step/cycle:
            # its occurrences become fresh latency samples instead of
            # accumulating into the first step's instance
            if hop < prev_hop.get(line_key, hop):
                cycles[line_key] = cycles.get(line_key, 0) + 1
            prev_hop[line_key] = hop
            cycle = cycles.get(line_key, 0)
        key = (ev.plane, ev.line, ev.stage, hop, cycle)
        first = key not in instances
        instances[key] = instances.get(key, 0.0) + ev.dur_ns / 1e6
        stage_events[ev.stage] = stage_events.get(ev.stage, 0) + 1
        stage_kind[ev.stage] = ev.kind
        if hop is not None:
            slot = hop_busy.setdefault(hop, {"compute": 0.0, "transfer": 0.0})
            if ev.kind in slot:
                slot[ev.kind] += ev.dur_ns / 1e6
            if first:
                hop_samples[hop] = hop_samples.get(hop, 0) + 1
    per_stage: dict[str, list[float]] = {}
    for (_, _, stage, _, _), busy in instances.items():
        per_stage.setdefault(stage, []).append(busy)
    stages = [
        {
            "stage": stage,
            "kind": stage_kind[stage],
            "events": stage_events[stage],
            "busy_ms": round(sum(samples), 4),
            "p50_ms": round(percentile(samples, 0.5), 4),
            "p95_ms": round(percentile(samples, 0.95), 4),
        }
        for stage, samples in per_stage.items()
    ]
    stages.sort(key=lambda r: -r["busy_ms"])
    hops = [
        {
            "hop": hop,
            "compute_ms": round(hop_busy[hop]["compute"], 4),
            "transfer_ms": round(hop_busy[hop]["transfer"], 4),
            "samples": hop_samples.get(hop, 0),
        }
        for hop in sorted(hop_busy)
    ]
    return {
        "stages": stages,
        "hops": hops,
        "total_busy_ms": round(sum(r["busy_ms"] for r in stages), 4),
    }


def _merge_intervals(spans: list[tuple[int, int]]) -> list[tuple[int, int]]:
    if not spans:
        return []
    spans = sorted(spans)
    out = [spans[0]]
    for lo, hi in spans[1:]:
        if lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def measured_overlap(events: list[OpEvent]) -> dict[str, Any]:
    """Measured compute/transfer overlap over one capture.

    Walks the wall-clock timeline: merges all transfer spans (KV
    rotations, all-to-alls, catch-up permutes) and all compute spans into
    interval unions, and reports what fraction of transfer wall time ran
    concurrently with compute anywhere on the chip —
    ``overlap_fraction = overlapped_ms / transfer_ms`` (0.0 when the
    capture has no transfer spans; ``transfer_ms`` of 0 means the
    schedule's communication never reached the timeline, which is its own
    finding).  This is the empirical counterpart of
    ``ring_comms_accounting``'s ``hop_overlap_fraction`` (compute time at
    peak over max(compute, transfer at ICI bandwidth)): the analytic one
    says whether the shapes *can* hide the hop, this one says whether the
    schedule *did*.
    """
    transfer = _merge_intervals(
        [(e.start_ns, e.end_ns) for e in events if e.kind == "transfer"]
    )
    compute = _merge_intervals(
        [(e.start_ns, e.end_ns) for e in events if e.kind == "compute"]
    )
    transfer_ns = sum(hi - lo for lo, hi in transfer)
    compute_ns = sum(hi - lo for lo, hi in compute)
    overlapped = 0
    ci = 0
    for lo, hi in transfer:
        while ci < len(compute) and compute[ci][1] <= lo:
            ci += 1
        cj = ci
        while cj < len(compute) and compute[cj][0] < hi:
            overlapped += min(hi, compute[cj][1]) - max(lo, compute[cj][0])
            cj += 1
    return {
        "compute_ms": round(compute_ns / 1e6, 4),
        "transfer_ms": round(transfer_ns / 1e6, 4),
        "overlapped_ms": round(overlapped / 1e6, 4),
        "overlap_fraction": (
            round(overlapped / transfer_ns, 4) if transfer_ns else 0.0
        ),
    }


def overlap_report(
    source: str | list[OpEvent],
    *,
    analytic: float | dict | None = None,
    tolerance: float = 0.25,
    ring_size: int | None = None,
) -> dict[str, Any]:
    """Timeline + measured overlap for a capture, compared against the
    analytic model when one is supplied.

    ``source`` is a capture directory/file or pre-parsed events;
    ``analytic`` is ``ring_comms_accounting(...)`` output (its
    ``hop_overlap_fraction`` is used) or a bare fraction.  When both
    numbers exist and disagree by more than ``tolerance``, the report
    carries ``agrees=False`` plus a one-line ``finding`` — a model that
    no longer describes the hardware is itself a regression
    (docs/observability.md §Observatory).
    """
    if isinstance(source, str):
        events, note = read_xplane_events(source)
    else:
        events, note = source, ""
    report: dict[str, Any] = {"parsed_events": len(events)}
    if note:
        report["note"] = note
        return report
    report["timeline"] = stage_timeline(events, ring_size=ring_size)
    report.update(measured_overlap(events))
    if analytic is not None:
        if isinstance(analytic, dict):
            analytic = analytic.get("hop_overlap_fraction", 0.0)
        report["analytic_overlap_fraction"] = round(float(analytic), 4)
        delta = abs(report["overlap_fraction"] - float(analytic))
        report["overlap_delta"] = round(delta, 4)
        report["tolerance"] = tolerance
        report["agrees"] = delta <= tolerance
        if not report["agrees"]:
            report["finding"] = (
                f"measured overlap {report['overlap_fraction']:.3f} vs "
                f"analytic {float(analytic):.3f} (|delta| "
                f"{delta:.3f} > tolerance {tolerance:.3f}) — the comms "
                f"model no longer describes this capture"
            )
    return report
