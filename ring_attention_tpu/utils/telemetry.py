"""Telemetry spine: in-graph metrics, host-side logging, MFU/hop accounting.

The reference has no timers or profiler hooks at all (SURVEY §5), and this
repo's own bench history shows the cost: BENCH_r04/r05 report ``value: 0.0``
with "device probe hung" and no per-phase breakdown to say whether the ring
hop, the Ulysses all-to-all, or the kernel itself regressed.  FlashAttention
(arXiv 2205.14135) made IO-awareness the design axis; this module is the
measurement side of that, plus TASP-style (arXiv 2509.26541) topology-aware
communication accounting, in four pieces:

- **In-graph collection** — :class:`TrainMetrics` (the extended stats
  pytree ``make_train_step(collect_metrics=True)`` carries: loss,
  grad-norm, nonfinite/skipped-step counts) and :class:`Telemetry`, a
  trace-time scalar registry: instrumented code calls
  ``telemetry.observe(name, scalar)``, which is a strict no-op unless a
  ``collecting()`` context is active at the same trace level — so the
  annotations cost nothing (and change no HLO) when nobody is listening.
- **Host-side logging** — :class:`MetricsLogger`, a rolling JSONL writer
  (one line per step window, schema-versioned, atomic append) with
  optional CSV / TensorBoard export and a reader that survives a writer
  killed mid-line.  ``tools/trace_report.py`` renders its output.
- **MFU / comms accounting** — analytic flash-FLOP formulas
  (:func:`flash_attention_flops`, :func:`transformer_step_flops`),
  :func:`achieved_mfu` against the chip's bf16 peak, and
  :func:`ring_comms_accounting`: hop-count, bytes-moved-per-hop, and the
  per-hop compute/transfer overlap fraction for a (ring x ulysses)
  factoring — PR 3's "ulysses x fewer hops" claim as a number logged
  every step instead of an HLO pin we trust.
- **Diagnostic summaries** — :func:`attention_logit_summaries`: exact
  max-logit and softmax-entropy of an attention call via an online
  blockwise sweep (O(bucket) memory).  This is an *extra* O(n^2 d) pass:
  run it on a probe batch every N steps, never inside the hot step.

Like ``resilience.py``, this module is stdlib-only at module level (jax is
imported inside functions), so ``bench.py``'s parent process can load it by
file path before the subprocess-isolated device probe.
"""

from __future__ import annotations

import collections
import contextlib
import csv
import json
import os
import threading
import time
from typing import Any, Iterator, NamedTuple

try:
    from .tracing import monotonic_wall as _monotonic_wall
except ImportError:  # standalone file-path load (tools, bench parent)
    def _monotonic_wall() -> tuple[float, float]:
        return time.monotonic(), time.time()  # ra: allow(RA014 the standalone-load fallback IS the seam's mirror)


def _active_tracer():
    """The process-global span tracer, or None on a standalone file-path
    load (tools) where the relative import is unavailable."""
    try:
        from . import tracing
    except ImportError:
        return None
    return tracing.get_tracer()

# JSONL row schema version.  Bump when a field is renamed or its meaning
# changes; adding fields is backward compatible and needs no bump.
# v1: schema, step, time, plus free-form metric scalars (see
# docs/observability.md for the glossary emitted by examples/train.py).
SCHEMA_VERSION = 1

# bf16 dense peak TFLOPs per chip by TPU generation — the denominator of
# every MFU number this framework reports (bench.py mirrors this table; its
# parent process must stay import-free of the package until the device
# probe passes).
PEAK_TFLOPS = {
    "v5 lite": 197.0,  # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6e": 918.0,
}

# per-direction ICI link bandwidth (GB/s) by generation — used only for
# the analytic per-hop overlap fraction (a planning number, not a
# measurement; the measured truth is an XProf capture)
ICI_GBPS = {
    "v5 lite": 186.0,
    "v5e": 186.0,
    "v5p": 306.0,
    "v4": 268.0,
    "v6e": 448.0,
}

# attention matmul counts (shared with bench.py): 2 matmuls forward
# (q@k^T, p@v); backward recomputes scores and adds 4 grad matmuls
# (dv, dp, dq, dk) => fwd+bwd is 7
FWD_MATMULS = 2
FWDBWD_MATMULS = 7

# per-launch dispatch cost the scan-path ring pays at every hop boundary
# (host dispatch + Mosaic program setup, ~5us — the order XLA's launch
# path costs on current TPU runtimes).  The fused ring's whole point is
# that this term, and the launch boundary it models, do not exist: every
# hop after the first starts inside the already-running kernel.
DISPATCH_OVERHEAD_S = 5e-6


# ----------------------------------------------------------------------
# In-graph scalar collection
# ----------------------------------------------------------------------


class TrainMetrics(NamedTuple):
    """Extended per-step stats pytree carried through
    ``make_train_step(collect_metrics=True)``.

    Scalars live on device (the step stays one fused executable; nothing
    here adds a collective — pinned by
    ``tests/test_telemetry.py::test_metrics_add_no_collectives``):

    - ``loss`` — this step's loss (f32; NOT masked on a skipped step, so
      logs show the offending value).
    - ``grad_norm`` — this step's global gradient L2 norm, pre-clip.
    - ``step_ok`` — whether this step's update was applied (always True
      when ``skip_nonfinite=False``, even for a non-finite step).
    - ``skipped`` — running count of skipped updates (stays 0 unguarded).
    - ``nonfinite`` — running count of steps whose loss or grad norm was
      non-finite, applied or not: under ``skip_nonfinite=False`` this is
      the "the run is corrupting itself" alarm the guard would have
      stopped.
    """

    loss: Any  # f32 scalar
    grad_norm: Any  # f32 scalar
    step_ok: Any  # bool scalar
    skipped: Any  # int32 scalar, running
    nonfinite: Any  # int32 scalar, running


def init_train_metrics(skipped: int = 0, nonfinite: int = 0) -> TrainMetrics:
    """Seed carry for the instrumented step; ``skipped``/``nonfinite`` let a
    resumed run continue its counters from a checkpointed ``StepStats``."""
    import jax.numpy as jnp

    return TrainMetrics(
        loss=jnp.float32(0.0),
        grad_norm=jnp.float32(0.0),
        step_ok=jnp.asarray(True),
        skipped=jnp.asarray(skipped, jnp.int32),
        nonfinite=jnp.asarray(nonfinite, jnp.int32),
    )


class Telemetry:
    """Trace-time registry of named in-graph scalars + host-side events.

    ``observe(name, value)`` is sprinkled through instrumented code and is
    a strict no-op (not even a dict lookup on the value) unless a
    ``collecting()`` context is active — so instrumentation points cost
    nothing when nobody is listening, and the compiled program is
    bit-identical with telemetry off.

    ``collecting()`` must be entered at the SAME trace level as the
    observations it collects — typically *inside* the jitted function::

        tel = Telemetry()

        @jax.jit
        def fwd(x):
            with tel.collecting() as col:
                out = model(x)
            return out, col.values()   # observed scalars become outputs

    Observations made at a deeper transform level (inside ``shard_map``,
    ``lax.scan`` bodies, or a ``custom_vjp`` trace) CANNOT escape to an
    outer collector — jax would report a leaked tracer.  Instrumentation
    points inside those regions must aggregate locally first (or be
    logged through the analytic accounting below instead).

    ``event(kind, **fields)`` records host-side events (degraded kernels,
    probe failures) that :class:`MetricsLogger` drains into the JSONL
    stream as ``{"event": kind, ...}`` rows.
    """

    def __init__(self) -> None:
        self._stores: list[dict[str, Any]] = []
        self._events: list[dict[str, Any]] = []
        self._lock = threading.Lock()

    # -- in-graph scalars -------------------------------------------------

    class _Collector:
        def __init__(self, store: dict[str, Any]):
            self._store = store

        def values(self) -> dict[str, Any]:
            return dict(self._store)

    @contextlib.contextmanager
    def collecting(self) -> Iterator["Telemetry._Collector"]:
        store: dict[str, Any] = {}
        self._stores.append(store)
        try:
            yield Telemetry._Collector(store)
        finally:
            self._stores.pop()

    def active(self) -> bool:
        return bool(self._stores)

    def observe(self, name: str, value: Any) -> None:
        """Record scalar ``value`` under ``name`` in the innermost active
        collector; silently dropped when none is active.  ``value`` may be
        a thunk (callable taking no args) so the metric's compute is only
        traced when someone is listening."""
        if not self._stores:
            return
        if callable(value):
            value = value()
        self._stores[-1][name] = value

    # -- host-side events -------------------------------------------------

    def event(self, kind: str, **fields: Any) -> None:
        # monotonic+wall pair (shared helper with tracing.py): wall alone
        # cannot order events across processes — an NTP step or host skew
        # reorders a merged timeline; the mono stamp pins local order and
        # the merger's clock-offset correction handles the rest
        mono, wall = _monotonic_wall()
        with self._lock:
            self._events.append(
                {"event": kind, "time": wall, "mono": round(mono, 6),
                 **fields}
            )

    def events(self) -> tuple[dict[str, Any], ...]:
        with self._lock:
            return tuple(self._events)

    def drain_events(self) -> list[dict[str, Any]]:
        with self._lock:
            out, self._events = self._events, []
            return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


#: process-global default registry (instrumented library code observes
#: here; tests and power users may build private instances)
telemetry = Telemetry()


def _on_degradation(component: str, reason: str) -> None:
    """Listener wired onto ``resilience.degradation``: every kernel
    fallback lands as a telemetry event, so a run that silently lost its
    fast kernels shows up in the metrics stream and bench JSON — not just
    as a one-shot warning scrolled out of the log."""
    telemetry.event("degraded", component=component, reason=reason)


def _wire_degradation() -> None:
    try:
        from . import resilience
    except ImportError:  # standalone file-path load (bench.py parent)
        return
    resilience.degradation.add_listener(_on_degradation)


_wire_degradation()


def degradation_fields() -> dict[str, Any]:
    """Summary fields for result JSON (bench workers): ``{}`` when nothing
    degraded, else ``degraded=1`` plus the components and last reason."""
    try:
        from . import resilience
    except ImportError:
        return {}
    events = resilience.degradation.events()
    if not events:
        return {}
    return {
        "degraded": 1,
        "degraded_components": sorted({e.component for e in events}),
        "degraded_reason": events[-1].reason,
    }


# ----------------------------------------------------------------------
# Host-side metrics logging (JSONL / CSV / TensorBoard)
# ----------------------------------------------------------------------


class MetricsLogger:
    """Rolling JSONL metrics writer: one line per step window.

    Every row carries ``schema`` (:data:`SCHEMA_VERSION`), ``step``, and
    ``time``; remaining fields are the caller's scalars.  Writes go
    through a single ``os.write`` on an ``O_APPEND`` fd, so concurrent
    writers interleave whole lines and a killed writer leaves at most one
    torn FINAL line — which :func:`read_metrics` skips — never a corrupt
    middle.  Host-side events registered on ``telemetry`` (kernel
    degradation, probe failures) are drained into the stream as their own
    rows, and any drained ``degraded`` event also marks the NEXT metric
    row with ``degraded=1`` so a plain metrics consumer sees it too.

    ``csv_path`` mirrors metric rows (not event rows) to a CSV whose
    header is fixed by the first row.  ``tensorboard_dir`` mirrors scalar
    fields via ``jax.profiler``'s summary writer when TensorBoard is
    importable — missing TB never fails training.
    """

    def __init__(
        self,
        directory: str,
        *,
        filename: str = "metrics.jsonl",
        csv_path: str | None = None,
        tensorboard_dir: str | None = None,
        registry: Telemetry | None = None,
    ) -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._registry = registry if registry is not None else telemetry
        self._csv_path = csv_path
        self._csv_fields: list[str] | None = None
        self._tb = None
        if tensorboard_dir is not None:
            try:  # pragma: no cover - TB optional in CI
                from torch.utils.tensorboard import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception:
                try:
                    from tensorboardX import SummaryWriter  # type: ignore

                    self._tb = SummaryWriter(tensorboard_dir)
                except Exception:
                    self._tb = None

    def _append(self, row: dict[str, Any]) -> None:
        data = (json.dumps(row, sort_keys=True) + "\n").encode()
        os.write(self._fd, data)  # O_APPEND: one atomic whole-line append

    def log(self, step: int, **metrics: Any) -> dict[str, Any]:
        """Write one metric row (plus any pending event rows); scalars are
        coerced to host floats/ints (a device array forces a sync — call
        this at your logging cadence, not every step)."""
        pending = self._registry.drain_events()
        degraded = 0
        for ev in pending:
            self._append({"schema": SCHEMA_VERSION, **ev})
            if ev.get("event") == "degraded":
                degraded += 1
        mono, wall = _monotonic_wall()
        row: dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "step": int(step),
            "time": round(wall, 3),
            "mono": round(mono, 6),
        }
        if degraded:
            row["degraded"] = degraded
        for key, val in metrics.items():
            row[key] = _to_scalar(val)
        self._append(row)
        if self._csv_path is not None:
            self._write_csv(row)
        if self._tb is not None:  # pragma: no cover - TB optional
            for key, val in row.items():
                if isinstance(val, (int, float)) and key not in (
                    "schema", "step", "time", "mono",
                ):
                    self._tb.add_scalar(key, val, int(step))
        return row

    def _write_csv(self, row: dict[str, Any]) -> None:
        first = self._csv_fields is None
        if first:
            self._csv_fields = sorted(row)
        with open(self._csv_path, "a", newline="") as f:
            writer = csv.DictWriter(
                f, fieldnames=self._csv_fields, extrasaction="ignore"
            )
            if first:
                writer.writeheader()
            writer.writerow(row)

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
        if self._tb is not None:  # pragma: no cover
            self._tb.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _to_scalar(val: Any) -> Any:
    """Host scalar from python/numpy/jax values; strings/lists pass through."""
    if isinstance(val, (str, bool, int, float)) or val is None:
        return val
    if isinstance(val, (list, tuple, dict)):
        return val
    try:
        f = float(val)
    except (TypeError, ValueError):
        return str(val)
    return int(f) if f.is_integer() and abs(f) < 2**53 else f


def read_metrics(path: str) -> list[dict[str, Any]]:
    """Parse a metrics JSONL file (or a directory holding
    ``metrics.jsonl``), skipping torn/garbage lines — the reader half of
    the killed-writer contract."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    rows: list[dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line from a killed writer
            if isinstance(row, dict):
                rows.append(row)
    return rows


# ----------------------------------------------------------------------
# Numerics flight recorder
# ----------------------------------------------------------------------

# Flight-dump schema.  v1: {"schema", "trigger": {"kind", "time", ...},
# "context", "rows": [last-N metric rows, oldest first], "events"}.
# v2: + "spans" (last-N open/closed span rows from the active
# utils/tracing.py tracer — the incident's local timeline) and "mono"
# monotonic stamps alongside every "time" wall stamp.
FLIGHT_SCHEMA_VERSION = 2


class FlightRecorder:
    """Rolling ring buffer of the last N metric rows + host events, dumped
    as one JSON file when something goes wrong.

    A NaN at step 40k is useless as a bare counter; what the operator
    needs is the preceding trajectory — was ``grad_norm`` trending up for
    2k steps (diverging run) or flat until one step (bad batch / hardware
    fault)?  The recorder keeps that trajectory in memory at O(window)
    cost and writes it only on a trigger:

    - **nonfinite-skip** — :meth:`observe_step` watches the
      :class:`TrainMetrics` carry and dumps when a step is skipped or the
      nonfinite counter advances;
    - **degradation / retry exhaustion** — :meth:`install` registers
      listeners on ``resilience.degradation`` and the ``with_retries``
      failure hook;
    - **crash (incl. RetraceError)** — wrap the loop in :meth:`guard`;
      any escaping exception dumps before re-raising.

    Dumps are atomic (write-then-rename, like ``utils/checkpoint.py``) so
    a crash mid-dump can never leave a torn file, and each trigger gets
    its own numbered file — a cascade (NaN then crash) keeps both.
    ``context`` (static run config: mesh shape, hop config, remat policy)
    rides along in every dump.  Format: docs/observability.md
    §Observatory.
    """

    def __init__(
        self,
        directory: str,
        *,
        window: int = 64,
        registry: Telemetry | None = None,
        context: dict[str, Any] | None = None,
        max_dumps_per_trigger: int = 5,
        span_window: int = 32,
    ) -> None:
        if window < 1:
            raise ValueError(
                f"FlightRecorder: window must be >= 1, got {window}"
            )
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.span_window = span_window
        self._rows: collections.deque = collections.deque(maxlen=window)
        self._events: collections.deque = collections.deque(maxlen=window)
        self._registry = registry if registry is not None else telemetry
        self._context = dict(context or {})
        self._lock = threading.Lock()
        self._last_nonfinite: int | None = None  # set by first observe_step
        self._last_skipped: int | None = None
        self._n_dumps = 0
        # per-trigger-kind cap: a run that goes permanently non-finite
        # must not write one dump per step for the rest of the run — the
        # first few carry the diagnostic value, the rest are disk burn
        self._max_per_trigger = max_dumps_per_trigger
        self._per_trigger: dict[str, int] = {}
        self.suppressed: dict[str, int] = {}
        self.dumps: list[str] = []

    # -- feeding the buffer ------------------------------------------------

    def record(self, step: int, **metrics: Any) -> None:
        """Append one metric row (host-coerced scalars) to the window."""
        mono, wall = _monotonic_wall()
        row = {"step": int(step), "time": round(wall, 3),
               "mono": round(mono, 6)}
        for key, val in metrics.items():
            row[key] = _to_scalar(val)
        with self._lock:
            self._rows.append(row)

    def note_event(self, kind: str, **fields: Any) -> None:
        """Append a host-side event (checkpoint saved, lr change) to the
        window without going through the global registry."""
        mono, wall = _monotonic_wall()
        with self._lock:
            self._events.append(
                {"event": kind, "time": round(wall, 3),
                 "mono": round(mono, 6), **fields}
            )

    def observe_step(self, step: int, metrics: "TrainMetrics") -> str | None:
        """Record this step's :class:`TrainMetrics` row and dump when it
        shows trouble: the step was skipped, or the nonfinite counter
        advanced (unguarded runs — the update was applied anyway).

        Reading the metrics forces a device sync; call at your logging
        cadence, or per step in loops that already block each step.
        Returns the dump path when a dump was triggered, else None.
        """
        row = {
            "loss": _to_scalar(metrics.loss),
            "grad_norm": _to_scalar(metrics.grad_norm),
            "step_ok": bool(metrics.step_ok),
            "skipped": int(metrics.skipped),
            "nonfinite": int(metrics.nonfinite),
        }
        self.record(step, **row)
        # watermarks seed from the FIRST observed row: a resumed run
        # whose checkpoint carried nonzero skipped/nonfinite counters
        # must not false-alarm on its first healthy step (step_ok still
        # catches a genuinely-bad first step)
        if self._last_skipped is None:
            self._last_skipped = row["skipped"]
            self._last_nonfinite = row["nonfinite"]
        trigger = None
        if row["skipped"] > self._last_skipped or not row["step_ok"]:
            trigger = "nonfinite_skip"
        elif row["nonfinite"] > self._last_nonfinite:
            trigger = "nonfinite_applied"
        self._last_skipped = row["skipped"]
        self._last_nonfinite = row["nonfinite"]
        if trigger is None:
            return None
        return self.dump(trigger, step=step, loss=row["loss"],
                         grad_norm=row["grad_norm"])

    # -- triggers ----------------------------------------------------------

    def dump(self, trigger: str, **detail: Any) -> str | None:
        """Write the window to ``flight_NNN_<trigger>.json`` atomically and
        return the path; ``None`` when nothing was written — either the
        write failed (never raises: a full disk must not mask the
        original fault; the failure lands as an event row in the next
        dump) or this trigger kind already hit ``max_dumps_per_trigger``
        (``suppressed`` counts what was withheld)."""
        mono, wall = _monotonic_wall()
        tracer = _active_tracer()
        spans = tracer.last_spans(self.span_window) if tracer else []
        with self._lock:
            count = self._per_trigger.get(trigger, 0)
            if self._max_per_trigger and count >= self._max_per_trigger:
                if trigger not in self.suppressed:
                    self._events.append({
                        "event": "flight_dumps_capped", "trigger": trigger,
                        "limit": self._max_per_trigger,
                        "time": round(wall, 3),
                        "mono": round(mono, 6),
                    })
                self.suppressed[trigger] = self.suppressed.get(trigger, 0) + 1
                return None
            self._per_trigger[trigger] = count + 1
            self._n_dumps += 1
            payload = {
                "schema": FLIGHT_SCHEMA_VERSION,
                "trigger": {
                    "kind": trigger,
                    "time": round(wall, 3),
                    "mono": round(mono, 6),
                    **{k: _to_scalar(v) for k, v in detail.items()},
                },
                "context": dict(self._context),
                "rows": list(self._rows),
                "events": list(self._events)
                + list(self._registry.events()),
                # the incident's local timeline: the last-N closed spans
                # plus everything still open on the active tracer — what
                # the process was DOING when the trigger fired, not just
                # what its counters said
                "spans": spans,
            }
            safe = "".join(
                c if c.isalnum() or c in "-_" else "_" for c in trigger
            )[:40]
            path = os.path.join(
                self.directory, f"flight_{self._n_dumps:03d}_{safe}.json"
            )
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:
            # return None, not the path: a caller printing "dump at X"
            # for a file that was never written sends the operator
            # chasing a ghost.  The cap slot is refunded — N failed
            # writes (disk briefly full) must not silence the trigger
            # kind for the rest of the run.
            with self._lock:
                self._per_trigger[trigger] -= 1
            self.note_event("flight_dump_failed", path=path, error=str(e))
            return None
        with self._lock:
            self.dumps.append(path)
        return path

    @contextlib.contextmanager
    def guard(self, label: str = "train_loop") -> Iterator["FlightRecorder"]:
        """Dump on any escaping exception (``RetraceError``, OOM, a bug),
        then re-raise — the crash arrives with its trajectory attached."""
        try:
            yield self
        except BaseException as e:
            self.dump(
                "crash", label=label,
                error=f"{type(e).__name__}: {e}"[:500],
            )
            raise

    def install(self) -> "FlightRecorder":
        """Wire the automatic host-side triggers: every kernel degradation
        and every exhausted ``with_retries`` ladder dumps the window.
        Idempotent; returns self for chaining.  The registries are
        process-global — call :meth:`uninstall` when the recorder's run
        ends before the process does (tests, config sweeps), or dead
        recorders keep dumping into stale directories forever."""
        try:
            from . import resilience
        except ImportError:  # standalone file-path load
            return self
        resilience.degradation.add_listener(self._on_degraded)
        resilience.add_failure_listener(self._on_retry_exhausted)
        return self

    def uninstall(self) -> "FlightRecorder":
        """Detach the :meth:`install` listeners (no-op if never
        installed)."""
        try:
            from . import resilience
        except ImportError:
            return self
        resilience.degradation.remove_listener(self._on_degraded)
        resilience.remove_failure_listener(self._on_retry_exhausted)
        return self

    def _on_degraded(self, component: str, reason: str) -> None:
        self.dump("degraded", component=component, reason=reason)

    def _on_retry_exhausted(self, where: str, error: str) -> None:
        self.dump("retry_exhausted", where=where, error=error)


def read_flight_dump(path: str) -> dict[str, Any]:
    """Parse one flight dump, with a loud error naming an unknown schema
    (forward-compat: readers must not silently misread a v3 dump).
    v1 dumps (no "spans"/"mono") stay readable — the additions were
    backward compatible; the reader normalizes them with an empty
    "spans" list."""
    with open(path) as f:
        payload = json.load(f)
    schema = payload.get("schema")
    if schema not in (1, FLIGHT_SCHEMA_VERSION):
        raise ValueError(
            f"read_flight_dump: {path} has schema {schema!r}; this reader "
            f"understands <= {FLIGHT_SCHEMA_VERSION}"
        )
    payload.setdefault("spans", [])
    return payload


# ----------------------------------------------------------------------
# MFU / FLOP / comms accounting
# ----------------------------------------------------------------------


def device_peak_tflops(device: Any = None) -> float:
    """bf16 peak TFLOPs of ``device`` (default: ``jax.devices()[0]``);
    unknown kinds fall back to the v5e figure — bench.py's convention."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device)).lower()
    return next((v for k, v in PEAK_TFLOPS.items() if k in kind), 197.0)


def device_ici_gbps(device: Any = None) -> float:
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device)).lower()
    return next((v for k, v in ICI_GBPS.items() if k in kind), 186.0)


def flash_attention_flops(
    seq_q: int,
    seq_k: int | None = None,
    *,
    heads: int,
    dim_head: int,
    causal: bool = False,
    backward: bool = False,
    batch: int = 1,
) -> float:
    """Analytic FLOPs of one flash-attention call.

    Two matmuls forward (``q@k^T`` and ``p@v``, each
    ``2 * seq_q * seq_k * dim_head`` MACs-as-FLOPs per head); backward
    recomputes scores and adds the 4 gradient matmuls (dv, dp, dq, dk) —
    7 matmuls total, bench.py's ``FWDBWD_MATMULS``.  ``causal`` halves the
    work (only the lower triangle is computed).  Softmax/normalization
    vector work is excluded by convention — MFU counts MXU work.
    """
    if seq_k is None:
        seq_k = seq_q
    matmuls = FWDBWD_MATMULS if backward else FWD_MATMULS
    flops = matmuls * 2.0 * seq_q * seq_k * heads * dim_head * batch
    return flops * 0.5 if causal else flops


def transformer_step_flops(
    n_params: int,
    tokens: int,
    *,
    depth: int,
    heads: int,
    dim_head: int,
    seq_len: int,
    causal: bool = True,
    batch: int = 1,
) -> float:
    """Analytic FLOPs of one train step (fwd+bwd) of a dense transformer.

    The standard ``6 * params * tokens`` matmul estimate (2 fwd + 4 bwd
    FLOPs per param per token) plus the attention score/grad matmuls the
    param count does not see (:func:`flash_attention_flops` per layer).
    Good to ~10% for MFU trend lines; the measured truth is
    ``compiled.cost_analysis()`` where the backend provides it.
    """
    dense = 6.0 * float(n_params) * float(tokens)
    attn = depth * flash_attention_flops(
        seq_len, heads=heads, dim_head=dim_head, causal=causal,
        backward=True, batch=batch,
    )
    return dense + attn


def achieved_mfu(flops: float, seconds: float, peak_tflops: float) -> float:
    """Model FLOPs utilization: achieved / peak, in [0, ~1]."""
    if seconds <= 0 or peak_tflops <= 0:
        return 0.0
    return (flops / seconds / 1e12) / peak_tflops


def compiled_cost(compiled: Any) -> dict[str, float]:
    """Best-effort ``cost_analysis()`` of a compiled executable:
    ``{"xla_flops": ..., "bytes_accessed": ...}`` (empty when the backend
    offers no analysis — never raises)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out: dict[str, float] = {}
        if ca.get("flops"):
            out["xla_flops"] = float(ca["flops"])
        if ca.get("bytes accessed"):
            out["bytes_accessed"] = float(ca["bytes accessed"])
        return out
    except Exception:  # noqa: BLE001 — diagnostics must never fail a run
        return {}


def compiled_memory(compiled: Any) -> dict[str, int]:
    """Best-effort ``memory_analysis()`` of a compiled executable: the
    compiler's own peak-memory accounting (``temp_bytes`` is the scratch
    high-water mark — the number the blockwise-FFN/remat knobs exist to
    shrink), as ``{"temp_bytes", "argument_bytes", "output_bytes",
    "alias_bytes"(+host_* when a host memory space is in play)}``.  Empty
    when the backend offers no analysis — never raises.  Works on the CPU
    backend too, which is what lets bench.py's ``train1m`` phase prove the
    chunked-FFN memory claim on a wedged-TPU round (docs/memory.md)."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return {}
        out: dict[str, int] = {}
        for attr, key in (
            ("temp_size_in_bytes", "temp_bytes"),
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
        ):
            v = getattr(ma, attr, None)
            if v is not None:
                out[key] = int(v)
        for attr, key in (
            ("host_temp_size_in_bytes", "host_temp_bytes"),
            ("host_argument_size_in_bytes", "host_argument_bytes"),
            ("host_output_size_in_bytes", "host_output_bytes"),
        ):
            v = getattr(ma, attr, None)
            if v:  # host figures are 0 unless offload is active
                out[key] = int(v)
        return out
    except Exception:  # noqa: BLE001 — diagnostics must never fail a run
        return {}


def train_memory_estimate(
    *,
    seq_len: int,
    dim: int,
    depth: int,
    heads: int,
    vocab: int,
    n_params: int,
    batch: int = 1,
    ff_mult: int = 4,
    dtype_bytes: int = 2,
    ff_chunk_size: int | None = None,
    loss_chunk_size: int | None = None,
    remat_policy: str | None = None,
    offload_opt_state: bool = False,
    shard_opt_data: int = 1,
    seq_shards: int = 1,
    compute_dtype: str | None = None,
) -> dict[str, Any]:
    """Analytic per-chip peak-HBM model of one rematted train step.

    The measured truth is ``compiled_memory()`` of the actual executable;
    this formula exists so bench.py can print an estimate for shapes it
    did not compile (the 1M-token target on a wedged-TPU round) and so a
    config can be sanity-checked against a chip's HBM before burning a
    hardware window.  Terms (per chip, sequence split ``seq_shards``-ways):

    - params: weights (model dtype) + Adam moments (2x f32) + f32 grads,
      moments dropped from HBM when ``offload_opt_state``, divided
      ``shard_opt_data``-ways when ZeRO-1 sharding spreads them over the
      data axes (``make_train_step(shard_opt_state=True)``; pass the
      full data-parallel world — both tiers of a hierarchical mesh);
    - saved per layer: the two rematted block inputs ``2*(b, n, dim)``,
      plus the policy's keeps (``save_attn``: ``(b, n, dim)`` out +
      f32 ``(b, h, n)`` lse; ``offload_attn`` keeps those on host);
    - transient peak: the largest single recompute working set —
      the FFN intermediate ``(b, n_or_chunk, mult*dim)`` (THE term
      ``ff_chunk_size`` shrinks), the CE logits ``(b, n_or_chunk, vocab)``
      f32 (``loss_chunk_size``), and the flash workspace (bucket-local,
      negligible at these scales).
    """
    n = seq_len // max(seq_shards, 1)
    b = batch
    act = dtype_bytes

    params_bytes = n_params * act + n_params * 4  # weights + f32 grads
    opt_bytes = (
        0 if offload_opt_state
        else 2 * n_params * 4 // max(int(shard_opt_data), 1)
    )
    saved = 2 * b * n * dim * act  # the two block inputs per layer
    policy = remat_policy or "nothing_saveable"
    if policy in ("save_attn", "save_attn_and_ffn_inputs"):
        saved += b * n * dim * act + b * heads * n * 4  # flash_out + lse
    if policy in ("save_ffn_inputs", "save_attn_and_ffn_inputs"):
        saved += b * n * dim * act  # ffn_in
    saved *= depth

    ff_n = min(ff_chunk_size, n) if ff_chunk_size else n
    ce_n = min(loss_chunk_size, n) if loss_chunk_size else n
    transient = max(
        b * ff_n * ff_mult * dim * act,  # FFN intermediate (+grad twin)
        b * ce_n * vocab * 4,  # CE logits in f32
    ) * 2  # forward value + its cotangent live together in backward

    total = params_bytes + opt_bytes + saved + transient
    # the attention matmul feed (per layer, transient): one q/k/v copy at
    # the compute operand width — int8 quarters/halves these (PR 13,
    # docs/precision.md) while the f32 online-softmax accumulator state
    # is INVARIANT (the contract the precision auditor proves); reported
    # as dedicated keys, not folded into the peak (the FFN/CE transients
    # above dominate it at every modeled shape)
    operand_bytes = 1 if compute_dtype == "int8" else act
    attn_operand_bytes = 3 * b * n * dim * operand_bytes
    attn_accumulator_bytes = b * n * (dim + 2 * heads) * 4
    return {
        "peak_hbm_bytes": int(total),
        "peak_hbm_gb": round(total / 2**30, 3),
        "params_bytes": int(params_bytes + opt_bytes),
        "saved_activation_bytes": int(saved),
        "transient_bytes": int(transient),
        "compute_dtype": compute_dtype,
        "attn_operand_bytes": int(attn_operand_bytes),
        "attn_accumulator_bytes": int(attn_accumulator_bytes),
    }


def ring_comms_accounting(
    *,
    ring_size: int,
    seq_len: int,
    kv_heads: int,
    dim_head: int,
    ulysses_size: int = 1,
    heads: int | None = None,
    dtype_bytes: int = 2,
    batch: int = 1,
    depth: int = 1,
    passes: int | None = None,
    causal: bool = True,
    peak_tflops: float | None = None,
    ici_gbps: float | None = None,
    counter_rotate: bool = False,
    hop_compression: str | None = None,
    compute_dtype: str | None = None,
    impl: str = "scan",
) -> dict[str, Any]:
    """Topology-aware per-step communication accounting for a
    (ring x ulysses) sequence-parallel factoring (TASP, arXiv 2509.26541).

    All numbers are analytic — derived from shapes and the mesh factoring,
    so they cost nothing to log every step:

    - ``ring_hops`` — inter-device transfers in one attention call's
      latency chain: ``passes - 1`` (the last hop's rotation is elided).
      The pure-ring equivalent at the same world is
      ``ring_size * ulysses_size - 1`` (``pure_ring_hops``) — PR 3's
      "ulysses x fewer hops" claim as a logged number.
    - ``hop_bytes`` — K+V bytes ppermuted per hop per device (the ring
      circulates kv-head-sized blocks of the post-all-to-all chunk).
      With ``hop_compression="int8"`` the payload is int8 values + four
      bitcast f32 scale bytes per ``(head, token)`` row, so this shrinks
      ``dtype_bytes * dim_head / (dim_head + 4)``-fold (~3.8x from f32 at
      d=64; the contract ``analysis/contracts.py`` pins the same formula
      against traced payloads).
    - ``ring_bytes_per_step`` — per device, forward only; backward
      circulates (k, v) plus f32 (dk, dv) accumulators (~3x with default
      ``dkv_dtype``), reported as ``ring_bytes_per_step_bwd``.
    - ``a2a_bytes_per_step`` — Ulysses leg: q in + out back per device
      (kv rides :func:`~..parallel.ulysses.kv_head_reshard`'s all-gather,
      counted as ``a2a_kv_bytes``).
    - ``hop_overlap_fraction`` — analytic per-hop compute time at peak
      over max(compute, transfer at ICI bandwidth): 1.0 means the hop's
      flash compute fully hides the transfer (the overlap the reference
      lacks); < 1.0 means the ring is transfer-bound at these shapes.

    ``counter_rotate=True`` accounts the TokenRing schedule
    (``parallel/ring.py::_counter_fwd``): the forward alternates Q-pack
    rotations (f32 ``[q | acc | m | l]``, reported as ``q_pack_bytes``)
    one ring direction with KV rotations the other, plus one out/lse
    catch-up; the backward circulates only the q-side pack with KV and
    dKV resident.  Extra keys:

    - ``fwd_collectives`` / ``bwd_collectives`` — ppermutes per attention
      call (baseline ``passes - 1`` / ``2 * (passes - 1) + 1``; counter
      ``passes`` / ``passes`` — one extra forward, repaid in backward).
    - ``fwd_link_direction_bytes`` — the busier ICI direction's forward
      rotation traffic per device: the counter schedule splits the
      payloads across both full-duplex directions, the baseline loads one.

    ``compute_dtype="int8"`` (PR 13, the quantized QK^T/PV kernel path,
    ``docs/precision.md``) accounts the matmul FEED rather than the wire:
    ``matmul_operand_bytes`` — the q/k/v operand bytes one hop's kernels
    read, at 1 byte/element instead of ``dtype_bytes`` — and the per-hop
    compute time in the overlap model runs at the int8 MXU rate (~2x the
    bf16 peak on v5e/v5p), so ``hop_overlap_fraction`` reflects that a
    quantized hop has HALF the compute available to hide the same
    transfer.  ``accumulator_bytes`` — the f32 ``(acc, m, l)`` state —
    is emitted under every compute_dtype and is invariant by
    construction: the contract the precision auditor proves.

    ``impl`` selects the analytic execution model the numbers describe:

    - ``"scan"`` (default) — one kernel launch per hop
      (``parallel/ring.py``'s scanned/unrolled schedule): ``passes``
      launches per forward, a :data:`DISPATCH_OVERHEAD_S` dispatch term
      per launch, and the per-hop transfer exposed to the launch boundary
      — the overlap denominator is
      ``max(compute, transfer + dispatch)``, because a transfer finishing
      inside the next launch's dispatch window hides nothing.
    - ``"fused"`` — the single-launch fused ring
      (``ops/pallas_ring.py``): the analytic hop count (``ring_hops``,
      the data that must move) is IDENTICAL, but ``kernel_launches``
      drops to 1, ``dispatch_overhead_s`` to 0.0, ``fwd_collectives`` to
      0 (hops are in-kernel remote DMAs, not ppermutes — the contract
      row pins this), and the overlap denominator loses the dispatch
      term: ``max(compute, transfer)``, the model ``overlap_report``
      holds a fused capture against.  ``counter_rotate`` has no fused
      form and raises.
    """
    if heads is None:
        heads = kv_heads
    if impl not in ("scan", "fused"):
        raise ValueError(
            f"ring_comms_accounting: impl={impl!r}; want \"scan\" (one "
            'launch per hop) or "fused" (single-launch fused ring)'
        )
    if impl == "fused" and counter_rotate:
        raise ValueError(
            "ring_comms_accounting: counter_rotate has no fused form — "
            "the alternating Q/KV schedule cannot ride one kernel launch "
            '(parallel/ring.py raises on the same combination)'
        )
    if hop_compression not in (None, "int8"):
        raise ValueError(
            f"ring_comms_accounting: hop_compression={hop_compression!r}; "
            'want None or "int8" (parallel/ring.py accepts the same values)'
        )
    if compute_dtype not in (None, "int8"):
        raise ValueError(
            f"ring_comms_accounting: compute_dtype={compute_dtype!r}; "
            'want None or "int8" (parallel/ring.py accepts the same values)'
        )
    world = ring_size * ulysses_size
    if seq_len % world:
        raise ValueError(
            f"ring_comms_accounting: seq_len {seq_len} must divide over "
            f"the {world}-device sequence-parallel world"
        )
    if passes is None:
        passes = ring_size
    passes = min(passes, ring_size)
    # resident shard and post-all-to-all ring chunk lengths
    n_chunk = seq_len // ring_size  # what the ring circulates / attends
    hops = max(passes - 1, 0)
    pure_ring_hops = max(world - 1, 0)
    # the ring moves the device's kv-head block of the chunk each hop;
    # int8 compression ships 1-byte values + 4 bitcast f32 scale bytes
    # per (head, token) row in the same single payload
    kv_heads_local = max(kv_heads // max(ulysses_size, 1), 1)
    if hop_compression == "int8":
        hop_bytes = 2 * batch * kv_heads_local * n_chunk * (dim_head + 4)
    else:
        hop_bytes = (
            2 * batch * kv_heads_local * n_chunk * dim_head * dtype_bytes
        )
    heads_local = max(heads // max(ulysses_size, 1), 1)
    if counter_rotate:
        # forward: ceil((P-1)/2) Q-pack rotations one direction,
        # floor((P-1)/2) KV rotations the other, + one out/lse catch-up
        # (f32 [out | lse], rides the KV direction as a composed permute)
        q_pack_bytes = 4 * batch * heads_local * n_chunk * (2 * dim_head + 2)
        q_rots = (passes - 1 + 1) // 2 if passes > 1 else 0
        kv_rots = (passes - 1) // 2
        catchup = (
            4 * batch * heads_local * n_chunk * (dim_head + 1)
            if (passes // 2) % max(ring_size, 1)
            else 0
        )
        fwd_collectives = hops + (1 if catchup else 0)
        ring_bytes = q_rots * q_pack_bytes + kv_rots * hop_bytes + catchup
        fwd_dir_bytes = max(
            q_rots * q_pack_bytes, kv_rots * hop_bytes + catchup
        )
        # backward: ONE f32 [q | do | dq | lse | delta] pack circulates;
        # (k, v) and the f32 (dk, dv) accumulators stay resident
        bwd_pack = 4 * batch * heads_local * n_chunk * (3 * dim_head + 2)
        bwd_collectives = passes
        ring_bytes_bwd = hops * bwd_pack + (
            4 * batch * heads_local * n_chunk * dim_head  # dq catch-up
        )
        worst_hop_bytes = max(hop_bytes, q_pack_bytes)
    else:
        q_pack_bytes = 0
        fwd_collectives = hops
        bwd_collectives = max(2 * passes - 1, 0)
        ring_bytes = hops * hop_bytes
        fwd_dir_bytes = ring_bytes  # everything rides one link direction
        # backward recirculates exact-dtype (k, v) + f32 (dk, dv): the
        # compressed forward payload never enters the backward ring
        kv_exact = 2 * batch * kv_heads_local * n_chunk * dim_head
        ring_bytes_bwd = hops * (kv_exact * dtype_bytes + kv_exact * 4)
        worst_hop_bytes = hop_bytes
    n_local = seq_len // world
    a2a_bytes = (
        2 * batch * heads * n_local * dim_head * dtype_bytes
        if ulysses_size > 1 else 0
    )
    a2a_kv_bytes = (
        2 * batch * kv_heads * n_local * dim_head * dtype_bytes
        * max(ulysses_size - 1, 0)
        if ulysses_size > 1 else 0
    )
    # analytic overlap: one full hop's flash compute vs its transfer
    hop_flops = flash_attention_flops(
        n_chunk, n_chunk, heads=heads_local, dim_head=dim_head,
        causal=False, batch=batch,
    )
    if causal:
        hop_flops *= 0.5  # averaged over hops, half the band is masked
    if peak_tflops is None:
        try:
            peak_tflops = device_peak_tflops()
        except Exception:  # noqa: BLE001 — accounting must not need a device
            peak_tflops = PEAK_TFLOPS["v5e"]
    if ici_gbps is None:
        try:
            ici_gbps = device_ici_gbps()
        except Exception:  # noqa: BLE001
            ici_gbps = ICI_GBPS["v5e"]
    # int8 matmuls run at ~2x the bf16 MXU rate (v5e/v5p), so a quantized
    # hop finishes its compute in half the time — less of it available to
    # hide the same ICI transfer
    matmul_peak = peak_tflops * (2.0 if compute_dtype == "int8" else 1.0)
    compute_s = hop_flops / (matmul_peak * 1e12)
    # the counter schedule's worst rotation is whichever circulating
    # payload is larger (Q-pack vs KV handle); baseline it's the KV hop
    transfer_s = worst_hop_bytes / (ici_gbps * 1e9)
    # launch model: the scan path pays a dispatch boundary per hop that
    # the transfer cannot hide behind; the fused ring has no boundary
    if impl == "fused":
        kernel_launches = 1
        dispatch_overhead_s = 0.0
        exposed_s = transfer_s
    else:
        kernel_launches = passes
        dispatch_overhead_s = DISPATCH_OVERHEAD_S * passes
        exposed_s = transfer_s + DISPATCH_OVERHEAD_S
    overlap = compute_s / max(compute_s, exposed_s, 1e-30)
    # the matmul feed (per hop per device): q read once + the held k/v
    # span, at the compute operand width; the f32 (acc, m, l) state is
    # the invariant the precision auditor pins — never quantized
    operand_bytes = 1 if compute_dtype == "int8" else dtype_bytes
    matmul_operand_bytes = (
        batch * heads_local * n_chunk * dim_head
        + 2 * batch * kv_heads_local * n_chunk * dim_head
    ) * operand_bytes
    accumulator_bytes = 4 * batch * heads_local * n_chunk * (dim_head + 2)
    if impl == "fused":
        # hops are in-kernel remote DMAs: the forward issues ZERO
        # ppermutes (analysis/contracts.py::check_fused_ring_contract);
        # the backward retains the scan-path schedule (exact residuals)
        fwd_collectives = 0
    return {
        "impl": impl,
        "kernel_launches": kernel_launches,
        "dispatch_overhead_s": dispatch_overhead_s,
        "ring_size": ring_size,
        "ulysses_size": ulysses_size,
        "counter_rotate": counter_rotate,
        "hop_compression": hop_compression,
        "compute_dtype": compute_dtype,
        "matmul_operand_bytes": matmul_operand_bytes,
        "accumulator_bytes": accumulator_bytes,
        "ring_hops": hops,
        "pure_ring_hops": pure_ring_hops,
        "ring_hops_per_step": hops * depth * 2,  # fwd + bwd rings
        "hop_bytes": hop_bytes,
        "q_pack_bytes": q_pack_bytes,
        "fwd_collectives": fwd_collectives,
        "bwd_collectives": bwd_collectives,
        "fwd_link_direction_bytes": fwd_dir_bytes * depth,
        "ring_bytes_per_step": ring_bytes * depth,
        "ring_bytes_per_step_bwd": ring_bytes_bwd * depth,
        "a2a_bytes_per_step": a2a_bytes * depth * 2,
        "a2a_kv_bytes": a2a_kv_bytes * depth,
        "hop_overlap_fraction": round(overlap, 4),
    }


# ----------------------------------------------------------------------
# Diagnostic attention summaries (exact, blockwise, opt-in)
# ----------------------------------------------------------------------


def attention_logit_summaries(
    q: Any,
    k: Any,
    *,
    scale: float | None = None,
    causal: bool = False,
    bucket_size: int = 512,
    softclamp_value: float | None = None,
) -> dict[str, Any]:
    """Exact max-logit and mean softmax-entropy of ``softmax(q @ k^T)``.

    Max attention logits drifting up is the canonical early-warning for
    attention-entropy collapse (and the thing ``softclamp_value`` exists
    to bound); row entropy collapsing toward 0 means degenerate one-hot
    attention.  Computed in an online blockwise sweep — memory is one
    ``(nq, bucket)`` tile, never ``(nq, nk)`` — tracking per-row
    ``(m, l, t)`` where ``t = sum exp(s - m) * s`` gives the exact
    entropy ``H = lse - t / l`` without a second pass.

    This is an EXTRA O(n^2 d) pass over scores: run it on a probe batch
    every N steps (or feed the result to ``telemetry.observe``), never
    inside the hot train step.  jit-compatible; differentiation is
    blocked (``stop_gradient``) — these are diagnostics, not losses.

    Returns ``{"max_logit", "softmax_entropy", "softmax_entropy_min"}``
    (f32 scalars: global max, mean row entropy in nats, min row entropy).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops.attention import MASK_VALUE, softclamp

    q = lax.stop_gradient(q)
    k = lax.stop_gradient(k)
    b, h, nq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    nk = k.shape[2]
    if scale is None:
        scale = d**-0.5
    bk = min(bucket_size, nk)
    while nk % bk:
        bk -= 1
    qg = q.reshape(b, hk, g, nq, d).astype(jnp.float32)
    ks = jnp.moveaxis(
        k.reshape(b, hk, nk // bk, bk, d), 2, 0
    ).astype(jnp.float32)

    rows = jnp.arange(nq)

    def body(carry, xs):
        m, l, t = carry
        k_j, j = xs
        s = jnp.einsum("bhgid,bhjd->bhgij", qg, k_j) * scale
        if softclamp_value is not None:
            s = softclamp(s, softclamp_value)
        visible = None
        if causal:
            cols = j * bk + jnp.arange(bk)
            visible = (
                cols[None, None, None, None, :]
                <= (nk - nq + rows)[None, None, None, :, None]
            )
            s = jnp.where(visible, s, MASK_VALUE)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if visible is not None:
            # exact zeros (a fully-masked tile would otherwise leave
            # p = exp(0) = 1 rows) and s zeroed in the entropy product so
            # MASK_VALUE never multiplies into t (it would overflow f32)
            p = jnp.where(visible, p, 0.0)
            s = jnp.where(visible, s, 0.0)
        l = l * alpha + p.sum(-1)
        t = t * alpha + (p * s).sum(-1)
        return (m_new, l, t), None

    m0 = jnp.full((b, hk, g, nq), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, hk, g, nq), jnp.float32)
    (m, l, t), _ = lax.scan(
        body, (m0, l0, l0), (ks, jnp.arange(nk // bk))
    )
    l = jnp.maximum(l, 1e-30)
    lse = m + jnp.log(l)
    entropy = lse - t / l  # H = lse - E_p[s], exact
    return {
        "max_logit": m.max(),
        "softmax_entropy": entropy.mean(),
        "softmax_entropy_min": entropy.min(),
    }
