"""Honest wall-clock timing under async dispatch.

JAX dispatch is asynchronous, and some transports (this image's TPU
tunnel among them) additionally make ``jax.block_until_ready`` a no-op
and let independently-enqueued executions complete out of order.  Any
timing loop built on ``block_until_ready`` can then report numbers that
are hundreds of times the hardware peak.  The only measurement that
survives such a transport is:

1. run all iterations *inside one executable*, chained by a real data
   dependency (``lax.scan`` whose carry feeds the next step),
2. synchronize by fetching a scalar derived from the result (a value
   fetch must round-trip), and
3. subtract the separately measured fetch round trip (min of several
   samples, so one latency spike cannot eat the measurement).

These helpers implement that recipe; ``bench.py`` builds on them.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Sequence

try:
    from .tracing import perf_counter as _perf_counter
except ImportError:  # standalone file-path load (bench parent)
    _perf_counter = time.perf_counter

__all__ = ["enable_compile_cache", "fetch_rtt", "timed_chained"]


def enable_compile_cache(cache_dir: str | None = None) -> str:
    """Point jax's persistent executable cache at ``cache_dir`` (default:
    ``.jax_cache_tpu/`` in the repo root).

    On the flaky TPU tunnel, long relay compiles are the wedge risk
    (``docs/hardware_log.md``): with the cache, each program's compile
    only has to succeed ONCE across worker subprocesses and resumed
    hardware sessions.  Shared by ``bench.py`` and the ``tools/``
    hardware scripts so they all hit one cache."""
    import jax

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            ".jax_cache_tpu",
        )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    # cache every executable over the time threshold regardless of size
    # (the hop-sequence/train programs are exactly the large ones)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return cache_dir


def fetch_rtt(samples: int = 3) -> float:
    """Seconds for one host<->device scalar fetch (min over ``samples``)."""
    import jax.numpy as jnp

    from . import compat

    f = compat.jit(lambda x: x + 1)
    _ = float(f(jnp.float32(0)))  # compile outside the timed region
    best = float("inf")
    for i in range(samples):
        t0 = _perf_counter()
        _ = float(f(jnp.float32(i)))
        best = min(best, _perf_counter() - t0)
    return best


def timed_chained(
    chained_fn: Callable[..., object],
    args: Sequence[object],
    iters: int,
    *,
    return_value: bool = False,
) -> tuple[float, float] | tuple[float, float, float]:
    """(compile_seconds, seconds_per_iteration[, value]) for a chained run.

    ``chained_fn`` must be a jitted callable that runs ``iters``
    data-dependent iterations on device and returns a scalar (convertible
    with ``float``); with ``return_value=True`` that scalar is returned
    too.  Raises ``RuntimeError`` if the measured time is not above the
    fetch round trip — a nonsense number is worse than no number.
    """
    t0 = _perf_counter()
    _ = float(chained_fn(*args))
    first_total = _perf_counter() - t0
    rtt = fetch_rtt()
    t0 = _perf_counter()
    value = float(chained_fn(*args))
    total = _perf_counter() - t0
    if total <= rtt:
        raise RuntimeError(
            f"measurement ({total * 1e3:.1f} ms) not above fetch RTT "
            f"({rtt * 1e3:.1f} ms); increase iters"
        )
    # the first call is compile + one full execution of the chain
    compile_s = max(first_total - total, 0.0)
    per_iter = (total - rtt) / iters
    if return_value:
        return compile_s, per_iter, value
    return compile_s, per_iter
