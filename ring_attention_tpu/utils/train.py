"""Training-step composition: gradient accumulation + optimizer sharding.

The reference leaves the training loop to the user (its ``assert.py`` test
driver wraps models in DDP and calls ``loss.backward()`` by hand,
ref ``assert.py:97-137``); at long-context scale the loop itself becomes
framework territory — a quarter-million-token batch rarely fits activation
memory at the global batch size the optimizer wants, and Adam moments for
a replicated model are the next thing to blow HBM after activations.

Two composable pieces, both pure functions over pytrees so they nest
inside ``jit``/``shard_map`` like everything else here:

- :func:`make_train_step` — one optimizer step over ``accum_steps``
  microbatches, grads averaged in f32 via a ``lax.scan`` (sequential
  activation peaks, one weight update).
- :func:`shard_optimizer_state` — ZeRO-1-style: spread optimizer-moment
  arrays across a mesh axis with ``with_sharding_constraint`` (parameters
  stay replicated; XLA inserts the gather around the update).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class StepStats(NamedTuple):
    """Per-step resilience telemetry carried through the guarded step.

    ``step_ok`` — whether THIS step's update was applied (False: non-finite
    loss or gradients were detected and the optimizer update was skipped).
    ``skipped`` — running count of skipped steps since
    :func:`init_step_stats`; a handful per multi-hour run is survivable
    noise, a growing streak means the run has diverged and should stop.
    """

    step_ok: jax.Array  # bool scalar
    skipped: jax.Array  # int32 scalar


def init_step_stats() -> StepStats:
    return StepStats(
        step_ok=jnp.asarray(True), skipped=jnp.asarray(0, jnp.int32)
    )


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: Any,
    *,
    accum_steps: int = 1,
    skip_nonfinite: bool = False,
    clip_grad_norm: float | None = None,
    jit_donate: bool = False,
    collect_metrics: bool = False,
    offload_opt_state: bool = False,
    offload_mesh: Mesh | None = None,
    shard_opt_state: bool = False,
    shard_mesh: Mesh | None = None,
    on_step_end: Callable[..., None] | None = None,
) -> Callable:
    """Build ``step(params, opt_state, *batch) -> (params, opt_state, loss)``.

    ``loss_fn(params, *microbatch)`` must return a scalar.  Each array in
    ``batch`` is split along its leading axis into ``accum_steps`` equal
    microbatches; gradients are accumulated in float32 and averaged, then
    applied in ONE optimizer update — the activation-memory peak is one
    microbatch's, the optimizer sees the full-batch gradient.  With
    ``accum_steps=1`` this is a plain fused value-and-grad step.

    The returned step is jit-compatible and mesh-agnostic: microbatching
    slices the leading (batch) axis only, so data/sequence shardings on
    the non-leading axes pass through untouched.

    Resilience options (``utils/resilience.py`` is the companion test
    harness; see ``docs/resilience.md``):

    - ``clip_grad_norm`` — clip the (full-batch) gradient to this global
      L2 norm before the update, the standard guard against loss spikes.
    - ``skip_nonfinite=True`` — the guarded step: when the loss or any
      gradient is non-finite the optimizer update is SKIPPED inside the
      jitted step (params and optimizer state pass through bit-identical)
      instead of corrupting the parameters; one poisoned batch then costs
      one step, not the run.  The step signature changes to
      ``step(params, opt_state, stats, *batch) ->
      (params, opt_state, stats, loss)`` where ``stats`` is a
      :class:`StepStats` carry seeded by :func:`init_step_stats` —
      ``stats.step_ok`` reports this step, ``stats.skipped`` counts all
      skips.  The returned loss is NOT masked on a skipped step, so logs
      show the offending value.
    - ``jit_donate=True`` — return the step already jit-compiled with
      ``(params, opt_state)`` donated (``utils/compat.py jit``): XLA
      reuses their buffers for the updated state instead of
      double-allocating — at long context the Adam moments are the next
      HBM cliff after activations.  Callers jitting by hand should pass
      ``donate_argnums=(0, 1)`` themselves.
    - ``offload_opt_state=True`` — opt-in host offload of the optimizer
      state (``docs/memory.md``): the updated state is transferred into
      the backend's host memory space (``pinned_host``) inside the step,
      so the Adam moments — 2 model-sized f32 buffers — stop occupying
      HBM between steps.  Seed the loop by placing the initial state
      there too: ``opt_state = compat.host_device_put(opt.init(params),
      mesh)``.  Placement preserves each leaf's sharding (a ZeRO-1
      sharded state stays sharded on host); ``offload_mesh`` only feeds
      the replicated fallback on jax builds without
      ``TransferToMemoryKind``.
      On backends without an addressable host space (jax 0.4.x CPU) the
      transfer is the identity and the step is unchanged — the
      graceful-degradation contract every compat shim follows; the
      placement is auditable via ``analysis.recompile.audit_host_offload``
      and ``tools/check_contracts.py --memory``.
    - ``collect_metrics=True`` — the instrumented step
      (``utils/telemetry.py``): the signature becomes
      ``step(params, opt_state, metrics, *batch) ->
      (params, opt_state, metrics, loss)`` where ``metrics`` is a
      :class:`~.telemetry.TrainMetrics` carry seeded by
      :func:`~.telemetry.init_train_metrics` holding this step's loss and
      pre-clip global gradient norm plus running skipped/nonfinite
      counters.  Composes with ``skip_nonfinite`` (the metrics carry then
      *replaces* the ``StepStats`` argument — it is a superset).  Every
      metric derives from values the step already computes, so
      instrumentation adds no collectives to the compiled program
      (pinned by ``tests/test_telemetry.py``).
    - ``shard_opt_state=True`` — ZeRO-1 optimizer-state sharding
      (``shard_mesh`` required): every eligible opt-state leaf gets a
      data-axis ``with_sharding_constraint`` inside the step (both tiers
      — ``("dcn_data", "data")`` — on a hierarchical mesh), via
      :func:`shard_optimizer_state`.  Adam's two model-sized f32 moment
      buffers then cost ``1/data_world`` HBM per chip; gradients and
      parameters stay replicated, XLA inserts the gather around the
      update.  Seed the loop the same way::

          opt_state = shard_optimizer_state(opt.init(params), mesh)

      Composes with ``offload_opt_state`` (constrain FIRST, then park on
      host — a sharded state stays sharded in host memory) and with the
      elastic checkpoint manager (each process saves only its shard
      group of the now-sharded moments; restore re-scatters).  Audited
      by ``analysis/recompile.audit_donation`` / ``audit_host_offload``
      and pinned in ``tests/test_elastic.py``.
    - ``on_step_end`` — a HOST callback ``on_step_end(outputs)`` invoked
      after every step call with the step's full output tuple.  This is
      the hook the elastic runtime hangs off (``elastic/``): the async
      checkpointer snapshots state from it and ``PreemptionGuard`` checks
      its drain flag — neither belongs inside the compiled program.  The
      callback runs OUTSIDE the jitted step, after dispatch: the output
      arrays are handed over un-fetched, so a callback that only inspects
      Python state adds no device sync (one that reads values forces the
      step to finish, same as any host read).  Unset, this is a strict
      no-op: the returned step is the exact same callable, not a wrapper.
      When set, the wrapper exposes the undecorated step as
      ``step.__wrapped__`` — ``tests/test_elastic.py`` pins that its
      compiled program carries the identical collective sequence to the
      hookless step (the hook adds zero collectives by construction).
      Do NOT wrap the hooked step in an outer ``jax.jit`` (the hook
      would be traced away); the wrapper detects tracing and raises —
      jit ``step.__wrapped__`` or pass ``jit_donate=True`` instead.
    """
    if accum_steps < 1:
        raise ValueError(f"make_train_step: accum_steps must be >= 1, got {accum_steps}")
    if clip_grad_norm is not None and clip_grad_norm <= 0:
        raise ValueError(
            f"make_train_step: clip_grad_norm must be > 0, got {clip_grad_norm}"
        )
    if shard_opt_state and shard_mesh is None:
        raise ValueError(
            "make_train_step: shard_opt_state=True needs shard_mesh= "
            "(the mesh whose data axis the optimizer state shards over)"
        )
    grad_fn = jax.value_and_grad(loss_fn)

    def compute_update(params, opt_state, *batch):
        if accum_steps == 1:
            loss, grads = grad_fn(params, *batch)
        else:
            def split(x):
                n = x.shape[0]
                if n % accum_steps:
                    raise ValueError(
                        f"make_train_step: leading batch dim {n} not "
                        f"divisible by accum_steps={accum_steps}"
                    )
                return x.reshape(accum_steps, n // accum_steps, *x.shape[1:])

            micro = jax.tree.map(split, batch)
            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )

            def body(carry, mb):
                acc, loss_sum = carry
                loss, grads = grad_fn(params, *mb)
                acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), acc, grads
                )
                return (acc, loss_sum + loss), None

            (gsum, loss_sum), _ = lax.scan(
                body, (zeros, jnp.float32(0.0)), micro
            )
            inv = 1.0 / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g * inv).astype(p.dtype), gsum, params
            )
            loss = loss_sum * inv

        # one global norm serves clipping, the non-finite guard, AND the
        # metrics carry: any NaN/inf in any leaf propagates into it, and
        # clipping by a finite factor keeps non-finite values non-finite,
        # so checking the pre-clip norm is equivalent to post-clip
        gnorm = (
            optax.global_norm(grads)
            if (clip_grad_norm is not None or skip_nonfinite
                or collect_metrics)
            else None
        )
        if clip_grad_norm is not None:
            clip = jnp.minimum(
                1.0, clip_grad_norm / jnp.maximum(gnorm, 1e-12)
            )
            grads = jax.tree.map(
                lambda g: (g * clip).astype(g.dtype), grads
            )

        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt_state, loss, gnorm

    def place_opt(opt_state):
        # placement runs LAST in the step (after any skip-guard select):
        # ZeRO-1 data-axis constraint first (per-program, so the
        # partitioner keeps the moments sharded), then the host offload
        # — a sharded state stays sharded in host memory; both are
        # no-ops when their knob is off
        if shard_opt_state:
            from ..parallel.mesh import data_partition

            opt_state = shard_optimizer_state(
                opt_state, shard_mesh, axis=data_partition(shard_mesh)
            )
        if not offload_opt_state:
            return opt_state
        from . import compat

        return compat.host_device_put(opt_state, offload_mesh)

    def finish(step):
        if jit_donate:
            from . import compat

            step = compat.jit(step, donate_argnums=(0, 1))
        if on_step_end is None:
            return step  # strict no-op: the very same callable
        import functools

        @functools.wraps(step)
        def stepped(*args, **kwargs):
            out = step(*args, **kwargs)
            # a host hook baked into a trace would fire ONCE at compile
            # time and never again — the drain check / async snapshot it
            # exists for would silently stop running.  Fail loudly
            # instead of being traced away.
            if any(isinstance(x, jax.core.Tracer)
                   for x in jax.tree_util.tree_leaves(out)):
                raise RuntimeError(
                    "make_train_step(on_step_end=...): the hooked step "
                    "was traced by an outer jax.jit, which would "
                    "silently drop the host hook. jit the inner step "
                    "instead (step.__wrapped__), or build with "
                    "jit_donate=True so make_train_step jits it for you."
                )
            on_step_end(out)
            return out

        stepped.__wrapped__ = step  # the lowerable inner step (HLO pin)
        return stepped

    if not skip_nonfinite and not collect_metrics:

        def step(params, opt_state, *batch):
            new_params, new_opt_state, loss, _ = compute_update(
                params, opt_state, *batch
            )
            return new_params, place_opt(new_opt_state), loss

        return finish(step)

    def apply_or_skip(ok, new_params, new_opt_state, params, opt_state):
        if not skip_nonfinite:
            return new_params, new_opt_state

        def keep_old(new, old):
            return jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new, old
            )

        # jnp.where with the old value on the skip branch is bit-identical
        # (no arithmetic touches the kept params) — the property the
        # fault-injection suite asserts
        return (
            keep_old(new_params, params), keep_old(new_opt_state, opt_state)
        )

    if not collect_metrics:

        def guarded_step(params, opt_state, stats: StepStats, *batch):
            new_params, new_opt_state, loss, gnorm = compute_update(
                params, opt_state, *batch
            )
            # one scalar covers every gradient leaf: any NaN/inf propagates
            # into the global norm (see compute_update)
            ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
            params, opt_state = apply_or_skip(
                ok, new_params, new_opt_state, params, opt_state
            )
            stats = StepStats(
                step_ok=ok,
                skipped=stats.skipped + jnp.where(ok, 0, 1).astype(jnp.int32),
            )
            return params, place_opt(opt_state), stats, loss

        return finish(guarded_step)

    from .telemetry import TrainMetrics

    def metric_step(params, opt_state, metrics: TrainMetrics, *batch):
        new_params, new_opt_state, loss, gnorm = compute_update(
            params, opt_state, *batch
        )
        finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)
        # step_ok reports whether the update was APPLIED: without the
        # guard every step applies; nonfinite still counts the poison
        ok = finite if skip_nonfinite else jnp.asarray(True)
        params, opt_state = apply_or_skip(
            finite, new_params, new_opt_state, params, opt_state
        )
        one = jnp.asarray(1, jnp.int32)
        zero = jnp.asarray(0, jnp.int32)
        metrics = TrainMetrics(
            loss=loss.astype(jnp.float32),
            grad_norm=gnorm.astype(jnp.float32),
            step_ok=ok,
            skipped=metrics.skipped
            + (jnp.where(finite, zero, one) if skip_nonfinite else zero),
            nonfinite=metrics.nonfinite + jnp.where(finite, zero, one),
        )
        return params, place_opt(opt_state), metrics, loss

    return finish(metric_step)


def shard_optimizer_state(
    opt_state: Any, mesh: Mesh, axis: str | tuple = "data"
) -> Any:
    """ZeRO-1-style optimizer-state sharding over one or more mesh axes.

    Every float array in ``opt_state`` whose leading dimension divides by
    the axis size gets ``with_sharding_constraint(P(axis))`` on that
    dimension; everything else (step counters, odd shapes) stays
    replicated.  ``axis`` may be a tuple of mesh axis names — on a
    hierarchical mesh pass ``("dcn_data", "data")`` (or just
    :func:`~ring_attention_tpu.parallel.mesh.data_partition`) so the
    moments spread over the FULL data-parallel world, both tiers.  Apply
    once to the freshly-initialized state AND inside the jitted step to
    the updated state (constraints guide the partitioner per-program) —
    or build the step with ``make_train_step(shard_opt_state=True,
    shard_mesh=mesh)``, which does the in-step half for you::

        opt_state = shard_optimizer_state(opt.init(params), mesh)

        @jax.jit
        def step(params, opt_state, batch):
            ...
            opt_state = shard_optimizer_state(opt_state, mesh)
            return params, opt_state, loss

    Adam on a replicated model keeps 2 extra model-sized f32 buffers; over
    a ``data=8`` axis this drops per-chip moment memory 8x while gradients
    and parameters stay replicated (the reference has no equivalent — its
    DDP replicates optimizer state per rank).
    """
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    entry = tuple(axes) if len(axes) > 1 else axes[0]

    def constrain(x):
        if (
            isinstance(x, jax.Array)
            and x.ndim >= 1
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.shape[0] % size == 0
            and x.shape[0] > 0
        ):
            spec = P(entry, *([None] * (x.ndim - 1)))
            return lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec)
            )
        return x

    return jax.tree.map(constrain, opt_state)
