"""JAX version compatibility shims (part of the resilience layer).

The kernels target current JAX (``jax.typeof`` varying-axes metadata,
top-level ``jax.shard_map`` with ``check_vma``), but CI and dev boxes can
run older releases where those APIs don't exist yet — and a framework
whose import crashes on the CPU-only box that would have caught a bug is
not resilient.  Each shim degrades to the semantically-equivalent older
API; where the newer API only adds metadata that old JAX cannot represent
(vma), the fallback is the identity, which is exactly what old JAX's
``shard_map`` assumes.
"""

from __future__ import annotations

from typing import Any

import jax


def typeof(x: Any):
    """``jax.typeof`` (new) or the abstract value (old) — both expose
    shape/dtype; only the new one carries ``vma``, and every caller here
    reads ``vma`` via ``getattr(..., frozenset())``."""
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    return jax.core.get_aval(x)


def pcast(x: Any, axes, to: str = "varying"):
    """``lax.pcast`` when it exists; identity otherwise.

    Callers only reach this with non-empty ``axes`` when :func:`typeof`
    reported varying-axes metadata — which old JAX never does, so the
    identity fallback is unreachable there by construction (kept total
    anyway: resilience code must not be the thing that crashes)."""
    from jax import lax

    fn = getattr(lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` (new, ``check_vma``) or
    ``jax.experimental.shard_map.shard_map`` (old, ``check_rep``).

    The two kwargs gate the same per-output replication/varying checker
    across the rename.  On old JAX the checker is force-disabled: its
    replication-rule table predates primitives this codebase relies on
    (``checkpoint_name`` residuals raise ``NotImplementedError: No
    replication rule for name``), and a checker that crashes working
    programs is strictly worse than no checker — new-JAX CI keeps the
    real ``check_vma`` coverage.
    """
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as old_shard_map

    return old_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def jit(fn, *, donate_argnums=(), **kwargs):
    """``jax.jit`` with buffer donation, degrading to no donation.

    Donation is an aliasing hint — XLA reuses the donated input buffers
    for outputs instead of double-allocating (the train loop's
    ``(params, opt_state)`` are exactly the buffers whose copies would
    otherwise double peak optimizer-state memory).  Old/exotic jax builds
    that reject the kwarg fall back to a plain jit: the program is then
    merely less memory-efficient, never wrong."""
    if donate_argnums:
        try:
            return jax.jit(fn, donate_argnums=donate_argnums, **kwargs)
        except TypeError:
            pass
    return jax.jit(fn, **kwargs)


def profiler_trace(logdir: str):
    """``jax.profiler.trace(logdir)`` across the 0.4.x → 0.5+ surface.

    The context-manager form exists everywhere this repo runs, but newer
    releases grew extra keyword defaults (``create_perfetto_link``/
    ``create_perfetto_trace``) whose *absence* is the portable spelling —
    and on builds without the context manager at all, the start/stop pair
    is composed into one here.  Callers go through
    ``utils/profiling.trace`` (docs/observability.md §Observatory); this
    shim is the single place a profiler entry-point difference may live.
    """
    cm = getattr(jax.profiler, "trace", None)
    if cm is not None:
        return cm(logdir)

    import contextlib

    @contextlib.contextmanager
    def _fallback():
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()

    return _fallback()


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (new name) or ``pltpu.TPUCompilerParams``
    (old name) — same dataclass across the rename; every field this repo
    passes (``dimension_semantics``) exists in both."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def host_memory_kind() -> str | None:
    """The device-addressable host memory space ("pinned_host" on TPU/GPU
    builds with offload support), or None when the backend exposes none.

    CPU backends report only "unpinned_host" — which IS host memory
    already, so "offloading" there is meaningless and callers correctly
    degrade to the identity.  Every probe failure (old jax without
    ``addressable_memories``, exotic backends) reads as "no host space":
    offload is an optimization and must never be the thing that crashes.
    """
    try:
        kinds = {
            m.kind
            for d in jax.local_devices()
            for m in d.addressable_memories()
        }
    except Exception:  # noqa: BLE001 — any probe failure means "unsupported"
        return None
    return "pinned_host" if "pinned_host" in kinds else None


def host_sharding(sharding):
    """``sharding`` moved into the host memory space, or None when this
    backend has no host space / the sharding cannot express memory kinds
    (old jax).  Callers treat None as "keep the buffer where it is"."""
    kind = host_memory_kind()
    if kind is None:
        return None
    try:
        return sharding.with_memory_kind(kind)
    except (AttributeError, ValueError):
        return None


def host_device_put(tree, mesh=None):
    """Move every array leaf of ``tree`` into host memory, PRESERVING its
    sharding; the identity when the backend has no host memory space.

    This is the jax-0.4.x-safe offload primitive: ``jax.device_put`` onto
    a memory-kind target is the documented in-graph transfer
    (``with_sharding_constraint`` did not learn memory kinds until later
    releases).  Placement keeps each leaf's partitioning — a ZeRO-1
    sharded optimizer state stays sharded on host, never silently
    re-replicated N-x:

    - concrete leaves (seeding the loop outside jit) move via their own
      ``sharding.with_memory_kind``;
    - traced leaves (inside the step) move via ``TransferToMemoryKind``,
      which changes only the memory space and lets the partitioner keep
      the layout it chose; ``mesh`` is only the replicated fallback for
      jax builds without it.

    Used by ``make_train_step(offload_opt_state=True)`` for the Adam
    moments — the next HBM cliff after activations (docs/memory.md).
    """
    from jax.sharding import (
        NamedSharding,
        PartitionSpec,
        SingleDeviceSharding,
    )

    kind = host_memory_kind()
    if kind is None:
        return tree

    def fallback_sharding():
        if mesh is not None:
            return NamedSharding(mesh, PartitionSpec(), memory_kind=kind)
        return SingleDeviceSharding(jax.devices()[0], memory_kind=kind)

    def place(x):
        if isinstance(x, jax.core.Tracer):
            try:  # private in 0.4.x (public jax.sharding export came later)
                from jax._src.sharding_impls import TransferToMemoryKind

                return jax.device_put(x, TransferToMemoryKind(kind))
            except Exception:  # noqa: BLE001 — degrade, never crash
                return jax.device_put(x, fallback_sharding())
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            try:
                return jax.device_put(x, sharding.with_memory_kind(kind))
            except (AttributeError, ValueError):
                pass
        return jax.device_put(x, fallback_sharding())

    return jax.tree.map(place, tree)


def bound_axis_names():
    """Every mesh axis name bound at this point of the trace, in mesh
    binding order — or None when the axis environment is unreadable.

    The fused remote tier needs MESH-coordinate device ids (a coordinate
    per mesh axis, not just the ring axis: a LOGICAL id built from the
    ring coordinate alone addresses the wrong device on any multi-axis
    mesh).  ``get_axis_env().axis_sizes`` is an insertion-ordered dict of
    bound axes on every jax this repo supports; its private home moved
    across releases, and a None here just means "no coordinate table",
    which callers treat as "take the gathered-KV local tier instead" —
    introspection failure must degrade, never crash."""
    for mod in ("jax._src.core", "jax.core"):
        try:
            import importlib

            env = importlib.import_module(mod).get_axis_env()
            return tuple(env.axis_sizes.keys())
        except Exception:  # noqa: BLE001 — degrade, never crash
            continue
    return None


def axis_size(axis_name):
    """``lax.axis_size`` (new) or the bound axis frame's size (old).

    Both return a static Python int inside ``shard_map``, so callers can
    keep using it for loop bounds and shape arithmetic."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return getattr(frame, "size", frame)
