"""Span-based distributed tracing: the cluster's flight-data substrate.

PR 15 made the runtime multi-process (DCN mesh, elastic checkpoints,
watchdog, chaos) but observability stayed per-process: JSONL metrics and
an xplane profiler can say *this* process was slow, never *which rank
stalled the barrier* or *what the whole cluster was doing in the five
seconds before the exit-114*.  This module adds the missing layer:

* **Spans** — named intervals with a ``trace_id`` / ``span_id`` / parent
  hierarchy (thread-local nesting) and **dual timestamps**: a monotonic
  reading for durations (immune to NTP steps) and a wall reading for
  cross-process alignment.  Both are read back-to-back by
  :func:`monotonic_wall`, the one timestamp helper the rest of the
  package routes through (lint rule RA014 enforces the seam).
* **Per-process span files** — ``spans_pNNNNN.jsonl``, O_APPEND exactly
  like ``MetricsLogger``: one :func:`os.write` per line is atomic, so a
  process killed mid-write (chaos, preemption, OOM) leaves at most one
  torn final line, which :func:`read_spans` skips.  A span row is
  emitted when the span *closes*; :meth:`Tracer.instant` rows and
  :meth:`Tracer.flush_open` (called on abort paths before ``os._exit``)
  are durable the moment the write returns.
* **The merger** — :func:`merge_trace_dir` joins every process's file
  into one cluster timeline.  Wall clocks skew across hosts, so each
  process stamps a ``rendezvous`` row as it *exits* a shared coordinator
  barrier (all processes leave a barrier at nearly the same true
  instant); :func:`clock_offsets` averages the per-tag deltas against a
  reference process and the merger adds the offset to every wall time.
* **Renderers** — :func:`render_timeline` (text table),
  :func:`to_chrome_trace` (Chrome trace-event / Perfetto JSON; load in
  ``chrome://tracing`` or https://ui.perfetto.dev), and
  :func:`reconstruct_incident` (a chaos kill or watchdog abort becomes
  an annotated "what was everyone doing" dump — victim, fault window,
  stragglers — from the span files alone).
* **:class:`LatencyHistogram`** — fixed log-spaced buckets (64 buckets,
  1 µs lower edge, x sqrt(2) per bucket) so per-token decode latencies
  recorded on different processes **merge associatively** by elementwise
  add; percentiles are deterministic integers (a bucket upper edge in
  ns), which is what lets ``analysis/perfgate.py`` pin them as an exact
  gate family.

Stdlib-only at module level (like telemetry/resilience): tools load this
file standalone by path, and nothing here may import jax.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import math
import os
import threading
import time
import uuid
from typing import Any, Iterator

#: Stamped on every span row.  Bump when a field is renamed or changes
#: meaning; readers skip rows from schemas they don't understand.
TRACE_SCHEMA_VERSION = 1

#: Per-process span file name: ``spans_p00000.jsonl`` etc.
SPAN_FILE_PREFIX = "spans_p"
SPAN_FILE_SUFFIX = ".jsonl"

#: Environment hooks: workers (tests/elastic_worker.py, chaos fleets)
#: opt into tracing by env so the parent needs no per-worker plumbing.
TRACE_DIR_ENV = "RING_ATTN_TRACE_DIR"

#: Instant-row names the incident reconstructor anchors on.
INCIDENT_ANCHORS = ("chaos/kill", "watchdog/abort")


# ----------------------------------------------------------------------
# The timestamp seam (lint RA014 routes host clock reads through here)
# ----------------------------------------------------------------------


def monotonic_wall() -> tuple[float, float]:
    """One ``(monotonic, wall)`` pair read back-to-back — the dual
    timestamp every span and telemetry row carries.  Monotonic orders
    and measures within a process (NTP-step immune); wall aligns across
    processes after :func:`clock_offsets` correction."""
    return time.monotonic(), time.time()


def wall() -> float:
    """Wall-clock seconds (``time.time``) — for mtime comparisons and
    human-facing stamps, never for durations."""
    return time.time()


def monotonic() -> float:
    """Monotonic seconds — for deadlines and durations."""
    return time.monotonic()


def perf_counter() -> float:
    """Highest-resolution monotonic counter — for benchmark timing."""
    return time.perf_counter()


# ----------------------------------------------------------------------
# Tracer
# ----------------------------------------------------------------------


class SpanHandle:
    """The live span a ``with tracer.span(...)`` block holds: carries the
    ids and start stamps; ``set(**attrs)`` attaches attributes that ride
    the row emitted at close."""

    __slots__ = ("span_id", "parent_id", "name", "mono", "wall", "attrs")

    def __init__(self, span_id: int, parent_id: int | None, name: str,
                 mono: float, wall_s: float, attrs: dict[str, Any]):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.mono = mono
        self.wall = wall_s
        self.attrs = attrs

    def set(self, **attrs: Any) -> "SpanHandle":
        self.attrs.update(attrs)
        return self


class _NullHandle:
    """The no-op handle the null tracer yields: accepts attributes and
    drops them."""

    __slots__ = ()
    span_id = None
    parent_id = None
    name = ""

    def set(self, **attrs: Any) -> "_NullHandle":
        return self


_NULL_HANDLE = _NullHandle()


class NullTracer:
    """The unconfigured default: every call is a cheap no-op so library
    instrumentation never needs an ``if tracing:`` guard."""

    enabled = False
    process = 0
    trace_id = ""

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[_NullHandle]:
        yield _NULL_HANDLE

    def instant(self, name: str, **attrs: Any) -> None:
        pass

    def rendezvous(self, tag: str) -> None:
        pass

    def flush_open(self, reason: str = "") -> None:
        pass

    def last_spans(self, n: int = 32) -> list[dict[str, Any]]:
        return []

    def close(self) -> None:
        pass


NULL = NullTracer()


class Tracer:
    """Per-process span writer.

    One O_APPEND fd per process (``spans_pNNNNN.jsonl``); every emitted
    row is a single atomic :func:`os.write` so concurrent threads
    interleave whole lines and a kill tears at most the final line.
    Span rows are emitted at close (start stamps + duration); open spans
    live in memory until then — :meth:`flush_open` persists them with
    ``kind="open"`` on abort paths, and :meth:`last_spans` hands the
    recent window (open + closed) to ``FlightRecorder.dump``.

    A write failure (full disk) drops the row and counts it in
    ``dropped`` — tracing must never take down the run it observes.
    """

    enabled = True

    def __init__(self, directory: str | os.PathLike, *, process: int = 0,
                 trace_id: str | None = None, keep: int = 256) -> None:
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.process = int(process)
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.path = os.path.join(
            self.directory,
            f"{SPAN_FILE_PREFIX}{self.process:05d}{SPAN_FILE_SUFFIX}",
        )
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._lock = threading.Lock()
        self._local = threading.local()
        self._n = 0
        self._open: dict[int, SpanHandle] = {}
        self._recent: list[dict[str, Any]] = []
        self._keep = max(int(keep), 1)
        self.dropped = 0
        mono, wall_s = monotonic_wall()
        self._emit({
            "kind": "process", "name": "process", "span": self._next_id(),
            "parent": None, "mono": mono, "wall": wall_s,
            "attrs": {"pid": os.getpid()},
        })

    # -- plumbing ------------------------------------------------------

    def _next_id(self) -> int:
        with self._lock:
            self._n += 1
            return self._n

    def _stack(self) -> list[SpanHandle]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _emit(self, row: dict[str, Any]) -> None:
        full = {
            "schema": TRACE_SCHEMA_VERSION,
            "trace": self.trace_id,
            "proc": self.process,
            **row,
        }
        data = (json.dumps(full, sort_keys=True) + "\n").encode()
        with self._lock:
            try:
                os.write(self._fd, data)  # O_APPEND: one atomic line
            except OSError:
                self.dropped += 1
                return
            self._recent.append(full)
            if len(self._recent) > self._keep:
                del self._recent[: len(self._recent) - self._keep]

    # -- the span API --------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[SpanHandle]:
        """Open a span; emitted as one row when the block exits.  An
        exception escaping the block stamps ``error=<type name>`` before
        re-raising (a barrier timeout becomes a visible straggler span,
        not a vanished one)."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        mono, wall_s = monotonic_wall()
        handle = SpanHandle(
            self._next_id(), parent, name, mono, wall_s, dict(attrs)
        )
        stack.append(handle)
        with self._lock:
            self._open[handle.span_id] = handle
        try:
            yield handle
        except BaseException as e:
            handle.attrs.setdefault("error", type(e).__name__)
            raise
        finally:
            stack.pop()
            with self._lock:
                self._open.pop(handle.span_id, None)
            dur = time.monotonic() - handle.mono
            self._emit({
                "kind": "span", "name": handle.name,
                "span": handle.span_id, "parent": handle.parent_id,
                "mono": handle.mono, "wall": handle.wall,
                "dur": round(dur, 6), "attrs": handle.attrs,
            })

    def instant(self, name: str, **attrs: Any) -> None:
        """Emit a zero-duration event row immediately (durable before
        any subsequent ``os._exit`` — the chaos kill points rely on
        this)."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        mono, wall_s = monotonic_wall()
        self._emit({
            "kind": "instant", "name": name, "span": self._next_id(),
            "parent": parent, "mono": mono, "wall": wall_s,
            "attrs": dict(attrs),
        })

    def rendezvous(self, tag: str) -> None:
        """Stamp a clock-alignment row: call as this process *exits* a
        shared coordinator barrier — every process leaves the same
        barrier at nearly the same true instant, which is what
        :func:`clock_offsets` needs to cancel wall-clock skew."""
        mono, wall_s = monotonic_wall()
        self._emit({
            "kind": "rendezvous", "name": "rendezvous",
            "span": self._next_id(), "parent": None,
            "mono": mono, "wall": wall_s, "attrs": {"tag": tag},
        })

    def flush_open(self, reason: str = "") -> None:
        """Persist every currently-open span with ``kind="open"`` and
        its duration so far.  Abort paths (watchdog, preemption) call
        this right before ``os._exit`` so the timeline shows what was
        in flight when the process died."""
        with self._lock:
            pending = sorted(self._open.values(), key=lambda h: h.span_id)
        now = time.monotonic()
        for handle in pending:
            self._emit({
                "kind": "open", "name": handle.name,
                "span": handle.span_id, "parent": handle.parent_id,
                "mono": handle.mono, "wall": handle.wall,
                "dur": round(now - handle.mono, 6),
                "attrs": {**handle.attrs,
                          **({"flush": reason} if reason else {})},
            })

    def last_spans(self, n: int = 32) -> list[dict[str, Any]]:
        """The most recent ``n`` emitted rows plus every still-open span
        (as ``kind="open"`` dicts) — the local timeline context a
        ``FlightRecorder`` incident dump carries."""
        now = time.monotonic()
        with self._lock:
            recent = list(self._recent[-n:])
            open_rows = [
                {
                    "schema": TRACE_SCHEMA_VERSION, "trace": self.trace_id,
                    "proc": self.process, "kind": "open",
                    "name": h.name, "span": h.span_id,
                    "parent": h.parent_id, "mono": h.mono, "wall": h.wall,
                    "dur": round(now - h.mono, 6), "attrs": dict(h.attrs),
                }
                for h in sorted(self._open.values(), key=lambda h: h.span_id)
            ]
        return (recent + open_rows)[-max(n, len(open_rows)):]

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None  # type: ignore[assignment]


# ----------------------------------------------------------------------
# Process-global tracer registry
# ----------------------------------------------------------------------

_REGISTRY_LOCK = threading.Lock()
_TRACER: Tracer | None = None


def configure(directory: str | os.PathLike, *, process: int = 0,
              trace_id: str | None = None, keep: int = 256) -> Tracer:
    """Install the process-global tracer (replacing any previous one)."""
    global _TRACER
    with _REGISTRY_LOCK:
        if _TRACER is not None:
            _TRACER.close()
        _TRACER = Tracer(
            directory, process=process, trace_id=trace_id, keep=keep
        )
        return _TRACER


def configure_from_env(process: int | None = None) -> Tracer | None:
    """Install a tracer when ``RING_ATTN_TRACE_DIR`` is set (the worker
    opt-in: chaos fleets pass it via ``extra_env``); returns None and
    changes nothing otherwise."""
    directory = os.environ.get(TRACE_DIR_ENV)
    if not directory:
        return None
    if process is None:
        process = int(os.environ.get("RING_ATTN_TRACE_PROC", "0"))
    return configure(directory, process=process)


def get_tracer() -> Tracer | NullTracer:
    """The installed tracer, or the no-op :data:`NULL` when none is."""
    return _TRACER if _TRACER is not None else NULL


def shutdown() -> None:
    """Close and uninstall the process-global tracer (tests)."""
    global _TRACER
    with _REGISTRY_LOCK:
        if _TRACER is not None:
            _TRACER.close()
            _TRACER = None


# ----------------------------------------------------------------------
# Reading + merging
# ----------------------------------------------------------------------


def read_spans(path: str | os.PathLike) -> list[dict[str, Any]]:
    """Parse one span file, skipping blank/torn/unknown-schema lines —
    a process killed mid-write tears at most the final line, and that
    must never take the whole timeline down with it."""
    rows: list[dict[str, Any]] = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn tail from a killed writer
            if (not isinstance(row, dict)
                    or row.get("schema") != TRACE_SCHEMA_VERSION):
                continue
            rows.append(row)
    return rows


def load_trace_dir(directory: str | os.PathLike) -> dict[int, list[dict]]:
    """Every process's rows, keyed by process index (from the file
    name: ``spans_p00001.jsonl`` -> 1)."""
    by_proc: dict[int, list[dict]] = {}
    for name in sorted(os.listdir(directory)):
        if not (name.startswith(SPAN_FILE_PREFIX)
                and name.endswith(SPAN_FILE_SUFFIX)):
            continue
        stem = name[len(SPAN_FILE_PREFIX):-len(SPAN_FILE_SUFFIX)]
        try:
            proc = int(stem)
        except ValueError:
            continue
        rows = read_spans(os.path.join(directory, name))
        if rows:
            by_proc[proc] = rows
    return by_proc


def clock_offsets(by_proc: dict[int, list[dict]],
                  reference: int | None = None) -> dict[int, float]:
    """Seconds to ADD to each process's wall stamps to align them with
    the reference process (lowest index by default).

    Uses the shared-rendezvous model: every process emits a
    ``rendezvous`` row with the same tag as it exits the same
    coordinator barrier, so for each shared tag the reference's wall
    minus this process's wall estimates the skew; tags are averaged.
    Processes with no shared rendezvous get offset 0.0 (wall clocks on
    one host are already close)."""
    if not by_proc:
        return {}
    ref = min(by_proc) if reference is None else reference
    marks: dict[int, dict[str, float]] = {}
    for proc, rows in by_proc.items():
        marks[proc] = {}
        for row in rows:
            if row.get("kind") == "rendezvous":
                tag = (row.get("attrs") or {}).get("tag")
                if isinstance(tag, str):
                    marks[proc][tag] = float(row["wall"])
    offsets = {proc: 0.0 for proc in by_proc}
    ref_marks = marks.get(ref, {})
    for proc in by_proc:
        if proc == ref:
            continue
        shared = sorted(set(ref_marks) & set(marks[proc]))
        if shared:
            deltas = [ref_marks[t] - marks[proc][t] for t in shared]
            offsets[proc] = sum(deltas) / len(deltas)
    return offsets


def merge_spans(by_proc: dict[int, list[dict]],
                reference: int | None = None) -> dict[str, Any]:
    """The cluster timeline: every row stamped with its corrected start
    time ``t`` (reference-process wall clock) and ``t_end`` for spans,
    sorted by ``t``.  Returns ``{"spans", "offsets", "processes",
    "t0"}`` where ``t0`` is the earliest corrected time (the timeline
    zero every renderer subtracts)."""
    offsets = clock_offsets(by_proc, reference)
    merged: list[dict[str, Any]] = []
    for proc, rows in by_proc.items():
        off = offsets.get(proc, 0.0)
        for row in rows:
            out = dict(row)
            out["proc"] = proc
            out["t"] = float(row["wall"]) + off
            dur = row.get("dur")
            if isinstance(dur, (int, float)):
                out["t_end"] = out["t"] + float(dur)
            merged.append(out)
    merged.sort(key=lambda r: (r["t"], r["proc"], r.get("span", 0)))
    return {
        "spans": merged,
        "offsets": offsets,
        "processes": sorted(by_proc),
        "t0": merged[0]["t"] if merged else 0.0,
    }


def merge_trace_dir(directory: str | os.PathLike,
                    reference: int | None = None) -> dict[str, Any]:
    """:func:`load_trace_dir` + :func:`merge_spans` in one call."""
    return merge_spans(load_trace_dir(directory), reference)


# ----------------------------------------------------------------------
# Renderers
# ----------------------------------------------------------------------


def _fmt_attrs(attrs: dict[str, Any] | None, limit: int = 60) -> str:
    if not attrs:
        return ""
    text = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
    return text if len(text) <= limit else text[: limit - 1] + "…"


def render_timeline(merged: dict[str, Any], *,
                    limit: int | None = None) -> str:
    """The cluster timeline as a text table (one row per span/event,
    times relative to the merged ``t0``)."""
    spans = merged["spans"]
    if limit is not None and limit > 0:
        spans = spans[-limit:]
    t0 = merged.get("t0", 0.0)
    lines = [
        f"cluster timeline: {len(spans)} rows, "
        f"processes {merged.get('processes', [])}, "
        f"offsets {{{', '.join(f'{p}: {o:+.4f}s' for p, o in sorted(merged.get('offsets', {}).items()))}}}",
        f"{'t(s)':>10}  {'proc':>4}  {'kind':<10} {'dur(ms)':>9}  name / attrs",
    ]
    for row in spans:
        dur = row.get("dur")
        dur_txt = f"{dur * 1e3:9.2f}" if isinstance(dur, (int, float)) else " " * 9
        attrs = _fmt_attrs(row.get("attrs"))
        name = row.get("name", "?")
        lines.append(
            f"{row['t'] - t0:10.4f}  p{row['proc']:<3}  "
            f"{row.get('kind', '?'):<10} {dur_txt}  {name}"
            + (f"  [{attrs}]" if attrs else "")
        )
    return "\n".join(lines)


def to_chrome_trace(merged: dict[str, Any]) -> dict[str, Any]:
    """Chrome trace-event JSON (the Perfetto/chrome://tracing format):
    each process is a pid lane, spans are complete ("X") events, instants
    are "i" events, all in microseconds relative to the merged t0."""
    t0 = merged.get("t0", 0.0)
    events: list[dict[str, Any]] = []
    for proc in merged.get("processes", []):
        events.append({
            "ph": "M", "name": "process_name", "pid": proc, "tid": 0,
            "args": {"name": f"process {proc}"},
        })
    for row in merged["spans"]:
        ts = int(round((row["t"] - t0) * 1e6))
        base = {
            "name": row.get("name", "?"), "cat": row.get("kind", "span"),
            "pid": row["proc"], "tid": 0, "ts": ts,
            "args": dict(row.get("attrs") or {}),
        }
        dur = row.get("dur")
        if isinstance(dur, (int, float)):
            events.append({**base, "ph": "X",
                           "dur": int(round(float(dur) * 1e6))})
        else:
            events.append({**base, "ph": "i", "s": "p"})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def reconstruct_incident(merged: dict[str, Any], *,
                         window_s: float = 5.0) -> str | None:
    """The annotated "what was the cluster doing" dump for the last
    incident in the timeline, from span files alone.

    Anchors on the final ``chaos/kill`` / ``watchdog/abort`` instant (a
    flushed-open or errored span marks the victim too, but the kill
    instants are authoritative).  The reconstruction names the victim
    process, the fault window (``chaos/armed`` -> kill), every
    barrier/lock wait that overlapped the incident (the stragglers —
    a ``BarrierTimeout``-errored wait is the survivor watching the
    victim die), and the full timeline slice of the ``window_s``
    seconds before the anchor.  Returns None when no anchor exists.
    """
    spans = merged["spans"]
    t0 = merged.get("t0", 0.0)
    anchors = [r for r in spans
               if r.get("kind") == "instant"
               and r.get("name") in INCIDENT_ANCHORS]
    if not anchors:
        return None
    anchor = anchors[-1]
    victim = anchor["proc"]
    at = anchor["t"]
    attrs = anchor.get("attrs") or {}
    lines = [
        f"INCIDENT: {anchor['name']} on process {victim} "
        f"at t=+{at - t0:.4f}s"
        + (f"  [{_fmt_attrs(attrs)}]" if attrs else ""),
    ]
    armed = [r for r in spans
             if r["proc"] == victim and r.get("name") == "chaos/armed"
             and r["t"] <= at]
    if armed and anchor["name"] == "chaos/kill":
        arm = armed[-1]
        lines.append(
            f"fault window: armed at t=+{arm['t'] - t0:.4f}s "
            f"[{_fmt_attrs(arm.get('attrs'))}] -> kill at "
            f"t=+{at - t0:.4f}s ({at - arm['t']:.4f}s armed)"
        )
    waits = [
        r for r in spans
        if r.get("kind") in ("span", "open")
        and (r.get("name", "").startswith(("barrier/", "lock/"))
             or "barrier" in r.get("name", ""))
        and r.get("t_end", r["t"]) >= at - window_s
    ]
    for r in waits:
        err = (r.get("attrs") or {}).get("error")
        mark = f" -> {err}" if err else ""
        who = "STRAGGLER WATCH" if (err or r.get("kind") == "open") else "wait"
        lines.append(
            f"{who}: process {r['proc']} {r['name']} "
            f"waited {float(r.get('dur') or 0.0) * 1e3:.1f} ms "
            f"(t=+{r['t'] - t0:.4f}s){mark}"
            + (f"  [{_fmt_attrs(r.get('attrs'))}]" if r.get("attrs") else "")
        )
    tail = [r for r in spans if at - window_s <= r["t"] <= at + window_s]
    lines.append(f"timeline (±{window_s:.1f}s around the incident):")
    lines.append(render_timeline({
        "spans": tail, "offsets": merged.get("offsets", {}),
        "processes": merged.get("processes", []), "t0": t0,
    }))
    return "\n".join(lines)


# ----------------------------------------------------------------------
# Mergeable fixed-bucket latency histogram
# ----------------------------------------------------------------------

#: 64 log-spaced buckets: lower edges 1000 ns * sqrt(2)^i — 1 µs up to
#: ~3040 s, ~41 buckets per factor-of-1e6.  The edges are FIXED integers
#: (never derived from data) so histograms recorded on any process in
#: any order merge by elementwise add — associative and commutative —
#: and percentiles are deterministic ints the perf gate can pin exactly.
HIST_BUCKETS = 64
_BASE_NS = 1000
BUCKET_BOUNDS_NS: tuple[int, ...] = tuple(
    int(_BASE_NS * 2 ** (i / 2)) for i in range(HIST_BUCKETS)
)
#: The overflow bucket's reported value (and the last bucket's upper
#: edge): one more sqrt(2) step past the final lower edge.
OVERFLOW_EDGE_NS = int(_BASE_NS * 2 ** (HIST_BUCKETS / 2))
HIST_SCALE = f"ns-pow2half-{HIST_BUCKETS}"


class LatencyHistogram:
    """Fixed-bucket latency histogram with associative merge.

    ``record`` costs one bisect; ``merge`` is elementwise integer add;
    ``percentile_ns(q)`` returns the upper edge (ns, int) of the bucket
    holding the ceil(q% * n)-th sample — a deterministic function of the
    counts, which is what makes p50/p95/p99 pinnable as exact gate
    signals and identical regardless of which process recorded what.
    """

    __slots__ = ("counts", "n", "sum_ns")

    def __init__(self) -> None:
        self.counts = [0] * (HIST_BUCKETS + 1)  # [...buckets..., overflow]
        self.n = 0
        self.sum_ns = 0

    def record(self, seconds: float) -> None:
        self.record_ns(int(seconds * 1e9))

    def record_ns(self, ns: int) -> None:
        ns = max(int(ns), 0)
        b = bisect.bisect_right(BUCKET_BOUNDS_NS, ns) - 1
        self.counts[max(b, 0)] += 1  # sub-µs readings land in bucket 0
        self.n += 1
        self.sum_ns += ns

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Elementwise add ``other`` into self (associative; returns
        self for chaining)."""
        if len(other.counts) != len(self.counts):
            raise ValueError(
                f"LatencyHistogram.merge: bucket count mismatch "
                f"({len(other.counts)} != {len(self.counts)})"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        self.sum_ns += other.sum_ns
        return self

    def percentile_ns(self, q: float) -> int:
        """Upper bucket edge (ns) covering the ceil(q% * n)-th smallest
        sample; 0 when empty.  Overflow samples report
        :data:`OVERFLOW_EDGE_NS`."""
        if self.n == 0:
            return 0
        rank = max(1, math.ceil(q / 100.0 * self.n))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                if i + 1 < HIST_BUCKETS:
                    return BUCKET_BOUNDS_NS[i + 1]
                return OVERFLOW_EDGE_NS
        return OVERFLOW_EDGE_NS  # unreachable: cum == n covers rank

    def percentile_ms(self, q: float) -> float:
        return self.percentile_ns(q) / 1e6

    def mean_ms(self) -> float:
        return (self.sum_ns / self.n) / 1e6 if self.n else 0.0

    def to_dict(self) -> dict[str, Any]:
        """JSON form with sparse counts (most of 65 buckets are empty)."""
        return {
            "scale": HIST_SCALE,
            "n": self.n,
            "sum_ns": self.sum_ns,
            "counts": {str(i): c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "LatencyHistogram":
        scale = data.get("scale")
        if scale != HIST_SCALE:
            raise ValueError(
                f"LatencyHistogram.from_dict: scale {scale!r} != "
                f"{HIST_SCALE!r} — merging across bucket layouts would "
                f"silently mis-bin"
            )
        hist = cls()
        for key, c in (data.get("counts") or {}).items():
            hist.counts[int(key)] = int(c)
        hist.n = int(data.get("n", sum(hist.counts)))
        hist.sum_ns = int(data.get("sum_ns", 0))
        return hist


__all__ = [
    "TRACE_SCHEMA_VERSION",
    "SPAN_FILE_PREFIX",
    "TRACE_DIR_ENV",
    "INCIDENT_ANCHORS",
    "monotonic_wall",
    "wall",
    "monotonic",
    "perf_counter",
    "SpanHandle",
    "NullTracer",
    "NULL",
    "Tracer",
    "configure",
    "configure_from_env",
    "get_tracer",
    "shutdown",
    "read_spans",
    "load_trace_dir",
    "clock_offsets",
    "merge_spans",
    "merge_trace_dir",
    "render_timeline",
    "to_chrome_trace",
    "reconstruct_incident",
    "HIST_BUCKETS",
    "BUCKET_BOUNDS_NS",
    "OVERFLOW_EDGE_NS",
    "HIST_SCALE",
    "LatencyHistogram",
]
