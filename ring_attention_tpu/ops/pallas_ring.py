"""Fused-ring Pallas forward: every ring hop inside ONE kernel launch.

The scan-path ring (``parallel/ring.py``) interleaves per-hop flash kernel
launches with ``lax.ppermute`` KV rotations, leaving XLA to decide how much
of each transfer hides behind compute — PR 8's ``measured_overlap`` exists
precisely because that slack is real.  This module removes the launch
boundary itself, in two tiers:

``fused_ring_local``
    One ``pallas_call`` whose innermost grid dimension walks the certified
    hop schedule (origin / hi / lo / work tables from
    ``parallel/ring.py::_fused_tables``) over an all-gathered KV span,
    carrying the f32 ``(acc, m, l)`` online-softmax state in VMEM scratch
    across every hop — zero per-hop dispatch, zero HBM round-trips of the
    accumulator, zero ``ppermute`` in the forward.  Runs compiled on TPU
    and in interpret mode on CPU (the parity-test tier), and accepts the
    int8 kernel feed from PR 13 (``quant.payload_kernel_feed`` /
    ``quant.quantize_kv_blocks``) so quantized QK^T/PV ride the same
    launch.

``fused_ring_remote``
    The ICI tier: the kernel itself double-buffers the NEXT rank's KV
    shard via async remote DMA (``pltpu.make_async_remote_copy`` into the
    alternate slot of an HBM ring buffer) while the current hop's tiles
    compute.  The circulated buffer and the cross-hop ``(acc, m, l)``
    carry are HBM-resident — compute stages tile-sized blocks through
    VMEM scratch, so the kernel fits arbitrary ``n_local`` — and each
    push is gated by a receiver-to-sender GRANT semaphore (the receiver
    signals its left neighbor once it has drained a slot's last read, so
    compute skew under causal ``works`` schedules can never let a DMA
    overwrite KV mid-read).  Remote descriptors address neighbors by
    per-axis MESH coordinates (:func:`neighbor_mesh_coords`), varying
    only the ring axis — correct on multi-axis (data × seq, hybrid DCN)
    meshes where a ring-rank-only LOGICAL id would target the wrong
    replica group; physical ICI adjacency holds because
    ``parallel/mesh.py::torus_ring_order`` fed mesh construction.  With
    an int8 ``pack_kv`` payload the per-row dequant scales travel inside
    the circulated buffer (bitcast into the trailing ``SCALE_BYTES``
    lanes), so quantized hops need no side-channel collective.  Executes
    on TPU only; on CPU it still *traces* — which is how
    ``analysis/contracts.py`` counts the in-kernel ``dma_start`` /
    semaphore primitives and proves the forward carries zero ppermutes.

Both tiers share ``ops/pallas_flash.py``'s tile math (``_online_update``)
and banded-offset mask contract (attend iff ``lo <= j - i <= hi`` in
per-hop local coordinates), so fused output is tile-order-identical to the
scan path and parity pins can be tight.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import EPSILON, MASK_VALUE
from .pallas_flash import (
    _block_sizes,
    _interpret_default,
    _online_update,
    _sds,
    _unify_vma,
)
from . import quant as _quant
from .quant import QuantizedBlockKV
from ..utils import compat
from ..utils.validate import check_attention_args

# One collective_id per concurrently-live barrier semaphore (Mosaic
# requirement); the fused ring is the only in-kernel collective in the
# package so a single id suffices.
COLLECTIVE_ID = 7

__all__ = [
    "COLLECTIVE_ID",
    "PROTOCOL",
    "fitted_blocks",
    "fused_ring_local",
    "fused_ring_remote",
    "neighbor_mesh_coords",
    "remote_supported",
]


def remote_supported() -> bool:
    """Does this jax expose the in-kernel remote-DMA surface we need?"""
    return all(
        hasattr(pltpu, name)
        for name in (
            "make_async_copy",
            "make_async_remote_copy",
            "get_barrier_semaphore",
            "semaphore_signal",
            "semaphore_wait",
            "SemaphoreType",
            "DeviceIdType",
        )
    )


def fitted_blocks(n_local: int, block_q: int | None, block_k: int | None):
    """The (bq, bk) the fused kernel will actually run for ``n_local`` —
    callers packing an int8 feed must quantize V at exactly this bk."""
    return _block_sizes(n_local, n_local, block_q, block_k)


# ---------------------------------------------------------------------------
# Local tier: one launch over an all-gathered KV span
# ---------------------------------------------------------------------------


def _fused_local_kernel(origins_ref, his_ref, los_ref, works_ref, *refs,
                        masked: bool, segmented: bool, quantized: bool,
                        kpb: int, spans: int, scale: float,
                        softclamp_value: float | None, bq: int, bk: int):
    """Grid ``(b, h, n_q_blocks, hops * kpb)``; the innermost dimension is
    the fused hop walk: ``s // kpb`` selects the hop (whose origin rank,
    band offsets and work flag arrive via scalar prefetch), ``s % kpb``
    the KV tile within that hop's block.  The ``(acc, m, l)`` scratch
    persists across the whole walk — the scan path's inter-launch carry,
    without the launches."""
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    scale_refs = None
    if quantized:
        scale_refs = refs[idx:idx + 3]
        idx += 3
    kvm_ref = refs[idx] if masked else None
    idx += 1 if masked else 0
    qseg_ref = kseg_ref = None
    if segmented:
        qseg_ref, kseg_ref = refs[idx:idx + 2]
        idx += 2
    out_ref, lse_ref = refs[idx:idx + 2]
    acc, m, l = refs[idx + 2:]

    s_id = pl.program_id(3)
    hop = s_id // kpb
    kb = s_id % kpb
    qi = pl.program_id(2)

    @pl.when(s_id == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, MASK_VALUE)
        l[:] = jnp.zeros_like(l)

    # Tile-level skip mirrors the scan path exactly: the per-hop work flag
    # is `_hop_has_work`, the band predicate is `_tile_has_work` — so the
    # fused walk touches the same tiles in the same order and parity can
    # pin tight.  Sentinel offsets (+-n_local) make both checks vacuous
    # for unbanded hops.
    row0, col0 = qi * bq, kb * bk
    hi, lo = his_ref[hop], los_ref[hop]
    tile_live = (
        (works_ref[hop] != 0)
        & (col0 <= row0 + bq - 1 + hi)
        & (col0 + bk - 1 >= row0 + lo)
    )

    @pl.when(tile_live)
    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if quantized:
            # int8 QK^T: per-row q/k dequant scales ride the matmul's free
            # indices; the softmax scale folds into the same rescale
            # (docs/precision.md — identical to pallas_flash._fwd_tile).
            qs_ref, ks_ref, _ = scale_refs
            s = s * ((qs_ref[0, 0] * scale)[:, None] * ks_ref[0, 0][None, :])
        elif scale != 1.0:
            s = s * scale
        if softclamp_value is not None:
            s = jnp.tanh(s / softclamp_value) * softclamp_value

        # Band mask in per-hop LOCAL coordinates — the same contract the
        # scan path passes per launch as SMEM scalars, here indexed per
        # hop from the prefetched schedule.
        rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + row0
        cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + col0
        diff = cols - rows
        keep = (diff <= hi) & (diff >= lo)
        if masked:
            keep = keep & kvm_ref[0][None, :]
        if segmented:
            keep = keep & (qseg_ref[0][:, None] == kseg_ref[0][None, :])
        s = jnp.where(keep, s, MASK_VALUE)

        _online_update(
            s, v_ref[0, 0], acc, m, l,
            v_scale=scale_refs[2][0, 0, 0] if quantized else None,
        )

    @pl.when(s_id == spans - 1)
    def _write():
        l_safe = jnp.maximum(l[:], EPSILON)
        out_ref[0, 0] = (acc[:] / l_safe).astype(out_ref.dtype)
        lse_ref[0, 0] = (m[:] + jnp.log(l_safe))[:, 0]


def fused_ring_local(
    q, k_all, v_all, kv_mask=None, *,
    origins, his, los, works, n_local,
    scale=1.0, softclamp_value=None,
    block_q=None, block_k=None,
    q_segment_ids=None, kv_segment_ids=None,
    kv_quantized: QuantizedBlockKV | None = None,
    interpret=None, name="fused_ring_local",
):
    """Fused-ring forward over a gathered KV span, one launch.

    Args:
      q: ``(b, h, n_local, d)`` — this rank's queries.  With
        ``kv_quantized`` the QK^T side is still quantized per-row here
        (the launcher quantizes q; k arrives pre-quantized in the feed).
      k_all / v_all: ``(b, hk, n_total, d)`` gathered KV in ring order
        (rank-major).  Ignored (may be the quantized values' dequant
        twins) when ``kv_quantized`` is given.
      kv_mask: optional ``(b, n_total)`` bool.
      origins / his / los / works: ``(hops,)`` int32 hop schedule
        (``parallel/ring.py::_fused_tables``) — origin rank per hop, band
        offsets in per-hop local coordinates (sentinels ±n_local when
        unbanded), live flag.
      kv_quantized: PR 13's int8 kernel feed over the GATHERED span
        (``quant.payload_kernel_feed`` / ``quant.quantize_kv_blocks``);
        its ``block`` must equal the fitted bk (``fitted_blocks``).

    Returns:
      ``(out, lse)`` — ``(b, h, n_local, d)`` in q.dtype and
      ``(b, h, n_local)`` f32, the fused-write contract of
      ``pallas_flash`` (lse = m + log l).
    """
    b, h, n_q, d = q.shape
    hk = k_all.shape[1]
    if h % hk:
        raise ValueError(f"query heads {h} not a multiple of kv heads {hk}")
    g = h // hk
    n_total = k_all.shape[2]
    if n_q != n_local:
        raise ValueError(f"q length {n_q} != n_local {n_local}")
    if n_total % n_local:
        raise ValueError(f"gathered span {n_total} not a multiple of {n_local}")
    hops = int(origins.shape[0])

    bq, bk = _block_sizes(n_local, n_local, block_q, block_k)
    kpb = n_local // bk
    spans = hops * kpb
    nqb = n_q // bq

    quantized = kv_quantized is not None
    if quantized:
        if kv_quantized.block != bk:
            raise ValueError(
                f"kv feed block {kv_quantized.block} != fitted bk {bk}; "
                "pack with fitted_blocks()"
            )
        q_in, qs = _quant.quantize_rows(q)
        k_in, ks = kv_quantized.k_q, kv_quantized.k_scale
        v_in, vs = kv_quantized.v_q, kv_quantized.v_scale
    else:
        q_in, k_in, v_in = q, k_all, v_all
        qs = ks = vs = None

    segmented = q_segment_ids is not None
    masked = kv_mask is not None
    if masked:
        kv_mask = kv_mask.astype(jnp.bool_)

    def q_map(bi, hd, qi, s, o, hi, lo, w):
        return (bi, hd, qi, 0)

    def kv_map(bi, hd, qi, s, o, hi, lo, w):
        return (bi, hd // g, o[s // kpb] * kpb + s % kpb, 0)

    def kcol_map(bi, hd, qi, s, o, hi, lo, w):
        return (bi, o[s // kpb] * kpb + s % kpb)

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bk, d), kv_map),
        pl.BlockSpec((1, 1, bk, d), kv_map),
    ]
    operands = [q_in, k_in, v_in]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bq), lambda bi, hd, qi, s, o, hi, lo, w:
                         (bi, hd, qi)),
            pl.BlockSpec((1, 1, bk), lambda bi, hd, qi, s, o, hi, lo, w:
                         (bi, hd // g, o[s // kpb] * kpb + s % kpb)),
            pl.BlockSpec((1, 1, 1), lambda bi, hd, qi, s, o, hi, lo, w:
                         (bi, hd // g, o[s // kpb] * kpb + s % kpb)),
        ]
        operands += [qs, ks, vs]
    if masked:
        in_specs.append(pl.BlockSpec((1, bk), kcol_map))
        operands.append(kv_mask)
    if segmented:
        in_specs.append(
            pl.BlockSpec((1, bq), lambda bi, hd, qi, s, o, hi, lo, w:
                         (bi, qi)))
        in_specs.append(pl.BlockSpec((1, bk), kcol_map))
        operands += [q_segment_ids, kv_segment_ids]

    kernel = functools.partial(
        _fused_local_kernel,
        masked=masked, segmented=segmented, quantized=quantized,
        kpb=kpb, spans=spans, scale=float(scale),
        softclamp_value=softclamp_value, bq=bq, bk=bk,
    )

    tables = [jnp.asarray(t, jnp.int32) for t in (origins, his, los, works)]
    unified = _unify_vma(*tables, *operands)
    tables, operands = unified[:4], unified[4:]
    like = operands[0]

    if interpret is None:
        interpret = _interpret_default()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, h, nqb, spans),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), q_map),
            pl.BlockSpec((1, 1, bq), lambda bi, hd, qi, s, o, hi, lo, w:
                         (bi, hd, qi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((b, h, n_q, d), q.dtype, like),
            _sds((b, h, n_q), jnp.float32, like),
        ],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
        name=name if not quantized else name + "_q8",
    )(*tables, *operands)
    return out, lse


# ---------------------------------------------------------------------------
# Remote tier: in-kernel async ICI DMA, double-buffered
# ---------------------------------------------------------------------------


def neighbor_mesh_coords(axis_name, ring_size: int):
    """``(2, naxes)`` int32 MESH coordinates of the ``[left, right]`` ring
    neighbors — per-axis indices over EVERY bound mesh axis, varying only
    along ``axis_name``.

    The remote-DMA/semaphore primitives take ``DeviceIdType.MESH``
    coordinates: the Mosaic lowering linearizes them over the WHOLE mesh
    (``coord . strides`` in mesh-axis order), so on a mesh with axes
    beyond the ring (``data``, ``dcn``, hybrid's node axis) every replica
    addresses the neighbor in its OWN replica group.  A bare ring-axis
    index with ``DeviceIdType.LOGICAL`` — the obvious spelling — is wrong
    there: logical ids span the full mesh, and every replica outside the
    first row would push its KV into a different replica group.

    Returns ``None`` when the bound axes cannot be introspected (exotic
    jax) or ``axis_name`` is not a single bound axis — callers degrade to
    the gather-based local tier.
    """
    names = compat.bound_axis_names()
    if names is None:
        return None
    try:
        if axis_name not in names:
            return None
    except TypeError:  # tuple-of-axes collectives have no single ring axis
        return None
    rank = lax.axis_index(axis_name)
    rows = []
    for nbr in ((rank - 1) % ring_size, (rank + 1) % ring_size):
        rows.append(jnp.stack([
            jnp.asarray(nbr if a == axis_name else lax.axis_index(a))
            for a in names
        ]))
    return jnp.stack(rows).astype(jnp.int32)


# ---------------------------------------------------------------------------
# The declared DMA/semaphore protocol of the remote kernel
# ---------------------------------------------------------------------------
#
# One row per copy / semaphore-handshake site group in
# ``_fused_remote_kernel``, in kernel program order.  This table IS the
# verified seam: ``analysis/schedverify.py`` extracts the actual
# primitives from the traced kernel jaxpr, cross-checks them against
# these rows (buffers, semaphores, remoteness, per-kind site counts),
# and model-checks the composed N-device schedule built from the rows —
# matched start/wait on both ends, no slot overwritten while a
# concurrent reader holds it, semaphore drain, deadlock freedom.  The
# fused contract's primitive counts (``contracts.check_fused_ring_
# contract``) are DERIVED from the ``sites`` fields, and lint RA015
# fences every remote-DMA/semaphore call site in this module to the
# functions named in ``fn`` — edit the kernel's hop schedule and the
# verifier, the contract, and the lint all move with this table or fail
# loudly.
#
# Field semantics (all values literal — the table is read by ``ast``
# from the lint, never imported there):
#   row        stable id, named in diagnostics
#   fn         innermost kernel function containing the primitive calls
#   op         copy | remote_copy | remote_drain | barrier | sem_signal
#              | sem_wait
#   src / dst  buffer names (kernel scratch/input refs); *_slot is a
#              python expression over ``hop``/``hops`` selecting the
#              kvbuf ring-buffer slot (None = not the circulated buffer)
#   sem / send_sem / recv_sem   semaphore scratch names ("barrier" is
#              the collective-id barrier from get_barrier_semaphore)
#   guard      hop-range predicate (expression over ``hop``/``hops``)
#   tile       grid position within the hop: "first" ((bhi, qi) ==
#              (0, 0)), "all" (every tile), "last" (the final tile) —
#              fixes program order inside a hop
#   to         remote target: None (local) | "left" | "right"
#   addressing "mesh" = per-axis MESH coordinates over every bound mesh
#              axis (neighbor_mesh_coords); the verifier proves this
#              resolves inside the sender's replica group on multi-axis
#              meshes, where a ring-rank LOGICAL id would not
#   inc/value  semaphore increment / wait decrement
#   sites      traced-jaxpr equation counts this row accounts for, by
#              primitive — summed into the fused contract's expected
#              counts
PROTOCOL = (
    # hop 0, first tile: local KV -> slot 0, then the seed barrier (no
    # peer pushes into an unseeded neighbor's alternate slot).
    {"row": "seed-k", "fn": "_seed", "op": "copy",
     "src": "k_src", "src_slot": None, "dst": "kvbuf", "dst_slot": "0",
     "sem": "load_sem", "guard": "hop == 0", "tile": "first", "to": None,
     "sites": {"dma_start": 1, "dma_wait": 1}},
    {"row": "seed-v", "fn": "_seed", "op": "copy",
     "src": "v_src", "src_slot": None, "dst": "kvbuf", "dst_slot": "0",
     "sem": "load_sem", "guard": "hop == 0", "tile": "first", "to": None,
     "sites": {"dma_start": 1, "dma_wait": 1}},
    {"row": "seed-barrier", "fn": "_seed", "op": "barrier",
     "sem": "barrier", "signal_to": ("left", "right"), "inc": 1,
     "value": 2, "addressing": "mesh", "guard": "hop == 0",
     "tile": "first",
     "sites": {"get_barrier_semaphore": 1, "semaphore_signal": 2,
               "semaphore_wait": 1}},
    # first tile, hop < hops-1: consume one receiver grant (the RIGHT
    # neighbor finished reading the slot this push will overwrite), then
    # start the async HBM->HBM push of the current slot into the right
    # neighbor's alternate slot.  Two dma_start sites: the static
    # cur == 0 / cur == 1 slot branches.
    {"row": "push-grant", "fn": "_flow", "op": "sem_wait",
     "sem": "grant_sem", "value": 1, "guard": "0 < hop < hops - 1",
     "tile": "first", "sites": {"semaphore_wait": 1}},
    {"row": "push-kv", "fn": "_copy", "op": "remote_copy",
     "src": "kvbuf", "src_slot": "hop % 2",
     "dst": "kvbuf", "dst_slot": "(hop + 1) % 2",
     "send_sem": "send_sem", "recv_sem": "recv_sem",
     "to": "right", "addressing": "mesh", "guard": "hop < hops - 1",
     "tile": "first", "sites": {"dma_start": 2}},
    # every tile: stage the (acc, m, l) carry in from its HBM spill,
    # stream KV blocks of the CURRENT slot through VMEM, spill the
    # carry back out.
    {"row": "carry-load-acc", "fn": "_load_state", "op": "copy",
     "src": "accb", "src_slot": None, "dst": "acc", "dst_slot": None,
     "sem": "load_sem", "guard": "hop > 0", "tile": "all", "to": None,
     "sites": {"dma_start": 1, "dma_wait": 1}},
    {"row": "carry-load-m", "fn": "_load_state", "op": "copy",
     "src": "mb", "src_slot": None, "dst": "m", "dst_slot": None,
     "sem": "load_sem", "guard": "hop > 0", "tile": "all", "to": None,
     "sites": {"dma_start": 1, "dma_wait": 1}},
    {"row": "carry-load-l", "fn": "_load_state", "op": "copy",
     "src": "lb", "src_slot": None, "dst": "l", "dst_slot": None,
     "sem": "load_sem", "guard": "hop > 0", "tile": "all", "to": None,
     "sites": {"dma_start": 1, "dma_wait": 1}},
    # 4 starts (2 prologue + 2 in-loop prefetch, k and v parts), 2 waits
    # (the loop body's per-part waits serve prologue and prefetch alike).
    {"row": "kv-stage", "fn": "kv_copies", "op": "copy",
     "src": "kvbuf", "src_slot": "hop % 2", "dst": "kvv",
     "dst_slot": None, "sem": "kv_sems", "guard": "True", "tile": "all",
     "to": None, "sites": {"dma_start": 4, "dma_wait": 2}},
    {"row": "carry-store-acc", "fn": "_store_state", "op": "copy",
     "src": "acc", "src_slot": None, "dst": "accb", "dst_slot": None,
     "sem": "load_sem", "guard": "hop < hops - 1", "tile": "all",
     "to": None, "sites": {"dma_start": 1, "dma_wait": 1}},
    {"row": "carry-store-m", "fn": "_store_state", "op": "copy",
     "src": "m", "src_slot": None, "dst": "mb", "dst_slot": None,
     "sem": "load_sem", "guard": "hop < hops - 1", "tile": "all",
     "to": None, "sites": {"dma_start": 1, "dma_wait": 1}},
    {"row": "carry-store-l", "fn": "_store_state", "op": "copy",
     "src": "l", "src_slot": None, "dst": "lb", "dst_slot": None,
     "sem": "load_sem", "guard": "hop < hops - 1", "tile": "all",
     "to": None, "sites": {"dma_start": 1, "dma_wait": 1}},
    # last tile, hop < hops-1: drain the outbound send and the inbound
    # landing (4 dma_wait sites: 2 static slot branches x send+recv),
    # then grant the LEFT neighbor's next push — it targets exactly the
    # slot this hop finished reading.  The last granted push is hop
    # hops-2 consuming the grant from hop hops-3, so signals and waits
    # balance and grant_sem drains to zero.
    {"row": "hop-drain", "fn": "_wait", "op": "remote_drain",
     "send_sem": "send_sem", "recv_sem": "recv_sem",
     "guard": "hop < hops - 1", "tile": "last",
     "sites": {"dma_wait": 4}},
    {"row": "grant", "fn": "_grant", "op": "sem_signal",
     "sem": "grant_sem", "inc": 1, "to": "left", "addressing": "mesh",
     "guard": "hop < hops - 2", "tile": "last",
     "sites": {"semaphore_signal": 1}},
)


def _fused_remote_kernel(his_ref, los_ref, works_ref, nbrs_ref, *refs,
                         quantized: bool, hops: int, naxes: int, bh: int,
                         nqb: int, n_local: int, d: int, scale: float,
                         softclamp_value: float | None, bq: int, bk: int):
    """Grid ``(hops, bh, n_q_blocks)`` — hop outermost so every tile of hop
    ``i`` computes against HBM ring-buffer slot ``i % 2`` while hop
    ``i+1``'s payload streams into the other slot.  Per hop: the FIRST
    tile starts the async HBM->HBM push of the current slot to the next
    rank's alternate slot, every tile stages ``(bq, bk)`` blocks of the
    current slot through VMEM and folds them into its ``(acc, m, l)``
    carry (itself staged per-tile through VMEM from an HBM spill buffer —
    the carry for the whole shard cannot be VMEM-resident at model
    sizes), and the LAST tile waits on the DMA pair — the overlap window
    is the whole hop's compute.

    Cross-device flow control is a receiver->sender grant: finishing hop
    ``i`` (all tiles computed, outbound send of slot ``i % 2`` drained)
    signals the LEFT neighbor's ``grant_sem``; that neighbor must consume
    one grant before its hop ``i+1`` push, which targets exactly the slot
    hop ``i`` was reading.  Without it a one-hop compute skew — guaranteed
    under causal schedules, where per-rank live-hop counts differ — would
    let the incoming DMA overwrite KV mid-read."""
    if quantized:
        q_ref, qs_ref, k_src, v_src = refs[:4]
        idx = 4
    else:
        q_ref, k_src, v_src = refs[:3]
        idx = 3
    out_ref, lse_ref = refs[idx:idx + 2]
    kvbuf, accb, mb, lb = refs[idx + 2:idx + 6]
    (kvv, acc, m, l, load_sem, kv_sems, send_sem, recv_sem,
     grant_sem) = refs[idx + 6:]

    hop = pl.program_id(0)
    bhi = pl.program_id(1)
    qi = pl.program_id(2)
    cur = lax.rem(hop, 2)

    def nbr(row):
        # MESH coords over every mesh axis — see neighbor_mesh_coords.
        return tuple(nbrs_ref[row, a] for a in range(naxes))

    @pl.when((hop == 0) & (bhi == 0) & (qi == 0))
    def _seed():
        # Local KV into slot 0 (HBM->HBM), then a neighbor barrier:
        # nobody pushes into a peer's alternate slot before that peer has
        # seeded.
        for part, src in enumerate((k_src, v_src)):
            cp = pltpu.make_async_copy(src, kvbuf.at[0, part], load_sem)
            cp.start()
            cp.wait()
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=nbr(0),
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id=nbr(1),
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    def _copy(src_slot, dst_slot):
        return pltpu.make_async_remote_copy(
            src_ref=kvbuf.at[src_slot],
            dst_ref=kvbuf.at[dst_slot],
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=nbr(1),
            device_id_type=pltpu.DeviceIdType.MESH,
        )

    @pl.when((bhi == 0) & (qi == 0) & (hop < hops - 1))
    def _push():
        # Flow control: the hop-i push writes the neighbor's slot
        # (i+1) % 2 — the slot it reads during its hop i-1.  One grant ==
        # "I finished hop i-1"; hop 0's target slot has never been read,
        # so only the seed barrier gates it.
        @pl.when(hop > 0)
        def _flow():
            pltpu.semaphore_wait(grant_sem, 1)

        # Static slot branches: the DMA descriptor's refs must be static.
        @pl.when(cur == 0)
        def _():
            _copy(0, 1).start()

        @pl.when(cur == 1)
        def _():
            _copy(1, 0).start()

    row0 = qi * bq
    live = (
        (works_ref[hop] != 0)
        & (0 <= row0 + bq - 1 + his_ref[hop])
        & (n_local - 1 >= row0 + los_ref[hop])
    )
    state = ((accb, acc), (mb, m), (lb, l))

    @pl.when(hop == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, MASK_VALUE)
        l[:] = jnp.zeros_like(l)

    @pl.when((hop > 0) & (live | (hop == hops - 1)))
    def _load_state():
        cps = [
            pltpu.make_async_copy(
                hb.at[bhi, pl.dslice(row0, bq)], vref, load_sem)
            for hb, vref in state
        ]
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        hi, lo = his_ref[hop], los_ref[hop]
        # Only the KV blocks the band touches: rows [row0, row0+bq) keep
        # cols j with lo <= j - i <= hi, clamped to the shard.  `live`
        # guarantees a non-empty range.
        kb_lo = jnp.maximum(row0 + lo, 0) // bk
        kb_hi = jnp.minimum(row0 + bq - 1 + hi, n_local - 1) // bk

        def kv_copies(kb, buf):
            # Double-buffered HBM->VMEM staging of one (bk, dd) K and V
            # block of the CURRENT slot; per-buffer DMA semaphore.
            return [
                pltpu.make_async_copy(
                    kvbuf.at[cur, part, bhi, pl.dslice(kb * bk, bk)],
                    kvv.at[buf, part],
                    kv_sems.at[buf],
                )
                for part in (0, 1)
            ]

        for cp in kv_copies(kb_lo, 0):
            cp.start()

        def body(i, carry):
            kb = kb_lo + i
            buf = lax.rem(i, 2)

            @pl.when(kb < kb_hi)
            def _prefetch():
                for cp in kv_copies(kb + 1, 1 - buf):
                    cp.start()

            for cp in kv_copies(kb, buf):
                cp.wait()

            kblk = kvv[buf, 0]
            vblk = kvv[buf, 1]
            k = kblk[:, :d] if quantized else kblk
            s = lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if quantized:
                ks = lax.bitcast_convert_type(
                    kblk[:, d:d + _quant.SCALE_BYTES], jnp.float32)
                s = s * ((qs_ref[0] * scale)[:, None] * ks[None, :])
            elif scale != 1.0:
                s = s * scale
            if softclamp_value is not None:
                s = jnp.tanh(s / softclamp_value) * softclamp_value
            rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + row0
            cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + kb * bk
            diff = cols - rows
            keep = (diff <= hi) & (diff >= lo)
            s = jnp.where(keep, s, MASK_VALUE)
            if quantized:
                # pack_kv(v_block=n_local) broadcast the whole-block v
                # scale to every row — row 0 of any slice recovers it.
                vs = lax.bitcast_convert_type(
                    vblk[0, d:d + _quant.SCALE_BYTES], jnp.float32)
                _online_update(s, vblk[:, :d], acc, m, l, v_scale=vs)
            else:
                _online_update(s, vblk, acc, m, l)
            return carry

        lax.fori_loop(0, kb_hi - kb_lo + 1, body, 0)

    @pl.when((hop < hops - 1) & (live | (hop == 0)))
    def _store_state():
        cps = [
            pltpu.make_async_copy(
                vref, hb.at[bhi, pl.dslice(row0, bq)], load_sem)
            for hb, vref in state
        ]
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

    @pl.when((bhi == bh - 1) & (qi == nqb - 1) & (hop < hops - 1))
    def _wait():
        @pl.when(cur == 0)
        def _():
            _copy(0, 1).wait()

        @pl.when(cur == 1)
        def _():
            _copy(1, 0).wait()

        # Slot `cur` is now dead here (every tile computed, outbound send
        # drained just above): grant the LEFT neighbor's next push — it
        # targets exactly this slot.  The last granted push is hop
        # hops-2, consuming the grant from hop hops-3: signals and waits
        # balance, the semaphore drains to zero.
        @pl.when(hop < hops - 2)
        def _grant():
            pltpu.semaphore_signal(grant_sem, inc=1, device_id=nbr(0),
                                   device_id_type=pltpu.DeviceIdType.MESH)

    @pl.when(hop == hops - 1)
    def _write():
        l_safe = jnp.maximum(l[:], EPSILON)
        out_ref[0] = (acc[:] / l_safe).astype(out_ref.dtype)
        lse_ref[0] = (m[:] + jnp.log(l_safe))[:, 0]


def fused_ring_remote(
    q, k, v, *,
    his, los, works, nbr_coords,
    scale=1.0, softclamp_value=None, block_q=None, block_k=None,
    payload=None, collective_id=COLLECTIVE_ID,
    name="fused_ring_remote",
):
    """Fused-ring forward with in-kernel async remote KV circulation.

    Call inside ``shard_map``: ``q`` ``(b, h, n_local, d)``, ``k``/``v``
    ``(b, hk, n_local, d)`` are this rank's shards; ``nbr_coords`` is the
    int32 ``(2, naxes)`` MESH-coordinate pair of the ``[rank-1, rank+1]``
    ring neighbors over EVERY mesh axis (:func:`neighbor_mesh_coords` —
    physical adjacency holds because ``torus_ring_order`` fed mesh
    construction).  KV is sent to ``rank+1`` each hop, so hop ``i`` holds
    origin ``(rank - i) % W`` — the same visit order as the scan path,
    which is what makes ``his``/``los``/``works`` (from
    ``_fused_tables``) directly reusable.

    The circulated double buffer and the cross-hop ``(acc, m, l)`` carry
    live in HBM (``ANY``-space buffers the caller discards); compute
    stages ``(bq, bk)`` blocks and per-tile carries through small VMEM
    scratch, so VMEM footprint is tile-sized and independent of
    ``n_local`` — whole-shard VMEM residency does not compile at model
    sizes (32k-token shards are hundreds of MB against ~16 MB of VMEM).

    ``payload`` selects the int8 wire: a ``quant.pack_kv(k, v,
    v_block=n_local)`` buffer ``(2, b, hk, n_local, d + SCALE_BYTES)``
    circulates INSTEAD of k/v, dequant scales riding its trailing lanes;
    q is quantized per-row here.  GQA is materialized (kv heads repeated
    to h) before folding to ``(b*h, n, d)`` — the remote tier trades that
    copy for whole-hop DMA granularity; masked/segmented configs take the
    local tier instead.

    TPU-execute only; traces on any backend (the contract row counts the
    lowered ``dma_start``/semaphore ops from exactly this trace).
    """
    check_attention_args("fused_ring_remote", q, k, v, None,
                         equal_qkv_len=True)
    b, h, n_q, d = q.shape
    hk = k.shape[1]
    g = h // hk
    n_local = n_q
    hops = int(his.shape[0])
    naxes = int(nbr_coords.shape[-1])
    quantized = payload is not None

    bq, bk = _block_sizes(n_local, n_local, block_q, block_k)
    nqb = n_local // bq
    bh = b * h

    def fold(x):
        if x.shape[1] != h:
            x = jnp.repeat(x, g, axis=1)
        return x.reshape(bh, *x.shape[2:])

    q_f = fold(q)
    hbm = pl.BlockSpec(memory_space=pltpu.ANY)
    if quantized:
        q8, qs = _quant.quantize_rows(q_f)
        operands = [q8, qs, fold(payload[0]), fold(payload[1])]
        dd = d + _quant.SCALE_BYTES
        kv_dtype = jnp.int8
        in_specs = [
            pl.BlockSpec((1, bq, d), lambda hop, bhi, qi, hi, lo, w, nb:
                         (bhi, qi, 0)),
            pl.BlockSpec((1, bq), lambda hop, bhi, qi, hi, lo, w, nb:
                         (bhi, qi)),
            hbm,
            hbm,
        ]
    else:
        operands = [q_f, fold(k), fold(v)]
        dd = d
        kv_dtype = k.dtype
        in_specs = [
            pl.BlockSpec((1, bq, d), lambda hop, bhi, qi, hi, lo, w, nb:
                         (bhi, qi, 0)),
            hbm,
            hbm,
        ]

    kernel = functools.partial(
        _fused_remote_kernel,
        quantized=quantized, hops=hops, naxes=naxes, bh=bh, nqb=nqb,
        n_local=n_local, d=d, scale=float(scale),
        softclamp_value=softclamp_value, bq=bq, bk=bk,
    )
    tables = [jnp.asarray(t, jnp.int32)
              for t in (his, los, works, nbr_coords)]
    unified = _unify_vma(*tables, *operands)
    tables, operands = unified[:4], unified[4:]
    like = operands[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(hops, bh, nqb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda hop, bhi, qi, hi, lo, w, nb:
                         (bhi, qi, 0)),
            pl.BlockSpec((1, bq), lambda hop, bhi, qi, hi, lo, w, nb:
                         (bhi, qi)),
            # HBM working buffers, returned-and-dropped: the circulated
            # double buffer and the cross-hop (acc, m, l) spill.
            hbm,
            hbm,
            hbm,
            hbm,
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 2, bk, dd), kv_dtype),
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.REGULAR,
        ],
    )
    out_f, lse_f, *_hbm_work = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((bh, n_local, d), q.dtype, like),
            _sds((bh, n_local), jnp.float32, like),
            _sds((2, 2, bh, n_local, dd), kv_dtype, like),
            _sds((bh, n_local, d), jnp.float32, like),
            _sds((bh, n_local, 1), jnp.float32, like),
            _sds((bh, n_local, 1), jnp.float32, like),
        ],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
            collective_id=collective_id,
        ),
        interpret=False,
        name=name if not quantized else name + "_q8",
    )(*tables, *operands)
    out = out_f.reshape(b, h, n_local, d)
    lse = lse_f.reshape(b, h, n_local)
    return out, lse
