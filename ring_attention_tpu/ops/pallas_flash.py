"""Pallas (Mosaic) flash-attention kernels for TPU.

TPU-native replacement for the reference's Triton kernels
(``triton_flash_attn.py``): the forward emits the raw online-softmax
partials ``(acc, m, l)`` so ring hops merge them exactly like the
reference's ``LOAD_ACCUMULATED`` resume path (ref
``triton_flash_attn.py:124-165``) — but as a pure-functional merge in XLA
rather than mutating kernel state, which is the idiom XLA can pipeline
with the ring ``ppermute``.

Masking uses the same unified *banded causal offset* contract as
``ops/flash.py`` (attend iff ``lo <= j - i <= hi``: plain causal hi =
offset, striped diagonal hi = 0/-1, windows via the lo offset), passed as
runtime scalars in SMEM so one compiled kernel
serves every ring position under SPMD (the reference compiles
``CAUSAL_MASK_DIAGONAL`` variants instead, ref ``triton_flash_attn.py:216-221``).

The backward is two kernels without atomics — a dk/dv pass (grid over KV
blocks, queries streamed) and a dq pass (grid over Q blocks, KV streamed) —
where the reference's Triton backward needs sequence-parallel
``atomic_add`` workarounds (ref ``triton_flash_attn.py:763-776``); TPU has
no relaxed atomics, and the two-pass structure is also what keeps every
matmul on the MXU with static layouts.

GQA: query heads are served by ``kv_head = q_head // g`` through BlockSpec
index maps (no materialized repeat); dk/dv are emitted per query head and
group-summed outside (ref ``ring_flash_attention.py:370-371``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import EPSILON, MASK_VALUE
from ..utils.validate import check_attention_args

# Tuned on TPU v5e (seq 262144, h=8, d=64, bf16, causal): 1024x1024 won the
# sweep at 57.7 fwd TFLOPs/chip; >=16MB f32 score tiles (2048x2048, 1024x4096)
# are rejected by Mosaic on this generation.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def _unify_vma(*arrays):
    """pcast every array to the union of all arrays' shard_map varying axes.

    Inside ``shard_map`` the traced causal offset (derived from
    ``axis_index``) varies over fewer mesh axes than q/k/v; pallas requires
    uniform varying-axes types across its operands."""
    union = set()
    for a in arrays:
        if a is not None:
            union |= set(getattr(jax.typeof(a), "vma", frozenset()))

    def cast(a):
        if a is None:
            return None
        missing = tuple(union - set(getattr(jax.typeof(a), "vma", frozenset())))
        return lax.pcast(a, missing, to="varying") if missing else a

    return [cast(a) for a in arrays]


def _sds(shape, dtype, like):
    """ShapeDtypeStruct matching ``like``'s shard_map varying-axes type."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def _block_sizes(nq: int, nk: int, block_q: int | None, block_k: int | None):
    bq = min(block_q or DEFAULT_BLOCK_Q, nq)
    bk = min(block_k or DEFAULT_BLOCK_K, nk)
    while nq % bq:
        bq //= 2
    while nk % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)



def _tile_has_work(offs_ref, row0, col0, bq, bk, causal, windowed):
    """Block-level skip predicate: does tile (rows row0.., cols col0..) touch
    the band ``offs[1] <= j - i <= offs[0]``?  True when not causal."""
    if not causal:
        return True
    ok = col0 <= row0 + bq - 1 + offs_ref[0]
    if windowed:
        ok = jnp.logical_and(ok, col0 + bk - 1 >= row0 + offs_ref[1])
    return ok


def _tile_keep(offs_ref, row0, col0, shape, q_dim, causal, windowed, kvm_ref):
    """Per-element keep mask for a score tile, or None if unmasked.

    ``q_dim`` is the tile dimension holding query rows (0 in fwd/dq tiles,
    1 in the transposed dk/dv tiles); the other dimension holds key cols.
    """
    masked = kvm_ref is not None
    if not (causal or masked):
        return None
    rows = row0 + lax.broadcasted_iota(jnp.int32, shape, q_dim)
    cols = col0 + lax.broadcasted_iota(jnp.int32, shape, 1 - q_dim)
    keep = None
    if causal:
        keep = cols <= rows + offs_ref[0]
        if windowed:
            keep = jnp.logical_and(keep, cols >= rows + offs_ref[1])
    if masked:
        kvm = kvm_ref[0] != 0
        kvm = kvm[None, :] if q_dim == 0 else kvm[:, None]
        keep = kvm if keep is None else jnp.logical_and(keep, kvm)
    return keep


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(
    # scalar prefetch
    offs_ref,  # (2,) int32: [band hi offset, band lo offset] (0 if unused)
    # inputs
    q_ref,  # (1, bq, d)
    k_ref,  # (1, bk, d)
    v_ref,  # (1, bk, d)
    kvm_ref,  # (1, bk) int8 or None
    # outputs
    acc_ref,  # (1, bq, d) f32
    m_ref,  # (1, bq, 1) f32
    l_ref,  # (1, bq, 1) f32
    # scratch
    acc,  # (bq, d) f32
    m,  # (bq, 1) f32
    l,  # (bq, 1) f32
    *,
    scale: float,
    softclamp_value: float | None,
    causal: bool,
    windowed: bool,
    masked: bool,
    bq: int,
    bk: int,
    nk_blocks: int,
):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, MASK_VALUE)
        l[:] = jnp.zeros_like(l)

    qi = pl.program_id(1)
    row0 = qi * bq
    col0 = ki * bk
    has_work = _tile_has_work(offs_ref, row0, col0, bq, bk, causal, windowed)

    @pl.when(has_work)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        if softclamp_value is not None:
            s = jnp.tanh(s / softclamp_value) * softclamp_value

        keep = _tile_keep(
            offs_ref, row0, col0, (bq, bk), 0, causal, windowed,
            kvm_ref if masked else None,
        )
        if keep is not None:
            s = jnp.where(keep, s, MASK_VALUE)

        m_prev = m[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l[:] = l[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[:] = acc[:] * alpha + pv
        m[:] = m_new

    @pl.when(ki == nk_blocks - 1)
    def _write():
        acc_ref[0] = acc[:]
        m_ref[0] = m[:]
        l_ref[0] = l[:]


class FlashPartials(NamedTuple):
    """Raw online-softmax partials: out = acc / l, lse = m + log l."""

    acc: jax.Array  # (b, h, nq, d) f32
    m: jax.Array  # (b, h, nq) f32
    l: jax.Array  # (b, h, nq) f32


def pallas_flash_partials(
    q: jax.Array,  # (b, h, nq, d)
    k: jax.Array,  # (b, hk, nk, d)
    v: jax.Array,  # (b, hk, nk, d)
    kv_mask: jax.Array | None = None,  # (b, nk) bool
    *,
    scale: float,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    softclamp_value: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> FlashPartials:
    """One flash sweep over a KV span, returning mergeable partials.

    ``window_lo``: absolute band lower offset (see ``ops/flash.py``);
    may be a traced per-device scalar under SPMD.
    """
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    g = h // hk
    bq, bk = _block_sizes(nq, nk, block_q, block_k)
    interpret = _interpret_default() if interpret is None else interpret

    causal = causal_offset is not None
    windowed = window_lo is not None and causal
    masked = kv_mask is not None

    offs = jnp.asarray(
        [
            causal_offset if causal else 0,
            window_lo if windowed else 0,
        ],
        jnp.int32,
    )

    q, k, v, kv_mask, offs = _unify_vma(q, k, v, kv_mask, offs)
    qr = q.reshape(b * h, nq, d)
    kr = k.reshape(b * hk, nk, d)
    vr = v.reshape(b * hk, nk, d)

    def q_map(bh, qi, ki, *_):
        return (bh, qi, 0)

    def kv_map(bh, qi, ki, *_):
        b_idx = bh // h
        kvh = (bh % h) // g
        return (b_idx * hk + kvh, ki, 0)

    in_specs = [
        pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, kr, vr]
    if masked:
        kvm = kv_mask.astype(jnp.int8)
        in_specs.append(
            pl.BlockSpec(
                (1, bk), lambda bh, qi, ki, *_: (bh // h, ki), memory_space=pltpu.VMEM
            )
        )
        inputs.append(kvm)

    kernel = functools.partial(
        _fwd_kernel if masked else _fwd_kernel_nomask,
        scale=scale,
        softclamp_value=softclamp_value,
        causal=causal,
        windowed=windowed,
        masked=masked,
        bq=bq,
        bk=bk,
        nk_blocks=nk // bk,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, nq // bq, nk // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
    )

    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((b * h, nq, d), jnp.float32, q),
            _sds((b * h, nq, 1), jnp.float32, q),
            _sds((b * h, nq, 1), jnp.float32, q),
        ],
        # batch*head and q-block grid dims are independent (megacore can
        # split them); the kv dim carries the online-softmax state
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(offs, *inputs)

    return FlashPartials(
        acc.reshape(b, h, nq, d),
        m.reshape(b, h, nq),
        l.reshape(b, h, nq),
    )


# variant without the mask ref in the signature (pallas requires the kernel
# arity to match the number of inputs)
def _fwd_kernel_nomask(offs_ref, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                       acc, m, l, **kw):
    _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, None, acc_ref, m_ref, l_ref,
                acc, m, l, **kw)


def init_partials(
    b: int, h: int, nq: int, d: int, like: jax.Array | None = None
) -> FlashPartials:
    """Identity element for :func:`merge_partials` (keeps the MASK_VALUE
    sentinel invariant local to this module)."""
    parts = FlashPartials(
        jnp.zeros((b, h, nq, d), jnp.float32),
        jnp.full((b, h, nq), MASK_VALUE, jnp.float32),
        jnp.zeros((b, h, nq), jnp.float32),
    )
    if like is not None:
        parts = FlashPartials(*_unify_vma(*parts, like)[:3])
    return parts


def merge_partials(a: FlashPartials, b: FlashPartials) -> FlashPartials:
    """Exact online-softmax merge of two partial sweeps (associative)."""
    m = jnp.maximum(a.m, b.m)
    ea = jnp.exp(a.m - m)
    eb = jnp.exp(b.m - m)
    return FlashPartials(
        a.acc * ea[..., None] + b.acc * eb[..., None],
        m,
        a.l * ea + b.l * eb,
    )


def finalize_partials(p: FlashPartials) -> tuple[jax.Array, jax.Array]:
    """Returns (out f32 (b,h,n,d), lse (b,h,n))."""
    out = p.acc / jnp.maximum(p.l, EPSILON)[..., None]
    lse = p.m + jnp.log(jnp.maximum(p.l, EPSILON))
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(
    offs_ref,
    q_ref,  # (1, bq, d)
    do_ref,  # (1, bq, d)
    lse_ref,  # (1, bq, 1)
    delta_ref,  # (1, bq, 1)
    k_ref,  # (1, bk, d)
    v_ref,  # (1, bk, d)
    kvm_ref,  # (1, bk) or None
    dk_ref,  # (1, bk, d) f32
    dv_ref,  # (1, bk, d) f32
    dk,  # scratch (bk, d) f32
    dv,  # scratch (bk, d) f32
    *,
    scale: float,
    softclamp_value: float | None,
    causal: bool,
    windowed: bool,
    masked: bool,
    bq: int,
    bk: int,
    nq_blocks: int,
):
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk[:] = jnp.zeros_like(dk)
        dv[:] = jnp.zeros_like(dv)

    ki = pl.program_id(1)
    row0 = qi * bq
    col0 = ki * bk
    has_work = _tile_has_work(offs_ref, row0, col0, bq, bk, causal, windowed)

    @pl.when(has_work)
    def _compute():
        kb = k_ref[0]
        qb = q_ref[0]
        # sT: (bk, bq) = k . q^T (contract d on both)
        sT = lax.dot_general(
            kb, qb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if softclamp_value is not None:
            sT = jnp.tanh(sT / softclamp_value) * softclamp_value

        pT = jnp.exp(sT - jnp.swapaxes(lse_ref[0], 0, 1))
        keep = _tile_keep(
            offs_ref, row0, col0, (bk, bq), 1, causal, windowed,
            kvm_ref if masked else None,
        )
        if keep is not None:
            pT = jnp.where(keep, pT, 0.0)

        dob = do_ref[0]
        dv[:] = dv[:] + lax.dot_general(
            pT.astype(dob.dtype), dob, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dpT: (bk, bq) = v . do^T
        dpT = lax.dot_general(
            v_ref[0], dob, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dsT = pT * (dpT - jnp.swapaxes(delta_ref[0], 0, 1))
        if softclamp_value is not None:
            dsT = dsT * (1.0 - (sT / softclamp_value) ** 2)
        dsT = dsT * scale
        dk[:] = dk[:] + lax.dot_general(
            dsT.astype(qb.dtype), qb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq_blocks - 1)
    def _write():
        dk_ref[0] = dk[:]
        dv_ref[0] = dv[:]


def _bwd_dkv_kernel_nomask(offs_ref, q_ref, do_ref, lse_ref, delta_ref,
                           k_ref, v_ref, dk_ref, dv_ref, dk, dv, **kw):
    _bwd_dkv_kernel(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                    None, dk_ref, dv_ref, dk, dv, **kw)


def _bwd_dq_kernel(
    offs_ref,
    q_ref,  # (1, bq, d)
    do_ref,  # (1, bq, d)
    lse_ref,  # (1, bq, 1)
    delta_ref,  # (1, bq, 1)
    k_ref,  # (1, bk, d)
    v_ref,  # (1, bk, d)
    kvm_ref,  # (1, bk) or None
    dq_ref,  # (1, bq, d) f32
    dq,  # scratch (bq, d) f32
    *,
    scale: float,
    softclamp_value: float | None,
    causal: bool,
    windowed: bool,
    masked: bool,
    bq: int,
    bk: int,
    nk_blocks: int,
):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq[:] = jnp.zeros_like(dq)

    qi = pl.program_id(1)
    row0 = qi * bq
    col0 = ki * bk
    has_work = _tile_has_work(offs_ref, row0, col0, bq, bk, causal, windowed)

    @pl.when(has_work)
    def _compute():
        qb = q_ref[0]
        kb = k_ref[0]
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if softclamp_value is not None:
            s = jnp.tanh(s / softclamp_value) * softclamp_value

        p = jnp.exp(s - lse_ref[0])
        keep = _tile_keep(
            offs_ref, row0, col0, (bq, bk), 0, causal, windowed,
            kvm_ref if masked else None,
        )
        if keep is not None:
            p = jnp.where(keep, p, 0.0)

        dob = do_ref[0]
        dp = lax.dot_general(
            dob, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        if softclamp_value is not None:
            ds = ds * (1.0 - (s / softclamp_value) ** 2)
        ds = ds * scale
        dq[:] = dq[:] + lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk_blocks - 1)
    def _write():
        dq_ref[0] = dq[:]


def _bwd_dq_kernel_nomask(offs_ref, q_ref, do_ref, lse_ref, delta_ref,
                          k_ref, v_ref, dq_ref, dq, **kw):
    _bwd_dq_kernel(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                   None, dq_ref, dq, **kw)


def pallas_flash_backward(
    do: jax.Array,  # (b, h, nq, d)
    q: jax.Array,
    k: jax.Array,  # (b, hk, nk, d)
    v: jax.Array,
    lse: jax.Array,  # (b, h, nq) f32
    delta: jax.Array,  # (b, h, nq) f32
    kv_mask: jax.Array | None = None,
    *,
    scale: float,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    softclamp_value: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Two-pass flash backward. Returns (dq, dk, dv), all f32, dk/dv with
    ``hk`` heads (GQA group-summed)."""
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    g = h // hk
    bq, bk = _block_sizes(nq, nk, block_q, block_k)
    interpret = _interpret_default() if interpret is None else interpret

    causal = causal_offset is not None
    windowed = window_lo is not None and causal
    masked = kv_mask is not None
    offs = jnp.asarray(
        [causal_offset if causal else 0, window_lo if windowed else 0], jnp.int32
    )

    q, k, v, do, lse, delta, kv_mask, offs = _unify_vma(
        q, k, v, do, lse, delta, kv_mask, offs
    )
    qr = q.reshape(b * h, nq, d)
    dor = do.reshape(b * h, nq, d).astype(q.dtype)
    lser = lse.reshape(b * h, nq, 1)
    deltar = delta.reshape(b * h, nq, 1)
    kr = k.reshape(b * hk, nk, d)
    vr = v.reshape(b * hk, nk, d)

    def q_map(bh, xi, yi, *_):
        del yi
        return (bh, xi, 0)

    def q_map_inner(bh, ki, qi, *_):
        del ki
        return (bh, qi, 0)

    def kv_map_outer(bh, ki, qi, *_):
        del qi
        b_idx = bh // h
        kvh = (bh % h) // g
        return (b_idx * hk + kvh, ki, 0)

    def kv_map_inner(bh, qi, ki, *_):
        b_idx = bh // h
        kvh = (bh % h) // g
        return (b_idx * hk + kvh, ki, 0)

    common = dict(
        scale=scale,
        softclamp_value=softclamp_value,
        causal=causal,
        windowed=windowed,
        masked=masked,
        bq=bq,
        bk=bk,
    )

    # ---- dk/dv pass: grid (bh, k blocks, q blocks) ----
    in_specs = [
        pl.BlockSpec((1, bq, d), q_map_inner, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq, d), q_map_inner, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq, 1), q_map_inner, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq, 1), q_map_inner, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map_outer, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map_outer, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, dor, lser, deltar, kr, vr]
    if masked:
        kvm = kv_mask.astype(jnp.int8)
        in_specs.append(
            pl.BlockSpec(
                (1, bk), lambda bh, ki, qi, *_: (bh // h, ki), memory_space=pltpu.VMEM
            )
        )
        inputs.append(kvm)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel if masked else _bwd_dkv_kernel_nomask,
        nq_blocks=nq // bq,
        **common,
    )
    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, nk // bk, nq // bq),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda bh, ki, qi, *_: (bh, ki, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk, d), lambda bh, ki, qi, *_: (bh, ki, 0),
                             memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        ),
        out_shape=[
            _sds((b * h, nk, d), jnp.float32, q),
            _sds((b * h, nk, d), jnp.float32, q),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(offs, *inputs)

    # GQA: sum per-query-head dk/dv over the group
    dk = dk_h.reshape(b, hk, g, nk, d).sum(axis=2)
    dv = dv_h.reshape(b, hk, g, nk, d).sum(axis=2)

    # ---- dq pass: grid (bh, q blocks, k blocks) ----
    in_specs = [
        pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map_inner, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map_inner, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, dor, lser, deltar, kr, vr]
    if masked:
        inputs.append(kvm)
        in_specs.append(
            pl.BlockSpec(
                (1, bk), lambda bh, qi, ki, *_: (bh // h, ki), memory_space=pltpu.VMEM
            )
        )

    dq_kernel = functools.partial(
        _bwd_dq_kernel if masked else _bwd_dq_kernel_nomask,
        nk_blocks=nk // bk,
        **common,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, nq // bq, nk // bk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        ),
        out_shape=_sds((b * h, nq, d), jnp.float32, q),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(offs, *inputs)

    return dq.reshape(b, h, nq, d), dk, dv


# ---------------------------------------------------------------------------
# User-facing single-device flash attention on the Pallas path
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _pallas_flash_core(q, k, v, kv_mask, scale, causal_offset, window,
                       softclamp_value, interpret):
    out, _ = _pallas_flash_fwd_impl(
        q, k, v, kv_mask, scale, causal_offset, window, softclamp_value, interpret
    )
    return out


def _pallas_flash_fwd_impl(q, k, v, kv_mask, scale, causal_offset, window,
                           softclamp_value, interpret):
    window_lo = causal_offset - (window - 1) if window is not None else None
    parts = pallas_flash_partials(
        q, k, v, kv_mask,
        scale=scale, causal_offset=causal_offset, window_lo=window_lo,
        softclamp_value=softclamp_value, interpret=interpret,
    )
    out, lse = finalize_partials(parts)
    return out.astype(q.dtype), lse


def _pallas_flash_core_fwd(q, k, v, kv_mask, scale, causal_offset, window,
                           softclamp_value, interpret):
    out, lse = _pallas_flash_fwd_impl(
        q, k, v, kv_mask, scale, causal_offset, window, softclamp_value, interpret
    )
    return out, (q, k, v, kv_mask, out, lse)


def _pallas_flash_core_bwd(scale, causal_offset, window, softclamp_value,
                           interpret, res, do):
    q, k, v, kv_mask, out, lse = res
    window_lo = causal_offset - (window - 1) if window is not None else None
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    dq, dk, dv = pallas_flash_backward(
        do, q, k, v, lse, delta, kv_mask,
        scale=scale, causal_offset=causal_offset, window_lo=window_lo,
        softclamp_value=softclamp_value, interpret=interpret,
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None


_pallas_flash_core.defvjp(_pallas_flash_core_fwd, _pallas_flash_core_bwd)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = False,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Exact flash attention on the Pallas TPU kernel path (GQA-aware).

    Same contract as ``ops.flash.flash_attention``; parity-tested against
    the oracle.  On non-TPU backends runs the kernels in interpreter mode.
    """
    check_attention_args("pallas_flash_attention", q, k, v, mask)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None:
        assert causal, "lookback windows require causal attention"
    if causal:
        mask = None
    causal_offset = k.shape[2] - q.shape[2] if causal else None
    return _pallas_flash_core(
        q, k, v, mask, scale, causal_offset, window, softclamp_value,
        interpret if interpret is not None else _interpret_default(),
    )
