"""Pallas (Mosaic) flash-attention kernels for TPU.

TPU-native replacement for the reference's Triton kernels
(``triton_flash_attn.py``): the forward either emits the raw online-softmax
partials ``(acc, m, l)`` for XLA-side merging, or — the ring-hop fast path —
*continues* a carry in-kernel (``carry=...``, the reference's
``LOAD_ACCUMULATED`` resume, ref ``triton_flash_attn.py:124-165``) and on
the final span writes normalized ``q.dtype`` output + lse directly
(``fused``, the reference's ``RETURN_NORMALIZED_OUTPUT``, ref
``triton_flash_attn.py:273-275``), so the f32 accumulator triple never
round-trips HBM between hops.

Masking uses the same unified *banded causal offset* contract as
``ops/flash.py`` (attend iff ``lo <= j - i <= hi``: plain causal hi =
offset, striped diagonal hi = 0/-1, windows via the lo offset), passed as
runtime scalars in SMEM so one compiled kernel
serves every ring position under SPMD (the reference compiles
``CAUSAL_MASK_DIAGONAL`` variants instead, ref ``triton_flash_attn.py:216-221``).

The backward is two kernels without atomics — a dk/dv pass (grid over KV
blocks, queries streamed) and a dq pass (grid over Q blocks, KV streamed) —
where the reference's Triton backward needs sequence-parallel
``atomic_add`` workarounds (ref ``triton_flash_attn.py:763-776``); TPU has
no relaxed atomics, and the two-pass structure is also what keeps every
matmul on the MXU with static layouts.

GQA: query heads are served by ``kv_head = q_head // g`` through BlockSpec
index maps (no materialized repeat); dk/dv are emitted per query head and
group-summed outside (ref ``ring_flash_attention.py:370-371``).

The in-kernel carry above still costs one launch PER HOP;
``ops/pallas_ring.py`` builds on this module's seams (``_block_sizes``
tile fitting, ``_online_update`` softmax algebra, the banded-offset mask
contract) to run the WHOLE ring schedule as ONE launch — the next hop's
KV double-buffered via in-kernel async remote DMA, the ``(acc, m, l)``
carry living in VMEM scratch (local tier) or staged per tile through an
HBM spill (remote tier).  ``impl="fused"`` on
``ring_flash_attention`` selects it; the backward retains this module's
two-pass kernels.
"""

from __future__ import annotations

import functools
import math
import os
import warnings
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import bisect

from .attention import EPSILON, MASK_VALUE, normalize_segment_ids
from . import quant as _quant
from .quant import QuantizedBlockKV
from ..utils import compat
from ..utils.validate import check_attention_args

# Tuned on TPU v5e (seq 262144, h=8, d=64, bf16, causal): 1024x1024 won both
# sweeps — 57.7 fwd TFLOPs/chip on the rectangular grid, 67.6 with the
# compacted causal grid (docs/hardware_log.md); >=16MB f32 score tiles
# (2048x2048, 1024x4096) are rejected by Mosaic on this generation.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# Per-pass backward tile defaults, used when the caller pins neither the
# shared block_q/block_k nor the per-pass overrides.  None = inherit
# DEFAULT_BLOCK_Q/K; the on-chip `tools/tpu_kernel_validate.py --bwd-sweep`
# results get pinned HERE (VERDICT r3 next #3) so every backward call
# site (ring hops, zigzag, single-sweep custom_vjp) picks them up.
DEFAULT_BLOCK_Q_DKV: int | None = None
DEFAULT_BLOCK_K_DKV: int | None = None
DEFAULT_BLOCK_Q_DQ: int | None = None
DEFAULT_BLOCK_K_DQ: int | None = None


def _unify_vma(*arrays):
    """pcast every array to the union of all arrays' shard_map varying axes.

    Inside ``shard_map`` the traced causal offset (derived from
    ``axis_index``) varies over fewer mesh axes than q/k/v; pallas requires
    uniform varying-axes types across its operands."""
    union = set()
    for a in arrays:
        if a is not None:
            union |= set(getattr(compat.typeof(a), "vma", frozenset()))

    def cast(a):
        if a is None:
            return None
        missing = tuple(union - set(getattr(compat.typeof(a), "vma", frozenset())))
        return compat.pcast(a, missing, to="varying") if missing else a

    return [cast(a) for a in arrays]


def _sds(shape, dtype, like):
    """ShapeDtypeStruct matching ``like``'s shard_map varying-axes type."""
    vma = getattr(compat.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


# log2-space scoring (candidate VPU optimization, A/B flag): fold
# scale*log2(e) into q so the per-tile softmax runs p = exp2(s2 - m2) with
# NO per-element multiply — neither the scale multiply nor exp's internal
# range-scaling one (exp lowers as exp2(x*log2e)).  p/l/acc are value-
# identical (exp2(a*log2e - b*log2e) == exp(a - b)); only the running max
# changes basis and converts back (m = m2*ln2) at the final write, a
# (bq, 1) op per block.  Costs one extra rounding of q by a non-power-of-
# two constant (~2^-24 f32 / ~2^-9 bf16 relative — the level of bf16
# storage noise).  Default OFF until measured on silicon: the win is zero
# if Mosaic dispatches exp at the same rate as exp2
# (docs/hardware_log.md round-5 roofline note).
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def _exp2_default() -> bool:
    """Env-var default for log2-space scoring — read at TRACE time.

    The flag is captured when a caller traces (first call / new shapes),
    so toggling ``RING_ATTN_EXP2`` after a jitted caller has compiled
    silently has no effect on that compilation — an A/B harness that
    flips the env var mid-process would re-measure the stale basis.
    In-process A/B therefore passes ``exp2=`` explicitly to the public
    entry points (``pallas_flash_attention`` / ``pallas_flash_partials``
    / ``pallas_flash_fused`` / ``pallas_flash_backward``), which both
    bypasses the env var and keys the jit cache correctly; the env var
    remains the right knob for per-process A/B (``tools/hw_session.sh``
    launches ``env RING_ATTN_EXP2=1 python bench.py ...``).  The
    attention custom_vjp resolves the flag ONCE per call in
    ``pallas_flash_attention``, so its forward and backward can never
    disagree on the basis.
    """
    return os.environ.get("RING_ATTN_EXP2", "0") == "1"


def _block_sizes(nq: int, nk: int, block_q: int | None, block_k: int | None):
    bq = min(block_q or DEFAULT_BLOCK_Q, nq)
    bk = min(block_k or DEFAULT_BLOCK_K, nk)
    while nq % bq:
        bq //= 2
    while nk % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)



def _tile_has_work(offs_ref, row0, col0, bq, bk, causal, windowed):
    """Block-level skip predicate: does tile (rows row0.., cols col0..) touch
    the band ``offs[1] <= j - i <= offs[0]``?  True when not causal."""
    if not causal:
        return True
    ok = col0 <= row0 + bq - 1 + offs_ref[0]
    if windowed:
        ok = jnp.logical_and(ok, col0 + bk - 1 >= row0 + offs_ref[1])
    return ok


def _tile_is_edge(offs_ref, row0, col0, bq, bk, causal, windowed):
    """True when the tile straddles the band boundary (some element is
    out-of-band) — only those tiles need the iota/compare/select mask.
    Mirrors the static ``interior`` classification in ``_band_tables`` so
    the compact and rectangular grids run identical per-tile expressions
    (keeping them bit-exact against each other)."""
    if not causal:
        return jnp.bool_(False)
    interior = col0 + bk - 1 <= row0 + offs_ref[0]
    if windowed:
        interior = jnp.logical_and(
            interior, col0 >= row0 + bq - 1 + offs_ref[1]
        )
    return jnp.logical_not(interior)


def _dispatch_tile(offs_ref, row0, col0, bq, bk, causal, windowed, tile):
    """Run ``tile()`` under the band's block-skip / edge-vs-interior
    predicates.  Interior tiles (fully in-band) take a variant with the
    causal/window mask construction statically compiled out — the per-tile
    iota/compare/select is ~half the non-matmul work, and at long sequence
    nearly every tile is interior.  A kv-padding mask still applies there.
    """
    if not causal:
        tile()
        return
    has_work = _tile_has_work(offs_ref, row0, col0, bq, bk, causal, windowed)
    edge = _tile_is_edge(offs_ref, row0, col0, bq, bk, causal, windowed)

    @pl.when(has_work & edge)
    def _compute_edge():
        tile()

    @pl.when(has_work & jnp.logical_not(edge))
    def _compute_interior():
        tile(causal=False, windowed=False)


def _dispatch_tile_compact(tf, tile):
    """Compact-grid analogue of :func:`_dispatch_tile`: the edge/interior
    classification was resolved at table-build time into the ``EDGE`` flag
    (compact grids exist only for static causal bands)."""
    work = (tf & _TF_WORK) != 0
    edge = (tf & _TF_EDGE) != 0

    @pl.when(work & edge)
    def _compute_edge():
        tile()

    @pl.when(work & jnp.logical_not(edge))
    def _compute_interior():
        tile(causal=False, windowed=False)


def _tile_closure(fn, kw, *args):
    """``tile(**over)`` closure for the dispatchers: runs ``fn(*args)`` with
    the kernel's shared tile kwargs, per-call-overridable (the interior fast
    path overrides ``causal``/``windowed``)."""

    def tile(**over):
        fn(*args, **{**kw, **over})

    return tile


def _tile_keep(offs_ref, row0, col0, shape, q_dim, causal, windowed, kvm_ref,
               qseg_ref=None, kseg_ref=None):
    """Per-element keep mask for a score tile, or None if unmasked.

    ``q_dim`` is the tile dimension holding query rows (0 in fwd/dq tiles,
    1 in the transposed dk/dv tiles); the other dimension holds key cols.
    ``qseg_ref``/``kseg_ref`` are per-token document ids ((1, bq)/(1, bk))
    for packed sequences — attention keeps same-document pairs only.
    """
    masked = kvm_ref is not None
    segmented = qseg_ref is not None
    if not (causal or masked or segmented):
        return None
    keep = None
    if causal:
        rows = row0 + lax.broadcasted_iota(jnp.int32, shape, q_dim)
        cols = col0 + lax.broadcasted_iota(jnp.int32, shape, 1 - q_dim)
        keep = cols <= rows + offs_ref[0]
        if windowed:
            keep = jnp.logical_and(keep, cols >= rows + offs_ref[1])
    if masked:
        kvm = kvm_ref[0] != 0
        kvm = kvm[None, :] if q_dim == 0 else kvm[:, None]
        keep = kvm if keep is None else jnp.logical_and(keep, kvm)
    if segmented:
        qs, ks = qseg_ref[0], kseg_ref[0]
        same = (
            qs[:, None] == ks[None, :]
            if q_dim == 0
            else ks[:, None] == qs[None, :]
        )
        keep = same if keep is None else jnp.logical_and(keep, same)
    return keep


# ---------------------------------------------------------------------------
# Compacted causal grids
#
# With a rectangular (outer, inner) tile grid, causal masking skips ~half the
# tiles via pl.when — but every skipped tile still costs a grid step and its
# automatic block DMA (measured on v5e at seq 262144: causal ran only 1.64x
# faster than full instead of 2x).  When the band is statically describable —
# offsets that ARE Python ints (the single-device path), or traced offsets
# whose candidate set is bracketed by a caller ``band_hint`` (ring hops: the
# unrolled hop loop knows each hop's possible offsets, parallel/ring.py) —
# we instead flatten the tile space to just the active tiles:
# scalar-prefetched tables map the linear grid step t to its (outer, inner)
# tile and carry first/last/has-work flags for the accumulator lifecycle.
# This is the TPU answer to the reference kernel's per-block early-exit
# (ref ``triton_flash_attn.py:188-199``): same skipping, but resolved at
# trace time into a smaller grid rather than at runtime.
# ---------------------------------------------------------------------------

_TF_FIRST, _TF_LAST, _TF_WORK, _TF_EDGE = 1, 2, 4, 8

# The compact grid's (t_q, t_k, flags) tables are scalar-prefetched into
# SMEM; small blocks at long sequence can blow past it (512x512 at seq
# 262144 is ~131k tiles x 3 tables x 4B ~ 1.6 MB — Mosaic rejects the
# compile).  Beyond this cap the rectangular grid (runtime predicates, no
# tables) is used instead.
_MAX_COMPACT_TILES = 65536


def _warn_demoted(kind: str, tiles: int, stacklevel: int = 4) -> None:
    """Loud demotion (VERDICT r2 weak #5): losing the compact grid is a
    ~1.17x silent perf cliff at the north-star shape; tell the user which
    call fell off and why so they can grow the block size.

    ``stacklevel`` points the warning at the user's call site: 4 for the
    forward (warn <- _flash_fwd_call <- partials/fused wrapper <- user),
    3 for the backward's one-shorter chain."""
    warnings.warn(
        f"pallas flash {kind}: compact causal grid demoted to the "
        f"rectangular grid ({tiles} band tiles > SMEM table cap "
        f"{_MAX_COMPACT_TILES}); skipped tiles now cost a grid step + block "
        f"DMA — use larger block_q/block_k to re-engage the compact grid",
        stacklevel=stacklevel,
    )


def _compact_maps(h: int, hk: int, g: int):
    """Index maps for a compacted grid (bh, t): q-side blocks follow the
    tile table's q entry, kv-side blocks its k entry (GQA head fold).
    ``qm_map`` serves per-token q-side row vectors (segment ids)."""

    def q_map(bh, t, offs, tq, tk, tf):
        return (bh, tq[t], 0)

    def kv_map(bh, t, offs, tq, tk, tf):
        return ((bh // h) * hk + (bh % h) // g, tk[t], 0)

    def kvm_map(bh, t, offs, tq, tk, tf):
        return (bh // h, tk[t])

    def qm_map(bh, t, offs, tq, tk, tf):
        return (bh // h, tq[t])

    def k_out_map(bh, t, offs, tq, tk, tf):
        return (bh, tk[t], 0)

    return q_map, kv_map, kvm_map, qm_map, k_out_map


def _static_band(causal, windowed, causal_offset, window_lo):
    """True when the band is known at trace time (compact grid usable)."""
    if not causal:
        return False
    if not isinstance(causal_offset, (int, np.integer)):
        return False
    return not windowed or isinstance(window_lo, (int, np.integer))


def _normalize_hint(causal, windowed, causal_offset, window_lo, band_hint):
    """Static band bounds ``(hi_work, hi_int, lo_work, lo_int)`` for compact
    table construction, or None when no static description exists.

    Exactly-static offsets collapse to a tight hint.  A caller-supplied
    ``band_hint`` describes *traced* offsets whose value set is known at
    trace time (ring hops: <= ring_size candidates): ``hi_work``/``lo_work``
    bound the band from OUTSIDE (superset — tiles beyond them are skipped
    for every candidate) and ``hi_int``/``lo_int`` from INSIDE
    (conservative — a tile is interior only if in-band for every
    candidate).  Edge tiles still mask with the runtime scalars, so any
    superset is correct; a tight one is fast.  This is the TPU answer to
    the reference kernel's runtime per-block early exit on ring hops
    (ref ``triton_flash_attn.py:188-199``).
    """
    if not causal:
        return None
    if band_hint is not None:
        hi_w, hi_i, lo_w, lo_i = band_hint
        if not windowed:
            lo_w = lo_i = 0
        return (int(hi_w), int(hi_i), int(lo_w), int(lo_i))
    if _static_band(causal, windowed, causal_offset, window_lo):
        hi = int(causal_offset)
        lo = int(window_lo) if windowed else 0
        return (hi, hi, lo, lo)
    return None


def _check_doc_starts(doc_starts, nq: int, nk: int):
    """Validate a declared packing layout: sorted unique int document start
    offsets beginning at 0, shared by queries and keys (``nq == nk``)."""
    if doc_starts is None:
        return None
    if nq != nk:
        raise ValueError(
            f"doc_starts declares one packing layout for q AND kv, which "
            f"needs nq == nk, got ({nq}, {nk})"
        )
    ds = tuple(int(s) for s in doc_starts)
    if not ds or ds[0] != 0 or list(ds) != sorted(set(ds)) or ds[-1] >= nk:
        raise ValueError(
            f"doc_starts must be sorted unique offsets starting at 0 and "
            f"< {nk}, got {doc_starts!r}"
        )
    return ds


def _docs_block_aligned(doc_starts, *block_sizes) -> bool:
    """True when every document boundary lands on every block boundary —
    the precondition for resolving the document mask at trace time."""
    return all(s % b == 0 for s in doc_starts for b in block_sizes)


def _doc_block_span(doc_starts, pos: int, block: int, n_blocks: int,
                    total: int) -> tuple[int, int]:
    """Inclusive block-index range of the document containing token ``pos``
    (block-aligned layouts only: each block then lies in exactly one doc)."""
    d = bisect.bisect_right(doc_starts, pos) - 1
    start = doc_starts[d]
    end = doc_starts[d + 1] if d + 1 < len(doc_starts) else total
    return start // block, min((end - 1) // block, n_blocks - 1)


def _doc_runtime_ids(doc_starts, n: int, batch: int) -> jax.Array:
    """(b, n) int32 segment ids realizing a declared packing layout — the
    in-kernel-mask fallback when the layout isn't block-aligned."""
    starts = jnp.asarray(doc_starts, jnp.int32)
    ids = jnp.searchsorted(starts, jnp.arange(n, dtype=jnp.int32),
                           side="right") - 1
    return jnp.broadcast_to(ids[None, :], (batch, n)).astype(jnp.int32)


def _band_tile_count(n_q_blocks, n_k_blocks, bq, bk, hint, windowed,
                     outer_is_q: bool, doc_starts=None) -> int:
    """Length of the :func:`_band_tables` tables, in closed form per outer
    row (no table construction — the SMEM cap check must not pay for
    building tables it is about to reject).  Pinned against the real
    tables in ``tests/test_pallas_flash.py``.

    ``doc_starts`` (block-aligned declared packing) intersects each outer
    row's active range with its document's block span — the tile-count
    arithmetic of the packed compact grid."""
    hi, _, lo, _ = hint
    outer_n = n_q_blocks if outer_is_q else n_k_blocks
    inner_n = n_k_blocks if outer_is_q else n_q_blocks
    count = 0
    for o in range(outer_n):
        if outer_is_q:
            row0 = o * bq
            # active ki: ki*bk <= row0+bq-1+hi; windowed: ki*bk+bk-1 >= row0+lo
            i_hi = min((row0 + bq - 1 + hi) // bk, inner_n - 1)
            i_lo = max(-((-(row0 + lo - bk + 1)) // bk), 0) if windowed else 0
        else:
            col0 = o * bk
            # active qi: col0 <= qi*bq+bq-1+hi; windowed: col0+bk-1 >= qi*bq+lo
            i_lo = max(-((-(col0 - hi - bq + 1)) // bq), 0)
            i_hi = (min((col0 + bk - 1 - lo) // bq, inner_n - 1)
                    if windowed else inner_n - 1)
        if doc_starts is not None:
            d_lo, d_hi = _doc_block_span(
                doc_starts,
                o * (bq if outer_is_q else bk),
                bk if outer_is_q else bq,
                inner_n,
                n_q_blocks * bq,
            )
            i_lo, i_hi = max(i_lo, d_lo), min(i_hi, d_hi)
        n = i_hi - i_lo + 1
        count += n if n > 0 else 1  # empty rows get a dummy entry
    return count


class BandPlan(NamedTuple):
    """The compact causal grid a flash launch would run, as data.

    The public seam over ``_band_tables`` / ``_band_tile_count`` for the
    tile-coverage prover (``analysis/coverage.py``) and the on-chip tile
    accounting (``tools/tpu_kernel_validate.py``): everything the kernels
    derive from a band description, without launching anything.

    ``tile_q`` / ``tile_k`` / ``flags`` are the scalar-prefetched tables
    (one entry per grid step; ``flags`` is the FIRST|LAST|WORK|EDGE word);
    ``tiles`` is the CLOSED-FORM count from :func:`_band_tile_count` —
    kept separate from ``len(tile_q)`` on purpose, so callers can hold
    the two implementations against each other (``tests/test_fuzz.py``).
    """

    tile_q: np.ndarray
    tile_k: np.ndarray
    flags: np.ndarray
    tiles: int  # closed-form _band_tile_count (== len(tile_q) by contract)
    block_q: int
    block_k: int
    n_q_blocks: int
    n_k_blocks: int
    hint: tuple[int, int, int, int]
    windowed: bool
    outer_is_q: bool
    doc_starts: tuple[int, ...] | None  # layout the TABLES carry (aligned)
    doc_aligned: bool  # False = declared layout fell back to runtime ids
    compact: bool  # tiles within the SMEM cap (the grid the launch uses)

    @property
    def work_tiles(self) -> int:
        return int((self.flags & _TF_WORK != 0).sum())

    @property
    def edge_tiles(self) -> int:
        return int((self.flags & (_TF_WORK | _TF_EDGE)
                    == (_TF_WORK | _TF_EDGE)).sum())


def band_plan(
    shape: tuple[int, int],
    block_sizes: tuple[int | None, int | None] | None = None,
    hint=0,
    windowed: bool | None = None,
    doc_starts: tuple[int, ...] | None = None,
    *,
    outer_is_q: bool = True,
) -> BandPlan:
    """Build the compact-grid tile plan for one banded flash sweep.

    Args:
      shape: ``(nq, nk)`` token extents of the sweep.
      block_sizes: ``(block_q, block_k)``; ``None`` entries take the
        kernel defaults through the same :func:`_block_sizes` fitting the
        launches use.
      hint: the static band — an int ``hi`` (plain causal offset), a
        ``(hi, lo)`` pair (``lo=None`` = no window), or the full
        ``(hi_work, hi_int, lo_work, lo_int)`` 4-tuple a ring hop's
        :func:`~ring_attention_tpu.parallel.ring._static_hop_band`
        produces (see :func:`_normalize_hint`).
      windowed: whether the band has a lower bound.  Inferred for
        int/pair hints; REQUIRED for a 4-tuple (its ``lo`` slots are
        meaningful only when windowed).
      doc_starts: declared packing layout (:func:`_check_doc_starts`).
        When it lands on the chosen block boundaries the tables drop
        cross-document tiles (``doc_aligned=True``); otherwise the plan
        mirrors the launch-time fallback — band-only tables,
        ``doc_aligned=False``, the document mask left to runtime ids.
      outer_is_q: q-major iteration (fwd/dq passes) vs k-major (dk/dv).
    """
    nq, nk = int(shape[0]), int(shape[1])
    bq, bk = _block_sizes(nq, nk, *(block_sizes or (None, None)))
    if isinstance(hint, (int, np.integer)):
        hint = (int(hint), int(hint), 0, 0)
        if windowed is None:
            windowed = False
        elif windowed:
            raise ValueError("band_plan: a windowed band needs a (hi, lo) "
                             "pair or a 4-tuple hint, not a bare hi")
    elif len(hint) == 2:
        hi, lo = hint
        windowed = lo is not None if windowed is None else windowed
        if windowed and lo is None:
            raise ValueError("band_plan: windowed=True needs a lower offset")
        hint = (int(hi), int(hi), int(lo or 0), int(lo or 0))
    elif len(hint) == 4:
        if windowed is None:
            raise ValueError(
                "band_plan: a 4-tuple hint needs an explicit windowed= — "
                "its lo slots are meaningful only under a window"
            )
        hint = tuple(int(x) for x in hint)
    else:
        raise ValueError(f"band_plan: hint {hint!r} must be an int, a "
                         f"(hi, lo) pair, or a 4-tuple")
    doc_starts = _check_doc_starts(doc_starts, nq, nk)
    doc_aligned = (doc_starts is not None
                   and _docs_block_aligned(doc_starts, bq, bk))
    doc_tables = doc_starts if doc_aligned else None
    nqb, nkb = nq // bq, nk // bk
    tiles = _band_tile_count(nqb, nkb, bq, bk, hint, windowed, outer_is_q,
                             doc_starts=doc_tables)
    tq, tk, tf = _band_tables(nqb, nkb, bq, bk, hint, windowed, outer_is_q,
                              doc_starts=doc_tables)
    return BandPlan(
        tile_q=tq, tile_k=tk, flags=tf, tiles=tiles, block_q=bq, block_k=bk,
        n_q_blocks=nqb, n_k_blocks=nkb, hint=hint, windowed=bool(windowed),
        outer_is_q=outer_is_q, doc_starts=doc_tables, doc_aligned=doc_aligned,
        compact=tiles <= _MAX_COMPACT_TILES,
    )


def _band_tables(n_q_blocks, n_k_blocks, bq, bk, hint, windowed,
                 outer_is_q: bool, doc_starts=None):
    """(t_q, t_k, flags) int32 tables enumerating active band tiles.

    Iteration order is outer-major so the inner dimension carries the
    accumulator: q-major for the fwd/dq passes (carry = online softmax /
    dq), k-major for the dk/dv pass.  Rows with no active tile get one
    dummy entry (flags = FIRST|LAST, no WORK) so their zero-initialized
    output block is still written, matching the rectangular grid's
    behavior for fully-masked rows.

    ``hint`` is ``(hi_work, hi_int, lo_work, lo_int)`` — see
    :func:`_normalize_hint`.  ``WORK`` uses the outer (superset) bounds;
    ``EDGE`` marks tiles not provably interior under the inner bounds, and
    only those construct the iota/compare/select mask (with the *runtime*
    band scalars) — under a long-sequence causal grid ~99% of the active
    tiles are interior.  Superset-only tiles are fully masked at run time;
    their contribution is wiped by the online-softmax rescale exactly like
    any fully-masked edge tile.

    ``doc_starts`` (a block-boundary-aligned declared packing layout, see
    :func:`_check_doc_starts`) additionally drops every cross-document
    tile: each block then lies in exactly one document, so a tile is
    active only when its q and k blocks share one — the packed-sequence
    analogue of the causal skip, resolved at trace time into a smaller
    grid rather than masked at run time.
    """
    hi_w, hi_i, lo_w, lo_i = hint
    tq, tk, tf = [], [], []
    outer_n = n_q_blocks if outer_is_q else n_k_blocks
    inner_n = n_k_blocks if outer_is_q else n_q_blocks

    def doc_of(pos):
        return bisect.bisect_right(doc_starts, pos) - 1

    for o in range(outer_n):
        start = len(tf)
        for i in range(inner_n):
            qi, ki = (o, i) if outer_is_q else (i, o)
            row0, col0 = qi * bq, ki * bk
            active = col0 <= row0 + bq - 1 + hi_w
            if windowed:
                active = active and col0 + bk - 1 >= row0 + lo_w
            if active and doc_starts is not None:
                active = doc_of(row0) == doc_of(col0)
            if active:
                interior = col0 + bk - 1 <= row0 + hi_i and (
                    not windowed or col0 >= row0 + bq - 1 + lo_i
                )
                tq.append(qi)
                tk.append(ki)
                tf.append(_TF_WORK | (0 if interior else _TF_EDGE))
        if len(tf) == start:  # empty row: dummy entry, write zeros
            tq.append(o if outer_is_q else 0)
            tk.append(0 if outer_is_q else o)
            tf.append(0)
        tf[start] |= _TF_FIRST
        tf[-1] |= _TF_LAST
    return (np.asarray(tq, np.int32), np.asarray(tk, np.int32),  # ra: allow(RA009 trace-time static tile tables — python ints, never traced)
            np.asarray(tf, np.int32))  # ra: allow(RA009 trace-time static tile tables — python ints, never traced)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_write(fused, outs, acc, m, l, exp2=False):
    """Final write: raw partials for ring merging, or the fused normalized
    output + lse when no merge follows (the reference's
    ``RETURN_NORMALIZED_OUTPUT``, ref ``triton_flash_attn.py:273-275``) —
    at seq 262144 the raw path round-trips a 512 MB f32 accumulator
    through HBM that the fused path never materializes.

    Under log2-space scoring the running max is in log2 units; it converts
    back to natural units here — a (bq, 1) op per block — so the emitted
    partials/lse contract is basis-independent (ring merging and XLA-path
    interop see identical values either way)."""
    if fused:
        out_ref, lse_ref = outs
        l_safe = jnp.maximum(l[:], EPSILON)
        out_ref[0] = (acc[:] / l_safe).astype(out_ref.dtype)
        m_nat = m[:] * LN2 if exp2 else m[:]
        lse_ref[0] = m_nat + jnp.log(l_safe)
    else:
        acc_ref, m_ref, l_ref = outs
        acc_ref[0] = acc[:]
        m_ref[0] = m[:] * LN2 if exp2 else m[:]
        l_ref[0] = l[:]


def _fwd_kernel(*refs, compact: bool, masked: bool, segmented: bool,
                fused: bool, resume: bool, nk_blocks: int, **tile_kw):
    """Unified forward kernel.

    Ref layout (pallas passes scalar-prefetch, inputs, outputs, scratch
    positionally; the static flags say which are present):
      scalars: offs (+ tq/tk/tf tile tables when ``compact``)
      inputs:  q, k, v (+ q/k/v dequant scales when the tile kwargs
               carry ``quantized`` — the int8 compute path: q/k/v are
               int8 values; the q/k scales are per-ROW f32 vectors
               ((1, bq)/(1, bk) blocks), the v scale a (1, 1) per-block
               scalar)
               (+ kv mask when ``masked``)
               (+ q/kv segment ids when ``segmented`` — packed sequences
                masked in-kernel; a block-aligned declared layout resolves
                them into the compact tables instead and ships no refs)
               (+ carry acc/m/l when ``resume`` — the running online-softmax
                state of previous ring hops, continued in-kernel exactly
                like the reference's ``LOAD_ACCUMULATED`` resume, ref
                ``triton_flash_attn.py:124-165``)
      outputs: (out, lse) when ``fused`` else (acc, m, l)
      scratch: acc (bq, d) f32, m (bq, 1) f32, l (bq, 1) f32
    """
    bq, bk = tile_kw["bq"], tile_kw["bk"]
    # consumed by _fwd_tile too
    tile_kw = dict(tile_kw, masked=masked, segmented=segmented)
    if compact:
        offs_ref, tq_ref, tk_ref, tf_ref = refs[:4]
        idx = 4
    else:
        offs_ref = refs[0]
        idx = 1
    q_ref, k_ref, v_ref = refs[idx:idx + 3]
    idx += 3
    scale_refs = None
    if tile_kw.get("quantized"):
        scale_refs = refs[idx:idx + 3]
        idx += 3
    kvm_ref = refs[idx] if masked else None
    idx += 1 if masked else 0
    qseg_ref = kseg_ref = None
    if segmented:
        qseg_ref, kseg_ref = refs[idx:idx + 2]
        idx += 2
    carry_refs = None
    if resume:
        carry_refs = refs[idx:idx + 3]
        idx += 3
    outs = refs[idx:idx + (2 if fused else 3)]
    acc, m, l = refs[idx + (2 if fused else 3):]

    if compact:
        t = pl.program_id(1)
        tf = tf_ref[t]
        first = (tf & _TF_FIRST) != 0
        last = (tf & _TF_LAST) != 0
        row0, col0 = tq_ref[t] * bq, tk_ref[t] * bk
    else:
        ki = pl.program_id(2)
        first = ki == 0
        last = ki == nk_blocks - 1
        row0, col0 = pl.program_id(1) * bq, ki * bk

    @pl.when(first)
    def _init():
        if resume:
            acc[:] = carry_refs[0][0]
            # carries cross hops in natural units (basis-independent
            # contract, see _fwd_write); log2-space kernels convert on load
            m[:] = (carry_refs[1][0] * LOG2E if tile_kw.get("exp2")
                    else carry_refs[1][0])
            l[:] = carry_refs[2][0]
        else:
            acc[:] = jnp.zeros_like(acc)
            m[:] = jnp.full_like(m, MASK_VALUE)
            l[:] = jnp.zeros_like(l)

    tile = _tile_closure(_fwd_tile, tile_kw, offs_ref, q_ref, k_ref, v_ref,
                         kvm_ref, qseg_ref, kseg_ref, scale_refs, acc, m, l,
                         row0, col0)
    if compact:
        _dispatch_tile_compact(tf, tile)
    else:
        _dispatch_tile(offs_ref, row0, col0, bq, bk, tile_kw["causal"],
                       tile_kw["windowed"], tile)

    @pl.when(last)
    def _write():
        _fwd_write(fused, outs, acc, m, l, exp2=tile_kw.get("exp2", False))


def _softclamp(s, clamp, exp2):
    """Clamp a score tile in natural units: ``c * tanh(s_nat / c)``, with
    ``s`` (and the result) in log2 units when ``exp2`` — the one clamp
    basis transform shared by the fwd tile and both bwd recomputes."""
    if exp2:
        return jnp.tanh(s * (LN2 / clamp)) * (clamp * LOG2E)
    return jnp.tanh(s / clamp) * clamp


def _softclamp_grad_factor(s_clamped, clamp, exp2):
    """tanh' = 1 - (clamped_natural / c)^2 from the post-clamp scores
    (log2-basis under ``exp2``); multiplies ds in both bwd passes."""
    s_nat = s_clamped * LN2 if exp2 else s_clamped
    return 1.0 - (s_nat / clamp) ** 2


def _online_update(s, v, acc, m, l, exp2=False, v_scale=None):
    """One online-softmax accumulator step over a masked score tile ``s``
    against value rows ``v`` — THE shared tile math of every forward-shaped
    kernel in this module (``p`` is cast to ``v.dtype`` so bf16 callers run
    the pv matmul in bf16 and f32 callers in f32).  With ``exp2`` the tile
    is in log2 space (s and m both scaled by log2e), so ``p``/``alpha``/
    ``l``/``acc`` come out value-identical with a cheaper exponential.

    ``v_scale`` (a per-tile f32 scalar) selects the int8 PV path: ``v``
    is then int8 values whose block dequant scale is ``v_scale``, ``p``
    quantizes to int8 per row (``quant.quantize_p`` — per-row absmax, so
    late tiles whose ``p`` is small against the RUNNING max keep their
    resolution), the PV matmul runs on int8 operands into an f32
    accumulator, and the dequant factors fold into one ``(bq, 1)``
    multiply on the product (the per-row p scale rides the free index;
    ``v_scale`` is scalar).  ``l`` sums the SAME quantized ``p`` so
    ``out = acc / l`` stays exactly normalized over the weights actually
    applied."""
    ex = jnp.exp2 if exp2 else jnp.exp
    m_prev = m[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = ex(s - m_new)
    alpha = ex(m_prev - m_new)
    if v_scale is None:
        l[:] = l[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[:] = acc[:] * alpha + pv
    else:
        p8, p_scale = _quant.quantize_p(p)
        # scale BEFORE the row-sum on purpose: reassociating to
        # sum(p8) * p_scale is value-identical but would accumulate
        # undequantized int8 content — the exact pattern the precision
        # auditor forbids (dequant-before-reduce, no exceptions)
        l[:] = l[:] * alpha + jnp.sum(
            p8.astype(jnp.float32) * p_scale, axis=1, keepdims=True,
        )
        pv8 = lax.dot_general(
            p8, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[:] = acc[:] * alpha + pv8 * (p_scale * v_scale)
    m[:] = m_new


def _fwd_tile(offs_ref, q_ref, k_ref, v_ref, kvm_ref, qseg_ref, kseg_ref,
              scale_refs, acc, m, l, row0, col0, *, scale, softclamp_value,
              causal, windowed, masked, segmented, bq, bk, exp2=False,
              quantized=False):
    q = q_ref[0]
    k = k_ref[0]
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if quantized:
        # int8 operands: s is the raw int8 QK^T accumulated in f32.  The
        # q/k scales ride the matmul's FREE indices (per-row absmax —
        # row/col vectors on the score tile), so dequantization is exact
        # and the softmax scale (and the log2-space basis factor) folds
        # into the same ONE fused rescale multiply (docs/precision.md)
        qs_ref, ks_ref, _ = scale_refs
        s = s * ((qs_ref[0] * (scale * LOG2E if exp2 else scale))[:, None]
                 * ks_ref[0][None, :])
    elif scale != 1.0:  # static: folded into q for power-of-two scales
        s = s * scale
    if softclamp_value is not None:
        s = _softclamp(s, softclamp_value, exp2)

    keep = _tile_keep(
        offs_ref, row0, col0, (bq, bk), 0, causal, windowed,
        kvm_ref if masked else None,
        qseg_ref if segmented else None,
        kseg_ref if segmented else None,
    )
    if keep is not None:
        s = jnp.where(keep, s, MASK_VALUE)

    _online_update(
        s, v_ref[0], acc, m, l, exp2=exp2,
        v_scale=scale_refs[2][0, 0] if quantized else None,
    )


class FlashPartials(NamedTuple):
    """Raw online-softmax partials: out = acc / l, lse = m + log l."""

    acc: jax.Array  # (b, h, nq, d) f32
    m: jax.Array  # (b, h, nq) f32
    l: jax.Array  # (b, h, nq) f32


def _flash_fwd_call(
    q, k, v, kv_mask, *,
    scale, causal_offset, window_lo, softclamp_value,
    block_q, block_k, band_hint, interpret, fused, carry=None,
    exp2=None, q_segment_ids=None, kv_segment_ids=None, doc_starts=None,
    compute_dtype=None, kv_quantized=None, name=None,
):
    """Shared forward launcher: one flash sweep over a KV span.

    ``fused=False`` returns mergeable :class:`FlashPartials` (ring hops);
    ``fused=True`` returns ``(out in q.dtype, lse f32)`` with normalization
    folded into the kernel's final write (no-merge callers).  ``carry``
    resumes a previous sweep's ``(acc, m, l)`` state in-kernel (the
    reference's ``LOAD_ACCUMULATED``, ref ``triton_flash_attn.py:124-165``)
    — one HBM read of the carry instead of an XLA-side
    :func:`merge_partials` that reads both operands and writes a third.

    Packed sequences: ``q_segment_ids``/``kv_segment_ids`` mask
    cross-document pairs in-kernel; ``doc_starts`` *declares* the packing
    layout statically, and when it lands on block boundaries under a
    compact causal grid the cross-document tiles are dropped from the grid
    at trace time instead (no refs, no per-tile mask) — misaligned or
    demoted layouts fall back to the in-kernel mask.

    ``compute_dtype="int8"`` runs QK^T and PV on int8 operands: q is
    quantized per q-block and k/v per KV-block (symmetric absmax,
    ``ops/quant.py``), the dequant-scale multiply folds into the per-tile
    softmax rescale, ``p`` quantizes at the fixed full scale for the PV
    matmul, and the ``(acc, m, l)`` state stays f32 end to end
    (``docs/precision.md``).  ``kv_quantized`` (a
    :class:`~ring_attention_tpu.ops.quant.QuantizedBlockKV` whose
    ``block`` equals this launch's fitted ``block_k``) feeds
    pre-quantized K/V directly — the ring's dequant-free hop composition;
    ``k``/``v`` may then be None."""
    b, h, nq, d = q.shape
    if compute_dtype not in (None, "int8"):
        raise ValueError(
            f"compute_dtype={compute_dtype!r}: supported values are None "
            '(model-dtype matmuls) and "int8" (quantized QK^T/PV)'
        )
    quantized = compute_dtype == "int8"
    if kv_quantized is not None and not quantized:
        raise ValueError('kv_quantized requires compute_dtype="int8"')
    kshape = (kv_quantized.k_q if kv_quantized is not None else k).shape
    _, hk, nk, _ = kshape
    g = h // hk
    bq, bk = _block_sizes(nq, nk, block_q, block_k)
    interpret = _interpret_default() if interpret is None else interpret
    doc_starts = _check_doc_starts(doc_starts, nq, nk)

    # power-of-two scale (every d = 4^k head dim, incl. the headline d=64
    # -> 1/8) folds into q exactly (exponent shift, bit-identical scores)
    # BEFORE the launch, deleting the per-tile (bq, bk) VPU multiply from
    # the score path — the roofline puts fwd within ~30% of VPU-bound
    # (docs/hardware_log.md, round-5 roofline note), so score-path VPU ops
    # are the scarce resource.  Non-power-of-two scales keep the in-kernel
    # multiply: folding those would round q a second time.
    # exp2 (explicit kw, or RING_ATTN_EXP2=1 when None — trace-time
    # capture, see _exp2_default) moves the whole tile into log2 space
    # (fold scale*log2e, exponentials become exp2).
    exp2 = _exp2_default() if exp2 is None else bool(exp2)
    if quantized:
        # int8 q cannot absorb a float fold; the softmax scale (and the
        # log2-space basis factor) ride the per-tile dequant multiply
        # instead — see _fwd_tile's quantized branch
        pass
    elif exp2:
        q = q * jnp.asarray(scale * LOG2E, q.dtype)
        scale = 1.0
    elif scale != 1.0 and math.frexp(float(scale))[0] == 0.5:
        q = q * jnp.asarray(scale, q.dtype)
        scale = 1.0

    causal = causal_offset is not None
    windowed = window_lo is not None and causal
    masked = kv_mask is not None
    resume = carry is not None

    offs = jnp.asarray(
        [
            causal_offset if causal else 0,
            window_lo if windowed else 0,
        ],
        jnp.int32,
    )

    hint = _normalize_hint(causal, windowed, causal_offset, window_lo,
                           band_hint)
    compact = hint is not None
    # trace-time doc skip needs a compact grid AND a block-aligned layout
    doc_tables = (
        doc_starts
        if compact and doc_starts is not None
        and _docs_block_aligned(doc_starts, bq, bk)
        else None
    )

    if compact:
        tiles = _band_tile_count(
            nq // bq, nk // bk, bq, bk, hint, windowed, outer_is_q=True,
            doc_starts=doc_tables,
        )
        compact = tiles <= _MAX_COMPACT_TILES
        if not compact:
            _warn_demoted("fwd", tiles)
            doc_tables = None

    if doc_tables is not None:
        # the tables carry the whole document mask: ship no segment refs
        q_segment_ids = kv_segment_ids = None
    elif doc_starts is not None and q_segment_ids is None:
        # misaligned/demoted declared layout: realize it as runtime ids
        q_segment_ids = kv_segment_ids = _doc_runtime_ids(doc_starts, nq, b)
    segmented = q_segment_ids is not None

    common = dict(
        scale=scale,
        softclamp_value=softclamp_value,
        causal=causal,
        windowed=windowed,
        masked=masked,
        segmented=segmented,
        bq=bq,
        bk=bk,
        exp2=exp2,
        quantized=quantized,
    )

    if compact:
        tq_a, tk_a, tf_a = (
            jnp.asarray(t)
            for t in _band_tables(nq // bq, nk // bk, bq, bk, hint,
                                  windowed, outer_is_q=True,
                                  doc_starts=doc_tables)
        )
        (q, k, v, kv_mask, q_segment_ids, kv_segment_ids, offs, tq_a, tk_a,
         tf_a) = _unify_vma(
            q, k, v, kv_mask, q_segment_ids, kv_segment_ids, offs, tq_a,
            tk_a, tf_a
        )
        scalars = (offs, tq_a, tk_a, tf_a)
        grid = (b * h, tq_a.shape[0])
        q_map, kv_map, kvm_map, qm_map, _ = _compact_maps(h, hk, g)
        semantics = ("parallel", "arbitrary")

        def qsc_map(bh, t, offs, tq, tk, tf):
            return (bh, tq[t])

        def ksc_map(bh, t, offs, tq, tk, tf):
            return ((bh // h) * hk + (bh % h) // g, tk[t])

        vsc_map = ksc_map  # v block scales index like k rows, block (1, 1)
    else:
        q, k, v, kv_mask, q_segment_ids, kv_segment_ids, offs = _unify_vma(
            q, k, v, kv_mask, q_segment_ids, kv_segment_ids, offs
        )
        scalars = (offs,)
        grid = (b * h, nq // bq, nk // bk)

        def q_map(bh, qi, ki, *_):
            return (bh, qi, 0)

        def kv_map(bh, qi, ki, *_):
            return ((bh // h) * hk + (bh % h) // g, ki, 0)

        def kvm_map(bh, qi, ki, *_):
            return (bh // h, ki)

        def qm_map(bh, qi, ki, *_):
            return (bh // h, qi)

        def qsc_map(bh, qi, ki, *_):
            return (bh, qi)

        def ksc_map(bh, qi, ki, *_):
            return ((bh // h) * hk + (bh % h) // g, ki)

        vsc_map = ksc_map

        # batch*head and q-block grid dims are independent (megacore can
        # split them); the kv dim carries the online-softmax state
        semantics = ("parallel", "parallel", "arbitrary")

    kernel = functools.partial(
        _fwd_kernel,
        compact=compact,
        fused=fused,
        resume=resume,
        nk_blocks=nk // bk,
        **common,
    )

    if quantized:
        # q quantizes per row HERE (it is exact bf16 at every call site —
        # ring hops re-quantize the rotating pack's q, cheap VPU work);
        # k/v either arrive pre-quantized (the ring's dequant-free hop
        # feed) or quantize now — k per row (a FREE index of QK^T, so the
        # scale pulls out exactly), v per KV-block (PV contracts over
        # tokens; only a per-block scalar pulls out of that matmul).
        qr, qs = _quant.quantize_rows(q.reshape(b * h, nq, d))
        if kv_quantized is not None:
            if kv_quantized.block != bk:
                raise ValueError(
                    f"kv_quantized was packed at v-block "
                    f"{kv_quantized.block} but this launch fitted "
                    f"block_k={bk}; quantize at the kernel's fitted block "
                    f"(see parallel/ring.py)"
                )
            kr = kv_quantized.k_q.reshape(b * hk, nk, d)
            vr = kv_quantized.v_q.reshape(b * hk, nk, d)
            ks = kv_quantized.k_scale.reshape(b * hk, nk)
            vs = kv_quantized.v_scale.reshape(b * hk, nk // bk)
        else:
            kr, ks = _quant.quantize_rows(k.reshape(b * hk, nk, d))
            vr, vs = _quant.quantize_blocks(v.reshape(b * hk, nk, d), bk)
        qs, ks, vs, kr, vr = (_unify_vma(x, q)[0] for x in (qs, ks, vs, kr, vr))
        qs = qs.astype(jnp.float32)
        ks = ks.astype(jnp.float32)
        vs = vs.astype(jnp.float32)
    else:
        qr = q.reshape(b * h, nq, d)
        kr = k.reshape(b * hk, nk, d)
        vr = v.reshape(b * hk, nk, d)

    in_specs = [
        pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, kr, vr]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bq), qsc_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk), ksc_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), vsc_map, memory_space=pltpu.VMEM),
        ]
        inputs += [qs, ks, vs]
    if masked:
        kvm = kv_mask.astype(jnp.int8)
        in_specs.append(pl.BlockSpec((1, bk), kvm_map, memory_space=pltpu.VMEM))
        inputs.append(kvm)
    if segmented:
        in_specs += [
            pl.BlockSpec((1, bq), qm_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk), kvm_map, memory_space=pltpu.VMEM),
        ]
        inputs += [
            q_segment_ids.astype(jnp.int32),
            kv_segment_ids.astype(jnp.int32),
        ]
    if resume:
        c_acc, c_m, c_l = (_unify_vma(x, q)[0] for x in carry)
        inputs += [
            c_acc.reshape(b * h, nq, d),
            c_m.reshape(b * h, nq, 1),
            c_l.reshape(b * h, nq, 1),
        ]
        in_specs += [
            pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        ]

    if fused:
        out_specs = [
            pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        ]
        out_shape = [
            _sds((b * h, nq, d), q.dtype, q),
            _sds((b * h, nq, 1), jnp.float32, q),
        ]
    else:
        out_specs = [
            pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        ]
        out_shape = [
            _sds((b * h, nq, d), jnp.float32, q),
            _sds((b * h, nq, 1), jnp.float32, q),
            _sds((b * h, nq, 1), jnp.float32, q),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
    )

    # stable kernel names: XProf shows the Mosaic custom-call under this
    # label, so traces attribute time to "which flash sweep" (resume = a
    # ring hop continuing a carry) — docs/observability.md
    if name is None:
        name = "flash_fwd_tile" if fused else "flash_partials_tile"
        if resume:
            name += "_resume"
        if quantized:
            name += "_q8"  # int8 sweeps attribute separately in XProf
    results = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=semantics
        ),
        interpret=interpret,
        name=name,
    )(*scalars, *inputs)

    if fused:
        out, lse = results
        return out.reshape(b, h, nq, d), lse.reshape(b, h, nq)
    acc, m, l = results
    return FlashPartials(
        acc.reshape(b, h, nq, d),
        m.reshape(b, h, nq),
        l.reshape(b, h, nq),
    )


def pallas_flash_partials(  # ra: allow(RA007 per-hop kernel launch; ring/zigzag entry points validate first)
    q: jax.Array,  # (b, h, nq, d)
    k: jax.Array,  # (b, hk, nk, d)
    v: jax.Array,  # (b, hk, nk, d)
    kv_mask: jax.Array | None = None,  # (b, nk) bool
    *,
    scale: float,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    softclamp_value: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    band_hint: tuple[int, int, int, int] | None = None,
    carry: FlashPartials | None = None,
    interpret: bool | None = None,
    exp2: bool | None = None,
    segment_ids=None,
    doc_starts: tuple[int, ...] | None = None,
    compute_dtype: str | None = None,
    kv_quantized: QuantizedBlockKV | None = None,
) -> FlashPartials:
    """One flash sweep over a KV span, returning mergeable partials.

    ``window_lo``: absolute band lower offset (see ``ops/flash.py``);
    may be a traced per-device scalar under SPMD.  ``band_hint`` supplies
    static band bounds for traced offsets so the compacted causal grid
    still engages (see :func:`_normalize_hint`).  ``carry`` continues a
    previous sweep's online softmax in-kernel (ring hops) — equivalent to
    ``merge_partials(carry, <this sweep>)`` without the XLA-side merge
    traffic.  ``exp2`` selects log2-space scoring explicitly (None =
    the ``RING_ATTN_EXP2`` env var, captured at trace time — see
    :func:`_exp2_default`); the emitted partials are natural-basis either
    way, so sweeps of different bases merge exactly.

    ``segment_ids`` (a ``(b, n)`` array or ``(q_ids, kv_ids)`` pair) masks
    cross-document pairs for packed sequences; ``doc_starts`` declares the
    packing statically so a block-aligned layout drops cross-document
    tiles from the compact grid at trace time (``docs/packing.md``).

    ``compute_dtype="int8"`` runs QK^T/PV on int8 operands with per-block
    absmax scales and f32 ``(acc, m, l)`` untouched; ``kv_quantized``
    feeds pre-quantized K/V directly (the ring's dequant-free hop
    composition — ``k``/``v`` may then be None).  See
    ``docs/precision.md``.
    """
    q_seg, kv_seg = normalize_segment_ids(
        segment_ids, q,
        kv_quantized.k_q if kv_quantized is not None else k,
        "pallas_flash_partials",
    )
    return _flash_fwd_call(
        q, k, v, kv_mask,
        scale=scale, causal_offset=causal_offset, window_lo=window_lo,
        softclamp_value=softclamp_value, block_q=block_q, block_k=block_k,
        band_hint=band_hint, interpret=interpret, fused=False, carry=carry,
        exp2=exp2, q_segment_ids=q_seg, kv_segment_ids=kv_seg,
        doc_starts=doc_starts, compute_dtype=compute_dtype,
        kv_quantized=kv_quantized,
    )


def pallas_flash_fused(  # ra: allow(RA007 final-hop kernel launch; ring entry points validate first)
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    scale: float,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    softclamp_value: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    band_hint: tuple[int, int, int, int] | None = None,
    carry: FlashPartials | None = None,
    interpret: bool | None = None,
    exp2: bool | None = None,
    segment_ids=None,
    doc_starts: tuple[int, ...] | None = None,
    compute_dtype: str | None = None,
    kv_quantized: QuantizedBlockKV | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Single-span forward with normalization fused into the final kernel
    write: returns ``(out in q.dtype, lse f32)`` directly.

    For callers with no downstream partial merge (the local/non-ring path,
    or a ring's LAST hop via ``carry``) this replaces ``finalize_partials``
    and skips materializing the f32 ``(acc, m, l)`` triple in HBM entirely
    (ref ``triton_flash_attn.py:273-275`` fuses the same way, and
    ``ring_flash_attention_cuda.py:134,182-186`` fuses it into the last
    hop).  ``band_hint`` (superset bounds for traced offsets) requires a
    ``carry``: a hint's superset-only tiles leave band-empty rows holding
    masked garbage that only a rescale against real content can wipe —
    with a carry the wipe happens in-kernel (by the ring's last hop every
    row's carry holds its own-diagonal content), without one there is no
    later merge to do it.
    """
    if band_hint is not None and carry is None:
        # not an assert: violating this silently yields uniform-weight
        # garbage for band-empty rows, and asserts vanish under python -O
        raise ValueError(
            "pallas_flash_fused: band_hint needs a carry (see docstring)"
        )
    q_seg, kv_seg = normalize_segment_ids(
        segment_ids, q,
        kv_quantized.k_q if kv_quantized is not None else k,
        "pallas_flash_fused",
    )
    return _flash_fwd_call(
        q, k, v, kv_mask,
        scale=scale, causal_offset=causal_offset, window_lo=window_lo,
        softclamp_value=softclamp_value, block_q=block_q, block_k=block_k,
        band_hint=band_hint, interpret=interpret, fused=True, carry=carry,
        exp2=exp2, q_segment_ids=q_seg, kv_segment_ids=kv_seg,
        doc_starts=doc_starts, compute_dtype=compute_dtype,
        kv_quantized=kv_quantized,
    )


# Decode streams the whole KV cache once per kv head; large blocks amortize
# grid steps and keep the DMA pipeline deep (2 x bk x d bf16 double-buffered
# = 4 MB of VMEM at 8192x64 — well under budget).
DEFAULT_BLOCK_DECODE = 8192


def _decode_fold_rows(q, hk):
    """Fold the GQA head group onto query rows — ``(b, h, nq, d) ->
    (b, hk, g*nq(+pad), d)`` — padding rows up to one sublane tile: Mosaic
    handles tiny row blocks unevenly across generations, and the pad rows
    cost nothing against a bandwidth-bound sweep (zero queries -> uniform
    weights -> finite outputs, sliced away by the caller).  One sublane
    tile is 32 / itemsize rows (8 for f32, 16 for bf16/f16, 32 for
    one-byte dtypes) — keyed on itemsize, not a bf16 check."""
    b, h, nq, d = q.shape
    g = h // hk
    rows = g * nq
    min_rows = max(8, 32 // jnp.dtype(q.dtype).itemsize)
    pad = (-rows) % min_rows
    qf = q.reshape(b, hk, rows, d)
    if pad:
        qf = jnp.pad(qf, [(0, 0), (0, 0), (0, pad), (0, 0)])
    return qf, rows, pad


def pallas_flash_decode(
    q: jax.Array,  # (b, h, nq, d) — nq is tiny (typically 1)
    k: jax.Array,  # (b, hk, nk, d)
    v: jax.Array,  # (b, hk, nk, d)
    kv_mask: jax.Array | None = None,  # (b, nk) bool, True = attend
    *,
    scale: float | None = None,
    softclamp_value: float | None = None,
    block_k: int | None = None,
    fused: bool = True,
    interpret: bool | None = None,
):
    """Decode-time flash attention: the KV cache is read once per *KV head*.

    The training kernels grid over ``b*h`` query heads, so under GQA each
    KV block is fetched ``g = h/hk`` times — irrelevant when compute
    dominates, but decode (``nq`` ~ 1) is pure HBM bandwidth: the KV read
    IS the cost.  Here the head group folds onto the query-row dimension
    (``(b, h, nq, d) -> (b, hk, g*nq, d)``) and the sweep grids over
    ``b*hk``, so every cache byte crosses HBM exactly once — the same
    single-kernel decode the reference reaches for via its Triton path
    (ref ``tree_attn_decoding.py:60-72``), minus its g-fold repeat
    (ref ``tree_attn_decoding.py:47-52`` materializes grouped queries).

    No causal band: decode queries attend the whole (masked) cache, like
    the reference decode (ref ``tree_attn_decoding.py:23-103``); cache
    validity (``[0, pos]``, lookback windows, ragged shards) is the
    ``kv_mask``.

    Returns:
      ``fused=True``: ``(out (b, h, nq, d) in q.dtype, lse (b, h, nq) f32)``
      — normalization in the kernel's final write; the single-device path.
      ``fused=False``: raw ``(acc (b, hk, g, nq, d), m, l (b, hk, g, nq))``
      f32 partials in the ``ops.flash.FlashCarry`` layout, for the
      tree-decode cross-device merge (``parallel/tree_decode.py``).
    """
    check_attention_args("pallas_flash_decode", q, k, v, kv_mask)
    b, h, nq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    if scale is None:
        scale = d**-0.5
    qf, rows, pad = _decode_fold_rows(q, hk)
    res = _flash_fwd_call(
        qf, k, v, kv_mask,
        scale=scale, causal_offset=None, window_lo=None,
        softclamp_value=softclamp_value,
        block_q=rows + pad, block_k=block_k or DEFAULT_BLOCK_DECODE,
        band_hint=None, interpret=interpret, fused=fused,
        name="flash_decode",
    )
    if fused:
        out, lse = res
        return (
            out[:, :, :rows].reshape(b, h, nq, d),
            lse[:, :, :rows].reshape(b, h, nq),
        )
    acc, m, l = res
    return (
        acc[:, :, :rows].reshape(b, hk, g, nq, d),
        m[:, :, :rows].reshape(b, hk, g, nq),
        l[:, :, :rows].reshape(b, hk, g, nq),
    )


class QuantizedKV(NamedTuple):
    """Int8 KV cache with per-token dequantization scales.

    Decode at long context is pure HBM bandwidth — the KV read IS the cost
    (measured 1.05 ms/token = 255 GB/s at a 1M-token bf16 cache on one
    v5e).  Storing the cache as int8 with one f32 scale per (head, token)
    row cuts the bytes per k-or-v row from 128 (bf16 at d=64) to 68
    (64 int8 + 4 scale), a 1.88x decode-bandwidth win, at per-row absmax
    quantization error (~0.4% RMS on gaussian activations).  No reference
    equivalent (its decode reads the fp16 cache directly,
    ref ``tree_attn_decoding.py:54-79``)."""

    k_q: jax.Array  # (b, hk, nk, d) int8
    k_scale: jax.Array  # (b, hk, nk) f32
    v_q: jax.Array  # (b, hk, nk, d) int8
    v_scale: jax.Array  # (b, hk, nk) f32


def quantize_kv_cache(k: jax.Array, v: jax.Array) -> QuantizedKV:
    """Per-token symmetric absmax int8 quantization of a KV cache
    (``ops/quant.py::quantize_rows`` — the one int8 codec seam)."""
    k_q, k_scale = _quant.quantize_rows(k)
    v_q, v_scale = _quant.quantize_rows(v)
    return QuantizedKV(k_q, k_scale, v_q, v_scale)


def dequantize_kv_cache(
    kv: QuantizedKV, dtype=jnp.bfloat16
) -> tuple[jax.Array, jax.Array]:
    """Materialize the KV a quantized cache represents (the non-pallas
    decode fallback and the parity-test oracle)."""
    k = _quant.dequantize_rows(kv.k_q, kv.k_scale, dtype)
    v = _quant.dequantize_rows(kv.v_q, kv.v_scale, dtype)
    return k, v


def _decode_q8_kernel(q_ref, kq_ref, ks_ref, vq_ref, vs_ref, *rest,
                      masked, fused, scale, softclamp_value, nk_blocks):
    kvm_ref = rest[0] if masked else None
    rest = rest[1 if masked else 0:]
    outs = rest[:-3]
    acc, m, l = rest[-3:]
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, MASK_VALUE)
        l[:] = jnp.zeros_like(l)

    # dequantize in f32: int8 -> f32 is exact and the scale multiply rides
    # the VPU while the sweep waits on the (now 1.88x smaller) KV DMA;
    # accumulation and final write are the shared _online_update/_fwd_write
    k = kq_ref[0].astype(jnp.float32) * ks_ref[0][:, None]
    s = lax.dot_general(
        q_ref[0].astype(jnp.float32), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = s * scale
    if softclamp_value is not None:
        s = jnp.tanh(s / softclamp_value) * softclamp_value
    if masked:
        s = jnp.where((kvm_ref[0] != 0)[None, :], s, MASK_VALUE)

    v = vq_ref[0].astype(jnp.float32) * vs_ref[0][:, None]
    _online_update(s, v, acc, m, l)

    @pl.when(ki == nk_blocks - 1)
    def _write():
        _fwd_write(fused, outs, acc, m, l)


def pallas_flash_decode_q8(
    q: jax.Array,  # (b, h, nq, d) — nq is tiny (typically 1)
    kv: QuantizedKV,
    kv_mask: jax.Array | None = None,  # (b, nk) bool, True = attend
    *,
    scale: float | None = None,
    softclamp_value: float | None = None,
    block_k: int | None = None,
    fused: bool = True,
    interpret: bool | None = None,
):
    """:func:`pallas_flash_decode` against an int8 :class:`QuantizedKV`
    cache: same GQA head-group fold (cache read once per *kv* head), but
    each KV token row crosses HBM as 64 int8 + one f32 scale instead of a
    bf16 row — the decode-bandwidth headline path for million-token
    caches.  Returns the same ``(out, lse)`` / partials contract as
    :func:`pallas_flash_decode`."""
    b, h, nq, d = q.shape
    _, hk, nk, _ = kv.k_q.shape
    g = h // hk
    if scale is None:
        scale = d**-0.5
    interpret = _interpret_default() if interpret is None else interpret
    masked = kv_mask is not None

    qf, rows, pad = _decode_fold_rows(q, hk)
    bq = rows + pad
    bk = min(block_k or DEFAULT_BLOCK_DECODE, nk)
    while nk % bk:
        bk //= 2

    # unify shard_map varying-axes across operands (a cache-validity mask
    # built from axis_index varies over fewer mesh axes than q; pallas
    # requires uniform vma types) — same contract as _flash_fwd_call
    qf, k_q, k_s, v_q, v_s, kv_mask = _unify_vma(
        qf, kv.k_q, kv.k_scale, kv.v_q, kv.v_scale, kv_mask
    )
    q = qf  # out_shape vma derives from the unified q
    qr = qf.reshape(b * hk, bq, d)
    kqr = k_q.reshape(b * hk, nk, d)
    ksr = k_s.astype(jnp.float32).reshape(b * hk, nk)
    vqr = v_q.reshape(b * hk, nk, d)
    vsr = v_s.astype(jnp.float32).reshape(b * hk, nk)

    def q_map(bh, ki):
        del ki
        return (bh, 0, 0)

    def kv_map(bh, ki):
        return (bh, ki, 0)

    def sc_map(bh, ki):
        return (bh, ki)

    def kvm_map(bh, ki):
        return (bh // hk, ki)

    in_specs = [
        pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk), sc_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk), sc_map, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, kqr, ksr, vqr, vsr]
    if masked:
        in_specs.append(
            pl.BlockSpec((1, bk), kvm_map, memory_space=pltpu.VMEM)
        )
        inputs.append(kv_mask.astype(jnp.int8))

    if fused:
        out_specs = [
            pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        ]
        out_shape = [
            _sds((b * hk, bq, d), q.dtype, q),
            _sds((b * hk, bq, 1), jnp.float32, q),
        ]
    else:
        out_specs = [
            pl.BlockSpec((1, bq, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), q_map, memory_space=pltpu.VMEM),
        ]
        out_shape = [
            _sds((b * hk, bq, d), jnp.float32, q),
            _sds((b * hk, bq, 1), jnp.float32, q),
            _sds((b * hk, bq, 1), jnp.float32, q),
        ]

    kernel = functools.partial(
        _decode_q8_kernel,
        masked=masked, fused=fused, scale=scale,
        softclamp_value=softclamp_value, nk_blocks=nk // bk,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(b * hk, nk // bk),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
    )
    results = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
        name="flash_decode_q8",
    )(*inputs)

    if fused:
        out, lse = results
        return (
            out.reshape(b, hk, bq, d)[:, :, :rows].reshape(b, h, nq, d),
            lse.reshape(b, hk, bq)[:, :, :rows].reshape(b, h, nq),
        )
    acc, m, l = results
    return (
        acc.reshape(b, hk, bq, d)[:, :, :rows].reshape(b, hk, g, nq, d),
        m.reshape(b, hk, bq)[:, :, :rows].reshape(b, hk, g, nq),
        l.reshape(b, hk, bq)[:, :, :rows].reshape(b, hk, g, nq),
    )


def init_partials(
    b: int, h: int, nq: int, d: int, like: jax.Array | None = None
) -> FlashPartials:
    """Identity element for :func:`merge_partials` (keeps the MASK_VALUE
    sentinel invariant local to this module)."""
    parts = FlashPartials(
        jnp.zeros((b, h, nq, d), jnp.float32),
        jnp.full((b, h, nq), MASK_VALUE, jnp.float32),
        jnp.zeros((b, h, nq), jnp.float32),
    )
    if like is not None:
        parts = FlashPartials(*_unify_vma(*parts, like)[:3])
    return parts


def merge_partials(a: FlashPartials, b: FlashPartials) -> FlashPartials:
    """Exact online-softmax merge of two partial sweeps (associative)."""
    m = jnp.maximum(a.m, b.m)
    ea = jnp.exp(a.m - m)
    eb = jnp.exp(b.m - m)
    return FlashPartials(
        a.acc * ea[..., None] + b.acc * eb[..., None],
        m,
        a.l * ea + b.l * eb,
    )


def finalize_partials(p: FlashPartials) -> tuple[jax.Array, jax.Array]:
    """Returns (out f32 (b,h,n,d), lse (b,h,n))."""
    out = p.acc / jnp.maximum(p.l, EPSILON)[..., None]
    lse = p.m + jnp.log(jnp.maximum(p.l, EPSILON))
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_parse_refs(refs, compact, masked, segmented, bq, bk):
    """Shared ref/position parsing for both backward kernels.

    Ref layout (pallas passes scalar-prefetch, inputs, outputs, scratch
    positionally; the static flags say which are present):
      scalars: offs (+ tq/tk/tf tile tables when ``compact``)
      inputs:  q, do, lse, delta, k, v (+ kv mask when ``masked``)
               (+ q/kv segment ids when ``segmented``)
      then kernel-specific outputs + scratch (the ``rest`` return).

    Returns ``(offs_ref, tiles, kvm_ref, qseg_ref, kseg_ref, first, last,
    row0, col0, tf, rest)`` where ``first``/``last`` bound the inner
    (accumulator-carrying) dimension, ``tiles = (q, do, lse, delta, k,
    v)`` refs, and ``tf`` is the compact grid's per-tile flag word (None
    on rectangular grids, whose callers derive first/last/row0/col0 from
    ``pl.program_id`` instead — those five slots come back as None here).
    """
    if compact:
        offs_ref, tq_ref, tk_ref, tf_ref = refs[:4]
        idx = 4
        t = pl.program_id(1)
        tf = tf_ref[t]
        first = (tf & _TF_FIRST) != 0
        last = (tf & _TF_LAST) != 0
        row0, col0 = tq_ref[t] * bq, tk_ref[t] * bk
        tf_or_none = tf
    else:
        offs_ref = refs[0]
        idx = 1
        first = last = row0 = col0 = tf_or_none = None  # caller fills in
    tiles = refs[idx:idx + 6]
    idx += 6
    kvm_ref = refs[idx] if masked else None
    idx += 1 if masked else 0
    qseg_ref = kseg_ref = None
    if segmented:
        qseg_ref, kseg_ref = refs[idx:idx + 2]
        idx += 2
    return (offs_ref, tiles, kvm_ref, qseg_ref, kseg_ref, first, last,
            row0, col0, tf_or_none, refs[idx:])


def _bwd_dkv_kernel(*refs, compact: bool, masked: bool, segmented: bool,
                    nq_blocks: int, **tile_kw):
    """dk/dv pass: the grid holds a KV block and streams query blocks
    (rect grid ``(bh, ki, qi)``; compact grid k-major tile tables)."""
    bq, bk = tile_kw["bq"], tile_kw["bk"]
    tile_kw = dict(tile_kw, masked=masked, segmented=segmented)
    (offs_ref, tiles, kvm_ref, qseg_ref, kseg_ref, first, last, row0, col0,
     tf, rest) = _bwd_parse_refs(refs, compact, masked, segmented, bq, bk)
    if not compact:
        ki, qi = pl.program_id(1), pl.program_id(2)
        first = qi == 0
        last = qi == nq_blocks - 1
        row0, col0 = qi * bq, ki * bk
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref = tiles
    dk_ref, dv_ref, dk, dv = rest

    @pl.when(first)
    def _init():
        dk[:] = jnp.zeros_like(dk)
        dv[:] = jnp.zeros_like(dv)

    tile = _tile_closure(_dkv_tile, tile_kw, offs_ref, q_ref, do_ref, lse_ref,
                         delta_ref, k_ref, v_ref, kvm_ref, qseg_ref, kseg_ref,
                         dk, dv, row0, col0)
    if compact:
        _dispatch_tile_compact(tf, tile)
    else:
        _dispatch_tile(offs_ref, row0, col0, bq, bk, tile_kw["causal"],
                       tile_kw["windowed"], tile)

    @pl.when(last)
    def _write():
        dk_ref[0] = dk[:]
        dv_ref[0] = dv[:]


def _dkv_tile(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
              kvm_ref, qseg_ref, kseg_ref, dk, dv, row0, col0, *, scale,
              softclamp_value, causal, windowed, masked, segmented, bq, bk,
              exp2=False):
    kb = k_ref[0]
    qb = q_ref[0]
    # sT: (bk, bq) = k . q^T (contract d on both)
    sT = lax.dot_general(
        kb, qb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if scale != 1.0:  # static: folded into q for power-of-two scales
        sT = sT * scale
    if softclamp_value is not None:
        sT = _softclamp(sT, softclamp_value, exp2)

    ex = jnp.exp2 if exp2 else jnp.exp
    pT = ex(sT - jnp.swapaxes(lse_ref[0], 0, 1))
    keep = _tile_keep(
        offs_ref, row0, col0, (bk, bq), 1, causal, windowed,
        kvm_ref if masked else None,
        qseg_ref if segmented else None,
        kseg_ref if segmented else None,
    )
    if keep is not None:
        pT = jnp.where(keep, pT, 0.0)

    dob = do_ref[0]
    dv[:] = dv[:] + lax.dot_general(
        pT.astype(dob.dtype), dob, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # dpT: (bk, bq) = v . do^T
    dpT = lax.dot_general(
        v_ref[0], dob, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dsT = pT * (dpT - jnp.swapaxes(delta_ref[0], 0, 1))
    if softclamp_value is not None:
        dsT = dsT * _softclamp_grad_factor(sT, softclamp_value, exp2)
    if scale != 1.0:  # folded q̃ makes dsT·q̃ carry the factor exactly
        dsT = dsT * scale
    dk[:] = dk[:] + lax.dot_general(
        dsT.astype(qb.dtype), qb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bwd_dq_kernel(*refs, compact: bool, masked: bool, segmented: bool,
                   nk_blocks: int, **tile_kw):
    """dq pass: the grid holds a Q block and streams KV blocks
    (rect grid ``(bh, qi, ki)``; compact grid q-major tile tables)."""
    bq, bk = tile_kw["bq"], tile_kw["bk"]
    tile_kw = dict(tile_kw, masked=masked, segmented=segmented)
    (offs_ref, tiles, kvm_ref, qseg_ref, kseg_ref, first, last, row0, col0,
     tf, rest) = _bwd_parse_refs(refs, compact, masked, segmented, bq, bk)
    if not compact:
        qi, ki = pl.program_id(1), pl.program_id(2)
        first = ki == 0
        last = ki == nk_blocks - 1
        row0, col0 = qi * bq, ki * bk
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref = tiles
    dq_ref, dq = rest

    @pl.when(first)
    def _init():
        dq[:] = jnp.zeros_like(dq)

    tile = _tile_closure(_dq_tile, tile_kw, offs_ref, q_ref, do_ref, lse_ref,
                         delta_ref, k_ref, v_ref, kvm_ref, qseg_ref, kseg_ref,
                         dq, row0, col0)
    if compact:
        _dispatch_tile_compact(tf, tile)
    else:
        _dispatch_tile(offs_ref, row0, col0, bq, bk, tile_kw["causal"],
                       tile_kw["windowed"], tile)

    @pl.when(last)
    def _write():
        dq_ref[0] = dq[:]


def _dq_tile(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
             kvm_ref, qseg_ref, kseg_ref, dq, row0, col0, *, scale,
             softclamp_value, causal, windowed, masked, segmented, bq, bk,
             exp2=False):
    qb = q_ref[0]
    kb = k_ref[0]
    s = lax.dot_general(
        qb, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if scale != 1.0:  # static: folded into q for power-of-two scales
        s = s * scale
    if softclamp_value is not None:
        s = _softclamp(s, softclamp_value, exp2)

    p = (jnp.exp2 if exp2 else jnp.exp)(s - lse_ref[0])
    keep = _tile_keep(
        offs_ref, row0, col0, (bq, bk), 0, causal, windowed,
        kvm_ref if masked else None,
        qseg_ref if segmented else None,
        kseg_ref if segmented else None,
    )
    if keep is not None:
        p = jnp.where(keep, p, 0.0)

    dob = do_ref[0]
    dp = lax.dot_general(
        dob, v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0])
    if softclamp_value is not None:
        ds = ds * _softclamp_grad_factor(s, softclamp_value, exp2)
    if scale != 1.0:  # folded q̃: dq is post-scaled once on the output
        ds = ds * scale
    dq[:] = dq[:] + lax.dot_general(
        ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def pallas_flash_backward(
    do: jax.Array,  # (b, h, nq, d)
    q: jax.Array,
    k: jax.Array,  # (b, hk, nk, d)
    v: jax.Array,
    lse: jax.Array,  # (b, h, nq) f32
    delta: jax.Array,  # (b, h, nq) f32
    kv_mask: jax.Array | None = None,
    *,
    scale: float,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    softclamp_value: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    block_q_dkv: int | None = None,
    block_k_dkv: int | None = None,
    block_q_dq: int | None = None,
    block_k_dq: int | None = None,
    band_hint: tuple[int, int, int, int] | None = None,
    interpret: bool | None = None,
    exp2: bool | None = None,
    segment_ids=None,
    doc_starts: tuple[int, ...] | None = None,
    compute_dtype: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Two-pass flash backward. Returns (dq, dk, dv), all f32, dk/dv with
    ``hk`` heads (GQA group-summed).

    The two passes stream in opposite directions (dk/dv holds KV and
    streams queries; dq holds Q and streams KV), so their optimal tile
    shapes differ; ``block_*_dkv`` / ``block_*_dq`` override the shared
    ``block_q`` / ``block_k`` per pass.

    ``segment_ids``/``doc_starts`` mirror the forward (packed sequences):
    cross-document terms drop out of ``p`` in both passes, and a
    block-aligned declared layout drops cross-document tiles from each
    pass's compact grid at trace time (checked against that pass's block
    sizes independently).

    ``compute_dtype`` is the knob SURFACE for the int8 backward; this
    round only ``None`` (bf16 matmuls) is implemented — the dk/dv/dq
    error budget does not yet admit int8 recompute (docs/precision.md §5),
    so an int8-forward model differentiates through exact-residual bf16
    backward passes."""
    if compute_dtype is not None:
        raise NotImplementedError(
            f"pallas_flash_backward: compute_dtype={compute_dtype!r} — the "
            "backward runs bf16 this round (dk/dv/dq error bounds, "
            "docs/precision.md §5); pass compute_dtype=None"
        )
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    g = h // hk
    q_seg, kv_seg = normalize_segment_ids(
        segment_ids, q, k, "pallas_flash_backward"
    )
    doc_starts = _check_doc_starts(doc_starts, nq, nk)

    # power-of-two scale folds into q here too (exact, see _flash_fwd_call):
    # s/sT recompute unchanged, dk = dsT·q̃ absorbs the factor exactly
    # (dk = scale·dsTᵀ·q = dsTᵀ·(scale·q)), and dq comes out unscaled —
    # multiplied once on the (nq, d) output below instead of per (bq, bk)
    # tile.  Deletes BOTH per-tile score-path multiplies from each pass.
    # In exp2 mode (RING_ATTN_EXP2=1) the fold is scale*log2e and lse
    # converts to log2 units once out here, so the in-tile p recompute is
    # a bare exp2; dk then carries a surplus log2e absorbed by a ln2
    # multiply on its (nk, d) output.  Explicit ``exp2=`` overrides the
    # env var (trace-time capture, see _exp2_default).
    exp2 = _exp2_default() if exp2 is None else bool(exp2)
    dq_post_scale = 1.0
    dkv_post_scale = 1.0
    if exp2:
        q = q * jnp.asarray(scale * LOG2E, q.dtype)
        lse = lse * LOG2E
        dq_post_scale = scale
        dkv_post_scale = LN2
        scale = 1.0
    elif scale != 1.0 and math.frexp(float(scale))[0] == 0.5:
        q = q * jnp.asarray(scale, q.dtype)
        dq_post_scale = scale
        scale = 1.0

    # per-call override > swept per-pass default > shared block_q/block_k
    if block_q_dkv is None and block_q is None:
        block_q_dkv = DEFAULT_BLOCK_Q_DKV
    if block_k_dkv is None and block_k is None:
        block_k_dkv = DEFAULT_BLOCK_K_DKV
    if block_q_dq is None and block_q is None:
        block_q_dq = DEFAULT_BLOCK_Q_DQ
    if block_k_dq is None and block_k is None:
        block_k_dq = DEFAULT_BLOCK_K_DQ
    bq1, bk1 = _block_sizes(
        nq, nk,
        block_q_dkv if block_q_dkv is not None else block_q,
        block_k_dkv if block_k_dkv is not None else block_k,
    )
    bq2, bk2 = _block_sizes(
        nq, nk,
        block_q_dq if block_q_dq is not None else block_q,
        block_k_dq if block_k_dq is not None else block_k,
    )
    interpret = _interpret_default() if interpret is None else interpret

    causal = causal_offset is not None
    windowed = window_lo is not None and causal
    masked = kv_mask is not None
    offs = jnp.asarray(
        [causal_offset if causal else 0, window_lo if windowed else 0], jnp.int32
    )

    hint = _normalize_hint(causal, windowed, causal_offset, window_lo,
                           band_hint)
    # each pass has its own grid/tables: the SMEM cap demotes them
    # independently (per-pass block sizes can put one over, not the other),
    # and the trace-time doc skip needs the layout aligned to that pass's
    # own block sizes
    compact_dkv = compact_dq = False
    docs_dkv = docs_dq = None
    dkv_tabs = dq_tabs = []
    if hint is not None:
        if doc_starts is not None:
            if _docs_block_aligned(doc_starts, bq1, bk1):
                docs_dkv = doc_starts
            if _docs_block_aligned(doc_starts, bq2, bk2):
                docs_dq = doc_starts
        tiles_dkv = _band_tile_count(
            nq // bq1, nk // bk1, bq1, bk1, hint, windowed, outer_is_q=False,
            doc_starts=docs_dkv,
        )
        tiles_dq = _band_tile_count(
            nq // bq2, nk // bk2, bq2, bk2, hint, windowed, outer_is_q=True,
            doc_starts=docs_dq,
        )
        compact_dkv = tiles_dkv <= _MAX_COMPACT_TILES
        compact_dq = tiles_dq <= _MAX_COMPACT_TILES
        if not compact_dkv:
            _warn_demoted("bwd dk/dv", tiles_dkv, stacklevel=3)
            docs_dkv = None
        if not compact_dq:
            _warn_demoted("bwd dq", tiles_dq, stacklevel=3)
            docs_dq = None
        if compact_dkv:
            dkv_tabs = [
                jnp.asarray(t)
                for t in _band_tables(nq // bq1, nk // bk1, bq1, bk1, hint,
                                      windowed, outer_is_q=False,
                                      doc_starts=docs_dkv)
            ]
        if compact_dq:
            dq_tabs = [
                jnp.asarray(t)
                for t in _band_tables(nq // bq2, nk // bk2, bq2, bk2, hint,
                                      windowed, outer_is_q=True,
                                      doc_starts=docs_dq)
            ]
    # runtime segment refs are needed by any pass whose tables don't carry
    # the document mask; a pass whose tables DO carry it skips the refs
    if doc_starts is not None and q_seg is None and not (
        docs_dkv is not None and docs_dq is not None
    ):
        q_seg = kv_seg = _doc_runtime_ids(doc_starts, nq, b)
    seg_dkv = q_seg is not None and docs_dkv is None
    seg_dq = q_seg is not None and docs_dq is None
    unified = _unify_vma(
        q, k, v, do, lse, delta, kv_mask, q_seg, kv_seg, offs,
        *dkv_tabs, *dq_tabs
    )
    q, k, v, do, lse, delta, kv_mask, q_seg, kv_seg, offs = unified[:10]
    dkv_tabs = unified[10:10 + len(dkv_tabs)]
    dq_tabs = unified[10 + len(dkv_tabs):]
    if q_seg is not None:
        q_seg = q_seg.astype(jnp.int32)
        kv_seg = kv_seg.astype(jnp.int32)
    qr = q.reshape(b * h, nq, d)
    dor = do.reshape(b * h, nq, d).astype(q.dtype)
    lser = lse.reshape(b * h, nq, 1)
    deltar = delta.reshape(b * h, nq, 1)
    kr = k.reshape(b * hk, nk, d)
    vr = v.reshape(b * hk, nk, d)

    def q_map(bh, xi, yi, *_):
        del yi
        return (bh, xi, 0)

    def q_map_inner(bh, ki, qi, *_):
        del ki
        return (bh, qi, 0)

    def kv_map_outer(bh, ki, qi, *_):
        del qi
        b_idx = bh // h
        kvh = (bh % h) // g
        return (b_idx * hk + kvh, ki, 0)

    def kv_map_inner(bh, qi, ki, *_):
        b_idx = bh // h
        kvh = (bh % h) // g
        return (b_idx * hk + kvh, ki, 0)

    # masked/segmented ride the kernel partials per pass (the two passes
    # can differ on segmented when only one pass's tables carry the docs)
    common1 = dict(
        scale=scale,
        softclamp_value=softclamp_value,
        causal=causal,
        windowed=windowed,
        bq=bq1,
        bk=bk1,
        exp2=exp2,
    )
    common2 = dict(common1, bq=bq2, bk=bk2)

    # ---- dk/dv pass: grid (bh, k blocks, q blocks), or compacted band ----
    if compact_dkv:
        (dkv_q_map, dkv_kv_map, dkv_kvm_map, dkv_qsm_map,
         dkv_out_map) = _compact_maps(h, hk, g)
        dkv_scalars = (offs, *dkv_tabs)
        dkv_grid = (b * h, dkv_tabs[0].shape[0])
        dkv_semantics = ("parallel", "arbitrary")
    else:
        dkv_q_map = q_map_inner
        dkv_kv_map = kv_map_outer
        dkv_kvm_map = lambda bh, ki, qi, *_: (bh // h, ki)  # noqa: E731
        dkv_qsm_map = lambda bh, ki, qi, *_: (bh // h, qi)  # noqa: E731
        dkv_out_map = lambda bh, ki, qi, *_: (bh, ki, 0)  # noqa: E731
        dkv_scalars = (offs,)
        dkv_grid = (b * h, nk // bk1, nq // bq1)
        dkv_semantics = ("parallel", "parallel", "arbitrary")
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel,
        compact=compact_dkv,
        masked=masked,
        segmented=seg_dkv,
        nq_blocks=nq // bq1,
        **common1,
    )

    in_specs = [
        pl.BlockSpec((1, bq1, d), dkv_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq1, d), dkv_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq1, 1), dkv_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq1, 1), dkv_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk1, d), dkv_kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk1, d), dkv_kv_map, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, dor, lser, deltar, kr, vr]
    if masked:
        kvm = kv_mask.astype(jnp.int8)
        in_specs.append(
            pl.BlockSpec((1, bk1), dkv_kvm_map, memory_space=pltpu.VMEM)
        )
        inputs.append(kvm)
    if seg_dkv:
        in_specs += [
            pl.BlockSpec((1, bq1), dkv_qsm_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk1), dkv_kvm_map, memory_space=pltpu.VMEM),
        ]
        inputs += [q_seg, kv_seg]

    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(dkv_scalars),
            grid=dkv_grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, bk1, d), dkv_out_map, memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk1, d), dkv_out_map, memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk1, d), jnp.float32),
                pltpu.VMEM((bk1, d), jnp.float32),
            ],
        ),
        out_shape=[
            _sds((b * h, nk, d), jnp.float32, q),
            _sds((b * h, nk, d), jnp.float32, q),
        ],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=dkv_semantics
        ),
        interpret=interpret,
        name="flash_bwd_dkv",
    )(*dkv_scalars, *inputs)

    # GQA: sum per-query-head dk/dv over the group
    dk = dk_h.reshape(b, hk, g, nk, d).sum(axis=2)
    dv = dv_h.reshape(b, hk, g, nk, d).sum(axis=2)
    if dkv_post_scale != 1.0:
        # exp2 mode: dsT·q̃ carries a surplus log2e; ln2 restores it
        # (one (nk, d) f32 multiply vs one per (bq, bk) tile)
        dk = dk * dkv_post_scale

    # ---- dq pass: grid (bh, q blocks, k blocks), or compacted band ----
    if compact_dq:
        dq_q_map, dq_kv_map, dq_kvm_map, dq_qsm_map, _ = _compact_maps(h, hk, g)
        dq_scalars = (offs, *dq_tabs)
        dq_grid = (b * h, dq_tabs[0].shape[0])
        dq_semantics = ("parallel", "arbitrary")
    else:
        dq_q_map = q_map
        dq_kv_map = kv_map_inner
        dq_kvm_map = lambda bh, qi, ki, *_: (bh // h, ki)  # noqa: E731
        dq_qsm_map = lambda bh, qi, ki, *_: (bh // h, qi)  # noqa: E731
        dq_scalars = (offs,)
        dq_grid = (b * h, nq // bq2, nk // bk2)
        dq_semantics = ("parallel", "parallel", "arbitrary")
    dq_kernel = functools.partial(
        _bwd_dq_kernel,
        compact=compact_dq,
        masked=masked,
        segmented=seg_dq,
        nk_blocks=nk // bk2,
        **common2,
    )

    in_specs = [
        pl.BlockSpec((1, bq2, d), dq_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq2, d), dq_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq2, 1), dq_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq2, 1), dq_q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk2, d), dq_kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk2, d), dq_kv_map, memory_space=pltpu.VMEM),
    ]
    inputs = [qr, dor, lser, deltar, kr, vr]
    if masked:
        inputs.append(kvm)
        in_specs.append(
            pl.BlockSpec((1, bk2), dq_kvm_map, memory_space=pltpu.VMEM)
        )
    if seg_dq:
        in_specs += [
            pl.BlockSpec((1, bq2), dq_qsm_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk2), dq_kvm_map, memory_space=pltpu.VMEM),
        ]
        inputs += [q_seg, kv_seg]

    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(dq_scalars),
            grid=dq_grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bq2, d), dq_q_map, memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((bq2, d), jnp.float32)],
        ),
        out_shape=_sds((b * h, nq, d), jnp.float32, q),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=dq_semantics
        ),
        interpret=interpret,
        name="flash_bwd_dq",
    )(*dq_scalars, *inputs)

    if dq_post_scale != 1.0:
        dq = dq * dq_post_scale  # f32 output, power-of-two: exact
    return dq.reshape(b, h, nq, d), dk, dv


# ---------------------------------------------------------------------------
# User-facing single-device flash attention on the Pallas path
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13)
)
def _pallas_flash_core(q, k, v, kv_mask, q_seg, kv_seg, scale, causal_offset,
                       window, softclamp_value, interpret, exp2, doc_starts,
                       compute_dtype=None):
    out, _ = _pallas_flash_fwd_impl(
        q, k, v, kv_mask, q_seg, kv_seg, scale, causal_offset, window,
        softclamp_value, interpret, exp2, doc_starts, compute_dtype
    )
    return out


def _pallas_flash_fwd_impl(q, k, v, kv_mask, q_seg, kv_seg, scale,
                           causal_offset, window, softclamp_value, interpret,
                           exp2, doc_starts, compute_dtype=None):
    window_lo = causal_offset - (window - 1) if window is not None else None
    # fused finalize: the kernel writes normalized q.dtype output + lse, so
    # the f32 (acc, m, l) triple never touches HBM (512 MB saved per call
    # at seq 262144, h=8, d=64)
    out, lse = _flash_fwd_call(
        q, k, v, kv_mask,
        scale=scale, causal_offset=causal_offset, window_lo=window_lo,
        softclamp_value=softclamp_value, block_q=None, block_k=None,
        band_hint=None, interpret=interpret, fused=True, exp2=exp2,
        q_segment_ids=q_seg, kv_segment_ids=kv_seg, doc_starts=doc_starts,
        compute_dtype=compute_dtype,
    )
    # named residuals: lets a remat policy save (out, lse) so the backward's
    # residual recompute elides this kernel (see parallel/ring.py, same names)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, lse


def _pallas_flash_core_fwd(q, k, v, kv_mask, q_seg, kv_seg, scale,
                           causal_offset, window, softclamp_value, interpret,
                           exp2, doc_starts, compute_dtype=None):
    out, lse = _pallas_flash_fwd_impl(
        q, k, v, kv_mask, q_seg, kv_seg, scale, causal_offset, window,
        softclamp_value, interpret, exp2, doc_starts, compute_dtype
    )
    return out, (q, k, v, kv_mask, q_seg, kv_seg, out, lse)


def _pallas_flash_core_bwd(scale, causal_offset, window, softclamp_value,
                           interpret, exp2, doc_starts, compute_dtype, res,
                           do):
    # the backward stays bf16 regardless of the forward's compute_dtype
    # this round: it recomputes scores from the EXACT residual (q, k, v)
    # — int8 touched only the forward's (out, lse) — docs/precision.md §5
    q, k, v, kv_mask, q_seg, kv_seg, out, lse = res
    window_lo = causal_offset - (window - 1) if window is not None else None
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    dq, dk, dv = pallas_flash_backward(
        do, q, k, v, lse, delta, kv_mask,
        scale=scale, causal_offset=causal_offset, window_lo=window_lo,
        softclamp_value=softclamp_value, interpret=interpret, exp2=exp2,
        segment_ids=(None if q_seg is None else (q_seg, kv_seg)),
        doc_starts=doc_starts,
    )
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None, None)


_pallas_flash_core.defvjp(_pallas_flash_core_fwd, _pallas_flash_core_bwd)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = False,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    head_chunks: int | None = None,
    interpret: bool | None = None,
    exp2: bool | None = None,
    segment_ids=None,
    doc_starts: tuple[int, ...] | None = None,
    compute_dtype: str | None = None,
) -> jax.Array:
    """Exact flash attention on the Pallas TPU kernel path (GQA-aware).

    Same contract as ``ops.flash.flash_attention``; parity-tested against
    the oracle.  On non-TPU backends runs the kernels in interpreter mode.

    ``head_chunks`` splits the launch into that many kernel calls over
    contiguous head groups (GQA groups stay aligned: chunk ``i`` holds q
    heads ``[i*h/c, (i+1)*h/c)`` against kv heads ``[i*hk/c, (i+1)*hk/c)``).
    Each chunk is an independent pallas program — fwd AND bwd via the
    per-chunk custom_vjp — so a shape whose single-program compile blows a
    compiler/relay size limit (observed: h=32 at seq 262144 on the v5e
    remote-compile relay) still runs at full rate, paying only c-1 extra
    kernel launches.  Heads are embarrassingly parallel in attention, so
    outputs are bit-identical to the unsplit launch.

    ``segment_ids`` (``(b, n)`` array or ``(q_ids, kv_ids)`` pair) masks
    cross-document attention for packed sequences — fwd and bwd.
    ``doc_starts`` is the *static* layout declaration: when its boundaries
    land on the kernel block sizes, cross-document tiles leave the compact
    causal grid at trace time (skipped, not masked); see
    ``docs/packing.md`` for the contract.

    ``compute_dtype="int8"`` quantizes the FORWARD's QK^T/PV matmul
    operands (per-block absmax, f32 accumulators untouched); the backward
    stays bf16 from the exact residuals — fwd error ≤ the int8-hop bound
    (``docs/precision.md``).
    """
    check_attention_args("pallas_flash_attention", q, k, v, mask)
    q_seg, kv_seg = normalize_segment_ids(
        segment_ids, q, k, "pallas_flash_attention"
    )
    if doc_starts is not None:
        doc_starts = _check_doc_starts(doc_starts, q.shape[2], k.shape[2])
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None:
        assert causal, "lookback windows require causal attention"
    if causal:
        mask = None
    causal_offset = k.shape[2] - q.shape[2] if causal else None
    interpret = interpret if interpret is not None else _interpret_default()
    # resolve the log2-space flag ONCE here: the custom_vjp's forward and
    # backward then share one basis even if the env var flips mid-call,
    # and an explicit exp2= keys the jit cache (see _exp2_default)
    exp2 = _exp2_default() if exp2 is None else bool(exp2)
    if head_chunks is not None and head_chunks > 1:
        h, hk = q.shape[1], k.shape[1]
        if h % head_chunks or hk % head_chunks:
            raise ValueError(
                f"pallas_flash_attention: head_chunks={head_chunks} must "
                f"divide both heads={h} and kv_heads={hk}"
            )
        hq_c, hk_c = h // head_chunks, hk // head_chunks
        outs = [
            _pallas_flash_core(
                q[:, i * hq_c:(i + 1) * hq_c],
                k[:, i * hk_c:(i + 1) * hk_c],
                v[:, i * hk_c:(i + 1) * hk_c],
                mask, q_seg, kv_seg, scale, causal_offset, window,
                softclamp_value, interpret, exp2, doc_starts, compute_dtype,
            )
            for i in range(head_chunks)
        ]
        return jnp.concatenate(outs, axis=1)
    return _pallas_flash_core(
        q, k, v, mask, q_seg, kv_seg, scale, causal_offset, window,
        softclamp_value, interpret, exp2, doc_starts, compute_dtype,
    )
