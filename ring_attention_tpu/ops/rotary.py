"""Shard-aware rotary position embeddings.

TPU-native analogue of ``RingRotaryEmbedding`` / ``apply_rotary_pos_emb``
(ref ``ring_attention.py:102-172``).  The reference's key subtlety is that
positions must reflect how the sequence was sharded:

  - plain ring sharding: rank ``r`` holds the contiguous slice
    ``[r * n_local, (r + 1) * n_local)`` (ref ``ring_attention.py:153-155``)
  - striped sharding: rank ``r`` holds every ``world``-th token starting at
    ``r``, i.e. global position of local index ``i`` is ``i * world + r``
    (ref ``ring_attention.py:142-151``; we stripe at token granularity, the
    reference's ``buckets=1`` fused-kernel case)

Here those are pure position computations: the model computes per-shard
positions (optionally inside ``shard_map`` using ``lax.axis_index``) and
feeds them to ``rotary_freqs`` -> ``apply_rotary``.  Rotary math is always
float32 (the reference forces fp32 via autocast-off, ref
``ring_attention.py:128,167``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ring_positions(n_local: int, rank: jax.Array | int, *, striped: bool, world: int) -> jax.Array:
    """Global token positions for one sequence shard.

    ``rank`` may be a traced scalar (e.g. ``lax.axis_index``) so the same
    compiled program serves every mesh position.
    """
    i = jnp.arange(n_local)
    if striped:
        return i * world + rank
    return i + rank * n_local


def hybrid_positions(
    n_local: int,
    ulysses_rank: jax.Array | int,
    ring_rank: jax.Array | int,
    *,
    ulysses: int,
    ring: int,
    striped: bool,
) -> jax.Array:
    """Global token positions for one shard of a factored ``seq = ulysses
    x ring`` layout (``parallel/hybrid.py``).

    The sequence dimension shards ring-major / ulysses-minor: ring rank
    ``r`` owns chunk ``r`` of ``ring`` chunks and ulysses rank ``u`` owns
    subchunk ``u`` within it, so local index ``i`` sits at in-chunk index
    ``u * n_local + i`` — equivalently, combined rank ``r * ulysses + u``
    of a ``ring * ulysses``-way contiguous sharding.  Striping (for the
    causal ring's load balance) interleaves at the OUTER ring degree only:
    in-chunk index ``j`` of ring rank ``r`` is global token ``j * ring +
    r``, exactly the layout ``stripe_permute(x, ring)`` + factored
    sharding produces.
    """
    j = ulysses_rank * n_local + jnp.arange(n_local)
    if striped:
        return j * ring + ring_rank
    return ring_rank * (ulysses * n_local) + j


def rotary_freqs(positions: jax.Array, dim: int, theta: float = 10000.0) -> jax.Array:
    """Angles ``(n, dim)`` for NeoX-style (half-rotation) rotary embedding."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.concatenate([freqs, freqs], axis=-1)


def rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary(x: jax.Array, freqs: jax.Array) -> jax.Array:
    """Apply rotary embedding.  ``x: (..., n, d)``, ``freqs: (n, d)``."""
    xf = x.astype(jnp.float32)
    out = xf * jnp.cos(freqs) + rotate_half(xf) * jnp.sin(freqs)
    return out.astype(x.dtype)
