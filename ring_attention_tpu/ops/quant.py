"""The int8 quantization seam: every absmax codec in the package.

Three call sites grew their own int8 arithmetic across PRs — the decode
cache (``flash_decode_q8``, PR 4 lineage), the ring hop payload
(``quantize_ring_payload``, PR 6), and now the int8 *compute* path through
the flash kernels (QK^T and PV on int8 operands).  They all share one
scheme — symmetric absmax, zero-point-free, full scale ``INT8_MAX = 127``
— and this module is its single home.  Lint rule RA012 flags raw
int8 quant/dequant arithmetic (the 127 full-scale constant) anywhere else
in the package, so a fourth codec cannot silently fork the convention.

Two scale granularities:

- **per-row** (:func:`quantize_rows`): one f32 scale per trailing-axis row
  — the ``(head, token)`` granularity of the decode cache and the PR 6
  hop payload.  Most accurate; usable wherever the scale rides a *free*
  index of the downstream matmul (the QK^T row/col, the decode dequant).
- **per-block** (:func:`quantize_blocks`): one f32 scale per ``block``
  tokens (a ``(block, d)`` slab).  This is what the int8 *compute* path
  needs: PV contracts over the key/token axis, so a per-token v scale
  cannot be pulled out of the matmul — only a per-KV-block scalar can,
  and then ``acc += (p8 · v8) * (vs / 127²)`` dequantizes exactly.

The single-array ring payload (:func:`pack_kv` / :func:`unpack_kv`) stays
shape-compatible across both granularities: per-block scales are
broadcast to every token row of their block before the bitcast, so a
block-quantized payload IS a valid row-payload (``unpack_kv`` dequantizes
it bit-exactly) while :func:`payload_block_scales` recovers the per-block
scalars for the kernel feed.  One wire format, two consumers — the
"dequant-free ring composition" seam (``docs/precision.md``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

# The symmetric int8 full-scale constant.  THE one place it is spelled:
# RA012 flags 127-arithmetic outside this module.
INT8_MAX = 127.0

# Bytes of one bitcast f32 scale appended per payload row (pack_kv).
SCALE_BYTES = 4


def quantize_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric absmax int8 quantization over the LAST axis.

    Returns ``(values int8 like x, scales f32 of x.shape[:-1])`` with
    ``x ≈ values * scales[..., None]``.  All-zero rows get scale 1.0 (and
    all-zero values), so dequantization is always finite.
    """
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / INT8_MAX
    safe = jnp.where(scale > 0, scale, 1.0)
    xq = jnp.round(xf / safe[..., None])
    return jnp.clip(xq, -INT8_MAX, INT8_MAX).astype(jnp.int8), scale


def dequantize_rows(values: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """Materialize what a :func:`quantize_rows` pair represents."""
    return (values.astype(jnp.float32) * scales[..., None]).astype(dtype)


def quantize_blocks(x: jax.Array, block: int) -> tuple[jax.Array, jax.Array]:
    """Per-block symmetric absmax over ``(block, d)`` token slabs.

    ``x`` is ``(..., n, d)`` with ``block`` dividing ``n``; returns
    ``(values int8 like x, scales f32 of x.shape[:-2] + (n // block,))``
    — one scalar per block of ``block`` tokens, the granularity the int8
    flash matmuls dequantize at (a per-tile scalar multiply).
    """
    n, d = x.shape[-2], x.shape[-1]
    if n % block:
        raise ValueError(
            f"quantize_blocks: block {block} must divide the token axis {n}"
        )
    xf = x.astype(jnp.float32)
    xb = xf.reshape(*x.shape[:-2], n // block, block, d)
    scale = jnp.max(jnp.abs(xb), axis=(-2, -1)) / INT8_MAX
    safe = jnp.where(scale > 0, scale, 1.0)
    xq = jnp.round(xb / safe[..., None, None])
    xq = jnp.clip(xq, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return xq.reshape(x.shape), scale


def dequantize_blocks(
    values: jax.Array, scales: jax.Array, block: int, dtype
) -> jax.Array:
    """Materialize what a :func:`quantize_blocks` pair represents."""
    n, d = values.shape[-2], values.shape[-1]
    vb = values.astype(jnp.float32).reshape(
        *values.shape[:-2], n // block, block, d
    )
    return (vb * scales[..., None, None]).reshape(values.shape).astype(dtype)


def quantize_p(p: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize an online-softmax probability tile to int8, per ROW.

    ``p = exp(s - m_new) >= 0``; each row scales by its own absmax
    (``rowmax / 127``) so late tiles — whose every ``p`` is small against
    the RUNNING max — keep ~7 bits of resolution instead of rounding to
    zero (a fixed full-scale quant would drop their contribution
    entirely).  The per-row scale rides the PV matmul's FREE index, so it
    pulls out of the contraction exactly: ``acc[i] += (p8 · v8)[i] *
    (p_scale[i] * v_scale)`` (``ops/pallas_flash.py::_online_update``).
    Using the same quantized ``p`` for the ``l`` normalizer keeps
    ``out = acc / l`` exactly normalized over the weights actually
    applied.  Returns ``(p8 int8, scale (rows, 1) f32)``; all-zero
    (fully masked) rows get scale 1.0 and zero values.
    """
    scale = jnp.max(p, axis=-1, keepdims=True) / INT8_MAX
    safe = jnp.where(scale > 0, scale, 1.0)
    return jnp.round(p / safe).astype(jnp.int8), safe


class QuantizedBlockKV(NamedTuple):
    """Quantized K/V operands ready for the int8 flash kernels.

    Scale granularity follows the matmul index each operand's scale must
    ride: ``k_scale`` is PER ROW — ``(b, hk, n)`` f32, the ``(head,
    token)`` granularity of the decode cache and the hop payload — since
    the key/token axis is a FREE index of QK^T (the scale pulls out as a
    per-column multiply on the score tile); ``v_scale`` is PER KV-BLOCK —
    ``(b, hk, n // block)`` f32 — since PV *contracts* over tokens and
    only a per-block scalar pulls out of that matmul.  ``block`` must
    equal the kernel's fitted ``block_k`` (asserted at launch)."""

    k_q: jax.Array
    k_scale: jax.Array
    v_q: jax.Array
    v_scale: jax.Array
    block: int


def quantize_kv_blocks(k: jax.Array, v: jax.Array, block: int) -> QuantizedBlockKV:
    """Quantize a K/V pair for the int8 compute path (k per row, v per
    KV-block — see :class:`QuantizedBlockKV`)."""
    k_q, k_s = quantize_rows(k)
    v_q, v_s = quantize_blocks(v, block)
    return QuantizedBlockKV(k_q, k_s, v_q, v_s, block)


# ---------------------------------------------------------------------------
# The single-array ring payload
# ---------------------------------------------------------------------------


def pack_kv(k: jax.Array, v: jax.Array, *, v_block: int | None = None) -> jax.Array:
    """Pack a K/V pair into ONE int8 ring-hop payload.

    Returns ``(2, b, hk, n, d + 4)`` int8 — k at index 0, v at index 1,
    channels ``[0:d]`` the quantized values and ``[d:d+4]`` the per-row
    f32 scale bitcast into its four bytes (one array = one ``ppermute``
    per hop; a collective move is bit-preserving, so the bitcast
    round-trips exactly).

    ``v_block=None`` quantizes both per token row (the PR 6 wire codec).
    ``v_block=B`` quantizes v per ``(B, d)`` slab and broadcasts each
    block's scale to its token rows before the bitcast (k stays per-row)
    — the payload is then bit-compatible with the row format
    (:func:`unpack_kv` dequantizes it exactly) AND
    :func:`payload_kernel_feed` can recover the :class:`QuantizedBlockKV`
    the int8 flash kernels consume, with no dequant→requant round trip.
    """
    k_q, k_s = quantize_rows(k)
    if v_block is None:
        v_q, v_s = quantize_rows(v)
    else:
        v_q, v_s = quantize_blocks(v, v_block)
        v_s = jnp.repeat(v_s, v_block, axis=-1)
    vals = jnp.stack([k_q, v_q])  # (2, b, hk, n, d) int8
    scales = jnp.stack([k_s, v_s])  # (2, b, hk, n) f32
    scale_bytes = lax.bitcast_convert_type(scales, jnp.int8)  # (..., n, 4)
    return jnp.concatenate([vals, scale_bytes], axis=-1)


def unpack_kv(payload: jax.Array, dtype) -> tuple[jax.Array, jax.Array]:
    """Materialize the ``(k, v)`` a packed payload represents (row- and
    block-quantized payloads alike — block scales ride per-row)."""
    d = payload.shape[-1] - SCALE_BYTES
    vals = payload[..., :d].astype(jnp.float32)
    scales = lax.bitcast_convert_type(
        payload[..., d:], jnp.float32
    )  # (2, b, hk, n)
    kv = vals * scales[..., None]
    return kv[0].astype(dtype), kv[1].astype(dtype)


def payload_kernel_feed(
    payload: jax.Array, v_block: int
) -> QuantizedBlockKV | None:
    """The dequant-free kernel feed of a ``pack_kv(v_block=...)`` payload.

    Slices the int8 values, reads k's per-row scales straight off the
    scale bytes, and recovers v's per-block scalars by sampling every
    ``v_block``-th row (they are block-constant by construction, so the
    sample is exact).  Valid only when ``v_block`` matches the
    granularity the payload was packed at — the ring entry quantizes at
    the kernel's fitted ``block_k`` precisely so this holds; returns None
    when the token count does not divide (caller falls back to
    :func:`unpack_kv`).
    """
    d = payload.shape[-1] - SCALE_BYTES
    n = payload.shape[-2]
    if n % v_block:
        return None
    vals = payload[..., :d]
    scales = lax.bitcast_convert_type(payload[..., d:], jnp.float32)
    return QuantizedBlockKV(
        vals[0], scales[0], vals[1], scales[1][..., ::v_block], v_block
    )
