"""Blockwise (flash) attention with exposed online-softmax partials.

This is the semantic contract of the whole framework, mirroring what the
reference's Triton kernel exposes to its ring loop: a forward that
*accumulates into* a running ``(acc, m, l)`` triple so successive KV blocks
(local buckets or remote ring hops) continue one online softmax
(ref ``triton_flash_attn.py:124-165`` LOAD_ACCUMULATED, and the pure-torch
analogue ``ring_flash_attention.py:194-218``).

Three public layers:

  - ``attend_blocks(q, k, v, carry, ...)`` — fold one KV span into a running
    ``(acc, m, l)`` carry via ``lax.scan`` over KV buckets.  The ring layer
    calls this once per hop.
  - ``flash_attention_partials`` — single-span forward returning
    ``(out_unnormalized_carry)`` plus the ``lse`` needed by backward and by
    tree decoding.
  - ``flash_attention`` — user-facing, ``jax.custom_vjp``-differentiable
    exact attention (GQA, causal/banded masks, key-padding, softclamp).

Masking is unified into a single *band of index offsets*: a tile ``(i, j)``
of local indices attends iff ``window_lo <= j - i <= offset`` (the lower
bound only when a lookback window applies).  Plain causal
attention over contiguous shards is ``offset = q_start - k_start``; striped
ring attention is ``offset = 0`` (inclusive diagonal) or ``-1`` (strict)
depending on rank order — this replaces the reference's three separate mask
constructions (``ring_flash_attention.py:174-192``, ``triton_flash_attn.py:216-221``).
``offset`` may be a traced scalar, so one compiled program serves every ring
position under SPMD.

All softmax state is float32 regardless of input dtype (the reference keeps
m/lse fp32 always, ``ring_flash_attention_cuda.py:251-259``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from .attention import (
    MASK_VALUE,
    EPSILON,
    PAD_SEGMENT_ID,
    normalize_segment_ids,
    segments_overlap,
    softclamp,
)
from ..utils import compat
from ..utils.validate import check_attention_args


class FlashCarry(NamedTuple):
    """Running online-softmax state.

    acc: (b, hk, g, nq, d) float32 — unnormalized output accumulator
    m:   (b, hk, g, nq)    float32 — running row max
    l:   (b, hk, g, nq)    float32 — running row sum of exp(s - m)
    """

    acc: jax.Array
    m: jax.Array
    l: jax.Array


def match_vma(x: jax.Array, like: jax.Array) -> jax.Array:
    """Mark ``x`` as varying over the same shard_map manual axes as ``like``.

    Under jax>=0.9 vma typing, freshly created constants inside ``shard_map``
    are "unvarying"; scan carries and custom_vjp outputs must match the
    varying type of data derived from sharded inputs.  No-op outside
    shard_map.
    """
    want = getattr(compat.typeof(like), "vma", frozenset())
    have = getattr(compat.typeof(x), "vma", frozenset())
    missing = tuple(want - have)
    if not missing:
        return x
    return compat.pcast(x, missing, to="varying")


def init_carry(
    b: int, hk: int, g: int, nq: int, d: int, like: jax.Array | None = None
) -> FlashCarry:
    carry = FlashCarry(
        acc=jnp.zeros((b, hk, g, nq, d), jnp.float32),
        m=jnp.full((b, hk, g, nq), MASK_VALUE, jnp.float32),
        l=jnp.zeros((b, hk, g, nq), jnp.float32),
    )
    if like is not None:
        carry = FlashCarry(*(match_vma(x, like) for x in carry))
    return carry


def _group_q(q: jax.Array, hk: int) -> jax.Array:
    """(b, h, n, d) -> (b, hk, g, n, d) without materializing repeated KV."""
    b, h, n, d = q.shape
    return q.reshape(b, hk, h // hk, n, d)


def _ungroup(x: jax.Array) -> jax.Array:
    b, hk, g, n, d = x.shape
    return x.reshape(b, hk * g, n, d)


def _tile_scores(
    qg: jax.Array,  # (b, hk, g, nq, d)
    k: jax.Array,  # (b, hk, bk, d)
    scale: float,
    softclamp_value: float | None,
) -> jax.Array:
    s = jnp.einsum(
        "bhgid,bhjd->bhgij", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if softclamp_value is not None:
        s = softclamp(s, softclamp_value)
    return s


def _tile_mask(
    nq: int,
    bk: int,
    j0: jax.Array | int,
    offset: jax.Array | int | None,
    window_lo: jax.Array | int | None,
    kv_mask_tile: jax.Array | None,
    q_seg: jax.Array | None = None,  # (b, nq)
    kv_seg_tile: jax.Array | None = None,  # (b, bk)
) -> jax.Array | None:
    """Boolean (…, nq, bk) tile mask (True = attend), or None if unmasked.

    ``j0`` is the starting local column index of this KV tile; rows are the
    full local query range ``[0, nq)``.
    """
    masks = []
    if offset is not None:
        i = jnp.arange(nq)[:, None]
        j = j0 + jnp.arange(bk)[None, :]
        band = j <= i + offset
        if window_lo is not None:
            # absolute lower offset: j >= i + window_lo (exact sliding
            # windows in both contiguous and striped layouts — callers
            # compute the right lo per layout/hop)
            band = band & (j >= i + window_lo)
        masks.append(band)
    if kv_mask_tile is not None:
        # (b, bk) -> (b, 1, 1, 1, bk)
        masks.append(kv_mask_tile[:, None, None, None, :])
    if q_seg is not None:
        # packed sequences: attend only within the same document
        masks.append(
            q_seg[:, None, None, :, None] == kv_seg_tile[:, None, None, None, :]
        )
    if not masks:
        return None
    out = masks[0]
    for m in masks[1:]:
        out = out & m
    return out


def _online_update(carry: FlashCarry, s: jax.Array, v: jax.Array) -> FlashCarry:
    """Fold one score tile ``s: (b,hk,g,nq,bk)`` and values ``v: (b,hk,bk,d)``."""
    acc, m, l = carry
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard: if m_new is still the sentinel (fully masked so far), exp(s - m)
    # would overflow; scale factor for the old acc is then irrelevant (l==0).
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhgij,bhjd->bhgid", p, v.astype(jnp.float32)
    )
    return FlashCarry(acc_new, m_new, l_new)


def _bucket_xs(b, hk, nk, d, bucket_size, k, v, kv_mask, kv_seg):
    """Scan inputs over KV buckets as a dict pytree (optional entries
    simply absent) — shared by the forward and backward bucket loops."""
    nb = nk // bucket_size
    xs = {
        "j": jnp.arange(nb),
        "k": k.reshape(b, hk, nb, bucket_size, d).transpose(2, 0, 1, 3, 4),
        "v": v.reshape(b, hk, nb, bucket_size, d).transpose(2, 0, 1, 3, 4),
    }
    if kv_mask is not None:
        xs["m"] = kv_mask.reshape(b, nb, bucket_size).transpose(1, 0, 2)
    if kv_seg is not None:
        xs["s"] = kv_seg.reshape(b, nb, bucket_size).transpose(1, 0, 2)
    return xs


def attend_blocks(  # ra: allow(RA007 mid-level block op; public entry points validate before the hop loop)
    q: jax.Array,  # (b, h, nq, d)
    k: jax.Array,  # (b, hk, nk, d)
    v: jax.Array,  # (b, hk, nk, d)
    carry: FlashCarry,
    *,
    scale: float,
    bucket_size: int | None = None,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    kv_mask: jax.Array | None = None,  # (b, nk) True = attend
    softclamp_value: float | None = None,
    q_segment_ids: jax.Array | None = None,  # (b, nq) int32
    kv_segment_ids: jax.Array | None = None,  # (b, nk) int32
) -> FlashCarry:
    """Fold one KV span into the running carry, scanning over KV buckets.

    ``window_lo`` is the band's absolute lower offset (attend iff
    ``window_lo <= j - i <= causal_offset``); for a contiguous layout with a
    token window ``w`` it is ``causal_offset - (w - 1)``.

    ``q_segment_ids``/``kv_segment_ids`` restrict attention to matching
    document ids (packed sequences); buckets whose id range provably shares
    no document with the queries skip their score/update work entirely
    (:func:`..attention.segments_overlap`) instead of masking it.
    """
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    qg = _group_q(q, hk)

    if bucket_size is None or bucket_size >= nk:
        s = _tile_scores(qg, k, scale, softclamp_value)
        mask = _tile_mask(nq, nk, 0, causal_offset, window_lo, kv_mask,
                          q_segment_ids, kv_segment_ids)
        if mask is not None:
            s = jnp.where(mask, s, MASK_VALUE)
        return _online_update(carry, s, v)

    assert nk % bucket_size == 0, "kv length must divide into buckets"
    xs = _bucket_xs(b, hk, nk, d, bucket_size, k, v, kv_mask, kv_segment_ids)

    def body(c, xs):
        def compute(c):
            s = _tile_scores(qg, xs["k"], scale, softclamp_value)
            mask = _tile_mask(
                nq, bucket_size, xs["j"] * bucket_size, causal_offset,
                window_lo, xs.get("m"), q_segment_ids, xs.get("s"),
            )
            if mask is not None:
                s = jnp.where(mask, s, MASK_VALUE)
            return _online_update(c, s, xs["v"])

        if "s" not in xs:
            return compute(c), None
        # whole-bucket skip: untouched carry is exactly what a fully-masked
        # bucket would leave behind (every masked p is wiped by the later
        # online rescale / merge), minus the bucket's FLOPs
        has = segments_overlap(q_segment_ids, xs["s"])
        return lax.cond(has, compute, lambda c: c, c), None

    carry, _ = lax.scan(body, carry, xs)
    return carry


def finalize(carry: FlashCarry) -> tuple[jax.Array, jax.Array]:
    """Normalize the carry: returns ``out (b,hk,g,nq,d)`` f32 and ``lse (b,hk,g,nq)``."""
    acc, m, l = carry
    out = acc / jnp.maximum(l, EPSILON)[..., None]
    lse = m + jnp.log(jnp.maximum(l, EPSILON))
    return out, lse


# ---------------------------------------------------------------------------
# Single-device flash attention with custom VJP
# ---------------------------------------------------------------------------


def _flash_fwd_impl(q, k, v, kv_mask, q_seg, kv_seg, scale, bucket_size,
                    causal_offset, window, softclamp_value):
    b, h, nq, d = q.shape
    hk = k.shape[1]
    window_lo = causal_offset - (window - 1) if window is not None else None
    carry = init_carry(b, hk, h // hk, nq, d, like=q)
    carry = attend_blocks(
        q, k, v, carry,
        scale=scale, bucket_size=bucket_size, causal_offset=causal_offset,
        window_lo=window_lo, kv_mask=kv_mask, softclamp_value=softclamp_value,
        q_segment_ids=q_seg, kv_segment_ids=kv_seg,
    )
    out_g, lse = finalize(carry)
    # named residuals: RingTransformer(remat_policy="save_attn") saves these
    # so the backward's residual recompute elides the whole bucket scan
    # (same names in parallel/ring.py and ops/pallas_flash.py)
    out = checkpoint_name(_ungroup(out_g).astype(q.dtype), "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, lse


def flash_backward_blocks(
    do: jax.Array,  # (b, h, nq, d)
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lse: jax.Array,  # (b, hk, g, nq) f32
    delta: jax.Array,  # (b, hk, g, nq) f32 = rowsum(do * o)
    *,
    scale: float,
    bucket_size: int | None = None,
    causal_offset: jax.Array | int | None = None,
    window_lo: jax.Array | int | None = None,
    kv_mask: jax.Array | None = None,
    softclamp_value: float | None = None,
    q_segment_ids: jax.Array | None = None,  # (b, nq) int32
    kv_segment_ids: jax.Array | None = None,  # (b, nk) int32
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Flash backward over one KV span.

    Returns ``(dq (b,h,nq,d), dk (b,hk,nk,d), dv (b,hk,nk,d))``, all float32.
    The ring layer calls this once per backward hop and accumulates dk/dv
    into the rotating buffer (ref ``ring_flash_attention.py:292-375``).

    Segment ids mask cross-document terms out of ``p`` (so dk/dv/dq carry
    no cross-document contributions), and buckets sharing no document with
    the queries skip straight to zero dk/dv — the backward twin of the
    forward's whole-bucket skip.
    """
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    g = h // hk
    qg = _group_q(q, hk)
    dog = _group_q(do, hk).astype(jnp.float32)

    bk = bucket_size if (bucket_size is not None and bucket_size < nk) else nk
    assert nk % bk == 0
    xs = _bucket_xs(b, hk, nk, d, bk, k, v, kv_mask, kv_segment_ids)

    def body(dq_acc, xs):
        def compute(dq_acc):
            k_j, v_j = xs["k"], xs["v"]
            s = _tile_scores(qg, k_j, scale, softclamp_value)
            mask = _tile_mask(nq, bk, xs["j"] * bk, causal_offset, window_lo,
                              xs.get("m"), q_segment_ids, xs.get("s"))
            p = jnp.exp(s - lse[..., None])  # (b,hk,g,nq,bk)
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
            dv_j = jnp.einsum("bhgij,bhgid->bhjd", p, dog)
            dp = jnp.einsum("bhgid,bhjd->bhgij", dog, v_j.astype(jnp.float32))
            ds = p * (dp - delta[..., None])
            if softclamp_value is not None:
                # s is post-clamp; d(clamp)/d(raw) = 1 - (s/c)^2
                ds = ds * (1.0 - (s / softclamp_value) ** 2)
            ds = ds * scale
            dk_j = jnp.einsum("bhgij,bhgid->bhjd", ds, qg.astype(jnp.float32))
            dq_acc = dq_acc + jnp.einsum(
                "bhgij,bhjd->bhgid", ds, k_j.astype(jnp.float32)
            )
            return dq_acc, (dk_j, dv_j)

        if "s" not in xs:
            return compute(dq_acc)
        zeros = match_vma(jnp.zeros((b, hk, bk, d), jnp.float32), q)
        has = segments_overlap(q_segment_ids, xs["s"])
        return lax.cond(
            has, compute, lambda a: (a, (zeros, zeros)), dq_acc
        )

    dq0 = match_vma(jnp.zeros((b, hk, g, nq, d), jnp.float32), q)
    dq_g, (dkb, dvb) = lax.scan(body, dq0, xs)
    dk = dkb.transpose(1, 2, 0, 3, 4).reshape(b, hk, nk, d)
    dv = dvb.transpose(1, 2, 0, 3, 4).reshape(b, hk, nk, d)
    return _ungroup(dq_g), dk, dv


@partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flash_attention_core(q, k, v, kv_mask, q_seg, kv_seg, causal_offset,
                          scale, bucket_size, window, softclamp_value):
    """custom_vjp core; ``causal_offset`` is an int scalar (possibly traced —
    the q-chunked path scans over per-chunk offsets) or None (no mask).

    An end-aligned offset (``nk - nq``) supports decode-style ``nq < nk``
    calls exactly like the oracle (ops/attention.py).
    """
    out, _ = _flash_fwd_impl(
        q, k, v, kv_mask, q_seg, kv_seg, scale, bucket_size, causal_offset,
        window, softclamp_value
    )
    return out


def _flash_core_fwd(q, k, v, kv_mask, q_seg, kv_seg, causal_offset, scale,
                    bucket_size, window, softclamp_value):
    out, lse = _flash_fwd_impl(
        q, k, v, kv_mask, q_seg, kv_seg, scale, bucket_size, causal_offset,
        window, softclamp_value
    )
    return out, (q, k, v, kv_mask, q_seg, kv_seg, causal_offset, out, lse)


def _flash_core_bwd(scale, bucket_size, window, softclamp_value, res, do):
    q, k, v, kv_mask, q_seg, kv_seg, causal_offset, out, lse = res
    hk = k.shape[1]
    window_lo = causal_offset - (window - 1) if window is not None else None
    with jax.named_scope("flash/bwd"):
        delta = (_group_q(do, hk).astype(jnp.float32)
                 * _group_q(out, hk).astype(jnp.float32)).sum(-1)
        dq, dk, dv = flash_backward_blocks(
            do, q, k, v, lse, delta,
            scale=scale, bucket_size=bucket_size, causal_offset=causal_offset,
            window_lo=window_lo, kv_mask=kv_mask,
            softclamp_value=softclamp_value,
            q_segment_ids=q_seg, kv_segment_ids=kv_seg,
        )
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None, None, None)


_flash_attention_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = False,
    bucket_size: int | None = None,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    q_chunk_size: int | None = None,
    segment_ids=None,
) -> jax.Array:
    """Single-device exact flash attention (GQA-aware), differentiable.

    Matches ``default_attention`` numerically (parity-tested); memory scales
    with ``bucket_size`` instead of ``nk**2``.  Any KV length is accepted:
    non-multiples of ``bucket_size`` are padded internally with masked-out
    slots (pad/slice sit outside the custom_vjp core, so dk/dv slice back
    automatically).  The causal band is end-aligned (``offset = nk - nq``),
    so decode-style ``nq < nk`` calls match the oracle.

    ``q_chunk_size`` additionally tiles the query dimension (two-level
    blocking): per-step score memory becomes ``q_chunk x bucket`` instead of
    ``nq x bucket`` — required for very long sequences on the XLA path (the
    Pallas kernels tile both dimensions natively).  Gradients of the shared
    K/V sum across chunks through autodiff.

    ``segment_ids`` enables packed-sequence attention: a ``(b, n)`` array of
    per-token document ids (or a ``(q_ids, kv_ids)`` pair), masking
    cross-document logits to exactly zero weight and skipping KV buckets
    that share no document with the queries (see ``docs/packing.md``).
    """
    check_attention_args("flash_attention", q, k, v, mask)
    q_seg, kv_seg = normalize_segment_ids(segment_ids, q, k, "flash_attention")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None:
        assert causal, "lookback windows require causal attention"
    if causal:
        mask = None  # reference asserts causal and key-pad mask are exclusive
    causal_offset = k.shape[2] - q.shape[2] if causal else None

    # pad KV once (shared by every q chunk): masked-out slots beyond nk
    k, v, mask, kv_seg = _pad_kv_to_bucket(q, k, v, mask, kv_seg, bucket_size)
    # causal_offset stays computed from the real nk: pad keys sit at
    # j >= nk_real > i + offset for every real row, and the key mask
    # excludes them for fully-padded rows anyway.

    nq = q.shape[2]
    if q_chunk_size is not None and nq > q_chunk_size:
        # lax.scan over equal-size q chunks: the chunk body compiles ONCE
        # regardless of chunk count (a Python loop here unrolled one
        # custom_vjp core per chunk — 128 copies at seq 262144 — blowing
        # compile time on exactly the long sequences this option targets).
        # The per-chunk causal offset rides the scan as a traced scalar;
        # K/V/mask are scan constants, so their grads accumulate through
        # the scan transpose.
        cq = q_chunk_size
        pad_q = (-nq) % cq
        if pad_q:
            q = jnp.pad(q, [(0, 0), (0, 0), (0, pad_q), (0, 0)])
            if q_seg is not None:
                # PAD_SEGMENT_ID matches nothing real; pad rows come out as
                # garbage-but-finite and are sliced off below
                q_seg = jnp.pad(q_seg, [(0, 0), (0, pad_q)],
                                constant_values=PAD_SEGMENT_ID)
        b, h, _, d = q.shape
        nc = (nq + pad_q) // cq
        qs = jnp.moveaxis(q.reshape(b, h, nc, cq, d), 2, 0)  # (nc, b, h, cq, d)
        xs = {"q": qs}
        if q_seg is not None:
            xs["qs"] = jnp.moveaxis(q_seg.reshape(b, nc, cq), 1, 0)

        if causal:
            # chunk rows start at start=i*cq, shifting the end-aligned band
            xs["off"] = causal_offset + jnp.arange(nc, dtype=jnp.int32) * cq

        def body(_, xs):
            with jax.named_scope("flash/fwd"):
                return None, _flash_attention_core(
                    xs["q"], k, v, mask, xs.get("qs"), kv_seg, xs.get("off"),
                    scale, bucket_size, window, softclamp_value,
                )

        _, outs = lax.scan(body, None, xs)

        out = jnp.moveaxis(outs, 0, 2).reshape(b, h, nc * cq, d)
        return out[:, :, :nq] if pad_q else out
    with jax.named_scope("flash/fwd"):
        return _flash_attention_core(
            q, k, v, mask, q_seg, kv_seg, causal_offset, scale, bucket_size,
            window, softclamp_value,
        )


def _pad_kv_to_bucket(q, k, v, mask, kv_seg, bucket_size):
    nk = k.shape[2]
    if bucket_size is None or nk % bucket_size == 0:
        return k, v, mask, kv_seg
    pad = bucket_size - nk % bucket_size
    widths = [(0, 0), (0, 0), (0, pad), (0, 0)]
    k = jnp.pad(k, widths)
    v = jnp.pad(v, widths)
    if mask is None:
        mask = jnp.arange(nk + pad)[None, :] < nk
        mask = jnp.broadcast_to(mask, (q.shape[0], nk + pad))
    else:
        mask = jnp.pad(mask, [(0, 0), (0, pad)], constant_values=False)
    if kv_seg is not None:
        kv_seg = jnp.pad(kv_seg, [(0, 0), (0, pad)],
                         constant_values=PAD_SEGMENT_ID)
    return k, v, mask, kv_seg
