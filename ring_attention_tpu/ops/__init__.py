from .attention import (
    default_attention,
    normalize_segment_ids,
    segments_overlap,
    softclamp,
    MASK_VALUE,
    EPSILON,
    PAD_SEGMENT_ID,
    SegmentIds,
)
from .flash import (
    FlashCarry,
    attend_blocks,
    finalize,
    flash_attention,
    flash_backward_blocks,
    init_carry,
)
from .pallas_flash import (
    BandPlan,
    QuantizedKV,
    band_plan,
    pallas_flash_attention,
    pallas_flash_decode,
    pallas_flash_decode_q8,
    quantize_kv_cache,
)
from . import quant
from .quant import QuantizedBlockKV
from .rotary import apply_rotary, ring_positions, rotary_freqs, rotate_half
from .. import masks as _masks


def attention(
    q,
    k,
    v,
    mask=None,
    *,
    causal: bool = False,
    window: int | None = None,
    softclamp_value: float | None = None,
    impl: str = "auto",
    bucket_size: int | None = None,
    q_chunk_size: int | None = None,
    head_chunks: int | None = None,
    interpret: bool | None = None,
    segment_ids=None,
    doc_starts: tuple[int, ...] | None = None,
    compute_dtype: str | None = None,
):
    """Single-device attention entry point with graceful kernel degradation.

    ``mask`` accepts either a ``(b, nk)`` boolean key-padding array (the
    classic form) or a :class:`ring_attention_tpu.masks.Mask` algebra
    expression — ``attention(q, k, v, mask=Causal() & SlidingWindow(512))``.
    A mask expression is resolved onto the kernel knobs through
    ``masks.kernel_form`` (``causal=True`` elsewhere is sugar for
    ``Causal()``), its lowering is CERTIFIED at trace time
    (sound/tight/complete against the mask's own oracle —
    ``masks.require_certified``, cached next to the compile cache), and
    it subsumes ``causal=`` / ``window=`` / ``doc_starts=`` (passing
    both raises).  Expressions beyond the kernel surface (prefix-LM,
    dilated, per-head) raise :class:`~ring_attention_tpu.masks.
    MaskLoweringError` naming the supported forms.

    ``impl`` selects the kernel path:

    - ``"pallas"`` — the Mosaic kernels (:func:`pallas_flash_attention`);
      failures propagate (an explicit request must fail loudly).
    - ``"xla"`` — the pure-XLA flash path (:func:`flash_attention`).
    - ``"auto"`` (default) — try Pallas, FALL BACK to XLA when the Pallas
      path cannot compile/lower on this backend (missing plugin, Mosaic
      rejection, older jax).  The first failure emits one warning and is
      recorded in ``ring_attention_tpu.utils.resilience.degradation`` —
      queryable, so a run that silently lost its fast kernels is
      distinguishable from one that never had them.  Resolution happens at
      trace time (an outer ``jax.jit`` compiles exactly one path), backed
      by a tiny one-shot compile probe so the choice is made *before* a
      caller's multi-minute compile bakes it in.  On non-TPU backends
      ``auto`` takes XLA silently (no degradation record): interpret-mode
      Pallas would be a pessimization there, not a fallback.

    ``bucket_size``/``q_chunk_size`` apply to the XLA path,
    ``head_chunks``/``interpret``/``doc_starts`` to the Pallas path; both
    sets are legal with ``impl="auto"`` (whichever path runs uses its
    own).  ``segment_ids`` (packed sequences) applies to both.

    ``compute_dtype="int8"`` (quantized QK^T/PV, ``docs/precision.md``)
    exists only on the Pallas kernels, so it suspends the graceful
    degradation: ``impl="xla"`` raises, and ``"auto"`` requires the probe
    to resolve Pallas and lets kernel failures PROPAGATE — silently
    falling back to bf16 compute would misreport every number a
    quantized run exists to measure.
    """
    from ..utils import resilience
    from ..utils.validate import check_attention_args

    attn_mask = None
    if isinstance(mask, _masks.Mask):
        attn_mask, mask = mask, None  # the padding-mask slot stays empty

    # validate BEFORE any fallback machinery (or mask resolution, which
    # reads shapes): a caller's input error must raise as itself, never
    # be mistaken for a kernel failure and mark the Pallas path degraded
    # for the rest of the process
    check_attention_args("attention", q, k, v, mask)

    if attn_mask is not None:
        if causal or window is not None:
            raise ValueError(
                "attention: a mask expression subsumes causal=/window= — "
                "compose them into the mask (causal=True is sugar for "
                "Causal())"
            )
        form = _masks.kernel_form(attn_mask)  # raises MaskLoweringError
        causal, window = form.causal, form.window
        if form.doc_starts is not None:
            if doc_starts is not None:
                raise ValueError(
                    "attention: the mask already declares a DocumentMask "
                    "packing; drop the doc_starts= argument"
                )
            doc_starts = form.doc_starts
        if form.needs_segment_ids and segment_ids is None:
            raise ValueError(
                "attention: the mask includes Segments() — pass the "
                "runtime segment_ids array"
            )
        if q.shape[2] == k.shape[2]:
            # trace-time certificate for the grids this call lowers to,
            # cached by (mask, shape, blocks, strategy, layout); cross-
            # attention spans have no self-attention grid to certify
            _masks.require_certified(
                attn_mask, _masks.spec_for_call("single", n=q.shape[2])
            )

    if head_chunks is not None and head_chunks > 1:
        h, hk = q.shape[1], k.shape[1]
        if h % head_chunks or hk % head_chunks:
            raise ValueError(
                f"attention: head_chunks={head_chunks} must divide both "
                f"heads={h} and kv_heads={hk}"
            )

    # doc_starts is a SEMANTIC input (a declared packing layout), not a
    # perf knob: a path that cannot resolve it into kernel tables must
    # realize it as runtime segment ids, never silently drop it — the
    # XLA fallback would otherwise compute cross-document attention
    xla_segment_ids = segment_ids
    if doc_starts is not None and segment_ids is None:
        from .pallas_flash import _check_doc_starts, _doc_runtime_ids

        nq, nk = q.shape[2], k.shape[2]
        _check_doc_starts(doc_starts, nq, nk)
        xla_segment_ids = _doc_runtime_ids(doc_starts, nq, q.shape[0])

    def run_xla():
        return flash_attention(
            q, k, v, mask, causal=causal, window=window,
            softclamp_value=softclamp_value, bucket_size=bucket_size,
            q_chunk_size=q_chunk_size, segment_ids=xla_segment_ids,
        )

    def run_pallas():
        resilience.get_injector().check(resilience.PALLAS_FAULT)
        return pallas_flash_attention(
            q, k, v, mask, causal=causal, window=window,
            softclamp_value=softclamp_value, head_chunks=head_chunks,
            interpret=interpret, segment_ids=segment_ids,
            doc_starts=doc_starts, compute_dtype=compute_dtype,
        )

    resolved = resilience.resolve_attention_impl(impl)
    if compute_dtype is not None:
        if compute_dtype != "int8":
            raise ValueError(
                f"attention: compute_dtype={compute_dtype!r}; supported "
                'values are None and "int8"'
            )
        if resolved == "xla":
            raise ValueError(
                'attention: compute_dtype="int8" runs on the Pallas '
                f'kernels only, but impl={impl!r} resolved to the XLA '
                "path — a silent bf16 fallback would misreport a "
                "quantized run (docs/precision.md)"
            )
        return run_pallas()  # failures propagate: no bf16 degradation
    if resolved == "xla":
        return run_xla()
    if impl != "auto":
        return run_pallas()
    try:
        # probe passed, but this call's exact shape/config can still hit a
        # trace-time lowering failure — catch it and degrade rather than
        # kill a run the XLA path could have carried
        return run_pallas()
    except Exception as e:  # noqa: BLE001 — any Pallas failure degrades
        resilience.degradation.record(resilience.PALLAS_COMPONENT, e)
        return run_xla()


__all__ = [
    "attention",
    "normalize_segment_ids",
    "segments_overlap",
    "PAD_SEGMENT_ID",
    "SegmentIds",
    "BandPlan",
    "band_plan",
    "QuantizedKV",
    "QuantizedBlockKV",
    "quant",
    "pallas_flash_attention",
    "pallas_flash_decode",
    "pallas_flash_decode_q8",
    "quantize_kv_cache",
    "default_attention",
    "softclamp",
    "MASK_VALUE",
    "EPSILON",
    "FlashCarry",
    "attend_blocks",
    "finalize",
    "flash_attention",
    "flash_backward_blocks",
    "init_carry",
    "apply_rotary",
    "ring_positions",
    "rotary_freqs",
    "rotate_half",
]
