from .attention import default_attention, softclamp, MASK_VALUE, EPSILON
from .flash import (
    FlashCarry,
    attend_blocks,
    finalize,
    flash_attention,
    flash_backward_blocks,
    init_carry,
)
from .pallas_flash import pallas_flash_attention, pallas_flash_decode
from .rotary import apply_rotary, ring_positions, rotary_freqs, rotate_half

__all__ = [
    "pallas_flash_attention",
    "pallas_flash_decode",
    "default_attention",
    "softclamp",
    "MASK_VALUE",
    "EPSILON",
    "FlashCarry",
    "attend_blocks",
    "finalize",
    "flash_attention",
    "flash_backward_blocks",
    "init_carry",
    "apply_rotary",
    "ring_positions",
    "rotary_freqs",
    "rotate_half",
]
