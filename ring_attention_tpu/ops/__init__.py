from .attention import default_attention, softclamp, MASK_VALUE, EPSILON
from .flash import (
    FlashCarry,
    attend_blocks,
    finalize,
    flash_attention,
    flash_backward_blocks,
    init_carry,
)
from .pallas_flash import (
    QuantizedKV,
    pallas_flash_attention,
    pallas_flash_decode,
    pallas_flash_decode_q8,
    quantize_kv_cache,
)
from .rotary import apply_rotary, ring_positions, rotary_freqs, rotate_half

__all__ = [
    "QuantizedKV",
    "pallas_flash_attention",
    "pallas_flash_decode",
    "pallas_flash_decode_q8",
    "quantize_kv_cache",
    "default_attention",
    "softclamp",
    "MASK_VALUE",
    "EPSILON",
    "FlashCarry",
    "attend_blocks",
    "finalize",
    "flash_attention",
    "flash_backward_blocks",
    "init_carry",
    "apply_rotary",
    "ring_positions",
    "rotary_freqs",
    "rotate_half",
]
