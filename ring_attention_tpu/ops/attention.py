"""Reference (oracle) dense attention in pure JAX.

TPU-native analogue of the reference's ``default_attention``
(``ring_attention.py:47-98`` in lucidrains/ring-attention-pytorch): an exact,
materialize-the-scores attention used as the ground truth for every parity
test, and as the ``force_regular_attn`` fallback in the model layer.

Capabilities (matching the reference oracle):
  - grouped-query attention: ``q`` has ``h = hk * g`` heads attending against
    ``hk`` key/value heads (ref ``ring_attention.py:64-68``)
  - logit soft-clamping ``c * tanh(s / c)`` (ref ``ring_attention.py:44-45,76-77``)
  - causal masking, or key-padding masking (mutually exclusive in the
    reference as well, ref ``ring_attention.py:81-88``)

Layout convention for all ops in this package: ``q: (b, h, n, d)``,
``k, v: (b, hk, n, d)`` — heads-major so the attention matmuls present
``(n, d) x (d, n)`` contractions that tile directly onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..utils import compat

# Large-but-finite mask value: avoids the NaNs that -inf produces for
# fully-masked rows (exp(-inf - -inf)).  The reference uses
# ``-torch.finfo(dtype).max`` the same way.
MASK_VALUE = -0.5 * float(jnp.finfo(jnp.float32).max)
EPSILON = 1e-10  # ref ring_attention_pytorch/ring_flash_attention.py:25

# Segment id reserved for padding: never equal to a real document id
# (real ids must be >= 0), so pad queries/keys attend only each other.
PAD_SEGMENT_ID = -1


class SegmentIds(NamedTuple):
    """Per-token document ids for packed-sequence (segment) attention.

    A query at row ``i`` may attend a key at column ``j`` only when
    ``q[.., i] == kv[.., j]`` (in addition to any causal band / key-padding
    mask / lookback window).  Same convention as the splash-attention
    kernels' ``SegmentIds``.  Real ids are ``>= 0``; ``PAD_SEGMENT_ID``
    marks padding.
    """

    q: jax.Array  # (b, nq) int32
    kv: jax.Array  # (b, nk) int32


def normalize_segment_ids(segment_ids, q, k, fn: str = "attention"):
    """``(q_seg, kv_seg)`` int32 arrays from the public ``segment_ids`` arg.

    Accepts a single ``(b, n)`` array (self-attention: used for both sides,
    requires ``nq == nk``), a ``(q, kv)`` pair / :class:`SegmentIds`, or
    None -> ``(None, None)``.  Shape-validated against q/k at trace time.
    """
    if segment_ids is None:
        return None, None
    from ..utils.validate import check_segment_ids

    if isinstance(segment_ids, (tuple, list, SegmentIds)):
        q_seg, kv_seg = segment_ids
    else:
        q_seg = kv_seg = segment_ids
    q_seg = jnp.asarray(q_seg)
    kv_seg = jnp.asarray(kv_seg)
    check_segment_ids(fn, q, k, q_seg, kv_seg)
    return q_seg.astype(jnp.int32), kv_seg.astype(jnp.int32)


def segments_overlap(q_seg: jax.Array, kv_seg: jax.Array) -> jax.Array:
    """Conservative "any shared document?" scalar for two id blocks.

    Disjoint id *ranges* imply no shared document regardless of ordering,
    so skipping on this predicate is always sound; overlapping ranges may
    still share nothing (the per-element mask handles those).
    """
    return (jnp.min(q_seg) <= jnp.max(kv_seg)) & (
        jnp.min(kv_seg) <= jnp.max(q_seg)
    )


def softclamp(x: jax.Array, value: float) -> jax.Array:
    """Soft clamp logits to (-value, value) via tanh (Gemma-style capping)."""
    return jnp.tanh(x / value) * value


@partial(compat.jit, static_argnames=("causal", "softclamp_value"))
def default_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = False,
    softclamp_value: float | None = None,
    segment_ids=None,
) -> jax.Array:
    """Exact dense attention oracle.

    Args:
      q: ``(b, h, nq, d)`` queries.
      k: ``(b, hk, nk, d)`` keys; ``h`` must be a multiple of ``hk`` (GQA).
      v: ``(b, hk, nk, d)`` values.
      mask: optional ``(b, nk)`` boolean key-padding mask, True = attend.
      causal: apply a causal mask (ignores ``mask`` if set, as the reference
        asserts the two are exclusive).
      softclamp_value: if set, logits are soft-clamped to this magnitude.
      segment_ids: packed-sequence document ids (see
        :func:`normalize_segment_ids`); composes with every other mask —
        cross-document logits are masked out.

    Returns:
      ``(b, h, nq, d)`` attention output in ``q.dtype``.
    """
    from ..utils.validate import check_attention_args

    check_attention_args("default_attention", q, k, v, mask)
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    g = h // hk
    q_seg, kv_seg = normalize_segment_ids(segment_ids, q, k, "default_attention")

    scale = d**-0.5
    qg = q.reshape(b, hk, g, nq, d).astype(jnp.float32)
    sim = jnp.einsum("bhgid,bhjd->bhgij", qg, k.astype(jnp.float32)) * scale

    if softclamp_value is not None:
        sim = softclamp(sim, softclamp_value)

    if causal:
        i = jnp.arange(nq)[:, None]
        j = jnp.arange(nk)[None, :]
        sim = jnp.where(j <= i + (nk - nq), sim, MASK_VALUE)
    elif mask is not None:
        sim = jnp.where(mask[:, None, None, None, :], sim, MASK_VALUE)

    if q_seg is not None:
        same = q_seg[:, None, None, :, None] == kv_seg[:, None, None, None, :]
        sim = jnp.where(same, sim, MASK_VALUE)

    attn = jax.nn.softmax(sim, axis=-1)
    out = jnp.einsum("bhgij,bhjd->bhgid", attn, v.astype(jnp.float32))
    return out.reshape(b, h, nq, d).astype(q.dtype)
