"""Reference (oracle) dense attention in pure JAX.

TPU-native analogue of the reference's ``default_attention``
(``ring_attention.py:47-98`` in lucidrains/ring-attention-pytorch): an exact,
materialize-the-scores attention used as the ground truth for every parity
test, and as the ``force_regular_attn`` fallback in the model layer.

Capabilities (matching the reference oracle):
  - grouped-query attention: ``q`` has ``h = hk * g`` heads attending against
    ``hk`` key/value heads (ref ``ring_attention.py:64-68``)
  - logit soft-clamping ``c * tanh(s / c)`` (ref ``ring_attention.py:44-45,76-77``)
  - causal masking, or key-padding masking (mutually exclusive in the
    reference as well, ref ``ring_attention.py:81-88``)

Layout convention for all ops in this package: ``q: (b, h, n, d)``,
``k, v: (b, hk, n, d)`` — heads-major so the attention matmuls present
``(n, d) x (d, n)`` contractions that tile directly onto the MXU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Large-but-finite mask value: avoids the NaNs that -inf produces for
# fully-masked rows (exp(-inf - -inf)).  The reference uses
# ``-torch.finfo(dtype).max`` the same way.
MASK_VALUE = -0.5 * float(jnp.finfo(jnp.float32).max)
EPSILON = 1e-10  # ref ring_attention_pytorch/ring_flash_attention.py:25


def softclamp(x: jax.Array, value: float) -> jax.Array:
    """Soft clamp logits to (-value, value) via tanh (Gemma-style capping)."""
    return jnp.tanh(x / value) * value


@partial(jax.jit, static_argnames=("causal", "softclamp_value"))
def default_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = False,
    softclamp_value: float | None = None,
) -> jax.Array:
    """Exact dense attention oracle.

    Args:
      q: ``(b, h, nq, d)`` queries.
      k: ``(b, hk, nk, d)`` keys; ``h`` must be a multiple of ``hk`` (GQA).
      v: ``(b, hk, nk, d)`` values.
      mask: optional ``(b, nk)`` boolean key-padding mask, True = attend.
      causal: apply a causal mask (ignores ``mask`` if set, as the reference
        asserts the two are exclusive).
      softclamp_value: if set, logits are soft-clamped to this magnitude.

    Returns:
      ``(b, h, nq, d)`` attention output in ``q.dtype``.
    """
    b, h, nq, d = q.shape
    _, hk, nk, _ = k.shape
    assert h % hk == 0, "query heads must be a multiple of kv heads"
    g = h // hk

    scale = d**-0.5
    qg = q.reshape(b, hk, g, nq, d).astype(jnp.float32)
    sim = jnp.einsum("bhgid,bhjd->bhgij", qg, k.astype(jnp.float32)) * scale

    if softclamp_value is not None:
        sim = softclamp(sim, softclamp_value)

    if causal:
        i = jnp.arange(nq)[:, None]
        j = jnp.arange(nk)[None, :]
        sim = jnp.where(j <= i + (nk - nq), sim, MASK_VALUE)
    elif mask is not None:
        sim = jnp.where(mask[:, None, None, None, :], sim, MASK_VALUE)

    attn = jax.nn.softmax(sim, axis=-1)
    out = jnp.einsum("bhgij,bhjd->bhgid", attn, v.astype(jnp.float32))
    return out.reshape(b, h, nq, d).astype(q.dtype)
