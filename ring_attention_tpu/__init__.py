"""ring-attention-tpu: a TPU-native long-context attention framework.

Built from scratch in JAX/XLA with the capabilities of
lucidrains/ring-attention-pytorch: ring attention (sequence-parallel exact
attention over a device mesh via shard_map + ppermute), striped ring
attention for causal load balance, zig-zag context parallelism (Llama-3
style), tree-attention single-token decoding over sharded KV caches,
grouped-query attention, per-layer lookback windows, shard-aware rotary
embeddings, and RingAttention / RingTransformer model layers.
"""

__version__ = "0.1.0"

from . import masks
from .masks import (
    And,
    Causal,
    Dilated,
    DocumentMask,
    Full,
    Not,
    Or,
    PerHead,
    PrefixLM,
    Segments,
    SlidingWindow,
)
from .models import FeedForward, RingAttention, RingTransformer, RMSNorm
from .utils import StepTimer, restore_checkpoint, save_checkpoint, trace
from .ops import (
    PAD_SEGMENT_ID,
    SegmentIds,
    apply_rotary,
    default_attention,
    flash_attention,
    pallas_flash_attention,
    pallas_flash_decode,
    pallas_flash_decode_q8,
    quantize_kv_cache,
    QuantizedKV,
    ring_positions,
    rotary_freqs,
)
from .parallel import (
    all_gather_variable,
    axis_rank,
    axis_world,
    compact_masked,
    create_mesh,
    hybrid_attention,
    ring_flash_attention,
    stripe_permute,
    stripe_unpermute,
    tree_attn_decode,
    ulysses_attention,
    zigzag_attention,
    zigzag_permute,
    zigzag_positions,
    zigzag_unpermute,
)

__all__ = [
    "And",
    "Causal",
    "Dilated",
    "DocumentMask",
    "FeedForward",
    "Full",
    "Not",
    "Or",
    "PerHead",
    "PrefixLM",
    "Segments",
    "SlidingWindow",
    "masks",
    "PAD_SEGMENT_ID",
    "SegmentIds",
    "StepTimer",
    "all_gather_variable",
    "axis_rank",
    "axis_world",
    "compact_masked",
    "restore_checkpoint",
    "save_checkpoint",
    "trace",
    "RMSNorm",
    "RingAttention",
    "RingTransformer",
    "apply_rotary",
    "create_mesh",
    "default_attention",
    "hybrid_attention",
    "flash_attention",
    "pallas_flash_attention",
    "pallas_flash_decode",
    "pallas_flash_decode_q8",
    "quantize_kv_cache",
    "QuantizedKV",
    "ring_flash_attention",
    "ring_positions",
    "rotary_freqs",
    "stripe_permute",
    "stripe_unpermute",
    "tree_attn_decode",
    "ulysses_attention",
    "zigzag_attention",
    "zigzag_permute",
    "zigzag_positions",
    "zigzag_unpermute",
]
