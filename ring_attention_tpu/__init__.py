"""ring-attention-tpu: a TPU-native long-context attention framework.

Built from scratch in JAX/XLA with the capabilities of
lucidrains/ring-attention-pytorch: ring attention (sequence-parallel exact
attention over a device mesh via shard_map + ppermute), striped ring
attention for causal load balance, grouped-query attention, per-layer
lookback windows, shard-aware rotary embeddings, and RingAttention /
RingTransformer model layers.
"""

__version__ = "0.1.0"

from .ops import (
    default_attention,
    flash_attention,
)

__all__ = [
    "default_attention",
    "flash_attention",
]
