"""Ring attention over a TPU mesh axis: `lax.ppermute` + online softmax.

TPU-native redesign of the reference's L1+L3 (``ring.py`` /
``ring_flash_attention.py`` / ``ring_flash_attention_cuda.py`` in
lucidrains/ring-attention-pytorch).  The reference hand-rolls a P2P ring
(batched isend/irecv + barrier per hop, ``ring.py:51-60``) and hand-written
autograd Functions (``ring_flash_attention.py:60-387``).  Here the entire
communication layer is one collective — ``lax.ppermute`` over a named mesh
axis inside ``shard_map`` — which XLA pipelines with the per-hop flash
compute (the overlap the reference explicitly lacks), and differentiation
is a ``jax.custom_vjp`` whose backward rotates ``(k, v, dk, dv)`` together,
finishing with a single composed catch-up ppermute that returns partial
dk/dv to their owner shard when ``max_ring_passes`` limits the loop
(ref ``ring_flash_attention.py:380-385``).

Two interchangeable per-hop compute paths (the reference's naive/Triton
split, ``ring_attention.py:424-451``):

  - ``impl="xla"``   — blockwise jnp flash (``ops/flash.py``), runs anywhere;
  - ``impl="pallas"`` — Mosaic kernels (``ops/pallas_flash.py``), the
    performance path on TPU: an unrolled hop loop whose kernels resume the
    ``(acc, m, l)`` carry in-kernel (the reference's ``LOAD_ACCUMULATED``)
    with compact causal grids per hop, fusing normalization into the final
    span's write (see ``_ring_fwd_pallas``).

Ring-set math (multiple independent rings inside one world,
ref ``ring.py:35-47``) needs no code at all: ppermute over the ``seq`` mesh
axis is automatically scoped per row of the ``(data, seq)`` mesh.

Masking unification (see ``ops/flash.py``): each hop computes a single
*causal offset* scalar from ``(my_rank, origin_rank)``:

  - plain causal:   ``offset = (rank - origin) * n_local`` — covers
    "skip hop entirely" (origin > rank), "triangular" (origin == rank) and
    "fully visible" (origin < rank) in one expression
    (ref ``ring_flash_attention.py:177-192``).
  - striped causal: ``offset = 0 if origin <= rank else -1`` — the
    inclusive/exclusive diagonal flip (ref ``triton_flash_attn.py:216-221``,
    ``ring_flash_attention_cuda.py:158-160``).

Hops that provably contribute nothing (plain causal, origin ahead of rank;
or beyond the lookback window) skip their compute through ``lax.cond`` —
the per-device branch resolves at run time from ``axis_index``, while the
ppermute stays outside the cond so the collective schedule is identical on
every device.

KV circulates as one or more *streams* (``_streams``): unidirectional is
one whole-block stream; ``bidirectional=True`` splits the block into two
halves ppermuted in opposite directions so per-hop transfers ride both
directions of the full-duplex ICI links (``docs/ring_overlap.md``).

Trace attribution (``docs/observability.md``): every hop's compute and
rotation carry stable ``jax.named_scope`` names — ``ring/hop{i}`` /
``ring/rotate{i}`` on the unrolled Pallas path (static hop index),
``ring/hop`` / ``ring/rotate`` on the scanned XLA path, ``ring/bwd_hop*``
and ``ring/catchup`` in backward — so an XProf capture splits device time
between per-hop flash compute and the ppermute chain.
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ..utils import compat
from ..ops.attention import EPSILON, normalize_segment_ids, segments_overlap
from ..ops.flash import (
    FlashCarry,
    attend_blocks,
    finalize,
    flash_backward_blocks,
    init_carry,
    match_vma,
    _group_q,
    _ungroup,
)
from ..ops.pallas_flash import (
    FlashPartials,
    _block_sizes,
    finalize_partials,
    pallas_flash_backward,
    pallas_flash_fused,
    pallas_flash_partials,
)
from ..ops import pallas_ring as _pallas_ring
from ..ops import quant as _quant
from .collectives import dequantize_ring_payload, quantize_ring_payload
from ..utils.validate import check_attention_args


def _ring_perm(axis_name: str, shift: int = 1) -> list[tuple[int, int]]:
    size = compat.axis_size(axis_name)
    return [(j, (j + shift) % size) for j in range(size)]


def _rotate(x, axis_name: str, shift: int = 1):
    # size-1 axes arise from degenerate hybrid factorings (ulysses == world
    # on a factored mesh): the identity rotation is a real collective on
    # some backends, so skip it rather than trust DCE (axis sizes are
    # static, so this resolves at trace time)
    if compat.axis_size(axis_name) == 1:
        return x
    return lax.ppermute(x, axis_name, _ring_perm(axis_name, shift))  # ra: allow(RA004 every caller wraps each rotation in its ring/rotate{i} hop scope)


def _streams(bidirectional: bool, n_local: int) -> list[tuple[int, int, int]]:
    """KV circulation streams as ``(shift, key_offset, key_len)``.

    Unidirectional: the whole local KV block rotates one way.  Bidirectional:
    the block is split in half along the sequence; the halves circulate in
    opposite directions, one ``ppermute`` each per hop.  Per-hop transfer
    volume is unchanged but rides both directions of the (full-duplex) ICI
    ring links, halving the exposed transfer time — the fallback/upgrade
    discussed in ``docs/ring_overlap.md``.  Device r's hop ``i`` attends the
    first half of origin ``r-i`` and the second half of origin ``r+i``; over
    ``ring_size`` hops that covers every origin's both halves exactly once.
    """
    if not bidirectional:
        return [(1, 0, n_local)]
    assert n_local % 2 == 0, (
        f"bidirectional ring needs an even local sequence, got {n_local}"
    )
    half = n_local // 2
    return [(1, 0, half), (-1, half, half)]


def _kv_handle(k, v, hop_compression, q8_block=None):
    """Circulating KV payload: a stacked ``(2, b, hk, n, d)`` array in the
    model dtype, or — with ``hop_compression="int8"`` — a single
    ``(2, b, hk, n, d + 4)`` int8 array (values + bitcast f32 scale bytes)
    quantized ONCE here and circulated unchanged (hops are lossless moves;
    see ``collectives.quantize_ring_payload``).  Either way ONE array, so
    every rotation is exactly one ``ppermute``.

    ``q8_block`` (set when ``compute_dtype="int8"`` rides the pallas
    path) packs v's scales per KV-block of that size instead of per row —
    bit-compatible on the wire and with :func:`_handle_kv`, but ALSO
    directly consumable by the int8 kernels (``quant.payload_kernel_feed``)
    with no dequant→requant round trip per hop."""
    if hop_compression is None:
        return jnp.stack([k, v])
    if q8_block is not None:
        return _quant.pack_kv(k, v, v_block=q8_block)
    return quantize_ring_payload(k, v)


def _handle_kv(handle, dtype):
    """The ``(k, v)`` a circulating handle represents, in ``dtype``."""
    if handle.dtype == jnp.int8:
        return dequantize_ring_payload(handle, dtype)
    return handle[0], handle[1]


def _handle_feed(handle, dtype, compute_dtype, q8_block):
    """Kernel-feed view of a circulating handle: ``(k, v, kv_quantized)``.

    The dequant-free composition seam: an int8-compressed hop payload
    under ``compute_dtype="int8"`` feeds the kernel DIRECTLY — int8
    values + per-row k scales + per-block v scales sliced straight out of
    the payload (``quant.payload_kernel_feed``), no dequantize at the hop
    and no re-quantize in the launcher.  The payload is quantized once at
    ring entry; dequantization happens only inside the kernel's
    accumulator rescale.  Every other combination degrades gracefully:
    a compressed payload under bf16 compute dequantizes as before, an
    uncompressed handle under int8 compute quantizes in the launcher
    (its k/v are exact, so this is the FIRST quantization, not a re-).
    """
    if handle.dtype == jnp.int8:
        if compute_dtype == "int8" and q8_block is not None:
            feed = _quant.payload_kernel_feed(handle, q8_block)
            if feed is not None:
                return None, None, feed
        return (*dequantize_ring_payload(handle, dtype), None)
    return handle[0], handle[1], None


def _handle_slice(handle, ofs, nk):
    """Token-range slice of a handle (bidirectional half-streams).  The
    compressed handle's per-row scale bytes ride the same token axis, so
    half-streams slice ONE shared quantization pass."""
    return handle[:, :, :, ofs:ofs + nk]


def _pack_counter(q, acc, m, l):
    """Flatten the counter-rotating Q-stream — the query block plus its
    online-softmax accumulators — into ONE f32 array ``(b, h, n, 2d + 2)``
    (channels ``[q | acc | m | l]``), so each Q-stream rotation is a single
    ``ppermute``.  All inputs are ``(b, h, n, ·)``; sub-f32 ``q`` round-trips
    through f32 bit-exactly, and the ``(acc, m, l)`` accumulators stay f32
    end to end (``analysis/recompile.py::audit_accumulator_dtypes``)."""
    return jnp.concatenate(
        [q.astype(jnp.float32), acc, m[..., None], l[..., None]], axis=-1
    )


def _unpack_counter(pack, d, dtype):
    """Inverse of :func:`_pack_counter`: ``(q, acc, m, l)``."""
    return (
        pack[..., :d].astype(dtype),
        pack[..., d:2 * d],
        pack[..., 2 * d],
        pack[..., 2 * d + 1],
    )


def _counter_origins(rank, i, ring_size):
    """``(q_origin, kv_origin)`` held by device ``rank`` at counter-rotation
    hop ``i``.

    The alternating schedule (Q-stream rotation with shift -1 after even
    hops, KV rotation with shift +1 after odd hops) means that before hop
    ``i`` the Q stream has moved ``ceil(i/2)`` times and the KV stream
    ``floor(i/2)`` times; either rotation advances the pairing by one, so
    ``q_origin - kv_origin ≡ i (mod ring)`` — hop ``i`` pairs each query
    block with the KV block ``i`` ranks behind it, exactly the baseline
    ring's visit order (windows and limited passes keep their semantics).

    Works for traced and static ``i`` alike.
    """
    nq = (i + 1) // 2
    nk = i // 2
    return (rank + nq) % ring_size, (rank - nk) % ring_size


def _q8_block(bucket_size, nq, nk):
    """The ``block_k`` a pallas launch over an ``(nq, nk)`` span will fit
    — the granularity the int8 compute path's v scales must be packed at
    for the dequant-free hop feed (one derivation shared by the payload
    packer and the kernel's own ``_block_sizes`` fitting)."""
    return _block_sizes(nq, nk, bucket_size, bucket_size)[1]


def _stream_state(bidirectional, passes, ring_size, n_local, k, v, kv_mask,
                  segment_ids=None, hop_compression=None, q8_bucket=False):
    """Streams + their sliced KV handles, mask shards, and kv segment-id
    shards (fwd and bwd share this so the fallback condition and slice
    bounds can never diverge).  Segment ids circulate exactly like the
    mask: the queries keep the local ids, the kv ids ride the ring.
    ``None`` payloads never enter the rotation state at all — an unmasked,
    unpacked hop ppermutes exactly its KV handle and nothing else.

    With ``hop_compression``, the whole block is quantized once and the
    (half-)streams slice the shared int8 payload + scales, so
    bidirectional halves ride one quantization pass.  Under
    ``compute_dtype="int8"`` (``q8_bucket`` set — the caller's
    bucket_size) each stream instead packs its own span with v scales at
    that span's fitted ``block_k``, so every hop's kernel can consume the
    payload directly (:func:`_handle_feed`); still one quantization per
    stream for the whole circulation.  ``q8_bucket=False`` (the default —
    distinct from ``None``, a legal bucket_size) disables the feed
    layout.

    Limited passes never see the reverse stream's useful origins in time
    (see the ``bidirectional`` docstring) — run unidirectional instead.
    """
    streams = _streams(bidirectional and passes == ring_size, n_local)
    if hop_compression is not None and q8_bucket is not False:
        kvs = tuple(
            _kv_handle(
                k[:, :, ofs:ofs + nk], v[:, :, ofs:ofs + nk],
                hop_compression,
                q8_block=_q8_block(q8_bucket, n_local, nk),
            )
            for (_, ofs, nk) in streams
        )
    else:
        whole = _kv_handle(k, v, hop_compression)
        kvs = tuple(_handle_slice(whole, ofs, nk) for (_, ofs, nk) in streams)
    masks = (
        tuple(kv_mask[:, ofs:ofs + nk] for (_, ofs, nk) in streams)
        if kv_mask is not None
        else ()
    )
    segs = (
        tuple(segment_ids[:, ofs:ofs + nk] for (_, ofs, nk) in streams)
        if segment_ids is not None
        else ()
    )
    return streams, kvs, masks, segs


def _stream_offsets(stream, rank, i, n_local, causal, striped, window,
                    ring_size):
    """Band offsets ``(hi, lo)`` for one stream at hop ``i``.

    A key at local index ``j`` within a half-block starting at ``key_offset``
    sits at block-local index ``j + key_offset``; in both contiguous and
    striped layouts that shifts the band bounds by exactly ``-key_offset``
    (global key position is affine in the block-local index with unit
    coefficient in the contiguous case and stride ``ring_size`` in the
    striped case — the offset divides out identically)."""
    shift, ofs, _ = stream
    origin = (rank - shift * i) % ring_size
    hi, lo = _hop_offsets(
        rank, origin, n_local, causal, striped, window, ring_size
    )
    if ofs and hi is not None:
        hi = hi - ofs
        lo = lo - ofs if lo is not None else None
    return hi, lo


def _hop_offsets(
    rank: jax.Array,
    origin: jax.Array,
    n_local: int,
    causal: bool,
    striped: bool,
    window: int | None,
    ring_size: int,
) -> tuple[jax.Array | None, jax.Array | None]:
    """Band offsets (hi, lo) for the tile (my queries) x (origin's keys).

    Attend iff ``lo <= j - i <= hi`` in local indices.  Contiguous layout:
    ``hi = (rank - origin) * n_local``, ``lo = hi - (window-1)``.  Striped
    layout (global pos ``i*W + rank`` / ``j*W + origin``): the diagonal flip
    ``hi = 0|-1`` and — exactly, unlike the reference's bucket-granular
    approximation (ref ring_flash_attention.py:95-103) — the window bound
    ``j*W + o >= i*W + r - w + 1  <=>  j >= i + ceil((r - o - w + 1)/W)``,
    an integer scalar per hop."""
    if not causal:
        return None, None
    if striped:
        hi = jnp.where(origin <= rank, 0, -1)
        if window is None:
            return hi, None
        lo = -((origin + window - 1 - rank) // ring_size)  # ceil division
        return hi, lo
    hi = (rank - origin) * n_local
    lo = hi - (window - 1) if window is not None else None
    return hi, lo


def _static_hop_band(stream, i: int, n_local, causal, striped, window,
                     ring_size):
    """Trace-time band description of hop ``i`` (a static Python int — the
    unrolled pallas hop loop) for one stream.

    Returns ``(full, band_hint)``:
      - ``full``: every device with work sees the whole span unmasked —
        the hop can run with ``hi = lo = None`` (no mask, no tables); the
        devices the band excludes entirely are already skipped by the
        traced ``has_work`` cond.
      - ``band_hint``: static ``(hi_work, hi_int, lo_work, lo_int)`` bounds
        over the hop's possible per-device offsets, letting the Pallas
        compact causal grid engage on ring hops even though the offsets
        themselves are traced (VERDICT r2 missing #1; the reference's
        runtime per-block early-exit, ref ``triton_flash_attn.py:188-199``).

    Contiguous layout: every working device shares one exact offset —
    hop ``i`` of the forward stream attends origin ``rank - i``, giving
    ``hi = i * n_local`` wherever ``rank >= i`` (the rest skip); the
    reverse stream's workers sit at ``(ring - i) * n_local``.  Striped
    layout: offsets take two values (diagonal flip 0/-1, and two window
    floors), so the hint brackets them.
    """
    if not causal:
        return False, None
    shift, ofs, nk = stream
    if striped:
        d0 = (-shift * i) % ring_size
        diffs = {d0} if i == 0 else {d0, d0 - ring_size}
        his = [(0 if d <= 0 else -1) - ofs for d in diffs]
        if window is None:
            return False, (max(his), min(his), 0, 0)
        los = [-((d + window - 1) // ring_size) - ofs for d in diffs]
        return False, (max(his), min(his), min(los), max(los))
    d = i if shift == 1 else (ring_size - i) % ring_size
    hi = d * n_local - ofs
    if window is None:
        return hi >= nk - 1, (hi, hi, 0, 0)
    lo = hi - (window - 1)
    return hi >= nk - 1 and lo <= -(n_local - 1), (hi, hi, lo, lo)


def _hop_has_work(
    hi: jax.Array | None,
    lo: jax.Array | None,
    n_q: int,
    n_k: int,
    q_seg: jax.Array | None = None,
    kv_seg: jax.Array | None = None,
) -> jax.Array:
    """Band-based skip, extended by the packed-sequence document check:
    a hop whose circulating kv block shares no document id range with the
    local queries contributes nothing and skips its compute — the ring-
    schedule analogue of the kernels' cross-document tile skip."""
    if hi is None:
        ok = jnp.bool_(True)
    else:
        ok = hi >= -(n_q - 1)
        if lo is not None:
            # lo > hi means an empty band: striped hops with window <
            # ring_size hold no in-window keys at all and skip entirely
            ok = ok & (lo <= n_k - 1) & (lo <= hi)
    if q_seg is not None:
        ok = ok & segments_overlap(q_seg, kv_seg)
    return ok


def _fit_bucket(bucket_size: int | None, nk: int) -> int | None:
    """Largest divisor of ``nk`` that is <= ``bucket_size``.

    Streams can be half the local shard (``bidirectional``), so a bucket
    fitted to the full shard need not divide the span actually attended;
    refitting here (shapes are static at trace time) keeps the fallback
    condition and the tile bounds in one place for fwd and bwd."""
    if bucket_size is None or nk == 0:
        return bucket_size
    b = min(bucket_size, nk)
    while nk % b:
        b -= 1
    if b * 2 <= bucket_size:
        warnings.warn(
            f"ring flash bucket refitted from {bucket_size} to {b} to divide "
            f"the {nk}-token KV stream; tiny buckets mean many small scan "
            f"steps — pick a bucket_size dividing the (half-)shard length",
            stacklevel=2,
        )
    return b


def _pallas_blocks(bucket_size, nq, nk):
    """Pallas-path analogue of :func:`_fit_bucket`'s visibility guarantee.

    The kernels' ``_block_sizes`` silently halves a block by powers of two
    until it divides the span — correct, but on a bidirectional half-stream
    whose length isn't divisible it's a silent perf cliff while the XLA
    path warns via ``_fit_bucket``.  Mirror the demotion here (shapes are
    static) and emit the same refit warning when a block lands at <= half
    of what was asked for."""
    if bucket_size is None:
        return None, None
    bq, bk = _block_sizes(nq, nk, bucket_size, bucket_size)
    if bq * 2 <= min(bucket_size, nq) or bk * 2 <= min(bucket_size, nk):
        warnings.warn(
            f"ring pallas blocks demoted from {bucket_size} to "
            f"(block_q={bq}, block_k={bk}) to divide the ({nq}, {nk}) span; "
            f"tiny blocks underfill the MXU — pick a bucket_size dividing "
            f"the (half-)shard length",
            stacklevel=2,
        )
    return bq, bk


def _span_ops(q, hk, scale, bucket_size, softclamp_value, q_segment_ids):
    """Per-hop (init, attend, final) for the XLA compute path.

    The carry is the online-softmax state; ``attend`` folds one KV span
    (the currently-held ring block) into it.  (The Pallas path has its own
    loop, :func:`_ring_fwd_pallas`, which resumes the carry in-kernel.)
    """
    b, h, n_local, d = q.shape
    g = h // hk

    def init():
        return init_carry(b, hk, g, n_local, d, like=q)

    def attend(carry, k, v, kv_mask, hi, lo, kv_seg=None):
        return attend_blocks(
            q, k, v, carry,
            scale=scale, bucket_size=_fit_bucket(bucket_size, k.shape[2]),
            causal_offset=hi, window_lo=lo, kv_mask=kv_mask,
            softclamp_value=softclamp_value,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_seg,
        )

    def final(carry):
        out_g, lse = finalize(carry)  # lse: (b, hk, g, n)
        return _ungroup(out_g).astype(q.dtype), lse

    return init, attend, final


def _span_bwd(impl, do, q, k, v, lse, delta, kv_mask, hi, lo, scale,
              bucket_size, softclamp_value, hk, band_hint=None,
              q_seg=None, kv_seg=None):
    """Per-hop backward: returns (dq (b,h,..), dk (b,hk,..), dv (b,hk,..))."""
    if impl == "pallas":
        bq, bk = _pallas_blocks(bucket_size, q.shape[2], k.shape[2])
        return pallas_flash_backward(
            do, q, k, v, lse, delta, kv_mask,
            scale=scale, causal_offset=hi, window_lo=lo,
            softclamp_value=softclamp_value,
            block_q=bq, block_k=bk,
            band_hint=band_hint,
            segment_ids=(None if q_seg is None else (q_seg, kv_seg)),
        )
    return flash_backward_blocks(
        do, q, k, v, lse, delta,
        scale=scale, bucket_size=_fit_bucket(bucket_size, k.shape[2]),
        causal_offset=hi, window_lo=lo, kv_mask=kv_mask,
        softclamp_value=softclamp_value,
        q_segment_ids=q_seg, kv_segment_ids=kv_seg,
    )


def _ring_fwd_pallas(
    q, k, v, kv_mask, segment_ids, axis_name, causal, striped, bucket_size,
    passes, window, softclamp_value, scale, bidirectional, ring_size, rank,
    n_local, hop_compression=None, compute_dtype=None,
):
    """Pallas ring forward: unrolled hops with in-kernel accumulator resume.

    The hop loop is a Python loop (``passes`` is static) so each hop's band
    is a trace-time constant and the compact causal grid engages on every
    hop (VERDICT r2 missing #1; under ``lax.scan`` the hop index is traced
    and every hop would pay the rectangular grid).  Each span's kernel
    *continues* the previous carry in-kernel — the reference's
    ``LOAD_ACCUMULATED`` resume (ref ``triton_flash_attn.py:124-165``) —
    instead of merging ``(acc, m, l)`` triples in XLA, and the final span
    writes normalized ``q.dtype`` output + lse directly (the reference's
    last-hop ``RETURN_NORMALIZED_OUTPUT``,
    ref ``ring_flash_attention_cuda.py:134,182-186``); devices whose final
    span is band-skipped normalize their carry in XLA instead.

    The first span (hop 0) always has work on every device — own-block
    attention in every layout — so it seeds the carry without a cond; and
    the last hop's post-compute rotations are omitted (their results are
    unused, and being outside any cond this is uniform across devices).
    """
    streams, kvs, masks, segs = _stream_state(
        bidirectional, passes, ring_size, n_local, k, v, kv_mask, segment_ids,
        hop_compression,
        q8_bucket=bucket_size if compute_dtype == "int8" else False,
    )
    n_spans = passes * len(streams)
    carry = None
    out = lse = None
    span = 0
    for i in range(passes):
        new_kvs, new_masks, new_segs = [], [], []
        for si, stream in enumerate(streams):
            kvx = kvs[si]
            mx = masks[si] if masks else None
            sx = segs[si] if segs else None
            hi, lo = _stream_offsets(
                stream, rank, i, n_local, causal, striped, window, ring_size
            )
            has_work = _hop_has_work(hi, lo, n_local, stream[2],
                                     segment_ids, sx)
            full, hint = _static_hop_band(
                stream, i, n_local, causal, striped, window, ring_size
            )
            if full:
                hi, lo, hint = None, None, None

            blk_q, blk_k = _pallas_blocks(
                bucket_size, q.shape[2], stream[2]
            )
            q8_blk = (_q8_block(bucket_size, q.shape[2], stream[2])
                      if compute_dtype == "int8" else None)
            seg_pair = None if sx is None else (segment_ids, sx)

            def partials(c, kvx=kvx, mx=mx, hi=hi, lo=lo, hint=hint,
                         blk_q=blk_q, blk_k=blk_k, seg_pair=seg_pair,
                         q8_blk=q8_blk):
                kx, vx, kvq = _handle_feed(kvx, q.dtype, compute_dtype,
                                           q8_blk)
                return pallas_flash_partials(
                    q, kx, vx, mx,
                    scale=scale, causal_offset=hi, window_lo=lo,
                    softclamp_value=softclamp_value,
                    block_q=blk_q, block_k=blk_k,
                    band_hint=hint, carry=c, segment_ids=seg_pair,
                    compute_dtype=compute_dtype, kv_quantized=kvq,
                )

            with jax.named_scope(f"ring/hop{i}"):
                if span == n_spans - 1:

                    def fuse(c, kvx=kvx, mx=mx, hi=hi, lo=lo, hint=hint,
                             blk_q=blk_q, blk_k=blk_k, seg_pair=seg_pair,
                             q8_blk=q8_blk):
                        kx, vx, kvq = _handle_feed(kvx, q.dtype,
                                                   compute_dtype, q8_blk)
                        return pallas_flash_fused(
                            q, kx, vx, mx,
                            scale=scale, causal_offset=hi, window_lo=lo,
                            softclamp_value=softclamp_value,
                            block_q=blk_q, block_k=blk_k,
                            # hint only rides along with a carry (see
                            # pallas_flash_fused); by the last hop every
                            # row's carry holds its own-diagonal content
                            band_hint=hint if c is not None else None,
                            carry=c, segment_ids=seg_pair,
                            compute_dtype=compute_dtype, kv_quantized=kvq,
                        )

                    if carry is None:  # ring of one: plain fused local sweep
                        out, lse = fuse(None)
                    else:

                        def fin(c):
                            o, s = finalize_partials(c)
                            return o.astype(q.dtype), s

                        out, lse = lax.cond(has_work, fuse, fin, carry)
                elif carry is None:
                    carry = partials(None)
                else:
                    carry = lax.cond(has_work, partials, lambda c: c, carry)
            span += 1
            if i < passes - 1:
                with jax.named_scope(f"ring/rotate{i}"):
                    new_kvs.append(_rotate(kvx, axis_name, stream[0]))
                    if mx is not None:
                        new_masks.append(_rotate(mx, axis_name, stream[0]))
                    if sx is not None:
                        new_segs.append(_rotate(sx, axis_name, stream[0]))
        if i < passes - 1:
            kvs, masks, segs = (
                tuple(new_kvs), tuple(new_masks), tuple(new_segs)
            )
    return out, lse


def _fused_tables(rank, passes, n_local, causal, striped, window, ring_size):
    """Per-hop ``(origins, his, los, works)`` int32 tables for the fused
    kernel — hop ``i`` visits origin ``(rank - i) % ring_size`` (the
    scan path's unidirectional whole-block stream order, and the order the
    remote tier's KV circulation produces by sending to ``rank + 1``).

    Band offsets come from the SAME certified constructor the scan path
    uses (:func:`_hop_offsets`), work flags from the same skip predicate
    (:func:`_hop_has_work`); ``None`` (unbanded) lowers to the sentinels
    ``hi = n_local`` / ``lo = -n_local``, vacuous over the in-kernel
    ``j - i`` range ``(-n_local, n_local)``.  The coverage prover holds
    these tables to the global-position oracle
    (``analysis/coverage.py::prove_fused``)."""
    origins, his, los, works = [], [], [], []
    for i in range(passes):
        origin = (rank - i) % ring_size
        hi, lo = _hop_offsets(
            rank, origin, n_local, causal, striped, window, ring_size
        )
        work = _hop_has_work(hi, lo, n_local, n_local)
        origins.append(origin)
        his.append(n_local if hi is None else hi)
        los.append(-n_local if lo is None else lo)
        works.append(work)

    def stack(xs):
        return jnp.stack([jnp.asarray(x).astype(jnp.int32) for x in xs])

    return stack(origins), stack(his), stack(los), stack(works)


def _gather_seq(x, axis_name, axis):
    """All-gather a shard along its token axis, ring-order (rank-major)."""
    if compat.axis_size(axis_name) == 1:
        return x
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)  # ra: allow(RA004 the one caller wraps the gather in its ring/fused_gather scope)


def _ring_fwd_fused(
    q, k, v, kv_mask, segment_ids, axis_name, causal, striped, bucket_size,
    passes, window, softclamp_value, scale, ring_size, rank, n_local,
    hop_compression=None, compute_dtype=None,
):
    """Fused-ring forward: the WHOLE hop schedule in one kernel launch
    (``ops/pallas_ring.py``), no per-hop dispatch, no ppermute.

    Two tiers.  On TPU with remote-DMA support, an unmasked, unpacked
    config, and a healthy remote-tier probe
    (``utils/resilience.fused_remote_available`` — a compile failure
    there records a degradation instead of crashing the model path), the
    remote tier circulates KV over ICI from inside the kernel
    (``fused_ring_remote`` — async double-buffered
    ``make_async_remote_copy`` per hop, overlap window = the whole hop's
    compute).  Everything else — interpret/CPU parity runs, masked or
    packed sequences, meshes whose axes cannot be introspected for MESH
    device ids — takes the local tier: one all-gather of the KV
    span, then the same single launch walking the same hop tables
    (``fused_ring_local``).  Both visit hops in scan-path order with
    scan-path band offsets, so parity against ``_ring_fwd_pallas`` is
    tile-order-exact.

    int8 composition (PR 13): ``hop_compression="int8"`` +
    ``compute_dtype="int8"`` feeds the kernel a ``pack_kv`` payload whose
    dequant scales ride the circulated buffer (remote tier) or the
    gathered feed (``payload_kernel_feed``, local tier); compression-only
    configs round-trip KV through the wire codec first so wire precision
    matches the scan path exactly.

    The backward is the retained scan-path pallas ring (``_ring_vjp_bwd``
    maps ``impl="fused"`` to ``"pallas"``): grads recompute from exact
    residuals per hop and this forward's ``(out, lse)`` already uses the
    flat pallas layout.
    """
    origins, his, los, works = _fused_tables(
        rank, passes, n_local, causal, striped, window, ring_size
    )
    blk_q, blk_k = _pallas_blocks(bucket_size, n_local, n_local)
    interpret = _pallas_ring._interpret_default()
    q8 = compute_dtype == "int8"
    wire8 = hop_compression is not None

    from ..utils import resilience as _resilience  # lazy: avoid import cycle

    remote_ok = (
        not interpret
        and _pallas_ring.remote_supported()
        and kv_mask is None
        and segment_ids is None
        and q8 == wire8  # plain hops, or the fully-int8 wire+compute pair
        and _resilience.fused_remote_available()  # probe-once, degrades
    )
    if remote_ok:
        # Per-axis MESH coordinates of the ring neighbors — None on
        # meshes we cannot introspect, which degrades to the local tier.
        nbr_coords = _pallas_ring.neighbor_mesh_coords(axis_name, ring_size)
    if remote_ok and nbr_coords is not None:
        payload = _quant.pack_kv(k, v, v_block=n_local) if q8 else None
        with jax.named_scope("ring/fused"):
            return _pallas_ring.fused_ring_remote(
                q, k, v, his=his, los=los, works=works,
                nbr_coords=nbr_coords,
                scale=scale, softclamp_value=softclamp_value,
                block_q=blk_q, block_k=blk_k, payload=payload,
            )

    if wire8 and not q8:
        # wire-precision parity with the scan path: the compressed ring
        # quantizes KV once at entry and dequantizes per hop — reproduce
        # that codec round trip before gathering
        k, v = dequantize_ring_payload(quantize_ring_payload(k, v), q.dtype)

    with jax.named_scope("ring/fused_gather"):
        k_all = _gather_seq(k, axis_name, 2)
        v_all = _gather_seq(v, axis_name, 2)
        mask_all = (None if kv_mask is None
                    else _gather_seq(kv_mask, axis_name, 1))
        seg_all = (None if segment_ids is None
                   else _gather_seq(segment_ids, axis_name, 1))

        kv_feed = None
        if q8:
            _, fit_k = _pallas_ring.fitted_blocks(n_local, blk_q, blk_k)
            if wire8:
                # the dequant-free composition: ONE pack at ring entry,
                # scales ride the gathered payload straight into the kernel
                payload = _quant.pack_kv(k, v, v_block=fit_k)
                payload_all = _gather_seq(payload, axis_name, 3)
                kv_feed = _quant.payload_kernel_feed(payload_all, fit_k)
            if kv_feed is None:
                kv_feed = _quant.quantize_kv_blocks(k_all, v_all, fit_k)

    with jax.named_scope("ring/fused"):
        return _pallas_ring.fused_ring_local(
            q, k_all, v_all, mask_all,
            origins=origins, his=his, los=los, works=works,
            n_local=n_local, scale=scale, softclamp_value=softclamp_value,
            block_q=blk_q, block_k=blk_k,
            q_segment_ids=segment_ids, kv_segment_ids=seg_all,
            kv_quantized=kv_feed, interpret=interpret,
        )


def _counter_static_band(i, n_local, causal, striped, window, ring_size):
    """Trace-time ``(full, band_hint)`` for counter-rotation hop ``i``.

    The pairing invariant ``q_origin - kv_origin ≡ i (mod ring)`` is
    exactly the baseline forward stream's offset distribution (hop ``i``
    of a ``shift=+1`` whole-block stream pairs each query block with the
    KV block ``i`` ranks behind), so the static band description is shared
    verbatim with :func:`_static_hop_band`."""
    return _static_hop_band(
        (1, 0, n_local), i, n_local, causal, striped, window, ring_size
    )


def _counter_fwd(
    q, k, v, kv_mask, segment_ids, axis_name, causal, striped, bucket_size,
    passes, window, softclamp_value, scale, impl, ring_size, rank, n_local,
    hop_compression, compute_dtype=None,
):
    """TokenRing counter-rotation forward (arXiv 2412.20501).

    Instead of pushing the whole KV payload through one ICI direction
    every hop, the Q shard — packed with its online-softmax accumulators
    ``(acc, m, l)`` into ONE f32 array (:func:`_pack_counter`) — rotates
    with shift ``-1`` after even hops while the KV handle rotates with
    shift ``+1`` after odd hops.  Either rotation advances the pairing
    ``q_origin - kv_origin`` by one (:func:`_counter_origins`), so hop
    ``i`` still attends the pairing the baseline ring visits at hop ``i``
    (windows and limited passes keep their semantics), but consecutive
    hops load opposite directions of the full-duplex links and each link
    direction carries roughly half the rotation traffic.

    ``impl="xla"`` runs the hops as a SINGLE ``lax.scan`` whose body
    covers one Q-rotation and one KV-rotation (two hops) — the schedule is
    uniform across devices and across iterations, so no collective ever
    sits under a ``lax.cond`` (``analysis/contracts.py``); an odd
    ``passes`` runs its trailing hop after the scan.  ``impl="pallas"``
    unrolls the hops so the static band hints engage the compact causal
    grid, resuming the ``(acc, m, l)`` carry in-kernel per hop.

    After the last hop the finalized ``(out, lse)`` pack sits
    ``passes // 2`` ranks from home (the Q-stream's net displacement);
    one composed catch-up ppermute returns it — forward collectives total
    ``passes`` vs the baseline's ``passes - 1``, repaid with interest by
    the backward (:func:`_counter_bwd`), which needs only ``passes``
    against the baseline's ``2 * passes - 1``.

    Returns ``(out (b, h, n, d) q.dtype, lse (b, h, n) f32)`` — the lse is
    FLAT (head-major) in both impls, unlike the baseline XLA path's
    grouped layout; :func:`_ring_vjp_bwd` dispatches on ``counter_rotate``
    before touching it.
    """
    b, h, n, d = q.shape
    hk = k.shape[1]
    g = h // hk
    # compute_dtype="int8" on the pallas path: pack the circulating KV
    # with v scales at the kernel's fitted block so every hop feeds the
    # int8 kernel DIRECTLY (quantize once at ring entry, dequantize only
    # in the accumulator rescale — no per-hop dequant→requant round trip)
    q8_blk = (_q8_block(bucket_size, n, n)
              if compute_dtype == "int8" and impl == "pallas" else None)
    kvh = _kv_handle(
        k, v, hop_compression,
        q8_block=q8_blk if hop_compression is not None else None,
    )
    mask, q_seg, kv_seg = kv_mask, segment_ids, segment_ids

    def span(i, qx, acc, m, l, kvh, mask, q_seg, kv_seg):
        """Fold pairing ``i`` into the flat ``(acc, m, l)`` accumulators."""
        qo, ko = _counter_origins(rank, i, ring_size)
        hi, lo = _hop_offsets(qo, ko, n_local, causal, striped, window,
                              ring_size)
        # has_work from the traced offsets BEFORE the full-span elision
        # nulls them: the devices a "full" band excludes entirely are
        # exactly the ones the cond must skip
        has_work = _hop_has_work(hi, lo, n_local, n_local, q_seg, kv_seg)
        hint = None
        if isinstance(i, int):
            full, hint = _counter_static_band(
                i, n_local, causal, striped, window, ring_size
            )
            if full:
                hi, lo, hint = None, None, None
        seg_pair = None if q_seg is None else (q_seg, kv_seg)

        def compute(args):
            acc, m, l = args
            if impl == "pallas":
                kx, vx, kvq = _handle_feed(kvh, q.dtype, compute_dtype,
                                           q8_blk)
                blk_q, blk_k = _pallas_blocks(bucket_size, n, n)
                p = pallas_flash_partials(
                    qx, kx, vx, mask,
                    scale=scale, causal_offset=hi, window_lo=lo,
                    softclamp_value=softclamp_value,
                    block_q=blk_q, block_k=blk_k, band_hint=hint,
                    carry=None if acc is None else FlashPartials(acc, m, l),
                    segment_ids=seg_pair,
                    compute_dtype=compute_dtype, kv_quantized=kvq,
                )
                return p.acc, p.m, p.l
            kx, vx = _handle_kv(kvh, q.dtype)
            carry = FlashCarry(
                acc.reshape(b, hk, g, n, d),
                m.reshape(b, hk, g, n),
                l.reshape(b, hk, g, n),
            )
            carry = attend_blocks(
                qx, kx, vx, carry,
                scale=scale, bucket_size=_fit_bucket(bucket_size, n),
                causal_offset=hi, window_lo=lo, kv_mask=mask,
                softclamp_value=softclamp_value,
                q_segment_ids=q_seg, kv_segment_ids=kv_seg,
            )
            return (
                carry.acc.reshape(b, h, n, d),
                carry.m.reshape(b, h, n),
                carry.l.reshape(b, h, n),
            )

        if acc is None:
            # hop 0 pairs every device's own (q, kv) block — always work,
            # seeds the pallas carry without a cond (like _ring_fwd_pallas)
            return compute((None, None, None))
        return lax.cond(has_work, compute, lambda a: a, (acc, m, l))

    if impl == "pallas":
        qx, acc, m, l = q, None, None, None
        for i in range(passes):
            with jax.named_scope(f"ring/hop{i}"):
                acc, m, l = span(i, qx, acc, m, l, kvh, mask, q_seg, kv_seg)
            if i < passes - 1:
                with jax.named_scope(f"ring/rotate{i}"):
                    if i % 2 == 0:  # Q-stream hops one way...
                        pack = _rotate(
                            _pack_counter(qx, acc, m, l), axis_name, -1
                        )
                        qx, acc, m, l = _unpack_counter(pack, d, q.dtype)
                        if q_seg is not None:
                            q_seg = _rotate(q_seg, axis_name, -1)
                    else:  # ...the KV stream hops the other
                        kvh = _rotate(kvh, axis_name, 1)
                        if mask is not None:
                            mask = _rotate(mask, axis_name, 1)
                        if kv_seg is not None:
                            kv_seg = _rotate(kv_seg, axis_name, 1)
    else:
        carry0 = init_carry(b, hk, g, n, d, like=q)
        pack = _pack_counter(
            q,
            carry0.acc.reshape(b, h, n, d),
            carry0.m.reshape(b, h, n),
            carry0.l.reshape(b, h, n),
        )

        def span_t(i, pack, kvh, mask, q_seg, kv_seg):
            qx, acc, m, l = _unpack_counter(pack, d, q.dtype)
            acc, m, l = span(i, qx, acc, m, l, kvh, mask, q_seg, kv_seg)
            return _pack_counter(qx, acc, m, l)

        def body(state, t):
            pack, kvh, mask, q_seg, kv_seg = state
            with jax.named_scope("ring/hop"):
                pack = span_t(2 * t, pack, kvh, mask, q_seg, kv_seg)
            # rotations AFTER compute, outside any cond: the collective
            # schedule is identical on every device and every iteration
            with jax.named_scope("ring/rotate"):
                pack = _rotate(pack, axis_name, -1)
                if q_seg is not None:
                    q_seg = _rotate(q_seg, axis_name, -1)
            with jax.named_scope("ring/hop"):
                pack = span_t(2 * t + 1, pack, kvh, mask, q_seg, kv_seg)
            with jax.named_scope("ring/rotate"):
                kvh = _rotate(kvh, axis_name, 1)
                if mask is not None:
                    mask = _rotate(mask, axis_name, 1)
                if kv_seg is not None:
                    kv_seg = _rotate(kv_seg, axis_name, 1)
            return (pack, kvh, mask, q_seg, kv_seg), None

        state = (pack, kvh, mask, q_seg, kv_seg)
        state, _ = lax.scan(body, state, jnp.arange(passes // 2))
        pack, kvh, mask, q_seg, kv_seg = state
        if passes % 2:
            with jax.named_scope("ring/hop"):
                pack = span_t(passes - 1, pack, kvh, mask, q_seg, kv_seg)
        _, acc, m, l = _unpack_counter(pack, d, q.dtype)

    out32 = acc / jnp.maximum(l, EPSILON)[..., None]
    lse = m + jnp.log(jnp.maximum(l, EPSILON))
    # the finalized rows belong to q_origin = rank + passes//2 (the
    # Q-stream's net displacement): one composed ppermute returns the
    # packed (out, lse) home
    shift = (passes // 2) % ring_size
    if shift:
        ret = jnp.concatenate([out32, lse[..., None]], axis=-1)
        with jax.named_scope("ring/catchup"):
            ret = _rotate(ret, axis_name, shift)
        out32, lse = ret[..., :d], ret[..., d]
    return out32.astype(q.dtype), lse


def _counter_bwd(
    do, q, k, v, kv_mask, segment_ids, out, lse, axis_name, causal, striped,
    bucket_size, passes, window, softclamp_value, scale, impl, ring_size,
    rank, n_local,
):
    """Counter-rotation backward: the Q-side circulates, KV and dKV rest.

    The forward's pairing order only has to be *covered*, not replayed, so
    the backward uses the cheapest schedule that covers it: ONE f32 pack
    ``[q | do | dq | lse | delta]`` (``(b, h, n, 3d + 2)``) rotates with
    shift ``-1`` every hop — a single ppermute, a uniform ``lax.scan``
    body on the XLA path — while ``(k, v)`` and the f32 ``(dk, dv)``
    accumulators stay RESIDENT on their owner shard.  Each visiting query
    block adds its contribution to the local dk/dv directly, so the
    baseline's second circulating payload (f32 dkv, ~2x the kv bytes) and
    its catch-up rotation disappear entirely: ``passes`` collectives vs
    the baseline backward's ``2 * passes - 1``.  After a full circulation
    the pack is home (its dq included); limited passes catch the dq
    channel up with one composed ppermute.
    """
    b, h, n, d = q.shape
    hk = k.shape[1]
    g = h // hk
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    pack = jnp.concatenate(
        [
            q.astype(jnp.float32),
            do.astype(jnp.float32),
            match_vma(jnp.zeros((b, h, n, d), jnp.float32), q),
            lse[..., None],
            delta[..., None],
        ],
        axis=-1,
    )
    dk = match_vma(jnp.zeros((b, hk, n, d), jnp.float32), q)
    dv = match_vma(jnp.zeros((b, hk, n, d), jnp.float32), q)

    def span(i, pack, dk, dv, q_seg):
        qx = pack[..., :d].astype(q.dtype)
        dox = pack[..., d:2 * d].astype(q.dtype)
        lse_x = pack[..., 3 * d]
        delta_x = pack[..., 3 * d + 1]
        qo = (rank + i) % ring_size  # pure Q-rotation: pairing i = hop i
        hi, lo = _hop_offsets(qo, rank, n_local, causal, striped, window,
                              ring_size)
        # has_work BEFORE the full-span elision nulls the offsets (the
        # excluded devices are the ones the cond must skip)
        has_work = _hop_has_work(hi, lo, n_local, n_local, q_seg,
                                 segment_ids)
        hint = None
        if isinstance(i, int):
            full, hint = _counter_static_band(
                i, n_local, causal, striped, window, ring_size
            )
            if full:
                hi, lo, hint = None, None, None
        if impl == "pallas":
            lse_s, delta_s = lse_x, delta_x  # flat (b, h, n)
        else:
            lse_s = lse_x.reshape(b, hk, g, n)
            delta_s = delta_x.reshape(b, hk, g, n)

        def work(args):
            dqc, dk, dv = args
            dq_i, dk_i, dv_i = _span_bwd(
                impl, dox, qx, k, v, lse_s, delta_s, kv_mask, hi, lo,
                scale, bucket_size, softclamp_value, hk, hint,
                q_seg, segment_ids,
            )
            return (
                dqc + dq_i.astype(jnp.float32),
                dk + dk_i.astype(jnp.float32),
                dv + dv_i.astype(jnp.float32),
            )

        dqc, dk, dv = lax.cond(
            has_work, work, lambda a: a, (pack[..., 2 * d:3 * d], dk, dv)
        )
        pack = jnp.concatenate(
            [pack[..., :2 * d], dqc, pack[..., 3 * d:]], axis=-1
        )
        return pack, dk, dv

    if impl == "pallas":
        q_seg = segment_ids
        for i in range(passes):
            with jax.named_scope(f"ring/bwd_hop{i}"):
                pack, dk, dv = span(i, pack, dk, dv, q_seg)
            if i < passes - 1:
                with jax.named_scope("ring/rotate"):
                    pack = _rotate(pack, axis_name, -1)
                    if q_seg is not None:
                        q_seg = _rotate(q_seg, axis_name, -1)
        disp = (passes - 1) % ring_size
    else:

        def body(state, i):
            pack, dk, dv, q_seg = state
            with jax.named_scope("ring/bwd_hop"):
                pack, dk, dv = span(i, pack, dk, dv, q_seg)
            with jax.named_scope("ring/rotate"):
                pack = _rotate(pack, axis_name, -1)
                if q_seg is not None:
                    q_seg = _rotate(q_seg, axis_name, -1)
            return (pack, dk, dv, q_seg), None

        (pack, dk, dv, _), _ = lax.scan(
            body, (pack, dk, dv, segment_ids), jnp.arange(passes)
        )
        disp = passes % ring_size

    # only the dq channel still needs delivering: catch it up alone
    dq = pack[..., 2 * d:3 * d]
    if disp:
        with jax.named_scope("ring/catchup"):
            dq = _rotate(dq, axis_name, disp)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None,
    axis_name: str,
    causal: bool = False,
    striped: bool = False,
    bucket_size: int | None = None,
    max_ring_passes: int | None = None,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
    bidirectional: bool = False,
    dkv_dtype: str | None = None,
    segment_ids: jax.Array | None = None,
    counter_rotate: bool = False,
    hop_compression: str | None = None,
    compute_dtype: str | None = None,
) -> jax.Array:
    """Sequence-parallel exact attention; call inside ``shard_map``.

    Args:
      q: ``(b, h, n_local, d)`` local query shard.
      k, v: ``(b, hk, n_local, d)`` local key/value shards (GQA when hk < h —
        the ring then only moves hk-sized blocks, the reference's
        bandwidth-saving trick, ref ``ring_attention.py:317-321``).
      kv_mask: optional ``(b, n_local)`` key-padding mask shard; rotates
        around the ring with k/v.
      segment_ids: optional ``(b, n_local)`` int document-id shard for
        packed sequences: the queries keep the local ids while a kv copy
        ppermutes around the ring with ``(k, v)`` (and with ``(dk, dv)``
        in backward), so every hop masks cross-document pairs and hops
        whose circulating block shares no document id range with the
        local queries skip their compute entirely.
      axis_name: mesh axis the sequence is sharded over.  May be a
        *sub-axis* of a larger factored mesh (hybrid Ulysses x Ring,
        ``parallel/hybrid.py``): every size used by the band offsets, the
        hop permutations, and the backward catch-up rotation derives from
        ``axis_size(axis_name)`` — never from the global device count — so
        the ring stays correct when other mesh axes shard heads or batch
        around it.  Striped layouts must be interleaved at exactly this
        axis's size.
      causal/striped: causal masking, with striped (balanced) layout if the
        sequence was stripe-permuted before sharding.
      bucket_size: flash tile size within a hop.
      max_ring_passes: limit hops for per-layer lookback windows
        (ref ``ring_flash_attention.py:95-103``).
      window: exact sliding-window lookback in tokens (exact in both
        contiguous and striped layouts).
      impl: compute path — ``"xla"`` / ``"pallas"`` run one flash call
        per hop with a ``ppermute`` rotation between launches;
        ``"fused"`` carries the WHOLE hop schedule inside one Pallas
        launch (``ops/pallas_ring.py``: in-kernel async remote KV DMA on
        TPU, gathered-span walk in interpret/CPU or masked/packed
        configs), with the scan-path pallas backward retained.  Use
        ``utils.resilience.resolve_ring_impl("auto")`` for recorded
        degradation to the scan path where the fused tier is unavailable.
      bidirectional: circulate the two halves of each KV block in opposite
        ring directions (one ``ppermute`` each per hop).  Same totals, but
        the transfer rides both directions of the full-duplex ICI links, so
        the exposed per-hop communication time halves.  Requires an even
        local sequence length.  Incompatible by construction with
        ``max_ring_passes < ring_size``: the reverse stream delivers
        *future* origins first, so a limited-pass window's trailing key
        halves would only arrive near the end of a full circulation —
        limited-pass calls silently run unidirectional instead (skipping
        hops saves more than duplex transfer does).
      dkv_dtype: dtype name for the circulating dk/dv accumulators in the
        backward ring.  Default None = float32 (exact accumulation across
        hops).  "bfloat16" halves the backward's ICI ring bandwidth the
        way the reference circulates half-precision dkv
        (ref ``ring_flash_attention_cuda.py:255-260``) at the cost of
        bf16 round-off per hop-accumulate — measured grad error vs f32
        stays within ~2e-2 on unit-variance inputs
        (``tests/test_ring.py::test_ring_dkv_bf16_circulation``).
      counter_rotate: TokenRing full-duplex scheme (arXiv 2412.20501): the
        Q shard packed with its online-softmax accumulators rotates one
        ring direction while the KV stream rotates the other, alternating
        hops, so each ICI direction carries about half the rotation
        traffic (:func:`_counter_fwd`); the backward circulates only the
        q-side pack with KV and the f32 dk/dv accumulators resident
        (:func:`_counter_bwd` — fewer collectives AND fewer bytes than the
        baseline backward).  Supersedes ``bidirectional`` — a KV half
        co-moving with the Q stream never advances its pairing, so the two
        schedules cannot compose (``docs/ring_overlap.md`` derives this);
        requesting both warns and runs pure counter-rotation.
      hop_compression: ``"int8"`` ships each forward KV hop as per-token
        symmetric-absmax int8 values + bitcast f32 scales in ONE payload —
        hop counts unchanged, hop bytes ~``dtype_bytes * d / (d + 4)``-x
        smaller (``collectives.quantize_ring_payload``).  Quantized once
        at ring entry (hops are lossless moves); the backward recomputes
        from the exact residual ``(k, v)``, and every ``(acc, m, l)`` /
        dk/dv accumulator stays f32 (``audit_accumulator_dtypes``).
      compute_dtype: ``"int8"`` runs the forward's QK^T and PV matmuls on
        int8 operands (pallas path only — q per-row, k per-row, v
        per-KV-block absmax scales; f32 ``(acc, m, l)`` untouched;
        ``docs/precision.md``).  Composes with ``hop_compression="int8"``
        into the dequant-free ring: the hop payload is packed with
        kernel-ready scales at ring entry and feeds every hop's kernel
        DIRECTLY — one quantization per payload for the whole
        circulation, no per-hop dequant→requant.  The backward stays bf16
        from the exact residuals this round.

    Cross-attention (unequal q/kv shard lengths) silently bypasses the ring
    and runs local flash over the local KV shard — the reference degrades
    the same way (ref ``ring_flash_attention.py:81-83``).

    Returns:
      ``(b, h, n_local, d)`` output shard, in ``q.dtype``.
    """
    check_attention_args("ring_flash_attention", q, k, v, kv_mask)
    segment_ids, _ = normalize_segment_ids(
        None if segment_ids is None else (segment_ids, segment_ids),
        q, q, "ring_flash_attention",
    )
    if hop_compression not in (None, "int8"):
        raise ValueError(
            f"hop_compression={hop_compression!r}: supported values are "
            'None (model-dtype hops) and "int8" (per-token absmax '
            "quantized hops)"
        )
    if compute_dtype not in (None, "int8"):
        raise ValueError(
            f"compute_dtype={compute_dtype!r}: supported values are None "
            '(model-dtype matmuls) and "int8" (quantized QK^T/PV)'
        )
    if compute_dtype == "int8" and impl not in ("pallas", "fused"):
        raise ValueError(
            'compute_dtype="int8" runs on the Pallas kernels only — pass '
            'impl="pallas" or impl="fused" (the XLA flash path has no '
            "int8 matmul form)"
        )
    if impl == "fused":
        if counter_rotate:
            raise ValueError(
                'impl="fused" carries the whole hop schedule in one kernel '
                "launch; the counter-rotation alternating Q/KV schedule "
                'has no fused form — pass impl="pallas" with counter_rotate'
            )
        if bidirectional:
            warnings.warn(
                'impl="fused" circulates whole KV blocks inside the kernel '
                "(the DMA is async either way); ignoring bidirectional "
                "half-streams",
                stacklevel=2,
            )
            bidirectional = False
    if counter_rotate and bidirectional:
        # a KV half-stream co-moving with the Q stream never advances its
        # pairing (docs/ring_overlap.md) — the schedules cannot compose,
        # and counter-rotation already loads both link directions
        warnings.warn(
            "counter_rotate already saturates both ICI directions; "
            "ignoring bidirectional half-streams",
            stacklevel=2,
        )
        bidirectional = False
    if q.shape[2] != k.shape[2]:
        # Cross-attention: each device attends its local KV shard only,
        # exactly like the reference's non-ring fallback.  The causal band
        # (if any) is end-aligned by flash_attention.
        if segment_ids is not None:
            # not an assert: under python -O this fallback would silently
            # compute cross-document attention (it never threads the ids)
            raise ValueError(
                "ring_flash_attention: segment_ids need equal q/kv shard "
                "lengths (packed self-attention); the cross-attention "
                "fallback does not define a kv-side packing"
            )
        from ..ops.flash import flash_attention
        from ..ops.pallas_flash import pallas_flash_attention

        if impl in ("pallas", "fused"):
            return pallas_flash_attention(
                q, k, v, kv_mask, causal=causal, window=window,
                softclamp_value=softclamp_value, scale=scale,
                compute_dtype=compute_dtype,
            )
        return flash_attention(
            q, k, v, kv_mask, causal=causal, bucket_size=bucket_size,
            window=window, softclamp_value=softclamp_value, scale=scale,
        )
    return _ring_flash_attention_core(
        q, k, v, kv_mask, segment_ids, axis_name, causal, striped,
        bucket_size, max_ring_passes, window, softclamp_value, scale, impl,
        bidirectional, dkv_dtype, counter_rotate, hop_compression,
        compute_dtype,
    )


@partial(
    jax.custom_vjp,
    nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18),
)
def _ring_flash_attention_core(
    q, k, v, kv_mask, segment_ids, axis_name, causal=False, striped=False,
    bucket_size=None, max_ring_passes=None, window=None,
    softclamp_value=None, scale=None, impl="xla", bidirectional=False,
    dkv_dtype=None, counter_rotate=False, hop_compression=None,
    compute_dtype=None,
):
    out, _ = _ring_fwd_impl(
        q, k, v, kv_mask, segment_ids, axis_name, causal, striped,
        bucket_size, max_ring_passes, window, softclamp_value, scale, impl,
        bidirectional, counter_rotate, hop_compression, compute_dtype,
    )
    return out


def _ring_fwd_impl(
    q, k, v, kv_mask, segment_ids, axis_name, causal, striped, bucket_size,
    max_ring_passes, window, softclamp_value, scale, impl, bidirectional,
    counter_rotate=False, hop_compression=None, compute_dtype=None,
):
    if window is not None:
        assert causal, "lookback windows require causal attention"
    b, h, n_local, d = q.shape
    hk = k.shape[1]
    if scale is None:
        scale = d**-0.5
    ring_size = compat.axis_size(axis_name)
    passes = min(max_ring_passes or ring_size, ring_size)
    rank = lax.axis_index(axis_name)

    if counter_rotate:
        out, lse = _counter_fwd(
            q, k, v, kv_mask, segment_ids, axis_name, causal, striped,
            bucket_size, passes, window, softclamp_value, scale, impl,
            ring_size, rank, n_local, hop_compression, compute_dtype,
        )
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, lse

    if impl == "fused":
        out, lse = _ring_fwd_fused(
            q, k, v, kv_mask, segment_ids, axis_name, causal, striped,
            bucket_size, passes, window, softclamp_value, scale,
            ring_size, rank, n_local, hop_compression, compute_dtype,
        )
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, lse

    if impl == "pallas":
        out, lse = _ring_fwd_pallas(
            q, k, v, kv_mask, segment_ids, axis_name, causal, striped,
            bucket_size, passes, window, softclamp_value, scale,
            bidirectional, ring_size, rank, n_local, hop_compression,
            compute_dtype,
        )
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, lse

    init, attend, final = _span_ops(
        q, hk, scale, bucket_size, softclamp_value, segment_ids
    )
    carry = init()
    # one stacked (k, v) message per stream per hop, ref ring_flash_attention.py:129
    streams, kvs, masks, segs = _stream_state(
        bidirectional, passes, ring_size, n_local, k, v, kv_mask, segment_ids,
        hop_compression,
    )

    def hop(i, flash, kvs, masks, segs):
        new_kvs, new_masks, new_segs = [], [], []
        for si, stream in enumerate(streams):
            kvx = kvs[si]
            mx = masks[si] if masks else None
            sx = segs[si] if segs else None
            hi, lo = _stream_offsets(
                stream, rank, i, n_local, causal, striped, window, ring_size
            )
            has_work = _hop_has_work(hi, lo, n_local, stream[2],
                                     segment_ids, sx)
            with jax.named_scope("ring/hop"):  # hop index is traced here
                def att(f, kvx=kvx, mx=mx, hi=hi, lo=lo, sx=sx):
                    kx, vx = _handle_kv(kvx, q.dtype)
                    return attend(f, kx, vx, mx, hi, lo, sx)

                flash = lax.cond(has_work, att, lambda f: f, flash)
            # rotate AFTER compute; collective outside the cond so the
            # schedule is uniform across devices
            with jax.named_scope("ring/rotate"):
                new_kvs.append(_rotate(kvx, axis_name, stream[0]))
                if mx is not None:
                    new_masks.append(_rotate(mx, axis_name, stream[0]))
                if sx is not None:
                    new_segs.append(_rotate(sx, axis_name, stream[0]))
        return flash, tuple(new_kvs), tuple(new_masks), tuple(new_segs)

    def body(c, i):
        flash, kvs, masks, segs = c
        return hop(i, flash, kvs, masks, segs), None

    (carry, _, _, _), _ = lax.scan(
        body, (carry, kvs, masks, segs), jnp.arange(passes)
    )

    out, lse = final(carry)
    # Named so a selective remat policy can SAVE the attention output and
    # lse (the custom_vjp residuals) — the backward's residual recompute
    # then dead-code-eliminates this whole ring scan instead of running a
    # second forward (RingTransformer(remat_policy="save_attn")).  The
    # local (non-ring) flash paths use the same names (ops/flash.py,
    # ops/pallas_flash.py).
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, lse


def _ring_vjp_fwd(
    q, k, v, kv_mask, segment_ids, axis_name, causal, striped, bucket_size,
    max_ring_passes, window, softclamp_value, scale, impl, bidirectional,
    dkv_dtype, counter_rotate, hop_compression, compute_dtype=None,
):
    out, lse = _ring_fwd_impl(
        q, k, v, kv_mask, segment_ids, axis_name, causal, striped,
        bucket_size, max_ring_passes, window, softclamp_value, scale, impl,
        bidirectional, counter_rotate, hop_compression, compute_dtype,
    )
    return out, (q, k, v, kv_mask, segment_ids, out, lse)


def _ring_vjp_bwd(
    axis_name, causal, striped, bucket_size, max_ring_passes, window,
    softclamp_value, scale, impl, bidirectional, dkv_dtype, counter_rotate,
    hop_compression, compute_dtype, res, do,
):
    # the backward ignores compute_dtype this round: grads recompute
    # scores in bf16 from the EXACT residual (q, k, v) — only the
    # forward's (out, lse) carry int8 error (docs/precision.md §5)
    if impl == "fused":
        # the fused forward retains this scan-path backward: its lse is
        # already the flat (b, h, n) pallas layout, grads recompute from
        # the exact residuals hop by hop, and the fused forward always
        # runs unidirectional (validation strips bidirectional)
        impl = "pallas"
    q, k, v, kv_mask, segment_ids, out, lse = res
    b, h, n_local, d = q.shape
    hk = k.shape[1]
    if scale is None:
        scale = d**-0.5
    ring_size = compat.axis_size(axis_name)
    passes = min(max_ring_passes or ring_size, ring_size)
    rank = lax.axis_index(axis_name)

    if counter_rotate:
        # the counter forward's lse is flat (b, h, n) for both impls; the
        # backward circulates the q-side pack with KV/dKV resident — the
        # forward's hop_compression never enters (grads recompute from the
        # exact residual k/v)
        dq, dk, dv = _counter_bwd(
            do, q, k, v, kv_mask, segment_ids, out, lse, axis_name, causal,
            striped, bucket_size, passes, window, softclamp_value, scale,
            impl, ring_size, rank, n_local,
        )
        return dq, dk, dv, None, None

    if impl == "pallas":
        # lse/delta in (b, h, n) layout
        delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    else:
        delta = (
            _group_q(do, hk).astype(jnp.float32)
            * _group_q(out, hk).astype(jnp.float32)
        ).sum(-1)

    streams, kvs, masks, segs = _stream_state(
        bidirectional, passes, ring_size, n_local, k, v, kv_mask, segment_ids
    )
    # circulating dk/dv accumulators: f32 by default; bf16 halves backward
    # ring bandwidth (ref ring_flash_attention_cuda.py:255-260)
    acc_dtype = jnp.dtype(dkv_dtype) if dkv_dtype is not None else jnp.float32
    dkvs = tuple(
        match_vma(jnp.zeros((2, b, hk, nk, d), acc_dtype), q)
        for (_, _, nk) in streams
    )
    dq = match_vma(jnp.zeros((b, h, n_local, d), jnp.float32), q)

    def hop(i, dq, kvs, dkvs, masks, segs):
        scope = f"ring/bwd_hop{i}" if isinstance(i, int) else "ring/bwd_hop"
        new_kvs, new_dkvs, new_masks, new_segs = [], [], [], []
        for si, stream in enumerate(streams):
            kvx, dkvx = kvs[si], dkvs[si]
            mx = masks[si] if masks else None
            sx = segs[si] if segs else None
            hi, lo = _stream_offsets(
                stream, rank, i, n_local, causal, striped, window, ring_size
            )
            has_work = _hop_has_work(hi, lo, n_local, stream[2],
                                     segment_ids, sx)
            if isinstance(i, int):
                full, hint = _static_hop_band(
                    stream, i, n_local, causal, striped, window, ring_size
                )
                if full:
                    hi, lo, hint = None, None, None
            else:
                hint = None

            def do_bwd(args, kvx=kvx, mx=mx, hi=hi, lo=lo, hint=hint, sx=sx):
                dq, dkvx = args
                dq_i, dk_i, dv_i = _span_bwd(
                    impl, do, q, kvx[0], kvx[1], lse, delta, mx, hi, lo,
                    scale, bucket_size, softclamp_value, hk, hint,
                    segment_ids, sx,
                )
                return dq + dq_i, (
                    dkvx.at[0].add(dk_i.astype(dkvx.dtype))
                    .at[1].add(dv_i.astype(dkvx.dtype))
                )

            with jax.named_scope(scope):
                dq, dkvx = lax.cond(has_work, do_bwd, lambda a: a, (dq, dkvx))
            with jax.named_scope("ring/rotate"):
                new_kvs.append(_rotate(kvx, axis_name, stream[0]))
                new_dkvs.append(_rotate(dkvx, axis_name, stream[0]))
                if mx is not None:
                    new_masks.append(_rotate(mx, axis_name, stream[0]))
                if sx is not None:
                    new_segs.append(_rotate(sx, axis_name, stream[0]))
        return (dq, tuple(new_kvs), tuple(new_dkvs), tuple(new_masks),
                tuple(new_segs))

    if impl == "pallas":
        # unrolled for static per-hop bands (see _ring_fwd_impl)
        for i in range(passes):
            dq, kvs, dkvs, masks, segs = hop(i, dq, kvs, dkvs, masks, segs)
    else:

        def body(c, i):
            dq, kvs, dkvs, masks, segs = c
            return hop(i, dq, kvs, dkvs, masks, segs), None

        (dq, kvs, dkvs, _, _), _ = lax.scan(
            body, (dq, kvs, dkvs, masks, segs), jnp.arange(passes)
        )

    # Catch-up rotation: after `passes` end-of-hop rotations by `shift` the
    # dkv shard on this device belongs to origin (rank - shift*passes);
    # one composed ppermute per stream returns every shard to its owner in
    # a single collective (the reference loops single hops instead,
    # ref ring_flash_attention.py:380-385).
    caught = []
    with jax.named_scope("ring/catchup"):
        for stream, dkvx in zip(streams, dkvs):
            shift = (stream[0] * (ring_size - passes)) % ring_size
            if shift:
                dkvx = lax.ppermute(
                    dkvx, axis_name, _ring_perm(axis_name, shift)
                )
            caught.append(dkvx)

    if len(caught) == 1:
        dkv = caught[0]
    else:
        dkv = jnp.concatenate(caught, axis=3)

    return (
        dq.astype(q.dtype),
        dkv[0].astype(k.dtype),
        dkv[1].astype(v.dtype),
        None,
        None,
    )


_ring_flash_attention_core.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)
